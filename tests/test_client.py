"""Client facade integration tests: the local-mode cluster end to end.

Ref model: yt/python/yt/wrapper usage patterns over a YTInstance local
cluster (yt_env.py) — cypress ops, static tables, dynamic tables,
operations, select_rows.
"""

import pytest

from ytsaurus_tpu import YtError
from ytsaurus_tpu.client import connect, infer_schema
from ytsaurus_tpu.schema import TableSchema


@pytest.fixture
def client(tmp_path):
    return connect(str(tmp_path))


# --- cypress ------------------------------------------------------------------

def test_cypress_crud(client):
    client.create("map_node", "//home/user", recursive=True)
    client.set("//home/user/@owner", "tester")
    assert client.get("//home/user/@owner") == "tester"
    assert client.exists("//home/user")
    assert client.list("//home") == ["user"]
    client.create("document", "//home/user/doc")
    client.set("//home/user/doc", {"a": [1, 2]})
    assert client.get("//home/user/doc") == {"a": [1, 2]}
    client.remove("//home/user")
    assert not client.exists("//home/user")


def test_master_recovery(tmp_path):
    client = connect(str(tmp_path))
    client.create("map_node", "//data", recursive=True)
    client.set("//data/@answer", 42)
    client.write_table("//data/t", [{"x": 1}, {"x": 2}])
    # Re-open the cluster from disk: WAL replay must restore everything.
    reopened = connect(str(tmp_path), fresh=True)
    assert reopened.get("//data/@answer") == 42
    assert reopened.read_table("//data/t") == [{"x": 1}, {"x": 2}]
    # Snapshot + more mutations + recovery.
    reopened.cluster.master.build_snapshot()
    reopened.set("//data/@post_snapshot", True)
    third = connect(str(tmp_path), fresh=True)
    assert third.get("//data/@answer") == 42
    assert third.get("//data/@post_snapshot") is True


# --- static tables ------------------------------------------------------------

def test_write_read_table_roundtrip(client):
    rows = [{"name": "a", "score": 1.5}, {"name": "b", "score": None}]
    client.write_table("//tmp/t", rows)
    assert client.read_table("//tmp/t") == \
        [{"name": b"a", "score": 1.5}, {"name": b"b", "score": None}]
    assert client.get("//tmp/t/@row_count") == 2


def test_append_creates_multiple_chunks(client):
    client.write_table("//tmp/t", [{"x": 1}])
    client.write_table("//tmp/t", [{"x": 2}], append=True)
    assert client.get("//tmp/t/@row_count") == 2
    assert len(client.get("//tmp/t/@chunk_ids")) == 2
    assert sorted(r["x"] for r in client.read_table("//tmp/t")) == [1, 2]


def test_infer_schema():
    schema = infer_schema([{"a": 1, "b": "x"}, {"a": 2.5, "b": None}])
    assert schema.get("a").type.value == "double"
    assert schema.get("b").type.value == "string"


def test_select_over_static_table(client):
    client.write_table("//tmp/t", [{"k": i, "v": i * 2} for i in range(10)])
    rows = client.select_rows("sum(v) AS s FROM [//tmp/t] GROUP BY 1 AS one")
    assert rows == [{"s": 90}]


def test_select_multi_chunk_distributed(client):
    for i in range(3):
        client.write_table("//tmp/t", [{"k": j + i * 10, "g": j % 2}
                                       for j in range(10)], append=bool(i))
    rows = client.select_rows(
        "g, count(*) AS c FROM [//tmp/t] GROUP BY g")
    assert sorted((r["g"], r["c"]) for r in rows) == [(0, 15), (1, 15)]


# --- dynamic tables -----------------------------------------------------------

DYN_SCHEMA = TableSchema.make([
    ("key", "int64", "ascending"), ("value", "string")], unique_keys=True)


def test_dynamic_table_lifecycle(client):
    client.create("table", "//dyn/t", recursive=True,
                  attributes={"schema": DYN_SCHEMA, "dynamic": True})
    client.mount_table("//dyn/t")
    client.insert_rows("//dyn/t", [{"key": 1, "value": "one"},
                                   {"key": 2, "value": "two"}])
    assert client.lookup_rows("//dyn/t", [(1,)]) == \
        [{"key": 1, "value": b"one"}]
    rows = client.select_rows("key, value FROM [//dyn/t] WHERE key > 1")
    assert rows == [{"key": 2, "value": b"two"}]
    # Unmount persists; remount restores.
    client.unmount_table("//dyn/t")
    client.mount_table("//dyn/t")
    assert client.lookup_rows("//dyn/t", [(2,)]) == \
        [{"key": 2, "value": b"two"}]


def test_dynamic_table_transactions(client):
    client.create("table", "//dyn/t", recursive=True,
                  attributes={"schema": DYN_SCHEMA, "dynamic": True})
    client.mount_table("//dyn/t")
    tx = client.start_transaction()
    client.insert_rows("//dyn/t", [{"key": 1, "value": "tx"}], tx=tx)
    # Not visible before commit.
    assert client.lookup_rows("//dyn/t", [(1,)]) == [None]
    client.commit_transaction(tx)
    assert client.lookup_rows("//dyn/t", [(1,)])[0]["value"] == b"tx"


def test_select_joins_static_dimension(client):
    client.create("table", "//dyn/facts", recursive=True,
                  attributes={"schema": TableSchema.make(
                      [("k", "int64", "ascending"), ("g", "int64")],
                      unique_keys=True), "dynamic": True})
    client.mount_table("//dyn/facts")
    client.insert_rows("//dyn/facts", [{"k": i, "g": i % 2}
                                       for i in range(6)])
    client.write_table("//tmp/dim", [{"g": 0, "name": "even"},
                                     {"g": 1, "name": "odd"}])
    rows = client.select_rows(
        "name, count(*) AS c FROM [//dyn/facts] "
        "JOIN [//tmp/dim] USING g GROUP BY name")
    assert sorted((r["name"], r["c"]) for r in rows) == \
        [(b"even", 3), (b"odd", 3)]


# --- operations ---------------------------------------------------------------

def test_sort_operation(client):
    client.write_table("//tmp/in", [{"k": 5 - i, "v": i} for i in range(5)])
    op = client.run_sort("//tmp/in", "//tmp/out", sort_by="k")
    assert op.state == "completed"
    out = client.read_table("//tmp/out")
    assert [r["k"] for r in out] == [1, 2, 3, 4, 5]
    assert client.get("//tmp/out/@sorted_by") == ["k"]
    # Operation recorded in cypress.
    ops = client.list("//sys/operations")
    assert op.id in ops
    assert client.get(f"//sys/operations/{op.id}/@state") == "completed"


def test_merge_operation_sorted(client):
    client.write_table("//tmp/a", [{"k": 1}, {"k": 3}])
    client.write_table("//tmp/b", [{"k": 2}, {"k": 4}])
    op = client.run_merge(["//tmp/a", "//tmp/b"], "//tmp/m", mode="sorted",
                          merge_by=["k"])
    assert op.state == "completed"
    assert [r["k"] for r in client.read_table("//tmp/m")] == [1, 2, 3, 4]


def test_map_operation(client):
    client.write_table("//tmp/in", [{"x": i} for i in range(4)])

    def mapper(rows):
        return [{"y": r["x"] * 10} for r in rows if r["x"] % 2 == 0]

    op = client.run_map(mapper, "//tmp/in", "//tmp/out")
    assert op.state == "completed"
    assert sorted(r["y"] for r in client.read_table("//tmp/out")) == [0, 20]


def test_failed_operation_records_error(client):
    client.write_table("//tmp/in", [{"x": 1}])

    def bad_mapper(rows):
        raise RuntimeError("boom")

    with pytest.raises(YtError):
        client.run_map(bad_mapper, "//tmp/in", "//tmp/out")
    ops = client.scheduler.list_operations()
    assert ops[-1].state == "failed"
    assert "boom" in str(ops[-1].error)


def test_sort_then_query_pipeline(client):
    # The classic platform flow: ingest → sort → query.
    client.write_table("//tmp/events",
                       [{"user": f"u{i % 3}", "amount": i} for i in range(30)])
    client.run_sort("//tmp/events", "//tmp/events_sorted", sort_by="user")
    rows = client.select_rows(
        "user, sum(amount) AS total FROM [//tmp/events_sorted] GROUP BY user")
    assert sorted((r["user"], r["total"]) for r in rows) == \
        [(b"u0", 135), (b"u1", 145), (b"u2", 155)]


# --- regression: review findings ---------------------------------------------

def test_torn_changelog_tail_truncated(tmp_path):
    client = connect(str(tmp_path))
    client.create("map_node", "//a", recursive=True)
    # Simulate a torn tail write.
    log = str(tmp_path) + "/master/changelog.log"
    with open(log, "ab") as f:
        f.write(b"\x7f\x01\x02")          # garbage partial record
    re1 = connect(str(tmp_path), fresh=True)
    re1.create("map_node", "//b", recursive=True)
    re2 = connect(str(tmp_path), fresh=True)
    assert re2.exists("//a") and re2.exists("//b")


def test_map_to_empty_output(client):
    client.write_table("//tmp/in", [{"x": 1}])
    op = client.run_map(lambda rows: [], "//tmp/in", "//tmp/out")
    assert op.state == "completed"
    assert client.read_table("//tmp/out") == []


def test_create_under_table_rejected(client):
    client.write_table("//tmp/t", [{"x": 1}])
    with pytest.raises(YtError):
        client.create("map_node", "//tmp/t/sub/x", recursive=True)


def test_remove_ancestor_evicts_tablets(client):
    client.create("table", "//dyn/t", recursive=True,
                  attributes={"schema": DYN_SCHEMA, "dynamic": True})
    client.mount_table("//dyn/t")
    client.insert_rows("//dyn/t", [{"key": 1, "value": "x"}])
    assert len(client.cluster.tablets) == 1
    client.remove("//dyn")
    assert len(client.cluster.tablets) == 0


def test_overwrite_clears_sorted_by(client):
    client.write_table("//tmp/in", [{"k": 2}, {"k": 1}])
    client.run_sort("//tmp/in", "//tmp/out", sort_by="k")
    assert client.get("//tmp/out/@sorted_by") == ["k"]
    client.write_table("//tmp/out", [{"k": 9}, {"k": 3}])
    assert not client.exists("//tmp/out/@sorted_by")


# --- ordered (queue) tables ---------------------------------------------------

ORDERED_SCHEMA = TableSchema.make([("msg", "string"), ("n", "int64")])


def _make_queue(client, path="//q/log"):
    client.create("table", path, recursive=True,
                  attributes={"schema": ORDERED_SCHEMA, "dynamic": True})
    client.mount_table(path)
    return path


def test_ordered_table_append_and_pull(client):
    q = _make_queue(client)
    first = client.push_queue(q, [{"msg": "a", "n": 1}, {"msg": "b", "n": 2}])
    assert first == 0
    client.insert_rows(q, [{"msg": "c", "n": 3}])   # insert_rows routes too
    rows = client.pull_queue(q, 1)
    assert [r["n"] for r in rows] == [2, 3]
    assert [r["msg"] for r in rows] == [b"b", b"c"]
    assert [r["$row_index"] for r in rows] == [1, 2]


def test_ordered_table_flush_trim_persist(client):
    q = _make_queue(client)
    client.push_queue(q, [{"msg": f"m{i}", "n": i} for i in range(10)])
    (tablet,) = client._mounted_tablets(q)
    tablet.flush()
    client.push_queue(q, [{"msg": "fresh", "n": 99}])
    rows = client.pull_queue(q, 8)
    assert [r["n"] for r in rows] == [8, 9, 99]
    client.trim_rows(q, 5)
    assert [r["n"] for r in client.pull_queue(q, 0)][:2] == [5, 6]
    # Unmount persists; remount restores indices and trim point.
    client.unmount_table(q)
    client.mount_table(q)
    rows = client.pull_queue(q, 0)
    assert [r["n"] for r in rows] == [5, 6, 7, 8, 9, 99]
    assert client.push_queue(q, [{"msg": "after", "n": 100}]) == 11


def test_ordered_table_query_with_row_index(client):
    q = _make_queue(client)
    client.push_queue(q, [{"msg": f"m{i % 2}", "n": i} for i in range(6)])
    rows = client.select_rows(
        f"msg, count(*) AS c FROM [{q}] WHERE $row_index >= 2 GROUP BY msg")
    assert sorted((r["msg"], r["c"]) for r in rows) == \
        [(b"m0", 2), (b"m1", 2)]


# --- formats ------------------------------------------------------------------

def test_formats_roundtrip():
    from ytsaurus_tpu.formats import dumps_rows, loads_rows
    rows = [{"a": 1, "b": "x"}, {"a": 2, "b": "y y"}]
    # yson/json preserve integer types; dsv is stringly typed.
    assert loads_rows(dumps_rows(rows, "yson"), "yson") == rows
    assert loads_rows(dumps_rows(rows, "json"), "json") == rows
    assert loads_rows(dumps_rows(rows, "dsv"), "dsv") == \
        [{"a": "1", "b": "x"}, {"a": "2", "b": "y y"}]
    blob = dumps_rows(rows, "schemaful_dsv", columns=["b", "a"])
    assert blob == b"x\t1\ny y\t2\n"
    back = loads_rows(blob, "schemaful_dsv", columns=["b", "a"])
    assert back[0] == {"b": "x", "a": "1"}


def test_dsv_escaping():
    from ytsaurus_tpu.formats import dumps_rows, loads_rows
    rows = [{"k": "a=b\tc\nd"}]
    blob = dumps_rows(rows, "dsv")
    assert loads_rows(blob, "dsv") == [{"k": "a=b\tc\nd"}]


def test_dsv_key_with_equals_roundtrips():
    from ytsaurus_tpu.formats import dumps_rows, loads_rows
    rows = [{"a=b": "v", "c": "x=y"}]
    assert loads_rows(dumps_rows(rows, "dsv"), "dsv") == rows


def test_queue_api_routing_guards(client):
    # Queue APIs on a sorted table / sorted APIs on a queue → typed errors.
    client.create("table", "//dyn/sorted", recursive=True,
                  attributes={"schema": DYN_SCHEMA, "dynamic": True})
    client.mount_table("//dyn/sorted")
    q = _make_queue(client, "//q/guard")
    with pytest.raises(YtError):
        client.pull_queue("//dyn/sorted", 0)
    with pytest.raises(YtError):
        client.trim_rows("//dyn/sorted", 1)
    with pytest.raises(YtError):
        client.lookup_rows(q, [(1,)])
    with pytest.raises(YtError):
        client.delete_rows(q, [(1,)])
    with pytest.raises(YtError):
        client.compact_table(q)


def test_table_format_io(client):
    client.write_table("//fmt/t", b'{"a": 1, "b": "x"}\n{"a": 2, "b": "y"}\n',
                       format="json",
                       schema=TableSchema.make([("a", "int64"),
                                                ("b", "string")]))
    assert client.read_table("//fmt/t") == \
        [{"a": 1, "b": b"x"}, {"a": 2, "b": b"y"}]
    blob = client.read_table("//fmt/t", format="json")
    assert b'"a": 1' in blob


def test_shard_pruning_by_chunk_stats(client):
    # Three chunks with disjoint key ranges; WHERE should prune to one.
    for base in (0, 100, 200):
        client.write_table("//tmp/sharded",
                           [{"k": base + i, "v": i} for i in range(10)],
                           append=base > 0)
    # Sanity: all rows reachable.
    assert len(client.select_rows("k FROM [//tmp/sharded]")) == 30
    rows = client.select_rows(
        "k, v FROM [//tmp/sharded] WHERE k >= 100 AND k < 110")
    assert sorted(r["k"] for r in rows) == list(range(100, 110))
    # Verify pruning actually happened: patch the cache to count reads.
    reads = []
    orig = client.cluster.chunk_cache.get
    client.cluster.chunk_cache.get = lambda cid: (reads.append(cid),
                                                  orig(cid))[1]
    client.select_rows("k FROM [//tmp/sharded] WHERE k = 205")
    client.cluster.chunk_cache.get = orig
    assert len(reads) == 1  # only the third chunk was touched


def test_pruning_conservative_on_or(client):
    client.write_table("//tmp/p", [{"k": i} for i in range(5)])
    client.write_table("//tmp/p", [{"k": i + 100} for i in range(5)],
                       append=True)
    rows = client.select_rows(
        "k FROM [//tmp/p] WHERE k = 1 OR k = 101")
    assert sorted(r["k"] for r in rows) == [1, 101]


def test_pruning_skipped_for_pre_stats_tables(client):
    # A table whose @chunk_stats is missing (pre-stats era) must not be
    # mis-pruned after an append adds stats for the new chunk only.
    client.write_table("//tmp/legacy", [{"k": i} for i in range(10)])
    client.cluster.master.commit_mutation(
        "remove", path="//tmp/legacy/@chunk_stats", force=True)
    client.write_table("//tmp/legacy", [{"k": 100 + i} for i in range(10)],
                       append=True)
    rows = client.select_rows("k FROM [//tmp/legacy] WHERE k = 5")
    assert [r["k"] for r in rows] == [5]


# --- multi-tablet (resharded) dynamic tables ----------------------------------

def test_reshard_and_multi_tablet_ops(client):
    client.create("table", "//dyn/sharded", recursive=True,
                  attributes={"schema": DYN_SCHEMA, "dynamic": True})
    client.mount_table("//dyn/sharded")
    client.insert_rows("//dyn/sharded",
                       [{"key": i, "value": f"v{i}"} for i in range(30)])
    client.unmount_table("//dyn/sharded")
    client.reshard_table("//dyn/sharded", [(10,), (20,)])
    client.mount_table("//dyn/sharded")
    tablets = client._mounted_tablets("//dyn/sharded")
    assert len(tablets) == 3
    # Existing rows redistributed: all keys still readable.
    rows = client.lookup_rows("//dyn/sharded", [(5,), (15,), (25,), (99,)])
    assert [r and r["key"] for r in rows] == [5, 15, 25, None]
    # New writes route to the right tablets.
    client.insert_rows("//dyn/sharded", [{"key": 3, "value": "low"},
                                         {"key": 29, "value": "high"}])
    assert tablets[0].active_store.key_count == 1
    assert tablets[2].active_store.key_count == 1
    # select spans all tablets.
    out = client.select_rows(
        "count(*) AS c FROM [//dyn/sharded] GROUP BY 1 AS o")
    assert out == [{"c": 30}]
    # Deletes route too.
    client.delete_rows("//dyn/sharded", [(15,)])
    assert client.lookup_rows("//dyn/sharded", [(15,)]) == [None]
    # Per-tablet persistence across remount.
    client.unmount_table("//dyn/sharded")
    client.mount_table("//dyn/sharded")
    rows = client.lookup_rows("//dyn/sharded", [(3,), (15,), (29,)])
    assert rows[0]["value"] == b"low"
    assert rows[1] is None
    assert rows[2]["value"] == b"high"


def test_reshard_requires_unmounted(client):
    client.create("table", "//dyn/r", recursive=True,
                  attributes={"schema": DYN_SCHEMA, "dynamic": True})
    client.mount_table("//dyn/r")
    with pytest.raises(YtError):
        client.reshard_table("//dyn/r", [(5,)])
    client.unmount_table("//dyn/r")
    with pytest.raises(YtError):
        client.reshard_table("//dyn/r", [(5, 6)])   # wrong key width
    with pytest.raises(YtError):
        client.reshard_table("//dyn/r", [(7,), (5,)])  # not increasing


def test_compact_resharded_table_survives_restart(tmp_path):
    client = connect(str(tmp_path))
    client.create("table", "//dyn/c", recursive=True,
                  attributes={"schema": DYN_SCHEMA, "dynamic": True})
    client.mount_table("//dyn/c")
    client.insert_rows("//dyn/c", [{"key": i, "value": f"v{i}"}
                                   for i in range(20)])
    client.unmount_table("//dyn/c")
    client.reshard_table("//dyn/c", [(10,)])
    client.mount_table("//dyn/c")
    client.insert_rows("//dyn/c", [{"key": 5, "value": "new5"},
                                   {"key": 15, "value": "new15"}])
    client.compact_table("//dyn/c")   # persists nested per-tablet chunks
    client.unmount_table("//dyn/c")
    reopened = connect(str(tmp_path), fresh=True)
    reopened.mount_table("//dyn/c")
    rows = reopened.lookup_rows("//dyn/c", [(5,), (15,), (19,)])
    assert rows[0]["value"] == b"new5"
    assert rows[1]["value"] == b"new15"
    assert rows[2]["value"] == b"v19"


def test_duplicate_pivots_rejected(client):
    client.create("table", "//dyn/dup", recursive=True,
                  attributes={"schema": DYN_SCHEMA, "dynamic": True})
    with pytest.raises(YtError):
        client.reshard_table("//dyn/dup", [(5,), (5,)])


def test_query_statistics_and_logging(client, capsys):
    import logging
    from ytsaurus_tpu.utils.logging import get_logger
    logger = get_logger("Query")
    old_level = logger.level
    logger.setLevel(logging.INFO)
    try:
        for base in (0, 100):
            client.write_table("//t/stats",
                               [{"k": base + i} for i in range(50)],
                               append=base > 0)
        client.select_rows("count(*) AS c FROM [//t/stats] WHERE k >= 100 "
                           "GROUP BY 1 AS o")
        stats = client.last_query_statistics
        assert stats.shards_pruned == 1          # first chunk pruned
        assert stats.rows_read == 50
        assert stats.rows_written == 1
        assert stats.execute_time > 0
        assert stats.compile_count >= 1
        # second run: cache hits, no compiles
        client.select_rows("count(*) AS c FROM [//t/stats] WHERE k >= 100 "
                           "GROUP BY 1 AS o")
        assert client.last_query_statistics.compile_count == 0
        assert client.last_query_statistics.cache_hits >= 1
        err = capsys.readouterr().err
        assert '"category": "ytsaurus_tpu.Query"' in err
        assert '"message": "select_rows"' in err
    finally:
        logger.setLevel(old_level)


def test_in_memory_mode_pins_tablet_chunks(client):
    client.create("table", "//dyn/mem", recursive=True,
                  attributes={"schema": DYN_SCHEMA, "dynamic": True,
                              "in_memory_mode": "uncompressed"})
    client.mount_table("//dyn/mem")
    client.insert_rows("//dyn/mem", [{"key": i, "value": f"v{i}"}
                                     for i in range(10)])
    client.unmount_table("//dyn/mem")
    client.mount_table("//dyn/mem")        # remount preloads + pins
    (tablet,) = client._mounted_tablets("//dyn/mem")
    cache = client.cluster.chunk_cache
    assert tablet.chunk_ids
    for cid in tablet.chunk_ids:
        assert cid in cache._entries and cid in cache._pinned
    # Pinned chunks survive eviction pressure.
    cache.capacity_bytes = 1
    client.write_table("//tmp/pressure", [{"x": i} for i in range(1000)])
    client.read_table("//tmp/pressure")
    for cid in tablet.chunk_ids:
        assert cid in cache._entries
    client.unmount_table("//dyn/mem")
    for cid in client.get("//dyn/mem/@tablet_chunk_ids")[0]:
        assert cid not in cache._pinned


def test_in_memory_pins_follow_flush_compact_and_remove(client):
    client.create("table", "//dyn/mem2", recursive=True,
                  attributes={"schema": DYN_SCHEMA, "dynamic": True,
                              "in_memory_mode": "uncompressed"})
    client.mount_table("//dyn/mem2")
    cache = client.cluster.chunk_cache
    client.insert_rows("//dyn/mem2", [{"key": 1, "value": "a"}])
    client.freeze_table("//dyn/mem2")      # flush-created chunk must pin
    (tablet,) = client._mounted_tablets("//dyn/mem2")
    assert all(cid in cache._pinned for cid in tablet.chunk_ids)
    client.insert_rows("//dyn/mem2", [{"key": 2, "value": "b"}])
    client.compact_table("//dyn/mem2")     # compacted chunk must pin
    assert tablet.chunk_ids
    assert all(cid in cache._pinned for cid in tablet.chunk_ids)
    pinned_before = set(tablet.chunk_ids)
    client.remove("//dyn")                 # removing the subtree unpins
    assert not (pinned_before & cache._pinned)


def test_in_memory_mode_ordered_table(client):
    client.create("table", "//q/mem", recursive=True,
                  attributes={"schema": ORDERED_SCHEMA, "dynamic": True,
                              "in_memory_mode": "uncompressed"})
    client.mount_table("//q/mem")
    client.push_queue("//q/mem", [{"msg": "x", "n": 1}])
    (tablet,) = client._mounted_tablets("//q/mem")
    tablet.flush()
    cache = client.cluster.chunk_cache
    assert all(cid in cache._pinned for cid in tablet.chunk_ids)


def test_computed_key_columns(client):
    # Hash-sharding key computed from the user id — the classic computed
    # column pattern.
    schema = TableSchema.make([
        {"name": "hash", "type": "uint64", "sort_order": "ascending",
         "expression": "farm_hash(user)"},
        {"name": "user", "type": "string", "sort_order": "ascending"},
        {"name": "n", "type": "int64"},
    ], unique_keys=True)
    client.create("table", "//dyn/computed", recursive=True,
                  attributes={"schema": schema, "dynamic": True})
    client.mount_table("//dyn/computed")
    client.insert_rows("//dyn/computed",
                       [{"user": "alice", "n": 1}, {"user": "bob", "n": 2}])
    rows = client.select_rows(
        "hash, user, n FROM [//dyn/computed] WHERE user = 'alice'")
    assert len(rows) == 1 and rows[0]["n"] == 1
    assert isinstance(rows[0]["hash"], int) and rows[0]["hash"] > 0
    # Same expression, same hash: re-insert overwrites the same key.
    client.insert_rows("//dyn/computed", [{"user": "alice", "n": 10}])
    rows = client.select_rows(
        "n FROM [//dyn/computed] WHERE user = 'alice'")
    assert rows == [{"n": 10}]
    # Writing the computed column directly is rejected.
    with pytest.raises(YtError):
        client.insert_rows("//dyn/computed",
                           [{"hash": 1, "user": "x", "n": 0}])


def test_computed_column_arithmetic(client):
    schema = TableSchema.make([
        {"name": "bucket", "type": "int64", "sort_order": "ascending",
         "expression": "id % 8"},
        {"name": "id", "type": "int64", "sort_order": "ascending"},
        {"name": "v", "type": "int64"},
    ], unique_keys=True)
    client.create("table", "//dyn/buckets", recursive=True,
                  attributes={"schema": schema, "dynamic": True})
    client.mount_table("//dyn/buckets")
    client.insert_rows("//dyn/buckets",
                       [{"id": i, "v": i * 10} for i in range(20)])
    rows = client.select_rows(
        "bucket, count(*) AS c FROM [//dyn/buckets] GROUP BY bucket")
    assert sorted((r["bucket"], r["c"]) for r in rows) == \
        [(b, 3 if b < 4 else 2) for b in range(8)]


def test_computed_keys_filled_for_lookup_and_delete(client):
    schema = TableSchema.make([
        {"name": "h", "type": "uint64", "sort_order": "ascending",
         "expression": "farm_hash(u)"},
        {"name": "u", "type": "string", "sort_order": "ascending"},
        {"name": "n", "type": "int64"},
    ], unique_keys=True)
    client.create("table", "//dyn/nat", recursive=True,
                  attributes={"schema": schema, "dynamic": True})
    client.mount_table("//dyn/nat")
    client.insert_rows("//dyn/nat", [{"u": "alice", "n": 1},
                                     {"u": "bob", "n": 2}])
    # Natural (computed-free) keys work for lookup and delete.
    rows = client.lookup_rows("//dyn/nat", [("alice",), ("carol",)])
    assert rows[0]["n"] == 1 and rows[1] is None
    client.delete_rows("//dyn/nat", [("bob",)])
    assert client.lookup_rows("//dyn/nat", [("bob",)]) == [None]
    # Plan cache: repeated fills reuse one built plan per schema.
    assert len(client._computed_plans) == 1


def test_copy_move_link(client):
    client.write_table("//a/t", [{"x": 1}, {"x": 2}])
    # copy: independent metadata, shared immutable chunks
    client.copy("//a/t", "//b/t", recursive=True)
    assert client.read_table("//b/t") == client.read_table("//a/t")
    client.write_table("//b/t", [{"x": 99}])          # diverges
    assert [r["x"] for r in client.read_table("//a/t")] == [1, 2]
    # move
    client.move("//a/t", "//a/renamed")
    assert not client.exists("//a/t")
    assert [r["x"] for r in client.read_table("//a/renamed")] == [1, 2]
    # link resolves through to the target
    client.link("//a/renamed", "//a/alias")
    assert client.read_table("//a/alias") == client.read_table("//a/renamed")
    # survives WAL recovery
    from ytsaurus_tpu.client import connect
    reopened = connect(client.cluster.root_dir, fresh=True)
    assert [r["x"] for r in reopened.read_table("//a/alias")] == [1, 2]
    # probes
    with pytest.raises(YtError):
        client.copy("//a/renamed", "//b/t")           # exists
    with pytest.raises(YtError):
        client.link("//no/such", "//a/badlink")


def test_move_mounted_table_rejected(client):
    client.create("table", "//dyn/m", recursive=True,
                  attributes={"schema": DYN_SCHEMA, "dynamic": True})
    client.mount_table("//dyn/m")
    with pytest.raises(YtError):
        client.move("//dyn/m", "//dyn/m2")


def test_move_failure_is_atomic(client):
    client.write_table("//m/src", [{"x": 1}])
    client.write_table("//m/dst", [{"x": 2}])
    with pytest.raises(YtError):
        client.move("//m/src", "//m/dst")      # exists → must not destroy src
    assert client.read_table("//m/src") == [{"x": 1}]


def test_move_link_moves_the_link(client):
    client.write_table("//m/t", [{"x": 7}])
    client.link("//m/t", "//m/l")
    client.move("//m/l", "//m/l2")
    assert client.read_table("//m/l2") == [{"x": 7}]
    assert client.read_table("//m/t") == [{"x": 7}]   # target untouched
    assert not client.exists("//m/l")


def test_copy_dynamic_table_survives_source_compaction(client):
    client.create("table", "//dyn/src", recursive=True,
                  attributes={"schema": DYN_SCHEMA, "dynamic": True})
    client.mount_table("//dyn/src")
    client.insert_rows("//dyn/src", [{"key": i, "value": f"v{i}"}
                                     for i in range(5)])
    with pytest.raises(YtError):
        client.copy("//dyn/src", "//dyn/copy")        # mounted → refuse
    client.unmount_table("//dyn/src")
    client.copy("//dyn/src", "//dyn/copy")
    # Compacting (which deletes chunks) on the ORIGINAL must not break the copy.
    client.mount_table("//dyn/src")
    client.insert_rows("//dyn/src", [{"key": 9, "value": "new"}])
    client.compact_table("//dyn/src")
    client.mount_table("//dyn/copy")
    rows = client.lookup_rows("//dyn/copy", [(0,), (4,), (9,)])
    assert rows[0]["value"] == b"v0" and rows[1]["value"] == b"v4"
    assert rows[2] is None                            # copy predates key 9


def test_mixed_width_computed_keys(client):
    schema = TableSchema.make([
        {"name": "b", "type": "int64", "sort_order": "ascending",
         "expression": "id % 2"},
        {"name": "id", "type": "int64", "sort_order": "ascending"},
        {"name": "v", "type": "int64"}], unique_keys=True)
    client.create("table", "//dyn/mix", recursive=True,
                  attributes={"schema": schema, "dynamic": True})
    client.mount_table("//dyn/mix")
    client.insert_rows("//dyn/mix", [{"id": i, "v": i} for i in range(4)])
    rows = client.lookup_rows("//dyn/mix", [(1, 3), (2,)])   # full + natural
    assert rows[0]["v"] == 3 and rows[1]["v"] == 2
    with pytest.raises(YtError):
        client.lookup_rows("//dyn/mix", [(1, 2, 3)])         # bad width


def test_collect_garbage(client):
    client.write_table("//g/t", [{"x": 1}])
    client.write_table("//g/t", [{"x": 2}])      # overwrite orphans chunk 1
    client.create("table", "//g/d", recursive=True,
                  attributes={"schema": DYN_SCHEMA, "dynamic": True})
    client.mount_table("//g/d")
    client.insert_rows("//g/d", [{"key": 1, "value": "live"}])
    client.freeze_table("//g/d")                 # runtime tablet chunk
    n_before = len(client.cluster.chunk_store.list_chunks())
    removed = client.collect_garbage()
    assert removed >= 1                          # the orphaned overwrite chunk
    # Everything still referenced survives and reads fine.
    assert client.read_table("//g/t") == [{"x": 2}]
    assert client.lookup_rows("//g/d", [(1,)])[0]["value"] == b"live"
    assert len(client.cluster.chunk_store.list_chunks()) == n_before - removed
    # Second sweep removes nothing.
    assert client.collect_garbage() == 0


def test_gc_refuses_during_operations(client):
    import threading
    client.write_table("//g/in", [{"x": i} for i in range(5)])
    gate = threading.Event()

    def slow_mapper(rows):
        gate.wait(5)
        return [{"y": r["x"]} for r in rows]

    op = client.scheduler.start_operation(
        "map", {"mapper": slow_mapper, "input_table_path": "//g/in",
                "output_table_path": "//g/out"}, sync=False)
    try:
        import time
        for _ in range(50):
            if op.state == "running":
                break
            time.sleep(0.05)
        with pytest.raises(YtError):
            client.collect_garbage()
    finally:
        gate.set()
    for _ in range(100):
        if op.state == "completed":
            break
        import time
        time.sleep(0.05)
    assert op.state == "completed"
    client.collect_garbage()       # fine once idle


def test_driver_command_registry(client):
    from ytsaurus_tpu.driver import COMMANDS, Driver
    d = Driver(client)
    d.execute("create", {"type": "map_node", "path": "//drv",
                         "recursive": True})
    d.execute("write_table", {"path": "//drv/t",
                              "rows": [{"x": 1}, {"x": 2}]})
    assert d.execute("read_table", {"path": "//drv/t"}) == \
        [{"x": 1}, {"x": 2}]
    op_id = d.execute("sort", {"input_table_path": "//drv/t",
                               "output_table_path": "//drv/sorted",
                               "sort_by": "x"})
    assert d.execute("get_operation",
                     {"operation_id": op_id})["state"] == "completed"
    rows = d.execute("select_rows",
                     {"query": "sum(x) AS s FROM [//drv/sorted] "
                               "GROUP BY 1 AS o"})
    assert rows == [{"s": 3}]
    assert d.execute("exists", {"path": "//drv/sorted"})
    with pytest.raises(YtError):
        d.execute("nonexistent_command")
    with pytest.raises(YtError):
        d.execute("get", {})                       # missing path
    with pytest.raises(YtError):
        d.execute("get", {"path": "//drv", "bogus": 1})
    # registry is the API surface: mutating flags are present
    assert COMMANDS["select_rows"].is_mutating is False
    assert COMMANDS["insert_rows"].is_mutating is True


def test_required_columns_enforced(client):
    schema = TableSchema.make([
        {"name": "k", "type": "int64", "sort_order": "ascending",
         "required": True},
        {"name": "v", "type": "string", "required": True},
    ], unique_keys=True)
    with pytest.raises(YtError):
        client.write_table("//req/static", [{"k": 1, "v": None}],
                           schema=schema.to_unsorted())
    client.create("table", "//req/d", recursive=True,
                  attributes={"schema": schema, "dynamic": True})
    client.mount_table("//req/d")
    with pytest.raises(YtError):
        client.insert_rows("//req/d", [{"k": 1}])     # missing required v
    client.insert_rows("//req/d", [{"k": 1, "v": "ok"}])
    assert client.lookup_rows("//req/d", [(1,)])[0]["v"] == b"ok"


def test_pruning_null_between_bound_not_pruned(client):
    # v BETWEEN # AND 1 admits null rows; a chunk whose non-null range is
    # outside [_, 1] but that contains nulls must still be read.
    client.write_table(
        "//tmp/nullb", [{"k": i, "v": None if i % 2 else 5 + i}
                        for i in range(4)],
        schema=TableSchema.make([("k", "int64"), ("v", "int64")]))
    rows = client.select_rows("k FROM [//tmp/nullb] WHERE v BETWEEN # AND 1")
    assert sorted(r["k"] for r in rows) == [1, 3]
