"""Dynamic table tests: MVCC writes/reads, flush/compaction, transactions,
lookup and select integration.

Modeled on the reference integration suite
yt/yt/tests/integration/dynamic_tables/test_sorted_dynamic_tables.py.
"""

import pytest

from ytsaurus_tpu import YtError
from ytsaurus_tpu.chunks.store import FsChunkStore
from ytsaurus_tpu.query import select_rows
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.tablet.tablet import Tablet
from ytsaurus_tpu.tablet.timestamp import MAX_TIMESTAMP
from ytsaurus_tpu.tablet.transactions import TransactionManager

SCHEMA = TableSchema.make([
    ("key", "int64", "ascending"),
    ("value", "string"),
    ("amount", "int64"),
], unique_keys=True)


@pytest.fixture
def tablet(tmp_path):
    return Tablet(SCHEMA, FsChunkStore(str(tmp_path)))


@pytest.fixture
def txm():
    return TransactionManager()


def _insert(txm, tablet, rows):
    tx = txm.start()
    txm.write_rows(tx, tablet, rows)
    return txm.commit(tx)


def test_insert_and_lookup(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "a", "amount": 10},
                          {"key": 2, "value": "b", "amount": 20}])
    rows = tablet.lookup_rows([(1,), (2,), (3,)])
    assert rows[0] == {"key": 1, "value": b"a", "amount": 10}
    assert rows[1] == {"key": 2, "value": b"b", "amount": 20}
    assert rows[2] is None


def test_overwrite_takes_latest(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "old", "amount": 1}])
    _insert(txm, tablet, [{"key": 1, "value": "new", "amount": 2}])
    (row,) = tablet.lookup_rows([(1,)])
    assert row["value"] == b"new" and row["amount"] == 2


def test_snapshot_isolation_timestamps(tablet, txm):
    ts1 = _insert(txm, tablet, [{"key": 1, "value": "v1", "amount": 1}])
    ts2 = _insert(txm, tablet, [{"key": 1, "value": "v2", "amount": 2}])
    (at_ts1,) = tablet.lookup_rows([(1,)], timestamp=ts1)
    (at_ts2,) = tablet.lookup_rows([(1,)], timestamp=ts2)
    (before,) = tablet.lookup_rows([(1,)], timestamp=ts1 - 1)
    assert at_ts1["value"] == b"v1"
    assert at_ts2["value"] == b"v2"
    assert before is None


def test_delete_row(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "x", "amount": 1}])
    tx = txm.start()
    txm.delete_rows(tx, tablet, [(1,)])
    del_ts = txm.commit(tx)
    (row,) = tablet.lookup_rows([(1,)])
    assert row is None
    # But the old version is still visible before the delete.
    (old,) = tablet.lookup_rows([(1,)], timestamp=del_ts - 1)
    assert old["value"] == b"x"


def test_flush_preserves_versions(tablet, txm):
    ts1 = _insert(txm, tablet, [{"key": 1, "value": "v1", "amount": 1}])
    ts2 = _insert(txm, tablet, [{"key": 1, "value": "v2", "amount": 2}])
    chunk_id = tablet.flush()
    assert chunk_id is not None
    assert tablet.active_store.key_count == 0
    (at_ts1,) = tablet.lookup_rows([(1,)], timestamp=ts1)
    (latest,) = tablet.lookup_rows([(1,)])
    assert at_ts1["value"] == b"v1"
    assert latest["value"] == b"v2"


def test_mixed_store_and_chunk_reads(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "flushed", "amount": 1}])
    tablet.flush()
    _insert(txm, tablet, [{"key": 2, "value": "fresh", "amount": 2}])
    rows = tablet.lookup_rows([(1,), (2,)])
    assert rows[0]["value"] == b"flushed"
    assert rows[1]["value"] == b"fresh"
    snapshot = tablet.read_snapshot()
    assert sorted(r["key"] for r in snapshot.to_rows()) == [1, 2]


def test_write_after_flush_overrides_chunk(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "old", "amount": 1}])
    tablet.flush()
    _insert(txm, tablet, [{"key": 1, "value": "new", "amount": 2}])
    (row,) = tablet.lookup_rows([(1,)])
    assert row["value"] == b"new"


def test_compaction_drops_superseded(tablet, txm):
    for i in range(3):
        _insert(txm, tablet, [{"key": 1, "value": f"v{i}", "amount": i}])
    tablet.flush()
    ts_now = txm.timestamps.generate()
    tablet.compact(retention_timestamp=ts_now)
    assert len(tablet.chunk_ids) == 1
    chunk = tablet.chunk_store.read_chunk(tablet.chunk_ids[0])
    assert chunk.row_count == 1          # only the latest version survives
    (row,) = tablet.lookup_rows([(1,)])
    assert row["value"] == b"v2"


def test_compaction_removes_deleted_keys(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "x", "amount": 1}])
    tx = txm.start()
    txm.delete_rows(tx, tablet, [(1,)])
    txm.commit(tx)
    tablet.flush()
    tablet.compact(retention_timestamp=txm.timestamps.generate())
    assert tablet.chunk_ids == []
    (row,) = tablet.lookup_rows([(1,)])
    assert row is None


def test_conflict_detection(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "base", "amount": 0}])
    tx1 = txm.start()
    tx2 = txm.start()
    txm.write_rows(tx1, tablet, [{"key": 1, "value": "a", "amount": 1}])
    txm.write_rows(tx2, tablet, [{"key": 1, "value": "b", "amount": 2}])
    txm.commit(tx1)
    with pytest.raises(YtError) as err:
        txm.commit(tx2)
    assert err.value.code == 1700
    assert tx2.state == "aborted"
    (row,) = tablet.lookup_rows([(1,)])
    assert row["value"] == b"a"


def test_non_conflicting_keys_commit(tablet, txm):
    tx1 = txm.start()
    tx2 = txm.start()
    txm.write_rows(tx1, tablet, [{"key": 1, "value": "a", "amount": 1}])
    txm.write_rows(tx2, tablet, [{"key": 2, "value": "b", "amount": 2}])
    txm.commit(tx1)
    txm.commit(tx2)
    assert len([r for r in tablet.lookup_rows([(1,), (2,)]) if r]) == 2


def test_multi_tablet_transaction_atomic(tmp_path, txm):
    t1 = Tablet(SCHEMA, FsChunkStore(str(tmp_path / "a")), tablet_id="a")
    t2 = Tablet(SCHEMA, FsChunkStore(str(tmp_path / "b")), tablet_id="b")
    tx = txm.start()
    txm.write_rows(tx, t1, [{"key": 1, "value": "x", "amount": 1}])
    txm.write_rows(tx, t2, [{"key": 1, "value": "y", "amount": 2}])
    ts = txm.commit(tx)
    # Same commit timestamp on both participants.
    assert t1.lookup_rows([(1,)], timestamp=ts)[0]["value"] == b"x"
    assert t2.lookup_rows([(1,)], timestamp=ts)[0]["value"] == b"y"
    assert t1.lookup_rows([(1,)], timestamp=ts - 1)[0] is None
    assert t2.lookup_rows([(1,)], timestamp=ts - 1)[0] is None


def test_abort_releases_locks(tablet, txm):
    tx1 = txm.start()
    txm.write_rows(tx1, tablet, [{"key": 1, "value": "a", "amount": 1}])
    txm.abort(tx1)
    tx2 = txm.start()
    txm.write_rows(tx2, tablet, [{"key": 1, "value": "b", "amount": 2}])
    txm.commit(tx2)
    (row,) = tablet.lookup_rows([(1,)])
    assert row["value"] == b"b"


def test_select_over_tablet_snapshot(tablet, txm):
    for i in range(20):
        _insert(txm, tablet, [{"key": i, "value": f"g{i % 3}",
                               "amount": i * 10}])
    tablet.flush()
    _insert(txm, tablet, [{"key": 100, "value": "g0", "amount": 5}])
    snapshot = tablet.read_snapshot()
    out = select_rows(
        "value, sum(amount) AS total FROM [//t] GROUP BY value",
        {"//t": snapshot})
    rows = {r["value"]: r["total"] for r in out.to_rows()}
    assert rows[b"g0"] == sum(i * 10 for i in range(0, 20, 3)) + 5
    assert rows[b"g1"] == sum(i * 10 for i in range(1, 20, 3))


def test_write_missing_value_column_becomes_null(tablet, txm):
    _insert(txm, tablet, [{"key": 1, "value": "full", "amount": 7}])
    _insert(txm, tablet, [{"key": 1, "value": "partial"}])
    (row,) = tablet.lookup_rows([(1,)])
    # Full-row write semantics: unspecified value columns become null.
    assert row == {"key": 1, "value": b"partial", "amount": None}


def test_batch_required_validation_is_all_or_nothing(tablet, txm):
    import dataclasses
    schema = dataclasses.replace(
        SCHEMA, columns=tuple(
            dataclasses.replace(c, required=(c.name == "value"))
            for c in SCHEMA.columns))
    from ytsaurus_tpu.chunks.store import FsChunkStore
    import tempfile
    t = Tablet(schema, FsChunkStore(tempfile.mkdtemp()))
    tx = txm.start()
    with pytest.raises(YtError):
        txm.write_rows(tx, t, [{"key": 1, "value": "ok"},
                               {"key": 2, "value": None}])
    # Nothing was recorded: commit applies zero rows.
    txm.commit(tx)
    assert t.lookup_rows([(1,), (2,)]) == [None, None]


def test_commit_to_unmounted_participant_applies_nothing(tmp_path, txm):
    t1 = Tablet(SCHEMA, FsChunkStore(str(tmp_path / "x")), tablet_id="x")
    t2 = Tablet(SCHEMA, FsChunkStore(str(tmp_path / "y")), tablet_id="y")
    tx = txm.start()
    txm.write_rows(tx, t1, [{"key": 1, "value": "a", "amount": 1}])
    txm.write_rows(tx, t2, [{"key": 2, "value": "b", "amount": 2}])
    t2.mounted = False
    with pytest.raises(YtError):
        txm.commit(tx)
    # Atomicity: the mounted participant must not have applied either.
    assert t1.lookup_rows([(1,)]) == [None]
    # And locks are free for a new transaction.
    t2.mounted = True
    tx2 = txm.start()
    txm.write_rows(tx2, t1, [{"key": 1, "value": "c", "amount": 3}])
    txm.commit(tx2)
    assert t1.lookup_rows([(1,)])[0]["value"] == b"c"


def test_lookup_row_cache(tablet, txm):
    _insert(txm, tablet, [{"key": i, "value": f"v{i}", "amount": i}
                          for i in range(10)])
    tablet.flush()
    r1 = tablet.lookup_rows([(3,)])[0]
    assert tablet.row_cache_misses >= 1
    hits0 = tablet.row_cache_hits
    r2 = tablet.lookup_rows([(3,)])[0]
    assert tablet.row_cache_hits == hits0 + 1
    assert r1 == r2
    # Writes invalidate: a new value must be visible immediately.
    _insert(txm, tablet, [{"key": 3, "value": "fresh", "amount": 99}])
    assert tablet.lookup_rows([(3,)])[0]["value"] == b"fresh"
    # Column projection applies after the cache (full row cached).
    narrow = tablet.lookup_rows([(3,)], column_names=["amount"])[0]
    assert narrow == {"amount": 99}
    # Timestamped (historical) reads bypass the cache.
    ts_hit = tablet.row_cache_hits
    tablet.lookup_rows([(3,)], timestamp=1)
    assert tablet.row_cache_hits == ts_hit


# --- per-column versioned writes (TVersionedRow partial-write semantics) ------

def _fresh_tablet(tmp_path, name="pc"):
    from ytsaurus_tpu.chunks.store import FsChunkStore
    from ytsaurus_tpu.tablet.tablet import Tablet
    schema = TableSchema.make([
        ("k", "int64", "ascending"), ("a", "int64"), ("b", "string"),
        ("c", "double")])
    return Tablet(schema, FsChunkStore(str(tmp_path / name)))


def test_partial_writes_merge_per_column(tmp_path):
    t = _fresh_tablet(tmp_path)
    t.write_row({"k": 1, "a": 10, "b": "x", "c": 1.5}, timestamp=100)
    t.write_row({"k": 1, "a": 20}, timestamp=200, update=True)
    t.write_row({"k": 1, "b": "y"}, timestamp=300, update=True)
    (row,) = t.lookup_rows([(1,)])
    assert (row["a"], row["b"], row["c"]) == (20, b"y", 1.5)
    # Historical reads see per-timestamp column states.
    (row,) = t.lookup_rows([(1,)], timestamp=250)
    assert (row["a"], row["b"], row["c"]) == (20, b"x", 1.5)
    (row,) = t.lookup_rows([(1,)], timestamp=150)
    assert (row["a"], row["b"], row["c"]) == (10, b"x", 1.5)


def test_partial_writes_survive_flush_and_compaction(tmp_path):
    t = _fresh_tablet(tmp_path)
    t.write_row({"k": 1, "a": 1, "b": "base", "c": 0.5}, timestamp=100)
    t.flush()
    t.write_row({"k": 1, "a": 2}, timestamp=200, update=True)
    t.flush()
    t.write_row({"k": 1, "c": 9.5}, timestamp=300, update=True)
    # Mixed store/chunk merge before compaction.
    (row,) = t.lookup_rows([(1,)])
    assert (row["a"], row["b"], row["c"]) == (2, b"base", 9.5)
    t.flush()
    t.compact()                 # full history retained (retention 0)
    (row,) = t.lookup_rows([(1,)], timestamp=250)
    assert (row["a"], row["b"], row["c"]) == (2, b"base", 0.5)
    (row,) = t.lookup_rows([(1,)])
    assert (row["a"], row["b"], row["c"]) == (2, b"base", 9.5)
    # Snapshot read path agrees.
    rows = t.read_snapshot().to_rows()
    assert rows == [{"k": 1, "a": 2, "b": b"base", "c": 9.5}]


def test_compaction_consolidates_partial_base(tmp_path):
    from ytsaurus_tpu.tablet.timestamp import MAX_TIMESTAMP
    t = _fresh_tablet(tmp_path)
    t.write_row({"k": 1, "a": 1, "b": "old", "c": 0.1}, timestamp=100)
    t.write_row({"k": 1, "a": 2}, timestamp=200, update=True)
    t.write_row({"k": 1, "b": "new"}, timestamp=300, update=True)
    t.flush()
    # Retention above all versions: history collapses to one merged base.
    t.compact(retention_timestamp=400)
    (row,) = t.lookup_rows([(1,)])
    assert (row["a"], row["b"], row["c"]) == (2, b"new", 0.1)
    chunk = t._decode(t.chunk_ids[0])
    versions = [r for r in chunk.to_rows()]
    assert len(versions) == 1   # consolidated single version


def test_delete_bounds_partial_merge(tmp_path):
    t = _fresh_tablet(tmp_path)
    t.write_row({"k": 1, "a": 1, "b": "x", "c": 1.0}, timestamp=100)
    t.delete_row((1,), timestamp=200)
    t.write_row({"k": 1, "a": 5}, timestamp=300, update=True)
    # Columns from before the delete must NOT leak through the merge.
    (row,) = t.lookup_rows([(1,)])
    assert row["a"] == 5 and row["b"] is None and row["c"] is None
    # And the same through flush + snapshot read.
    t.flush()
    rows = t.read_snapshot().to_rows()
    assert rows == [{"k": 1, "a": 5, "b": None, "c": None}]


def test_update_mode_via_client(tmp_path):
    from ytsaurus_tpu.client import connect
    client = connect(str(tmp_path / "cluster"))
    schema = TableSchema.make([("k", "int64", "ascending"),
                               ("x", "int64"), ("y", "int64")])
    client.create("table", "//dyn/u", recursive=True,
                  attributes={"schema": schema, "dynamic": True})
    client.mount_table("//dyn/u")
    client.insert_rows("//dyn/u", [{"k": 1, "x": 1, "y": 1}])
    client.insert_rows("//dyn/u", [{"k": 1, "x": 7}], update=True)
    (row,) = client.lookup_rows("//dyn/u", [(1,)])
    assert row["x"] == 7 and row["y"] == 1
    # Default overwrite mode nulls unstated columns.
    client.insert_rows("//dyn/u", [{"k": 1, "x": 8}])
    (row,) = client.lookup_rows("//dyn/u", [(1,)])
    assert row["x"] == 8 and row["y"] is None


def test_pre_percolumn_chunks_survive_compaction(tmp_path):
    """Chunks persisted BEFORE the $w: written-flag layout mean whole-row
    writes; reads AND compaction must honor that (reviewer-reproduced
    data-loss scenario)."""
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    t = _fresh_tablet(tmp_path, "legacy")
    # Build an old-format versioned chunk by hand (no $w columns).
    old_schema = TableSchema.make([
        ("k", "int64", "ascending"), ("$timestamp", "int64"),
        ("$tombstone", "boolean"), ("a", "int64"), ("b", "string"),
        ("c", "double")])
    chunk = ColumnarChunk.from_rows(
        old_schema, [{"k": 1, "$timestamp": 100, "$tombstone": False,
                      "a": 7, "b": b"x", "c": 2.5}])
    cid = t.chunk_store.write_chunk(chunk)
    t.chunk_ids.append(cid)
    (row,) = t.lookup_rows([(1,)])
    assert (row["a"], row["b"], row["c"]) == (7, b"x", 2.5)
    t.compact()
    (row,) = t.lookup_rows([(1,)])
    assert (row["a"], row["b"], row["c"]) == (7, b"x", 2.5)
    rows = t.read_snapshot().to_rows()
    assert rows == [{"k": 1, "a": 7, "b": b"x", "c": 2.5}]


def test_update_batch_validated_at_record_time(tmp_path):
    """A bad row in an update-mode batch must fail BEFORE anything is
    recorded — a commit-phase failure would half-apply the transaction."""
    from ytsaurus_tpu.chunks.store import FsChunkStore
    from ytsaurus_tpu.tablet.tablet import Tablet
    from ytsaurus_tpu.tablet.transactions import TransactionManager
    schema = TableSchema.make([
        ("k", "int64", "ascending"),
        {"name": "a", "type": "int64", "required": True},
        ("b", "int64")])
    t = Tablet(schema, FsChunkStore(str(tmp_path / "v")))
    tm = TransactionManager()
    tx = tm.start()
    with pytest.raises(YtError):
        tm.write_rows(tx, t, [{"k": 1, "a": 1, "b": 1},
                              {"k": 2, "a": None}], update=True)
    tm.commit(tx)               # nothing was recorded → empty commit
    assert t.lookup_rows([(1,), (2,)]) == [None, None]
    # Unknown columns also fail at record time.
    tx2 = tm.start()
    with pytest.raises(YtError):
        tm.write_rows(tx2, t, [{"k": 4, "nosuch": 5}], update=True)


def test_from_arrays_object_strings_with_nulls():
    import numpy as np
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    schema = TableSchema.make([("s", "string")])
    chunk = ColumnarChunk.from_arrays(
        schema, {"s": np.array([b"a", None, b"c"], dtype=object)})
    assert [r["s"] for r in chunk.to_rows()] == [b"a", None, b"c"]
