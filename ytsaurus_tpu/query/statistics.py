"""Query statistics (ref: client/query_client/query_statistics.h
TQueryStatistics — rows read/written, execute time, codegen time, incomplete
flags; aggregated across subqueries by the coordinator)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class QueryStatistics:
    rows_read: int = 0
    rows_written: int = 0
    bytes_read: int = 0              # resident bytes of scanned planes
    execute_time: float = 0.0        # seconds, wall, incl. device sync
    compile_time: float = 0.0        # seconds building device programs
    compile_count: int = 0           # programs compiled (cache misses)
    cache_hits: int = 0
    shards_total: int = 0
    shards_pruned: int = 0
    shards_skipped: int = 0          # LIMIT early-exit left these unread
    shards_staged: int = 0           # shards actually fetched/decoded
    retries: int = 0                 # transient per-shard retry attempts
    joins_executed: int = 0

    def to_dict(self) -> dict:
        from dataclasses import asdict
        return asdict(self)
