"""Equi-join execution: device sort-merge over columnar planes.

TPU-first redesign of the reference's MultiJoinOpHelper (cg_routines/
registry.cpp:599 — batched hash lookups into foreign tables): the foreign
side is lex-sorted by join key once, each self row finds its match range via
a vectorized lexicographic binary search, and the (self, foreign) index pairs
are materialized with a static output capacity computed host-side between the
two jitted phases (shape buckets keep recompiles bounded).
"""

from __future__ import annotations

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np

from ytsaurus_tpu.chunks.columnar import Column, ColumnarChunk, pad_capacity
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.ops.segments import lexsort_indices, sort_key_planes
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.engine.expr import (
    BindContext,
    ColumnBinding,
    EmitContext,
    ExprBinder,
    _merge_vocabs,
    _remap_table,
)
from ytsaurus_tpu.schema import EValueType, TableSchema


def _eval_keys(chunk: ColumnarChunk, schema: TableSchema,
               equations: tuple[ir.TExpr, ...]):
    """Evaluate join-key expressions over a chunk (eager device ops)."""
    bind_ctx = BindContext(columns={
        c.name: ColumnBinding(type=c.type, vocab=chunk.columns[c.name].dictionary)
        for c in schema})
    binder = ExprBinder(bind_ctx)
    bound = [binder.bind(e) for e in equations]
    ctx = EmitContext(
        columns={name: (col.data, col.valid)
                 for name, col in chunk.columns.items()},
        bindings=tuple(bind_ctx.bindings), capacity=chunk.capacity)
    planes = [b.emit(ctx) for b in bound]
    vocabs = [b.vocab for b in bound]
    return planes, vocabs


def _encode_keys(planes, vocabs, other_vocabs):
    """Normalize key planes for cross-table comparison: unify string vocabs,
    encode as (null_rank, value) pairs."""
    out = []
    for (data, valid), vocab, other in zip(planes, vocabs, other_vocabs):
        if vocab is not None or other is not None:
            merged = _merge_vocabs(vocab, other)
            table = _remap_table(
                vocab if vocab is not None else np.array([], dtype=object),
                merged)
            remap = jnp.asarray(table)
            data = remap[jnp.clip(data, 0, len(table) - 1)]
        if data.dtype == jnp.bool_:
            data = data.astype(jnp.int8)
        data = jnp.where(valid, data, jnp.zeros_like(data))
        out.append((valid.astype(jnp.int8), data))
    return out


def _lex_less(a_planes, b_planes, a_idx, b_idx, or_equal: bool):
    """Lexicographic a[a_idx] < b[b_idx] (or <= when or_equal) over encoded
    (null_rank, value) key plane pairs; null sorts before any value."""
    result = jnp.full(a_idx.shape, or_equal, dtype=bool)
    # Walk keys from least to most significant:
    for (av, ad), (bv, bd) in reversed(list(zip(a_planes, b_planes))):
        a_v, a_d = av[a_idx], ad[a_idx]
        b_v, b_d = bv[b_idx], bd[b_idx]
        lt = (a_v < b_v) | ((a_v == b_v) & (a_d < b_d))
        eq = (a_v == b_v) & (a_d == b_d)
        result = lt | (eq & result)
    return result


def _lex_searchsorted(sorted_planes, n_sorted: int, query_planes, side: str):
    """For each query row, binary-search the sorted key planes.
    side='left' → first index whose key >= query; 'right' → first > query."""
    cap_q = query_planes[0][0].shape[0]
    lo = jnp.zeros(cap_q, dtype=jnp.int64)
    hi = jnp.full(cap_q, n_sorted, dtype=jnp.int64)
    iters = max(1, int(np.ceil(np.log2(max(n_sorted, 2)))) + 1)
    q_idx = jnp.arange(cap_q)

    def body(_, carry):
        lo, hi = carry
        active = lo < hi
        mid = (lo + hi) // 2
        mid_c = jnp.clip(mid, 0, max(n_sorted - 1, 0))
        # Move right when sorted[mid] < query (left) / <= query (right).
        go_right = _lex_less(sorted_planes, query_planes, mid_c, q_idx,
                             or_equal=(side == "right"))
        lo = jnp.where(active & go_right, mid + 1, lo)
        hi = jnp.where(active & ~go_right, mid, hi)
        return lo, hi

    lo, hi = jax.lax.fori_loop(0, iters, body, (lo, hi))
    return lo


def execute_join(chunk: ColumnarChunk, combined_schema: TableSchema,
                 join: ir.JoinClause, foreign_chunk: ColumnarChunk
                 ) -> ColumnarChunk:
    """Materialize `chunk ⋈ foreign_chunk` into a wider columnar chunk.

    `combined_schema` is the namespace *after* this join (flat names).
    """
    self_planes, self_vocabs = _eval_keys(chunk, _chunk_namespace(chunk),
                                          join.self_equations)
    foreign_planes, foreign_vocabs = _eval_keys(
        foreign_chunk, join.foreign_schema, join.foreign_equations)

    self_keys = _encode_keys(self_planes, self_vocabs, foreign_vocabs)
    foreign_keys = _encode_keys(foreign_planes, foreign_vocabs, self_vocabs)

    # Sort foreign side; masked rows sink to the end.  jnp.lexsort treats the
    # LAST plane as most significant, so emit keys in reverse: first join key
    # must be most significant to agree with _lex_less.
    f_mask = foreign_chunk.row_valid
    sort_keys = []
    for v, d in reversed(foreign_keys):
        sort_keys.extend([d, v])
    sort_keys.append((~f_mask).astype(jnp.int8))
    f_order = lexsort_indices(sort_keys)
    f_sorted = [(v[f_order], d[f_order]) for v, d in foreign_keys]
    n_foreign = foreign_chunk.row_count

    lo = _lex_searchsorted(f_sorted, n_foreign, self_keys, "left")
    hi = _lex_searchsorted(f_sorted, n_foreign, self_keys, "right")
    s_mask = chunk.row_valid
    # SQL semantics: a null join key matches nothing (NULL = NULL is unknown).
    s_null = jnp.zeros(chunk.capacity, dtype=bool)
    for v, _ in self_keys:
        s_null = s_null | (v == 0)
    counts = jnp.where(s_mask & ~s_null, hi - lo, 0)
    if join.is_left:
        out_per_row = jnp.where(s_mask, jnp.maximum(counts, 1), 0)
    else:
        out_per_row = counts
    offsets = jnp.cumsum(out_per_row)
    total = int(offsets[-1])
    out_cap = pad_capacity(max(total, 1))

    out_idx = jnp.arange(out_cap)
    # Row r of self owns output slots [offsets[r-1], offsets[r]).
    starts = jnp.concatenate([jnp.zeros(1, dtype=offsets.dtype), offsets[:-1]])
    self_row = jnp.searchsorted(offsets, out_idx, side="right")
    self_row_c = jnp.clip(self_row, 0, chunk.capacity - 1)
    within = out_idx - starts[self_row_c]
    matched = counts[self_row_c] > 0
    foreign_pos = jnp.clip(lo[self_row_c] + within, 0, foreign_chunk.capacity - 1)
    foreign_row = f_order[foreign_pos]
    out_valid_row = out_idx < total

    columns: dict[str, Column] = {}
    for name, col in chunk.columns.items():
        data = col.data[self_row_c]
        valid = col.valid[self_row_c] & out_valid_row
        columns[name] = replace(col, data=data, valid=valid,
                                host_values=_gather_host(col, np.asarray(self_row_c), out_cap))
    skip = {c.name for c in _chunk_namespace(chunk)}
    for fname in join.foreign_columns:
        fcol = foreign_chunk.columns[fname]
        flat = f"{join.alias}.{fname}" if join.alias else fname
        data = fcol.data[foreign_row]
        valid = fcol.valid[foreign_row] & out_valid_row & matched
        columns[flat] = replace(fcol, data=data, valid=valid,
                                host_values=_gather_host(fcol, np.asarray(foreign_row), out_cap))
    out_columns = {}
    for col_schema in combined_schema:
        if col_schema.name not in columns:
            raise YtError(f"Join produced no column {col_schema.name!r}",
                          code=EErrorCode.QueryExecutionError)
        out_columns[col_schema.name] = columns[col_schema.name]
    return ColumnarChunk(schema=combined_schema, row_count=total,
                         columns=out_columns)


def _gather_host(col: Column, idx: np.ndarray, out_cap: int):
    if col.host_values is None:
        return None
    vals = [col.host_values[int(i)] if int(i) < len(col.host_values) else None
            for i in idx[:out_cap]]
    return vals


def _chunk_namespace(chunk: ColumnarChunk) -> TableSchema:
    return chunk.schema
