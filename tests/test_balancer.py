"""Tablet balancer: automatic reshard by size.

Ref model: server/tablet_balancer + partition sample keys
(tablet_node/partition.h) — split oversized tablets, merge tiny ones, at
quantile pivots over live keys.
"""

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.tablet.balancer import TabletBalancer

SCHEMA = TableSchema.make([
    ("k", "int64", "ascending"), ("v", "int64")], unique_keys=True)


@pytest.fixture
def client(tmp_path):
    return connect(str(tmp_path))


def make_table(client, path, n_rows, desired=100):
    client.create("table", path, recursive=True,
                  attributes={"schema": SCHEMA, "dynamic": True,
                              "desired_tablet_row_count": desired})
    client.mount_table(path)
    client.insert_rows(path, [{"k": i, "v": i} for i in range(n_rows)])


def test_split_oversized_tablet(client):
    make_table(client, "//t", 400, desired=100)
    balancer = TabletBalancer(client)
    assert balancer.needs_balancing("//t")
    assert balancer.balance_table("//t") is True
    counts = balancer.tablet_row_counts("//t")
    assert len(counts) == 4
    assert all(50 <= c <= 200 for c in counts)
    # Data intact across the reshard.
    assert client.select_rows("k FROM [//t] WHERE k = 399") == [{"k": 399}]
    assert sum(counts) == 400
    # Balanced now: no further reshard.
    assert balancer.balance_table("//t") is False


def test_merge_tiny_tablets(client):
    make_table(client, "//t", 40, desired=100)
    client.unmount_table("//t")
    client.reshard_table("//t", [(10,), (20,), (30,)])
    client.mount_table("//t")
    balancer = TabletBalancer(client)
    assert balancer.needs_balancing("//t")
    assert balancer.balance_table("//t") is True
    assert len(balancer.tablet_row_counts("//t")) == 1
    assert client.lookup_rows("//t", [(35,)]) == [{"k": 35, "v": 35}]


def test_step_respects_opt_out(client):
    make_table(client, "//busy", 400, desired=100)
    make_table(client, "//frozen", 400, desired=100)
    client.set("//frozen/@enable_tablet_balancer", False)
    balancer = TabletBalancer(client)
    out = balancer.step()
    assert out["//busy"] is True
    assert "//frozen" not in out
    assert len(balancer.tablet_row_counts("//frozen")) == 1
