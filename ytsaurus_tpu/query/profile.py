"""Per-query execution profiles + the bounded flight recorder (ISSUE 5).

Ref shape: the reference folds per-subquery TQueryStatistics up the
coordinator tree and exposes them with the query response
(client/query_client/query_statistics.h); slow queries additionally land
in a structured query log.  Here the finished trace spans of one query
fold into an `ExecutionProfile` — the EXPLAIN ANALYZE answer: wall /
compile / execute split (the first question any profile of a compiled
engine must answer — "An Empirical Analysis of Just-in-Time Compilation
in Modern Databases", PAPERS.md), rows scanned vs returned, cache and
retry counters, and the span tree — returned on the opt-in
`explain_analyze=` flag of `select_rows` and retained in the
FlightRecorder's bounded slow-query log (threshold + sampling from
config.TracingConfig).
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Optional

from ytsaurus_tpu.utils import tracing


class ExecutionProfile:
    """One query's structured profile (EXPLAIN ANALYZE payload)."""

    __slots__ = ("query", "trace_id", "pool", "user", "started_at",
                 "wall_time", "admission_wait", "compile_time",
                 "execute_time", "statistics", "rows")

    def __init__(self, query: str, trace_id: Optional[str], pool: str,
                 started_at: float, wall_time: float,
                 admission_wait: float, compile_time: float,
                 execute_time: float, statistics: dict,
                 rows: Optional[list] = None,
                 user: Optional[str] = None):
        self.query = query
        self.trace_id = trace_id
        self.pool = pool
        self.user = user or "root"
        self.started_at = started_at
        self.wall_time = wall_time
        self.admission_wait = admission_wait
        self.compile_time = compile_time
        self.execute_time = execute_time
        self.statistics = statistics
        self.rows = rows

    @classmethod
    def capture(cls, root_span, query: str, stats, wall_time: float,
                pool: Optional[str] = None,
                user: Optional[str] = None) -> "ExecutionProfile":
        """Fold one finished query into a profile.  `root_span` may be
        the NULL span (unsampled query): the profile still carries the
        wall time + statistics, just no trace id / span tree.  Admission
        wait rides as a tag on the root span (stamped by the gateway at
        the admit site) — reading it here costs a dict probe, not a scan
        of the span ring.  `user` defaults to the ambient authenticated
        principal, so per-tenant accounting attributes the query even on
        proxy paths that never pass identity explicitly."""
        stats_dict = stats.to_dict() if stats is not None else {}
        admission_wait = float(
            getattr(root_span, "tags", {}).get("admission_wait_s", 0.0))
        trace_id = getattr(root_span, "trace_id", None)
        if user is None:
            from ytsaurus_tpu.cypress.security import current_user
            user = current_user()
        return cls(query=query[:500], trace_id=trace_id,
                   pool=pool or "default", user=user,
                   started_at=time.time(),
                   wall_time=wall_time, admission_wait=admission_wait,
                   compile_time=float(stats_dict.get("compile_time", 0.0)),
                   execute_time=float(stats_dict.get("execute_time", 0.0)),
                   statistics=stats_dict)

    def span_tree(self) -> list[dict]:
        if self.trace_id is None:
            return []
        return tracing.span_tree(self.trace_id)

    def without_rows(self) -> "ExecutionProfile":
        """Shallow copy with the result rows dropped — what the flight
        recorder retains (profiles are bounded; result sets are not)."""
        if self.rows is None:
            return self
        clone = ExecutionProfile.__new__(ExecutionProfile)
        for slot in self.__slots__:
            setattr(clone, slot, getattr(self, slot))
        clone.rows = None
        return clone

    def to_dict(self, include_rows: bool = True) -> dict:
        out = {k: getattr(self, k) for k in self.__slots__ if k != "rows"}
        out["span_tree"] = self.span_tree()
        if include_rows and self.rows is not None:
            out["rows"] = self.rows
        return out

    def format(self) -> str:
        """Pretty text rendering (the CLI's EXPLAIN ANALYZE output)."""
        return format_profile_dict(self.to_dict(include_rows=False))


def _ms(seconds: float) -> str:
    return f"{seconds * 1e3:.2f}ms"


def format_profile_dict(p: dict) -> str:
    """THE EXPLAIN ANALYZE renderer, over the profile's dict form — one
    implementation for the in-process client (via ExecutionProfile.
    format) and the remote/HTTP CLI path (which only has the dict)."""
    stats = p.get("statistics") or {}
    lines = [
        f"query: {p.get('query')}",
        f"trace_id: {p.get('trace_id') or '<unsampled>'}  "
        f"pool: {p.get('pool')}  user: {p.get('user', 'root')}",
        f"wall {_ms(p.get('wall_time', 0.0))}  "
        f"(admission {_ms(p.get('admission_wait', 0.0))}, "
        f"compile {_ms(p.get('compile_time', 0.0))}, "
        f"execute {_ms(p.get('execute_time', 0.0))})",
        f"rows read {stats.get('rows_read', 0)} -> returned "
        f"{stats.get('rows_written', 0)}; shards "
        f"{stats.get('shards_total', 0)} "
        f"(pruned {stats.get('shards_pruned', 0)}, skipped "
        f"{stats.get('shards_skipped', 0)}); compile cache "
        f"{stats.get('cache_hits', 0)} hits / "
        f"{stats.get('compile_count', 0)} misses",
        # ISSUE 18: which execution tier served the query — the first
        # question a cold-shape latency investigation asks.
        f"execution tier: {stats.get('execution_tier', 'compiled')}",
        # ISSUE 19: whether string predicates ran on dict codes or fell
        # back to the decoded remap-table path.
        f"execution: {stats.get('execution_encoding', 'encoded')}",
    ]
    # ISSUE 8: why those misses happened (new fingerprint vs new shape
    # vs eviction) and which pow2 capacity buckets the programs ran
    # against — per-query bucket churn is a shape-spectrum leak.
    causes = [(label, stats.get(key, 0)) for label, key in
              (("new_fingerprint", "compile_new_fingerprint"),
               ("new_shape", "compile_new_shape"),
               ("evicted", "compile_evicted"),
               ("disk_hit", "compile_disk_hit"))]
    buckets = stats.get("capacity_buckets") or []
    if any(n for _label, n in causes) or buckets:
        cause_str = ", ".join(f"{label} {n}" for label, n in causes
                              if n) or "none"
        lines.append(f"compile misses: {cause_str}; capacity buckets "
                     f"{[int(b) for b in buckets]}")
    # ISSUE 12: which distributed lowering served the query — the fused
    # whole-plan program (one host sync) or the stitched ladder.
    if stats.get("whole_plan"):
        lines.append(
            f"distributed: whole-plan fused SPMD (overflow retries "
            f"{stats.get('whole_plan_retries', 0)})")
    # ISSUE 14: the cost-based join plan — execution order, per-side
    # broadcast/partition choice, estimated vs actual cardinality per
    # stage.  A bad plan (estimate orders of magnitude off the actual)
    # is diagnosable from the slow log without re-running the query.
    join_stages = [e for e in (stats.get("join_plan") or []) if e]
    if join_stages:
        from ytsaurus_tpu.query.planner import est_drift
        lines.append("join plan:")
        for i, entry in enumerate(join_stages):
            drift = est_drift(entry.get("est_rows", 0),
                              entry.get("actual_rows", 0))
            lines.append(
                f"  {i + 1}. {entry.get('table')} "
                f"[{entry.get('strategy')}] est rows "
                f"{entry.get('est_rows', 0)} -> actual "
                f"{entry.get('actual_rows', 0)} (drift {drift})")
    # ISSUE 20: the mesh telemetry block(s) each SPMD program returned
    # stacked with its result — per-shard row spread (the skew answer),
    # exchange traffic with quota headroom, and the compile-time memory
    # watermark.  Zero extra host syncs bought all of this.
    mesh_blocks = [b for b in (stats.get("mesh_blocks") or []) if b]
    if mesh_blocks:
        lines.append("mesh telemetry:")
        for i, blk in enumerate(mesh_blocks):
            out_rows = sorted(int(r) for r in blk.get("out_rows") or ())
            if out_rows:
                spread = (f"rows/shard min {out_rows[0]} / median "
                          f"{out_rows[len(out_rows) // 2]} / max "
                          f"{out_rows[-1]}")
            else:
                spread = "rows/shard n/a"
            lines.append(
                f"  {i + 1}. {blk.get('path', 'fused')} shards "
                f"{blk.get('shards', 0)}  {spread}  skew "
                f"{blk.get('skew', 1.0)}")
            for ex in blk.get("exchanges") or ():
                lines.append(
                    f"     exchange {ex.get('stage')}: "
                    f"{ex.get('rows', 0)} rows / {ex.get('bytes', 0)} "
                    f"bytes; quota {ex.get('quota', 0)} granted / "
                    f"{ex.get('demand', 0)} demanded (headroom "
                    f"{ex.get('headroom', 0.0)})")
            watermark = blk.get("memory_watermark_bytes")
            if watermark:
                lines.append(
                    f"     memory watermark {int(watermark)} bytes")
    tree = p.get("span_tree") or []
    if tree:
        lines.append("spans:")
        lines.extend(format_span_tree(tree))
    return "\n".join(lines)


def format_span_tree(nodes: list[dict], indent: int = 0) -> list[str]:
    """Indented one-line-per-span rendering of a span_tree() forest."""
    lines = []
    for node in nodes:
        tags = {k: v for k, v in (node.get("tags") or {}).items()}
        tag_str = "  " + " ".join(f"{k}={v}" for k, v in
                                  sorted(tags.items())) if tags else ""
        lines.append(f"{'  ' * indent}- {node['name']} "
                     f"{_ms(node.get('duration', 0.0))}{tag_str}")
        lines.extend(format_span_tree(node.get("children") or [],
                                      indent + 1))
    return lines


class FlightRecorder:
    """Bounded per-process retention of finished query profiles.

    Queries at/above TracingConfig.slow_query_threshold ALWAYS land in
    the slow log; the rest are sampled at `sample_rate` into the recent
    log.  Both logs are bounded deques — memory stays constant no matter
    the query rate."""

    def __init__(self):
        self._lock = threading.Lock()
        self._slow: "deque[ExecutionProfile]" = deque(maxlen=128)
        self._recent: "deque[ExecutionProfile]" = deque(maxlen=128)
        # Background-promotion events (ISSUE 18): a hot interpreted
        # fingerprint's compiled program swapped in mid-traffic.
        # Bounded like the logs; served next to the slow queries so
        # "why did this shape's latency step down" is answerable from
        # the recorder alone.
        self._promotions: "deque[dict]" = deque(maxlen=256)

    def note_promotion(self, fingerprint: str, compile_seconds: float,
                       runs_interpreted: int = 0,
                       capacity: int = 0) -> None:
        event = {"fingerprint": fingerprint,
                 "compile_seconds": round(compile_seconds, 6),
                 "runs_interpreted": int(runs_interpreted),
                 "capacity": int(capacity),
                 "promoted_at": time.time()}
        with self._lock:
            self._promotions.append(event)

    def promotions(self) -> list[dict]:
        with self._lock:
            return list(self._promotions)

    def _apply_config(self, cfg) -> None:
        if self._slow.maxlen != cfg.slow_log_capacity:
            with self._lock:
                self._slow = deque(self._slow,
                                   maxlen=cfg.slow_log_capacity)
        if self._recent.maxlen != cfg.recent_log_capacity:
            with self._lock:
                self._recent = deque(self._recent,
                                     maxlen=cfg.recent_log_capacity)

    def observe(self, profile: ExecutionProfile) -> None:
        from ytsaurus_tpu.config import tracing_config
        cfg = tracing_config()
        if not cfg.enabled:
            return
        self._apply_config(cfg)
        # Never retain result rows: the logs bound PROFILES, a pinned
        # explain_analyze result set would not be bounded by anything.
        profile = profile.without_rows()
        with self._lock:
            if profile.wall_time >= cfg.slow_query_threshold:
                self._slow.append(profile)
            elif cfg.sample_rate >= 1.0 or \
                    random.random() < cfg.sample_rate:
                self._recent.append(profile)

    def slow_queries(self) -> list[ExecutionProfile]:
        with self._lock:
            return list(self._slow)

    def recent(self) -> list[ExecutionProfile]:
        with self._lock:
            return list(self._recent)

    def clear(self) -> None:
        with self._lock:
            self._slow.clear()
            self._recent.clear()
            self._promotions.clear()

    def snapshot(self) -> dict:
        """Monitoring view (profiles without result rows)."""
        return {
            "slow_queries": [p.to_dict(include_rows=False)
                             for p in self.slow_queries()],
            "recent": [p.to_dict(include_rows=False)
                       for p in self.recent()],
            "promotions": self.promotions(),
        }


_recorder = FlightRecorder()


def get_flight_recorder() -> FlightRecorder:
    return _recorder
