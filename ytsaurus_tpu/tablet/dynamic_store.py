"""In-memory dynamic stores.

Ref: sorted_dynamic_store.h (MVCC edit lists) / ordered_dynamic_store.h.
SortedDynamicStore versions are per-column: a version records ONLY the
columns it wrote (update=True partial writes carry just those; overwrite
writes state every value column explicitly), and reads merge newest-per-
column above the latest delete — TVersionedRow semantics
(client/table_client/versioned_row.h:90, versioned_row_merger.h).
"""

from __future__ import annotations

import bisect
import threading
from typing import Any, Iterable, Optional, Sequence

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.tablet.timestamp import MAX_TIMESTAMP


class SortedDynamicStore:
    def __init__(self, schema: TableSchema):
        if not schema.is_sorted:
            raise YtError("Dynamic store requires a sorted schema")
        self.schema = schema
        self.key_names = schema.key_column_names
        self.value_names = [c.name for c in schema
                            if c.sort_order is None]
        self._rows: dict[tuple, list[tuple[int, Optional[dict]]]] = {}
        self._sorted_keys: list[tuple] = []
        self._lock = threading.Lock()
        self.store_row_count = 0          # versions stored
        self.min_timestamp = MAX_TIMESTAMP
        self.max_timestamp = 0
        # (store_row_count, chunk): versioned planes ingested once per
        # mutation generation for the vectorized read path.
        self._versioned_chunk_cache: "Optional[tuple[int, object]]" = None

    # -- write path ------------------------------------------------------------

    def key_of(self, row: dict) -> tuple:
        try:
            return tuple(row[name] for name in self.key_names)
        except KeyError as e:
            raise YtError(f"Row is missing key column {e.args[0]!r}",
                          code=EErrorCode.QueryTypeError)

    def write_row(self, row: dict, timestamp: int,
                  update: bool = False) -> None:
        """update=False (default): the write STATES every value column
        (missing ones become explicit nulls — the reference's overwrite
        mode).  update=True: only the provided columns are written; the
        rest merge from older versions per column (TVersionedRow partial
        writes, client/table_client/versioned_row.h:90 +
        versioned_row_merger.h)."""
        key = self.key_of(row)
        if update:
            values = {name: row[name] for name in self.value_names
                      if name in row}
        else:
            values = {name: row.get(name) for name in self.value_names}
        self._append(key, timestamp, values)

    def delete_row(self, key_row: dict | tuple, timestamp: int) -> None:
        key = key_row if isinstance(key_row, tuple) else self.key_of(key_row)
        self._append(key, timestamp, None)

    def _append(self, key: tuple, timestamp: int,
                values: Optional[dict]) -> None:
        with self._lock:
            versions = self._rows.get(key)
            if versions is None:
                versions = []
                self._rows[key] = versions
                bisect.insort(self._sorted_keys, _null_safe(key))
            versions.append((timestamp, values))
            self.store_row_count += 1
            self.min_timestamp = min(self.min_timestamp, timestamp)
            self.max_timestamp = max(self.max_timestamp, timestamp)

    # -- read path -------------------------------------------------------------

    def last_committed_timestamp(self, key: tuple) -> Optional[int]:
        versions = self._rows.get(key)
        if not versions:
            return None
        return max(ts for ts, _ in versions)

    def lookup_versions(self, key: tuple) -> list[tuple[int, Optional[dict]]]:
        """All versions for a key, newest first."""
        versions = self._rows.get(key, [])
        return sorted(versions, key=lambda v: -v[0])

    def iter_items(self) -> Iterable[tuple[tuple, list]]:
        """(key, versions) in key order (nulls first)."""
        with self._lock:
            keys = list(self._sorted_keys)
        for sk in keys:
            key = _null_unsafe(sk)
            # analyze: allow(guard-read): intentional lock-free read — the key list was snapshotted under the lock, version lists are append-only, and MVCC timestamp filtering tolerates a torn tail
            yield key, self._rows[key]

    @property
    def key_count(self) -> int:
        return len(self._rows)

    def to_versioned_chunk(self, versioned_schema):
        """This store's versions as device planes (versioned-schema
        ColumnarChunk, key-ordered, newest-first per key) — the
        ingestion step of the vectorized MVCC read path.  Memoized per
        mutation generation (store_row_count): repeated snapshots of an
        unchanged store never re-walk its Python rows."""
        with self._lock:
            count = self.store_row_count
        cached = self._versioned_chunk_cache
        if cached is not None and cached[0] == count:
            return cached[1]
        from ytsaurus_tpu.chunks.columnar import ColumnarChunk
        chunk = ColumnarChunk.from_rows(versioned_schema,
                                        self.versioned_rows())
        self._versioned_chunk_cache = (count, chunk)
        return chunk

    def versioned_rows(self) -> list[dict]:
        """Flatten to versioned row dicts (newest first per key) for
        flushing: key columns + $timestamp + $tombstone + value columns +
        per-column $w: written flags (partial writes carry False for
        columns the version does not state)."""
        out = []
        for key, versions in self.iter_items():
            for ts, state in sorted(versions, key=lambda v: -v[0]):
                row = {name: value for name, value in zip(self.key_names, key)}
                row["$timestamp"] = ts
                row["$tombstone"] = state is None
                for name in self.value_names:
                    written = state is not None and name in state
                    row[name] = state.get(name) if written else None
                    row[f"$w:{name}"] = written
                out.append(row)
        return out


def _null_safe(key: tuple) -> tuple:
    """Make keys with None sortable (null < everything, ref comparator)."""
    return tuple((v is not None, v if v is not None else 0) for v in key)


def _null_unsafe(sk: tuple) -> tuple:
    return tuple(v if present else None for present, v in sk)


class OrderedDynamicStore:
    """Append-only store backing ordered (queue) tables.

    Ref: tablet_node/ordered_dynamic_store.h."""

    def __init__(self, schema: TableSchema):
        self.schema = schema
        self._rows: list[tuple[int, dict]] = []
        self._lock = threading.Lock()

    def append_row(self, row: dict, timestamp: int) -> int:
        with self._lock:
            self._rows.append((timestamp, dict(row)))
            return len(self._rows) - 1

    def read(self, start_index: int = 0,
             limit: Optional[int] = None) -> list[dict]:
        with self._lock:
            end = len(self._rows) if limit is None else start_index + limit
            return [dict(row) | {"$row_index": i, "$timestamp": ts}
                    for i, (ts, row) in enumerate(self._rows[start_index:end],
                                                  start=start_index)]

    @property
    def row_count(self) -> int:
        with self._lock:
            return len(self._rows)
