"""Seeded chaos soak (ISSUE 2): select / sort / map_reduce workloads run
under deterministic fault schedules and must produce results BIT-IDENTICAL
to their fault-free runs — faults are a tested code path, not a hoped-for
one.  The final test asserts that, across the soak, every registered
failpoint site actually fired at least once (dead sites prove nothing).
"""

import os

import pytest

from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.chunks.replicated import ReplicatedChunkStore
from ytsaurus_tpu.client import YtClient, YtCluster
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.utils import failpoints

SEEDS = (11, 22, 33)

# Sites proven fired across this module; the coverage test at the bottom
# checks it against the full registry.
_FIRED: dict = {}


def _note_fired():
    for name, c in failpoints.counters().items():
        if c["triggers"] > 0:
            _FIRED[name] = _FIRED.get(name, 0) + c["triggers"]


def _chaos_client(root) -> YtClient:
    """A local cluster over a replicated chunk store (RF=2 across three
    locations): injected disk faults then exercise the replica ladder
    the way a real multi-location node would."""
    cluster = YtCluster(str(root), chunk_store=ReplicatedChunkStore(
        [os.path.join(str(root), f"loc{i}") for i in range(3)],
        replication_factor=2, blacklist_ttl=0.2))
    return YtClient(cluster)


def _rows(n, k0=0):
    return [{"k": k0 + i, "g": i % 7, "v": float(i % 50)} for i in range(n)]


# --- select -------------------------------------------------------------------


def test_select_soak(tmp_path):
    client = _chaos_client(tmp_path / "select")
    schema = TableSchema.make([("k", "int64"), ("g", "int64"),
                               ("v", "double")])
    chunks = [ColumnarChunk.from_rows(schema, [tuple(r.values())
                                               for r in _rows(200, k0=i * 200)])
              for i in range(4)]
    client._write_table_chunks("//soak/t", chunks, schema=schema)
    queries = (
        "g, sum(v) AS s, count(*) AS c FROM [//soak/t] GROUP BY g",
        # LIMIT plans stage shards lazily → the shard_materialize site.
        "k, v FROM [//soak/t] WHERE v > 10.0 LIMIT 50",
    )
    baseline = [client.select_rows(q) for q in queries]
    chunk_ids = client.get("//soak/t/@chunk_ids")
    spec = ("chunks.store.read=error:times=2;"
            "chunks.store.decode=error:times=1;"
            "query.shard_execute=error:times=2;"
            "query.shard_materialize=error:times=1")
    for seed in SEEDS:
        for cid in chunk_ids:
            client.cluster.chunk_cache.invalidate(cid)
        with failpoints.active(spec, seed=seed):
            got = [client.select_rows(q) for q in queries]
        assert got == baseline, f"select diverged under faults (seed {seed})"
    _note_fired()


# --- sort ---------------------------------------------------------------------


def test_sort_soak(tmp_path):
    client = _chaos_client(tmp_path / "sort")
    rows = [{"k": (i * 37) % 500, "v": float(i)} for i in range(500)]
    client.write_table("//soak/in", rows)
    client.scheduler.start_operation("sort", {
        "input_table_path": "//soak/in", "output_table_path": "//soak/out0",
        "sort_by": "k"})
    baseline = client.read_table("//soak/out0")
    # One rule per site per schedule (spec entries are keyed by site);
    # the write modes rotate across seeds instead.
    write_specs = ("chunks.store.write=error:times=1",
                   "chunks.store.write=torn-write:times=1",
                   "chunks.store.write=torn-write:times=2")
    for seed, wspec in zip(SEEDS, write_specs):
        spec = f"chunks.store.read=error:times=2;{wspec}"
        with failpoints.active(spec, seed=seed):
            client.scheduler.start_operation("sort", {
                "input_table_path": "//soak/in",
                "output_table_path": f"//soak/out{seed}",
                "sort_by": "k"})
        got = client.read_table(f"//soak/out{seed}")
        assert got == baseline, f"sort diverged under faults (seed {seed})"
    _note_fired()


# --- map_reduce ---------------------------------------------------------------


def test_map_reduce_soak(tmp_path):
    client = _chaos_client(tmp_path / "mr")
    rows = [{"k": i % 5, "v": i} for i in range(60)]
    client.write_table("//soak/in", rows)

    def run(out):
        client.scheduler.start_operation("map_reduce", {
            "map_command": "cat",
            "reduce_command": "cat",
            "input_table_path": "//soak/in", "output_table_path": out,
            "reduce_by": "k", "rows_per_job": 20, "partition_count": 3,
            "max_failed_job_count": 4, "format": "json"})
        return sorted(client.read_table(out),
                      key=lambda r: (r["k"], r["v"]))

    baseline = run("//soak/out0")
    schedules = (
        # Job start/finish faults: absorbed by the failure quarantine.
        "jobs.start=error:times=2;jobs.finish=error:times=1;"
        "scheduler.snapshot_record=delay:ms=1:times=2",
        # A slot thread dies mid-job: the orphan requeues, the slot
        # respawns, the operation still completes bit-identically.
        "jobs.worker_death=crash-once;jobs.start=delay:ms=1:times=2;"
        "scheduler.publish=delay:ms=1:times=1",
        # Disk faults under the job phases, including failed chunk
        # REMOVES (ISSUE 9 satellite): snapshot/intermediate GC hits
        # `chunks.store.remove`; removal is advisory, so a failed
        # unlink must leave results bit-identical (garbage files stay
        # behind for the next sweep, nothing else notices).
        "chunks.store.read=error:times=1;jobs.start=error:times=1;"
        "chunks.store.remove=error:times=2;"
        "scheduler.publish=delay:ms=1:times=1",
    )
    for seed, spec in zip(SEEDS, schedules):
        with failpoints.active(spec, seed=seed):
            got = run(f"//soak/out{seed}")
        assert got == baseline, \
            f"map_reduce diverged under faults (seed {seed})"
    _note_fired()


# --- rpc ----------------------------------------------------------------------


def test_rpc_soak():
    """Transport faults on both ends of a live RPC exchange: the
    RetryingChannel ladder must deliver identical results."""
    from ytsaurus_tpu.rpc.channel import Channel, RetryingChannel
    from ytsaurus_tpu.rpc.server import RpcServer, Service, rpc_method

    class Echo(Service):
        name = "echo"

        @rpc_method()
        def ping(self, body, attachments):
            return {"x": body.get("x", 0) * 2}, list(attachments)

    server = RpcServer([Echo()])
    server.start()
    try:
        channel = RetryingChannel(Channel(server.address, timeout=20))
        baseline = [channel.call("echo", "ping", {"x": i})[0]["x"]
                    for i in range(6)]
        channel.close()
        schedules = (
            "rpc.channel.send=error:times=2;"
            "rpc.server.recv=delay:ms=2:times=2",
            # Injected CONNECT refusal (ISSUE 9 satellite): raises
            # ConnectionError inside _connect, so the never-dispatched
            # (dispatched=False) resend path is the one that recovers.
            "rpc.channel.connect=error:times=1;"
            "rpc.server.recv=error:times=1",
            "rpc.channel.send=delay:ms=2:times=2;"
            "rpc.server.recv=error:times=1",
        )
        for seed, spec in zip(SEEDS, schedules):
            # Fresh channel per schedule: each run CONNECTS under the
            # active schedule (a pre-connected channel would never hit
            # the connect site).
            with failpoints.active(spec, seed=seed):
                channel = RetryingChannel(Channel(server.address,
                                                  timeout=20))
                got = [channel.call("echo", "ping", {"x": i})[0]["x"]
                       for i in range(6)]
                channel.close()
            assert got == baseline
    finally:
        server.stop()
    _note_fired()


# --- erasure ------------------------------------------------------------------


def test_erasure_soak(tmp_path):
    """Injected part loss + decode faults against an erasure-coded chunk
    behind the replicated ladder: reads reconstruct AND repair."""
    from ytsaurus_tpu.chunks.store import FsChunkStore

    store = ReplicatedChunkStore(
        [str(tmp_path / f"loc{i}") for i in range(2)],
        replication_factor=2, blacklist_ttl=0.1)
    schema = TableSchema.make([("k", "int64"), ("v", "double")])
    chunk = ColumnarChunk.from_rows(
        schema, [(i, float(i)) for i in range(300)])
    cid = store.write_chunk(chunk, erasure="rs_3_2")
    baseline = store.read_chunk(cid).to_rows()
    spec = ("chunks.erasure.part_read=error:times=1;"
            "chunks.erasure.decode=error:times=1")
    for seed in SEEDS:
        with failpoints.active(spec, seed=seed):
            assert store.read_chunk(cid).to_rows() == baseline
    _note_fired()


# --- SPMD degradation ladder --------------------------------------------------


@pytest.fixture(scope="module")
def _ladder_setup(request):
    mesh8 = request.getfixturevalue("mesh8")
    from ytsaurus_tpu.query.builder import build_query
    schema = TableSchema.make([("k", "int64"), ("g", "int64"),
                               ("v", "int64")])
    chunks = [ColumnarChunk.from_rows(
        schema, [(s * 64 + i, (s * 64 + i) % 5, i) for i in range(64)])
        for s in range(8)]
    plan = build_query("g, sum(v) AS s, count(*) AS c FROM [//t] GROUP BY g",
                       {"//t": schema})
    return mesh8, plan, chunks


def _canon(chunk):
    return sorted(chunk.to_rows(), key=lambda r: r["g"])


def test_distributed_ladder_soak(_ladder_setup):
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        coordinate_distributed,
    )
    mesh8, plan, chunks = _ladder_setup
    de = DistributedEvaluator(mesh8)
    baseline = _canon(coordinate_distributed(plan, mesh8, chunks,
                                             evaluator=de))
    # Rung 1 out: all_to_all fails once → gather-merge serves the query.
    with failpoints.active("parallel.all_to_all=error:times=1", seed=1):
        got = _canon(coordinate_distributed(plan, mesh8, chunks,
                                            evaluator=de))
    assert got == baseline
    # Rungs 1+2 out: the host coordinator (with its per-shard retry)
    # still answers, bit-identically.
    with failpoints.active("parallel.all_to_all=error:times=1;"
                           "parallel.gather=error:times=1", seed=2):
        got = _canon(coordinate_distributed(plan, mesh8, chunks,
                                            evaluator=de))
    assert got == baseline
    # Every rung dead → aggregate error, not a hang.
    from ytsaurus_tpu.errors import YtError
    with failpoints.active("parallel.all_to_all=error:times=4;"
                           "parallel.gather=error:times=4;"
                           "query.shard_execute=error:times=64", seed=3):
        with pytest.raises(YtError) as err:
            coordinate_distributed(plan, mesh8, chunks, evaluator=de)
    assert len(err.value.inner_errors) >= 2
    _note_fired()


# --- serving plane + artifact store (ISSUE 17) --------------------------------


def test_serving_and_artifact_sites_soak():
    """Overload-resilience failpoints: an injected routing-scrape
    failure degrades routing (never fails it), injected artifact-store
    faults are counted (never raised), and an injected failure INSIDE
    the brown-out decision falls back to full-fidelity execution."""
    import threading
    import time

    from ytsaurus_tpu.config import ServingConfig
    from ytsaurus_tpu.query.engine.aot_cache import ClusterArtifactStore
    from ytsaurus_tpu.query.routing import ReplicaRouter
    from ytsaurus_tpu.query.serving import QueryGateway

    # serving.route_scrape: the scrape fails, the replica degrades to
    # UNKNOWN (penalized in scoring) — no exception escapes.
    router = ReplicaRouter([("r0", "r0", "127.0.0.1:1")],
                           scrape_period=999.0)
    with failpoints.active("serving.route_scrape=error:p=1", seed=101):
        assert router.scrape_once() == 0
    assert router.scrape_errors_n >= 1
    assert router.replicas()[0].scrape_ok is False

    # aot.fetch / aot.publish: loud-but-safe — a fetch fault is one
    # more miss, a publish fault is one more error, the caller never
    # sees either.
    class _DeadBlobs:
        def put_blob(self, chunk_id, data):
            raise AssertionError("put_blob past an injected fault")

        def get_blob(self, chunk_id):
            raise AssertionError("get_blob past an injected fault")

    store = ClusterArtifactStore(_DeadBlobs())
    with failpoints.active("aot.fetch=error:p=1;aot.publish=error:p=1",
                           seed=102):
        assert store.fetch(("q", "fp")) is None
        assert store.publish(("q", "fp"), object(), "fp", 1.0) is False
    snap = store.snapshot()
    assert snap["misses"] >= 1 and snap["errors"] >= 1

    # serving.brownout: drive a gateway to rung 1 (a queued waiter is
    # all the pressure a 1e-9 threshold needs), then fail the
    # degradation decision itself — the admitted query must run at
    # full fidelity (rung 0 on its token), not die.
    gateway = QueryGateway(ServingConfig(
        slots=1, max_queue=8, brownout_rung1_seconds=1e-9,
        brownout_rung2_seconds=1e9, default_staleness_seconds=5.0))
    hold, entered = threading.Event(), threading.Event()

    def busy(token):
        entered.set()
        hold.wait(5.0)

    holder = threading.Thread(
        target=lambda: gateway.run_select(busy), daemon=True)
    holder.start()
    assert entered.wait(5.0)
    out = []
    with failpoints.active("serving.brownout=error:p=1", seed=103):
        waiter = threading.Thread(
            target=lambda: out.append(
                gateway.run_select(lambda token: ("ok", token.rung))),
            daemon=True)
        waiter.start()
        time.sleep(0.1)          # queued waiter -> pressure > rung 1
        hold.set()
        waiter.join(timeout=5)
        holder.join(timeout=5)
    assert out == [("ok", 0)]
    _note_fired()


# --- coverage -----------------------------------------------------------------


# The production site namespaces the coverage gate guards (scratch sites
# registered by unit tests — "t.*", "bench.*" — are out of scope).
_PRODUCT_PREFIXES = ("chunks.", "rpc.", "jobs.", "scheduler.", "query.",
                     "parallel.")

# Serving-plane + artifact-store sites (ISSUE 17) guarded by exact name:
# the wider "serving." namespace also holds sites owned by the
# test_serving soak, which runs after this module in the tier-1 order.
_EXACT_SITES = ("serving.route_scrape", "serving.brownout",
                "aot.fetch", "aot.publish")


def test_every_registered_site_fired():
    """The acceptance gate: failpoint counters prove every registered
    production site fired in at least one soak test above."""
    if not _FIRED:
        pytest.skip("soak tests did not run in this session")
    registered = {name for name in failpoints.registered_sites()
                  if name.startswith(_PRODUCT_PREFIXES) or
                  name in _EXACT_SITES}
    assert len(registered) >= 20, registered
    fired = {name for name, c in failpoints.counters().items()
             if c["triggers"] > 0} | set(_FIRED)
    silent = registered - fired
    assert not silent, f"failpoint sites never fired in the soak: {silent}"
