"""Hunks: out-of-row storage for large values.

Ref model: hunks (ytlib/table_client/hunks.h), hunk stores
(tablet_node/hunk_store.h), hunk chunk sweeper, TColumnSchema
max_inline_hunk_size.
"""

import pytest

from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.chunks.encoding import read_chunk_meta, serialize_chunk
from ytsaurus_tpu.chunks.hunks import HUNK_PREFIX, is_hunk_id
from ytsaurus_tpu.chunks.store import FsChunkStore
from ytsaurus_tpu.client import connect
from ytsaurus_tpu.schema import TableSchema

BIG = b"B" * 4096
BIG2 = b"C" * 8192

HUNK_SCHEMA = TableSchema.make([
    ("key", "int64", "ascending"),
    {"name": "v", "type": "string", "max_inline_hunk_size": 256},
], unique_keys=True)


def hunk_ids(store):
    return [cid for cid in store.list_chunks() if is_hunk_id(cid)]


def test_chunk_roundtrip_with_hunks(tmp_path):
    store = FsChunkStore(str(tmp_path))
    schema = TableSchema.make([
        ("k", "int64"),
        {"name": "v", "type": "string", "max_inline_hunk_size": 256}])
    rows = [{"k": 0, "v": b"small"}, {"k": 1, "v": BIG},
            {"k": 2, "v": BIG2}, {"k": 3, "v": None}]
    chunk = ColumnarChunk.from_rows(schema, rows)
    cid = store.write_chunk(chunk)
    # Payloads live out-of-row: the data chunk blob is small, two hunk
    # blobs exist, and the meta names them.
    assert len(store.get_blob(cid)) < 2048
    assert len(hunk_ids(store)) == 2
    meta = read_chunk_meta(store.get_blob(cid))
    assert sorted(meta["hunk_chunk_ids"]) == sorted(hunk_ids(store))
    assert store.read_chunk(cid).to_rows() == rows


def test_hunks_content_addressed_no_rewrite(tmp_path):
    store = FsChunkStore(str(tmp_path))
    schema = TableSchema.make([
        ("k", "int64"),
        {"name": "v", "type": "string", "max_inline_hunk_size": 256}])
    c1 = store.write_chunk(ColumnarChunk.from_rows(
        schema, [{"k": 1, "v": BIG}]))
    ids_before = hunk_ids(store)
    # A second chunk carrying the same big value reuses the same hunk blob.
    store.write_chunk(ColumnarChunk.from_rows(
        schema, [{"k": 2, "v": BIG}, {"k": 3, "v": b"tiny"}]))
    assert hunk_ids(store) == ids_before
    assert store.read_chunk(c1).to_rows() == [{"k": 1, "v": BIG}]


def test_serialize_without_store_keeps_inline(tmp_path):
    schema = TableSchema.make([
        ("k", "int64"),
        {"name": "v", "type": "string", "max_inline_hunk_size": 256}])
    blob = serialize_chunk(ColumnarChunk.from_rows(
        schema, [{"k": 1, "v": BIG}]))
    assert "hunk_chunk_ids" not in read_chunk_meta(blob)


def test_dynamic_table_hunks_end_to_end(tmp_path):
    client = connect(str(tmp_path))
    client.create("table", "//t", recursive=True,
                  attributes={"schema": HUNK_SCHEMA, "dynamic": True})
    client.mount_table("//t")
    client.insert_rows("//t", [{"key": 1, "v": b"small"},
                               {"key": 2, "v": BIG}])
    store = client.cluster.chunk_store
    tablet = client._mounted_tablets("//t")[0]
    tablet.flush()
    assert len(hunk_ids(store)) == 1
    # Reads resolve refs transparently.
    assert client.lookup_rows("//t", [(2,)]) == [{"key": 2, "v": BIG}]
    assert client.select_rows("key FROM [//t] WHERE v = 'small'") == \
        [{"key": 1}]
    # Compaction keeps the content-addressed hunk in place.
    ids_before = hunk_ids(store)
    client.insert_rows("//t", [{"key": 3, "v": BIG2}])
    tablet.flush()
    tablet.compact()
    assert set(ids_before) <= set(hunk_ids(store))
    assert client.lookup_rows("//t", [(2,), (3,)]) == [
        {"key": 2, "v": BIG}, {"key": 3, "v": BIG2}]
    # Survives unmount/remount (refs round-trip through the wire format).
    client.unmount_table("//t")
    client.mount_table("//t")
    assert client.lookup_rows("//t", [(3,)]) == [{"key": 3, "v": BIG2}]


def test_hunk_sweeper_gc(tmp_path):
    client = connect(str(tmp_path))
    client.create("table", "//t", recursive=True,
                  attributes={"schema": HUNK_SCHEMA, "dynamic": True})
    client.mount_table("//t")
    client.insert_rows("//t", [{"key": 1, "v": BIG}, {"key": 2, "v": BIG2}])
    tablet = client._mounted_tablets("//t")[0]
    tablet.flush()
    store = client.cluster.chunk_store
    assert len(hunk_ids(store)) == 2
    # Live hunks survive a GC pass.
    client.unmount_table("//t")
    client.collect_garbage()
    assert len(hunk_ids(store)) == 2
    client.mount_table("//t")
    # Dropping one big value orphans its hunk after compaction + GC.
    client.delete_rows("//t", [(2,)])
    tablet = client._mounted_tablets("//t")[0]
    tablet.flush()
    tablet.compact(retention_timestamp=2 ** 62)
    client.unmount_table("//t")
    removed = client.collect_garbage()
    assert removed >= 1
    remaining = hunk_ids(store)
    assert len(remaining) == 1
    client.mount_table("//t")
    assert client.lookup_rows("//t", [(1,)]) == [{"key": 1, "v": BIG}]
