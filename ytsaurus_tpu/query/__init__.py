"""QL query engine: front end (lexer/parser/builder), typed IR, XLA engine.

Re-architecture of the reference query library (yt/yt/library/query): the
LLVM-JIT backend behind EExecutionBackend (codegen_api/execution_backend.h)
becomes an XLA lowering over columnar planes.
"""

from ytsaurus_tpu.query.parser import parse_expression, parse_query
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.engine.evaluator import Evaluator, select_rows
