"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is unavailable in CI; all sharding/collective tests run on
a virtual 8-device CPU platform (xla_force_host_platform_device_count), per the
same strategy the reference uses for multi-node tests without a real cluster
(yt/python/yt/environment/yt_env.py local-mode clusters).

This must run before any JAX backend initializes.  The environment may have a
TPU plugin pre-registered by sitecustomize, so we switch platforms via
jax.config (which takes effect lazily at first backend use) rather than env.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
# Every test runs "sanitized": structural invariant checks at subsystem
# boundaries (utils/invariants.py — the debug-build assertion analog).
# Plain assignment, not setdefault: an inherited =0 from a profiling
# shell must not silently turn the sanitizer off for the whole suite.
os.environ["YT_TPU_INVARIANTS"] = "1"
# ... and "lock-sanitized" (ISSUE 15): utils/sanitizers.py wraps every
# registered hot lock, recording held-lock sets and acquisition-order
# edges live.  Must be set BEFORE any ytsaurus_tpu module constructs
# its locks (registration reads it once per lock creation);
# pytest_sessionfinish below reconciles the observed dynamic lock-order
# graph against the static analyzer's superset graph.
os.environ["YT_TPU_SANITIZE"] = "1"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: minutes-long compile-heavy suites excluded from the tier-1 "
        "quick pass (ROADMAP.md runs -m 'not slow')")
    # Buffer donation (ISSUE 19) stays armed in tests; CPU backends
    # ignore it with a per-call warning pytest's capture would surface.
    config.addinivalue_line(
        "filterwarnings",
        "ignore:Some donated buffers were not usable")


def pytest_sessionfinish(session, exitstatus):
    """The dynamic⊆static lock-order gate (ISSUE 15): every acquisition
    edge the runtime sanitizer observed across the WHOLE tier-1 run must
    exist in the static reconciliation graph — an edge the AST
    propagation cannot derive fails the build with the acquisition
    stacks attached (teach tools/analyze, or restructure the locking).
    Runs only when the suite actually exercised the sanitizer, and only
    on otherwise-green runs (a red run's report would bury the real
    failure)."""
    from ytsaurus_tpu.utils import sanitizers

    san = sanitizers.get_sanitizer()
    if san is None or exitstatus != 0 or not san.edge_snapshot():
        return
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.analyze import guard_inference, load_files

    graph = guard_inference.reconciliation_graph(load_files(repo))
    violations = sanitizers.reconcile(graph["edges"], graph["site_map"])
    report = san.counters()
    print(f"\n[sanitizer] {report['acquires']} instrumented acquires, "
          f"{report['edges_observed']} distinct lock-order edges, "
          f"{report['inversions']} inversions, "
          f"{report['sync_under_lock']} blocking-ops-under-lock, "
          f"{report['hold_violations']} hold-budget violations; "
          f"dynamic⊆static: "
          f"{'OK' if not violations else 'VIOLATED'}")
    if violations:
        for violation in violations:
            print(f"[sanitizer] {violation}")
        session.exitstatus = 1


@pytest.fixture(autouse=True)
def _failpoint_leak_guard():
    """Leak guard (ISSUE 2 satellite): a test that leaves a failpoint
    schedule active would inject faults into every later test — fail THAT
    test, loudly, and disarm before anything else runs."""
    yield
    from ytsaurus_tpu.utils import failpoints

    leaked = failpoints.active_spec()
    if leaked is not None:
        failpoints.deactivate()
        pytest.fail(f"test left failpoints active: {leaked!r}")


@pytest.fixture
def failpoints_active():
    """Scoped activation helper: `failpoints_active(spec, seed=7)` arms a
    schedule for the remainder of the test and guarantees disarm on
    teardown (even when the test body raises)."""
    from ytsaurus_tpu.utils import failpoints

    def arm(spec: str, seed: int = 0):
        failpoints.activate(spec, seed=seed)

    yield arm
    failpoints.deactivate()


@pytest.fixture(scope="session")
def mesh8():
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return Mesh(np.array(devices[:8]).reshape(8), ("shard",))
