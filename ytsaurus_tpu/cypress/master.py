"""Master: the metadata authority — WAL-then-apply mutations + snapshots.

Ref: Hydra's mutation pipeline (server/lib/hydra/hydra_manager.h
CommitMutation → decorated_automaton WAL-append-then-apply, snapshot build/
load in composite_automaton.h).  Single-replica stand-in with the same
durability contract: every mutation is appended (fsync'd) to the changelog
BEFORE applying to the in-memory tree; recovery = load last snapshot +
replay the changelog; snapshots truncate the log.

A real multi-peer deployment replicates the changelog via a quorum before
apply — the apply/recover machinery here is the automaton that would sit
under it.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

from ytsaurus_tpu import yson
from ytsaurus_tpu.cypress.tree import CypressTree
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils.diskio import fsync_dir as _fsync_dir
from ytsaurus_tpu.utils.varint import encode_varint_u, read_varint_u


class Changelog:
    """Length-prefixed YSON records, fsync'd on append (ref: file changelogs,
    server/lib/hydra/changelog.h)."""

    def __init__(self, path: str):
        self.path = path
        self._file = open(path, "ab")
        self._lock = threading.Lock()

    def append(self, record: dict) -> int:
        """Appends one record; returns the byte offset the record starts
        at (callers can truncate back to it to drop exactly this
        record)."""
        blob = yson.dumps(record, binary=True)
        with self._lock:
            offset = self._file.tell()
            self._file.write(encode_varint_u(len(blob)) + blob)
            self._file.flush()
            os.fsync(self._file.fileno())
            return offset

    def truncate_to(self, byte_len: int) -> None:
        with self._lock:
            self._file.truncate(byte_len)
            self._file.seek(byte_len)
            self._file.flush()
            os.fsync(self._file.fileno())

    def close(self) -> None:
        self._file.close()

    @staticmethod
    def read_all(path: str) -> tuple[list[dict], int]:
        """Returns (records, valid_byte_length).  A torn tail write stops the
        scan; the caller MUST truncate to valid_byte_length before appending,
        or post-recovery records land after garbage and vanish on the next
        recovery."""
        records = []
        try:
            with open(path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return [], 0
        pos = 0
        valid = 0
        while pos < len(data):
            try:
                length, pos = read_varint_u(data, pos)
                blob = data[pos:pos + length]
                if len(blob) != length:
                    break              # torn tail write → stop at last good
                records.append(yson.loads(blob))
                pos += length
                valid = pos
            except (ValueError, YtError):
                break
        return records, valid


class Master:
    """Applies named mutations through the WAL; exposes the Cypress tree."""

    SNAPSHOT = "snapshot.yson"
    CHANGELOG = "changelog.log"

    def __init__(self, root_dir: str, wal=None):
        from ytsaurus_tpu.cypress.quorum import LocalWal
        from ytsaurus_tpu.cypress.transactions import MasterTransactionManager
        self.root_dir = root_dir
        os.makedirs(root_dir, exist_ok=True)
        # Re-entrant BY CONTRACT: mutation_lock holders issue nested
        # commit_mutation calls (see the property below).
        self._lock = threading.RLock()
        self._poisoned = False
        self._mutation_listeners: list = []
        self._snapshot_seq = 0
        self.tree = CypressTree()
        self.tx_manager = MasterTransactionManager(self.tree)
        # wal: LocalWal (default) or QuorumWal over journal locations on
        # data nodes — recover() returns replayable records, append() is
        # the durability barrier, reset() truncates after snapshots.
        self.wal = wal if wal is not None else \
            LocalWal(os.path.join(root_dir, self.CHANGELOG))
        self._recover()

    # -- mutation pipeline -----------------------------------------------------

    _MUTATIONS = ("create", "remove", "set", "copy", "move", "link",
                  "tx_start", "tx_commit", "tx_abort", "lock", "batch")
    _TREE_MUTATIONS = ("create", "remove", "set", "copy", "move", "link")

    @property
    def mutation_lock(self):
        """Public handle on the mutation lock for callers that need an
        atomic read-modify-write spanning a read plus commit_mutation
        (e.g. the chaos coordinator's era bump, the replicator's
        liveness walk).  Guaranteed re-entrant: holders may issue nested
        commit_mutation calls."""
        return self._lock

    def commit_mutation(self, op: str, **args) -> Any:
        """Log, then apply (ref CommitMutation)."""
        if op not in self._MUTATIONS:
            raise YtError(f"Unknown mutation {op!r}")
        if op == "tx_start" and not args.get("tx_id"):
            # The id MUST be fixed before logging: replay regenerating a
            # fresh id would orphan every subsequent tx-scoped record.
            import uuid
            args["tx_id"] = uuid.uuid4().hex
        with self._lock:
            if self._poisoned:
                raise YtError(
                    "Master is read-only: a WAL append failed and the "
                    "in-memory state is ahead of the durable log; restart "
                    "the primary to recover",
                    code=EErrorCode.PeerUnavailable)
            # Validate BEFORE logging by applying to the live tree; Hydra
            # validates in the mutation handler too — a failed apply after a
            # logged record would poison recovery, so log only after the
            # apply succeeds, holding the lock (single-writer semantics).
            result = self._apply(op, args)
            try:
                self.wal.append({"op": op, "args": args})
            except YtError:
                # The tree now holds a mutation the log does not.  Under a
                # quorum WAL this is a routine network failure, and serving
                # further mutations would let later LOGGED records depend
                # on this unlogged one (divergence after replay).  Latch
                # read-only, like a Hydra leader restarting its automaton
                # on changelog failure.
                self._poisoned = True
                raise
            for listener in self._mutation_listeners:
                try:
                    listener(op, args, result)
                except Exception:   # noqa: BLE001 — observers never poison
                    pass
            return result

    def add_mutation_listener(self, listener) -> None:
        """Post-commit observer: listener(op, args, result) runs after a
        mutation is durably logged (Sequoia resolve-table maintenance,
        metrics).  Observers must not mutate the tree via
        commit_mutation from the callback (the lock is held)."""
        self._mutation_listeners.append(listener)

    def _apply(self, op: str, args: dict) -> Any:
        if op == "batch":
            # One WAL record applying several tree ops atomically — the
            # carrier for Hive message application (handler effects + the
            # last-applied bump must land together for exactly-once).
            # Name validation up front; RESOLUTION failures can still hit
            # any sub-op mid-batch (create over an existing node, remove
            # of a missing path), so each sub-op's undo is captured before
            # it applies and a failure rolls the earlier sub-ops back —
            # all-or-nothing, matching the single-WAL-record semantics
            # (the record is only logged if the whole apply succeeds).
            ops = args["ops"]
            for sub in ops:
                if sub["op"] not in ("create", "set", "remove"):
                    raise YtError(
                        f"batch sub-op {sub['op']!r} not allowed",
                        code=EErrorCode.Generic)
            undos: list = []
            results: list = []
            try:
                for sub in ops:
                    sub_args = dict(sub["args"])
                    undos.append(
                        self.tx_manager.capture_undo(sub["op"], sub_args))
                    results.append(self._apply(sub["op"], sub_args))
            except BaseException:
                # Any failure — resolution YtError or a malformed sub-op
                # raising KeyError — must roll earlier sub-ops back, or
                # the tree diverges from the (never-written) WAL record.
                try:
                    for undo in reversed(undos):
                        self.tx_manager.apply_undo(undo)
                except Exception:
                    # Rollback itself failed: the tree diverged from the
                    # log with no record to cover it — latch read-only.
                    self._poisoned = True
                raise
            return results
        # Transaction lifecycle + lock mutations (ref: transaction_server
        # master transactions riding the same Hydra mutation pipeline).
        if op == "tx_start":
            return self.tx_manager.start(args.get("tx_id"),
                                         args.get("parent_id"))
        if op == "tx_commit":
            return self.tx_manager.commit(args["tx_id"])
        if op == "tx_abort":
            return self.tx_manager.abort(args["tx_id"])
        if op == "lock":
            return self.tx_manager.lock(args["tx_id"], args["path"],
                                        args.get("mode", "exclusive"))
        # Tree mutations: lock-conflict check + undo capture first (the
        # undo must observe the pre-mutation state); the undo is recorded
        # only after the tree op succeeds.
        tx_id = args.get("tx")
        undo = self.tx_manager.before_mutation(tx_id, op,
                                               {k: v for k, v in args.items()
                                                if k != "tx"})
        result = self._apply_tree_op(op, args)
        self.tx_manager.after_mutation(tx_id, undo)
        return result

    def _apply_tree_op(self, op: str, args: dict) -> Any:
        if op == "create":
            return self.tree.create(
                args["path"], args["type"],
                attributes=args.get("attributes"),
                recursive=args.get("recursive", False),
                ignore_existing=args.get("ignore_existing", False))
        if op == "remove":
            return self.tree.remove(args["path"],
                                    recursive=args.get("recursive", True),
                                    force=args.get("force", False))
        if op == "set":
            return self.tree.set(args["path"], args.get("value"))
        if op == "copy":
            return self.tree.copy(args["src"], args["dst"],
                                  recursive=args.get("recursive", False))
        if op == "move":
            return self.tree.move(args["src"], args["dst"],
                                  recursive=args.get("recursive", False))
        if op == "link":
            return self.tree.link(args["target"], args["link"],
                                  recursive=args.get("recursive", False))
        raise AssertionError(op)

    # -- snapshots / recovery --------------------------------------------------

    def build_snapshot(self) -> None:
        """Serialize the tree, replicate the snapshot, truncate the
        changelog (ref snapshot build).  Remote replication happens FIRST:
        truncating quorum journals with a local-only snapshot would
        collapse metadata durability back to one disk."""
        with self._lock:
            seq = self._snapshot_seq + 1
            blob = yson.dumps({"seq": seq, "tree": self.tree.serialize(),
                               "transactions": self.tx_manager.serialize()},
                              binary=True)
            self.wal.store_snapshot(seq, blob)
            snap_path = os.path.join(self.root_dir, self.SNAPSHOT)
            tmp = snap_path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, snap_path)
            _fsync_dir(self.root_dir)      # make the rename durable first
            self._snapshot_seq = seq
            self.wal.reset()
            _fsync_dir(self.root_dir)

    @staticmethod
    def _load_snapshot_blob(blob: bytes) -> tuple[int, dict, dict]:
        data = yson.loads(blob)
        if isinstance(data, dict) and "seq" in data and "tree" in data:
            return (int(data["seq"]), data["tree"],
                    data.get("transactions") or {})
        return 0, data, {}          # pre-versioning format

    def _recover(self) -> None:
        # Recovery mutates tree/tx state through the same _apply path
        # as live mutations; holding the (re-entrant) mutation lock
        # keeps the single-writer discipline uniform — construction is
        # single-threaded, so this is contention-free, and a subclass
        # or restart path re-running recovery stays safe.
        with self._lock:
            self._recover_locked()

    def _recover_locked(self) -> None:
        local: "tuple[int, dict] | None" = None
        snap_path = os.path.join(self.root_dir, self.SNAPSHOT)
        if os.path.exists(snap_path):
            with open(snap_path, "rb") as f:
                local = self._load_snapshot_blob(f.read())
        remote = self.wal.fetch_snapshot()
        if remote is not None:
            remote = self._load_snapshot_blob(remote[1])
        # Newest snapshot wins: a lost local disk recovers from the
        # replicated copy, and journal tails always belong to the newest
        # snapshot epoch (reset happens after replication).
        best = max((s for s in (local, remote) if s is not None),
                   key=lambda s: s[0], default=None)
        if best is not None:
            from ytsaurus_tpu.cypress.transactions import (
                MasterTransactionManager,
            )
            self._snapshot_seq = best[0]
            self.tree = CypressTree.deserialize(best[1])
            self.tx_manager = MasterTransactionManager.deserialize(
                self.tree, best[2])
        for record in self.wal.recover():
            try:
                self._apply(record["op"], dict(record["args"]))
            except YtError:
                # Mutations are validated before logging; a failing replay
                # record means it raced a snapshot — skip.
                continue
