"""Query tracker: persistent queries with async execution.

Ref mapping (server/query_tracker):
  start_query / get_query / list_queries /   → same verbs on QueryTracker
  abort_query / read_query_result              (and driver commands)
  query state machine (pending → running →   → "state" on the query record
  completed | failed | aborted)
  queries stored in dynamic tables           → query records are cypress
  (//sys/query_tracker)                        documents under //sys/queries
  engine field (ql/yql/chyt/spyt)            → "ql" (native) + any engine
                                               registered via
                                               register_engine (the CHYT/
                                               YQL plug point)

Design delta: execution runs on a worker thread against the in-process
cluster; results persist on the query record (row sets are bounded by
result_row_limit with a truncated flag, matching the reference's result
row caps).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Optional

from ytsaurus_tpu.cypress.security import (
    ROOT_USER,
    SUPERUSERS,
    authenticated_user,
    current_user,
)
from ytsaurus_tpu.errors import EErrorCode, YtError

QUERIES_ROOT = "//sys/queries"

# engine name → fn(client, query_text) -> list[dict]
_ENGINES: dict[str, Callable] = {}


def register_engine(name: str, execute: Callable) -> None:
    """Plug in a query engine (the CHYT/YQL ecosystem hook)."""
    _ENGINES[name] = execute


def _ql_engine(client, query: str) -> list[dict]:
    return client.select_rows(query)


register_engine("ql", _ql_engine)


class QueryTracker:
    def __init__(self, client, result_row_limit: int = 10_000):
        self.client = client
        self.result_row_limit = result_row_limit
        self._threads: dict[str, threading.Thread] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------------ verbs

    def start_query(self, query: str, engine: str = "ql",
                    annotations: Optional[dict] = None,
                    sync: bool = False) -> str:
        if engine not in _ENGINES:
            # Ecosystem engines register on import; load them on first
            # use so `engine="chyt"` works without explicit wiring.
            import ytsaurus_tpu.ecosystem.sql  # noqa: F401
        if engine not in _ENGINES:
            raise YtError(f"Unknown query engine {engine!r}; "
                          f"available: {sorted(_ENGINES)}",
                          code=EErrorCode.QueryUnsupported)
        query_id = uuid.uuid4().hex[:16]
        user = current_user()
        path = f"{QUERIES_ROOT}/{query_id}"
        # Records are SYSTEM state (//sys/queries is tracker-owned); only
        # the query itself executes under the caller's principal.
        with authenticated_user(ROOT_USER):
            self.client.create("document", path, recursive=True)
            self.client.set(path, {
                "id": query_id, "engine": engine, "query": query,
                "state": "pending", "annotations": annotations or {},
                "user": user,
                "start_time": time.time(), "finish_time": None,
                "error": None, "result": None, "truncated": False,
            })
        if sync:
            self._execute(query_id)
        else:
            thread = threading.Thread(
                target=self._execute, args=(query_id,), daemon=True)
            with self._lock:
                self._threads[query_id] = thread
            thread.start()
        return query_id

    def _check_access(self, record: dict) -> None:
        """Query records are private to their user (superusers see all) —
        results are served from the record, so the ACL enforced at
        execution time must also gate record reads."""
        user = current_user()
        if record.get("user") in (None, user) or user == ROOT_USER:
            return
        try:
            groups = self.client.cluster.security.groups_of(user)
        except YtError:
            groups = set()
        if SUPERUSERS not in groups:
            raise YtError(
                f"User {user!r} cannot access query {record['id']} "
                f"of user {record['user']!r}",
                code=EErrorCode.AuthorizationError)

    def get_query(self, query_id: str) -> dict:
        record = dict(self.client.get(self._path(query_id)))
        self._check_access(record)
        record.pop("result", None)      # results via read_query_result
        return record

    def list_queries(self, state: Optional[str] = None,
                     engine: Optional[str] = None) -> list[dict]:
        if not self.client.exists(QUERIES_ROOT):
            return []
        out = []
        for qid in self.client.list(QUERIES_ROOT):
            try:
                rec = self.get_query(qid)
            except YtError:
                continue                 # not this user's query
            if state is not None and rec["state"] != state:
                continue
            if engine is not None and rec["engine"] != engine:
                continue
            out.append(rec)
        return sorted(out, key=lambda r: r["start_time"])

    def read_query_result(self, query_id: str) -> list[dict]:
        record = self.client.get(self._path(query_id))
        self._check_access(record)
        if record["state"] != "completed":
            raise YtError(
                f"Query {query_id} is {record['state']}, not completed",
                code=EErrorCode.OperationFailed,
                attributes={"error": record.get("error")})
        return list(record["result"] or [])

    def abort_query(self, query_id: str) -> None:
        path = self._path(query_id)
        record = dict(self.client.get(path))
        self._check_access(record)
        if record["state"] in ("completed", "failed", "aborted"):
            raise YtError(f"Query {query_id} is already {record['state']}",
                          code=EErrorCode.OperationFailed)
        record["state"] = "aborted"
        record["finish_time"] = time.time()
        with authenticated_user(ROOT_USER):
            self.client.set(path, record)

    def wait(self, query_id: str, timeout: float = 60.0) -> dict:
        """Join the worker thread (test/ops helper), then return the record."""
        with self._lock:
            thread = self._threads.get(query_id)
        if thread is not None:
            thread.join(timeout)
        return self.get_query(query_id)

    # --------------------------------------------------------------- execution

    def _path(self, query_id: str) -> str:
        path = f"{QUERIES_ROOT}/{query_id}"
        if not self.client.exists(path):
            raise YtError(f"No such query {query_id!r}",
                          code=EErrorCode.ResolveError)
        return path

    def _execute(self, query_id: str) -> None:
        path = f"{QUERIES_ROOT}/{query_id}"
        with authenticated_user(ROOT_USER):
            record = dict(self.client.get(path))
            if record["state"] != "pending":    # aborted before it ran
                return
            record["state"] = "running"
            self.client.set(path, record)
        try:
            # The engine runs AS THE QUERY'S USER — worker threads reset
            # the contextvar to root, which must not leak into execution.
            with authenticated_user(record.get("user") or ROOT_USER):
                rows = _ENGINES[record["engine"]](
                    self.client, record["query"])
            truncated = len(rows) > self.result_row_limit
            record.update(
                state="completed", finish_time=time.time(),
                result=rows[:self.result_row_limit], truncated=truncated)
        except Exception as err:        # failures persist on the record
            record.update(state="failed", finish_time=time.time(),
                          error=str(err))
        with authenticated_user(ROOT_USER):
            current = dict(self.client.get(path))
            if current["state"] == "aborted":   # lost the race to abort
                return
            self.client.set(path, record)
