"""`yt analyze` static-analysis suite (ISSUE 9): synthetic fixtures per
pass, waiver parsing, baseline-ratchet semantics, and the tier-1 gate —
the whole repo must be clean against the committed baseline (which the
ratchet then keeps monotone: counts may only decrease)."""

import json
import os
import textwrap

import pytest

from tools import analyze
from tools.analyze import (
    coverage,
    error_taxonomy,
    jax_hazards,
    lock_discipline,
)
from tools.analyze.core import (
    SourceFile,
    aggregate,
    check_ratchet,
    load_baseline,
    load_files,
    write_baseline,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def fixture(tmp_path, rel, source):
    """Write one fixture module under a synthetic repo root and return
    its SourceFile (paths matter: the jax/coverage passes scope by
    repo-relative prefix)."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    return SourceFile(rel, path.read_text())


def rules_of(findings):
    return sorted(f.rule for f in findings)


# --- lock discipline ----------------------------------------------------------


GUARDED_OK = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()   # guards: _items, total
            self._items = {}
            self.total = 0

        def put(self, k, v):
            with self._lock:
                self._items[k] = v
                self.total += 1

        def _evict_locked(self):
            self._items.clear()              # caller holds the lock

        def size(self):
            return len(self._items)          # reads are not flagged
"""


def test_lock_guarded_ok(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/fix_ok.py", GUARDED_OK)
    assert lock_discipline.run([f]) == []


def test_lock_unguarded_mutations_flagged(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/fix_bad.py", """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()   # guards: _items, total
                self._items = {}
                self.total = 0

            def put(self, k, v):
                self._items[k] = v               # unguarded subscript
                self.total += 1                  # unguarded augassign

            def note(self, v):
                self._items.setdefault(v, []).append(v)  # mutator call
    """)
    findings = lock_discipline.run([f])
    assert [f_.rule for f_ in findings] == ["lock-guard"] * 3
    assert {f_.line for f_ in findings} == {11, 12, 15}


def test_lock_waiver_and_missing_reason(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/fix_waive.py", """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()   # guards: total
                self.total = 0

            def bump(self):
                # analyze: allow(lock-guard): single-writer thread owns this counter
                self.total += 1

            def bump2(self):
                self.total += 1   # analyze: allow(lock-guard)
    """)
    findings = analyze.run_passes([f], only=["locks"])
    # bump: properly waived.  bump2: the waiver has no reason — the
    # lock-guard finding stands AND the bare waiver is itself flagged.
    assert rules_of(findings) == ["lock-guard", "waiver-reason"]


def test_lock_mutator_calls_in_statement_heads_flagged(tmp_path):
    """Mutator calls buried in return/if/for heads are mutations too —
    the review-time blind spot: `return self._items.pop(k)` outside the
    lock must be flagged like a bare-statement pop."""
    f = fixture(tmp_path, "ytsaurus_tpu/fix_heads.py", """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()   # guards: _items
                self._items = {}

            def take(self, k):
                return self._items.pop(k)       # in a return

            def drop_if(self, k):
                if self._items.pop(k, None):    # in a branch head
                    return True
                return False

            def take_ok(self, k):
                with self._lock:
                    return self._items.pop(k)
    """)
    findings = lock_discipline.run([f])
    assert rules_of(findings) == ["lock-guard", "lock-guard"]
    assert {f_.line for f_ in findings} == {10, 13}


def test_inline_waiver_does_not_bleed_to_next_line(tmp_path):
    """A trailing same-line waiver covers ONLY its line: the site on the
    next line still flags (standalone comment-above waivers are mapped
    forward at parse time instead)."""
    f = fixture(tmp_path, "ytsaurus_tpu/ops/fix_bleed.py", """
        import numpy as np

        def two_syncs(a, b):
            x = np.asarray(a)  # analyze: allow(host-sync): first is intentional
            y = np.asarray(b)
            return x, y
    """)
    findings = jax_hazards.run([f])
    assert rules_of(findings) == ["host-sync"]
    assert findings[0].line == 6


def test_lock_module_level_guard(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/fix_mod.py", """
        import threading

        _LOCK = threading.Lock()   # guards: _STATE
        _STATE = None

        def set_state(v):
            global _STATE
            _STATE = v             # unguarded

        def set_state_ok(v):
            global _STATE
            with _LOCK:
                _STATE = v
    """)
    findings = lock_discipline.run([f])
    assert rules_of(findings) == ["lock-guard"]
    assert findings[0].line == 9


def test_lock_annotation_typo_flagged(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/fix_typo.py", """
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()   # guards: _itemz
                self._items = {}
    """)
    findings = lock_discipline.run([f])
    assert rules_of(findings) == ["lock-annotation"]
    assert "_itemz" in findings[0].message


def test_lock_order_cycle_detected(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/fix_cycle.py", """
        import threading

        _A = threading.Lock()   # guards: _x
        _B = threading.Lock()   # guards: _y
        _x = 0
        _y = 0

        def ab():
            global _x, _y
            with _A:
                _x = 1
                with _B:
                    _y = 1

        def ba():
            global _x, _y
            with _B:
                _y = 2
                with _A:
                    _x = 2
    """)
    findings = lock_discipline.run([f])
    assert rules_of(findings) == ["lock-order"]
    assert "potential deadlock" in findings[0].message
    snapshot = lock_discipline.order_graph_snapshot([f])
    assert len(snapshot["cycles"]) == 1
    assert len(snapshot["edges"]) == 2


def test_lock_order_acyclic_and_call_propagation(tmp_path):
    # B is acquired inside a helper CALLED under A: the edge must still
    # appear (one-level call propagation), and no cycle exists.
    f = fixture(tmp_path, "ytsaurus_tpu/fix_calls.py", """
        import threading

        _A = threading.Lock()   # guards: _x
        _B = threading.Lock()   # guards: _y
        _x = 0
        _y = 0

        def inner():
            global _y
            with _B:
                _y = 1

        def outer():
            global _x
            with _A:
                _x = 1
                inner()
    """)
    assert lock_discipline.run([f]) == []
    snapshot = lock_discipline.order_graph_snapshot([f])
    assert snapshot["cycles"] == []
    assert any("_A" in a and "_B" in b
               for a, b, _site in snapshot["edges"])


# --- jax hazards --------------------------------------------------------------


def test_host_sync_flagged_in_hot_path(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/ops/fix_hot.py", """
        import jax.numpy as jnp
        import numpy as np

        def leak(col, n):
            host = np.asarray(col.data)          # sync
            one = col.data.sum().item()          # sync
            col.data.block_until_ready()         # sync
            total = jnp.sum(col.data)
            return host, one, float(total)       # sync (jnp local)

        def fine():
            return np.asarray([1, 2, 3])         # literal: host already
    """)
    findings = jax_hazards.run([f])
    assert rules_of(findings) == ["host-sync"] * 4
    assert {f_.line for f_ in findings} == {6, 7, 8, 10}


def test_host_sync_cold_module_and_sync_points_exempt(tmp_path):
    cold = fixture(tmp_path, "ytsaurus_tpu/client_fix.py", """
        import numpy as np

        def boundary(x):
            return np.asarray(x)        # client layer: syncs are fine
    """)
    hot = fixture(tmp_path, "ytsaurus_tpu/ops/fix_sync_point.py", """
        import numpy as np

        def finish(self):
            return int(self.count)       # declared sync point
    """)
    assert jax_hazards.run([cold, hot]) == []


def test_host_sync_waiver(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/ops/fix_waived.py", """
        import numpy as np

        def spill(col):
            # analyze: allow(host-sync): spills to host files by design
            return np.asarray(col.data)
    """)
    assert jax_hazards.run([f]) == []


def test_whole_plan_sync_rule(tmp_path):
    """ISSUE 12: inside the whole-plan module, any host sync outside the
    sanctioned final count read is a `whole-plan-sync` finding (the
    stricter rule REPLACES host-sync there — `finish`-style sync-point
    names are no escape hatch)."""
    f = fixture(tmp_path, "ytsaurus_tpu/parallel/whole_plan.py", """
        import jax.numpy as jnp
        import numpy as np

        def _run_exchange(columns, quota):
            counts = jnp.stack([c.sum() for c in columns])
            quota = int(counts.max())            # mid-plan sync: finding
            return quota

        def finish(pending):
            return np.asarray(pending.count)     # NOT sanctioned here

        def _read_counts(final):
            vals = np.asarray(final)             # THE sanctioned sync
            return int(vals[0]), int(vals[1])
    """)
    findings = jax_hazards.run([f])
    assert rules_of(findings) == ["whole-plan-sync"] * 2
    assert {f_.line for f_ in findings} == {7, 11}
    assert all("host-sync" not in f_.rule for f_ in findings)


def test_whole_plan_sync_waiver_and_clean(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/parallel/whole_plan.py", """
        import numpy as np

        def _prepare(pivots):
            # analyze: allow(whole-plan-sync): pivot sampling happens at prepare time, not between stages
            return np.asarray(pivots.data)

        def _read_counts(final):
            vals = np.asarray(final)
            return int(vals[0])
    """)
    assert jax_hazards.run([f]) == []


def test_whole_plan_sync_covers_fused_join_path(tmp_path):
    """ISSUE 14: the fused multiway-join path rides the same one-sync
    contract — a quota or count read inside `_run_join`-shaped code is
    a finding; the stacked telemetry read through `_read_counts` stays
    the single sanctioned transfer."""
    f = fixture(tmp_path, "ytsaurus_tpu/parallel/whole_plan.py", """
        import jax.numpy as jnp
        import numpy as np

        def _run_join(evaluator, plan, table):
            demand = jnp.stack([table.counts.max()])
            quota = int(demand.max())            # mid-join sync: finding
            return quota

        def _read_counts(final):
            vals = np.asarray(final)             # THE sanctioned sync
            return int(vals[0]), int(vals[1])
    """)
    findings = jax_hazards.run([f])
    assert rules_of(findings) == ["whole-plan-sync"]
    assert findings[0].line == 7


def test_whole_plan_module_baseline_is_empty():
    """The REAL whole-plan module carries zero mid-plan syncs (the
    acceptance gate: the only transfer is the final stacked count
    read)."""
    files = load_files(REPO, rel_paths=["ytsaurus_tpu/parallel/"
                                        "whole_plan.py"])
    findings = [f for f in jax_hazards.run(files)
                if f.rule == "whole-plan-sync"]
    assert findings == [], [f.format() for f in findings]


def test_traced_branch_flagged(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/ops/fix_traced.py", """
        import jax
        import jax.numpy as jnp
        from functools import partial

        @jax.jit
        def bad(x):
            if x > 0:                    # traced!
                return x
            return -x

        @jax.jit
        def ok_shape(x):
            if x.shape[0] > 4:           # static structure
                return x[:4]
            return x

        @partial(jax.jit, static_argnums=(1,))
        def ok_static(x, flag):
            if flag:                     # static argument
                return x
            return -x
    """)
    findings = jax_hazards.run([f])
    assert rules_of(findings) == ["traced-branch"]
    assert findings[0].line == 8


def test_dynamic_shape_flagged_and_bucketed_ok(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/ops/fix_shapes.py", """
        import jax

        def pad_capacity(n):
            return max(8, 1 << (n - 1).bit_length())

        def kernel(x):
            return x * 2

        jitted = jax.jit(kernel)

        def run(arr, n):
            bad = jitted(arr[:n])                  # fresh program per n
            good = jitted(arr[:pad_capacity(n)])   # pow2-bucketed
            fixed = jitted(arr[:128])              # constant bound
            return bad, good, fixed
    """)
    findings = jax_hazards.run([f])
    assert rules_of(findings) == ["dynamic-shape"]
    assert findings[0].line == 13


def test_dynamic_shape_tree_wide_on_run_plan_callees(tmp_path):
    """ISSUE 10 satellite: with capacity bucketing universal, an
    unbucketed dynamically-sized plane flowing into an evaluator
    dispatch (`run_plan`/`run_plan_async`, incl. METHOD calls) is a
    finding in ANY module — not just the declared hot paths."""
    f = fixture(tmp_path, "ytsaurus_tpu/server/fix_cold_module.py", """
        from ytsaurus_tpu.chunks.columnar import next_pow2

        def serve(evaluator, plan, planes, n):
            bad = evaluator.run_plan(plan, planes[:n])
            good = evaluator.run_plan(plan, planes[:next_pow2(n)])
            also_bad = evaluator.run_plan_async(plan, planes[:n])
            return bad, good, also_bad
    """)
    findings = jax_hazards.run([f])
    assert rules_of(findings) == ["dynamic-shape", "dynamic-shape"]
    assert [fd.line for fd in findings] == [5, 7]
    assert not jax_hazards.is_hot(f.path), \
        "the fixture must live OUTSIDE the hot prefixes to prove " \
        "tree-wide scope"


def test_decode_in_hot_path_flagged(tmp_path):
    """ISSUE 19: vocab gathers and decode-helper calls in a hot module
    are findings; the literal→code binders, the sync-point boundary,
    bytes codec calls, and waived sites are not."""
    f = fixture(tmp_path, "ytsaurus_tpu/query/engine/fix_decode.py", """
        import numpy as np

        def probe(col, rows):
            words = col.dictionary[rows]                 # per-row gather
            taken = np.take(col.vocab, rows)             # same, via take
            rows2 = decode_rows(col)                     # decode helper
            text = pattern.decode("utf-8")               # codec: exempt
            return words, taken, rows2, text

        def _vocab_code(vocab, value):
            idx = np.searchsorted(vocab, value)
            return idx if vocab[idx] == value else -1    # binder: exempt

        def to_rows(self):
            return [bytes(self.vocab[i]) for i in self.codes]  # boundary

        def spill(col, rows):
            # analyze: allow(decode-in-hot-path): materializes an export
            return col.dictionary[rows]
    """)
    findings = jax_hazards.run([f])
    assert rules_of(findings) == ["decode-in-hot-path"] * 3
    assert sorted(fd.line for fd in findings) == [5, 6, 7]


def test_decode_in_cold_module_exempt(tmp_path):
    """The client/server layers decode freely — materializing rows for
    humans is their job."""
    f = fixture(tmp_path, "ytsaurus_tpu/server/fix_decode_cold.py", """
        def render(col, rows):
            return [col.dictionary[i] for i in rows]
    """)
    assert jax_hazards.run([f]) == []


# --- failpoint & span coverage ------------------------------------------------


def test_failpoint_coverage(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/chunks/fix_io.py", """
        import os
        from ytsaurus_tpu.utils import failpoints

        _FP = failpoints.register_site("chunks.fix.write")

        def covered(path, blob):
            _FP.hit()
            with open(path, "wb") as f:
                f.write(blob)
            os.replace(path, path + ".pub")

        def naked(path):
            os.remove(path)

        # analyze: allow(failpoint): fixture waiver — cleanup helper
        def waived(path):
            os.remove(path)
    """)
    findings = coverage.run([f])
    assert rules_of(findings) == ["failpoint"]
    assert "naked" in findings[0].message


def test_failpoint_scope_is_server_chunk_rpc_only(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/cypress/fix_meta.py", """
        def save(path, blob):
            with open(path, "wb") as f:
                f.write(blob)
    """)
    assert coverage.run([f]) == []


def test_span_root_discipline(tmp_path):
    interior = fixture(tmp_path, "ytsaurus_tpu/tablet/fix_spans.py", """
        from ytsaurus_tpu.utils.tracing import child_span, start_query_span

        def good(x):
            with child_span("tablet.read"):
                return x

        def bad(x):
            with start_query_span("tablet.rogue_root"):
                return x
    """)
    entry = fixture(tmp_path, "ytsaurus_tpu/client.py", """
        from ytsaurus_tpu.utils.tracing import start_query_span

        def select(q):
            with start_query_span("select"):
                return q
    """)
    findings = coverage.run([interior, entry])
    assert rules_of(findings) == ["span-root"]
    assert findings[0].path == "ytsaurus_tpu/tablet/fix_spans.py"


# --- error taxonomy -----------------------------------------------------------


ERRORS_FIXTURE = """
    import enum

    class EErrorCode(enum.IntEnum):
        OK = 0
        Generic = 1
        Timeout = 3
        Waldo = 3          # duplicate: IntEnum silently aliases
"""


def test_duplicate_error_code_flagged(tmp_path):
    f = fixture(tmp_path, "ytsaurus_tpu/errors.py", ERRORS_FIXTURE)
    findings = error_taxonomy.run([f])
    assert rules_of(findings) == ["duplicate-code"]
    assert "Waldo" in findings[0].message


def test_raise_site_codes_checked(tmp_path):
    errors = fixture(tmp_path, "ytsaurus_tpu/errors.py", """
        import enum

        class EErrorCode(enum.IntEnum):
            OK = 0
            Generic = 1
            Timeout = 3
    """)
    raises = fixture(tmp_path, "ytsaurus_tpu/fix_raises.py", """
        from ytsaurus_tpu.errors import EErrorCode, YtError

        def a():
            raise YtError("x", code=EErrorCode.Timeout)      # fine

        def b():
            raise YtError("x", code=9999)                    # unknown

        def c():
            raise YtError("x", code=3)                       # bare int

        def d():
            raise YtError("x", code=EErrorCode.Missing)      # no member
    """)
    findings = error_taxonomy.run([errors, raises])
    assert rules_of(findings) == ["literal-code", "unregistered-code",
                                  "unregistered-code"]
    literal = next(f_ for f_ in findings if f_.rule == "literal-code")
    assert literal.severity == "warning"
    assert "EErrorCode.Timeout" in literal.message


# --- baseline ratchet ---------------------------------------------------------


def _findings(tmp_path, n):
    source = "import threading\n\n_L = threading.Lock()   # guards: _s\n_s = 0\n\n"
    for i in range(n):
        source += f"def f{i}():\n    global _s\n    _s = {i}\n\n"
    f = fixture(tmp_path, "ytsaurus_tpu/fix_ratchet.py", source)
    found = lock_discipline.run([f])
    assert len(found) == n
    return found


def test_ratchet_decrease_ok_increase_fails(tmp_path):
    findings = _findings(tmp_path, 2)
    key = findings[0].key()
    assert check_ratchet(findings, {key: 2}) == []      # at baseline
    assert check_ratchet(findings, {key: 3}) == []      # below: ok
    over = check_ratchet(findings, {key: 1})            # above: fails
    assert len(over) == 1 and "RATCHET" in over[0]


def test_ratchet_new_key_fails_and_update_tightens(tmp_path):
    findings = _findings(tmp_path, 2)
    fresh = check_ratchet(findings, {})
    assert len(fresh) == 2 and all(v.startswith("NEW") for v in fresh)
    path = str(tmp_path / "baseline.json")
    counts = write_baseline(findings, path)
    assert counts == aggregate(findings)
    assert check_ratchet(findings, load_baseline(path)) == []
    payload = json.loads(open(path).read())
    assert "decrease" in payload["comment"]


def test_run_passes_rejects_unknown_pass():
    with pytest.raises(ValueError):
        analyze.run_passes([], only=["nonsense"])


# --- the tier-1 gate ----------------------------------------------------------


def test_repo_clean_against_baseline():
    """THE gate: all six passes over the real tree, checked against the
    committed baseline.  A new finding (or a count regression) fails
    tier-1 — fix the code or waive with a reason; growing the baseline
    is not a fix."""
    files = load_files(REPO)
    findings = analyze.run_passes(files, root=REPO)
    violations = check_ratchet(findings, load_baseline())
    assert violations == [], "\n".join(violations)


def test_repo_lock_order_graph_is_acyclic():
    """Acceptance: the lock-ordering graph across the annotated modules
    is cycle-free, and the cross-object propagation is alive (the
    admission→accountant edge exists — admit() folds throttles into the
    accountant while holding the admission condition)."""
    files = load_files(REPO)
    snapshot = lock_discipline.order_graph_snapshot(files)
    assert snapshot["cycles"] == []
    assert len(snapshot["locks"]) >= 20
    assert any("AdmissionController._cond" in a and
               "ResourceAccountant._lock" in b
               for a, b, _site in snapshot["edges"])


def test_repo_annotations_cover_the_hot_modules():
    """The ISSUE 9 annotation sweep: every named hot module carries at
    least one `# guards:` lock annotation."""
    files = {f.path: f for f in load_files(REPO)}
    for rel in ("ytsaurus_tpu/query/serving.py",
                "ytsaurus_tpu/query/workload.py",
                "ytsaurus_tpu/query/engine/evaluator.py",
                "ytsaurus_tpu/utils/profiling.py",
                "ytsaurus_tpu/utils/tracing.py",
                "ytsaurus_tpu/rpc/channel.py",
                "ytsaurus_tpu/tablet/tablet.py",
                "ytsaurus_tpu/server/discovery.py",
                "ytsaurus_tpu/query/accounting.py",
                "ytsaurus_tpu/utils/slo.py",
                "ytsaurus_tpu/utils/failpoints.py"):
        locks, _ = lock_discipline.collect_locks(files[rel])
        assert locks, f"{rel} lost its # guards: annotations"


def test_cli_analyze_offline(capsys):
    """`yt analyze` runs without --proxy (offline subcommand) and
    reports the ratchet verdict."""
    from ytsaurus_tpu import cli
    assert cli.run(["analyze"]) == 0
    assert "static analysis clean" in capsys.readouterr().out
