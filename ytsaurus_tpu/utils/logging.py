"""Structured logging (ref: core/logging — async structured logs with
per-category levels; here: stdlib logging with a structured formatter and
per-category level control via YTSAURUS_TPU_LOG_LEVEL / _LOG_CATEGORIES)."""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any

_CONFIGURED = False


class _DynamicStderrHandler(logging.StreamHandler):
    """Resolves sys.stderr at emit time so redirection/capture works."""

    def __init__(self):
        logging.Handler.__init__(self)

    @property
    def stream(self):
        import sys
        return sys.stderr


class StructuredFormatter(logging.Formatter):
    """One JSON object per line: timestamp, level, category, message, fields."""

    def format(self, record: logging.LogRecord) -> str:
        entry: dict[str, Any] = {
            "ts": round(record.created, 6),
            "level": record.levelname.lower(),
            "category": record.name,
            "message": record.getMessage(),
        }
        fields = getattr(record, "fields", None)
        if fields:
            entry.update(fields)
        return json.dumps(entry, default=str)


def _configure() -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    _CONFIGURED = True
    root = logging.getLogger("ytsaurus_tpu")
    level_name = os.environ.get("YTSAURUS_TPU_LOG_LEVEL", "WARNING").upper()
    root.setLevel(getattr(logging, level_name, logging.WARNING))
    handler = _DynamicStderrHandler()
    handler.setFormatter(StructuredFormatter())
    root.addHandler(handler)
    root.propagate = False
    # Per-category overrides: "Query=debug,Tablet=info"
    overrides = os.environ.get("YTSAURUS_TPU_LOG_CATEGORIES", "")
    for part in overrides.split(","):
        if "=" in part:
            category, _, lvl = part.partition("=")
            logging.getLogger(f"ytsaurus_tpu.{category.strip()}").setLevel(
                getattr(logging, lvl.strip().upper(), logging.WARNING))


def get_logger(category: str) -> logging.Logger:
    """Category logger ('Query', 'Tablet', 'Master', …)."""
    _configure()
    return logging.getLogger(f"ytsaurus_tpu.{category}")


def log_event(logger: logging.Logger, level: int, message: str,
              **fields) -> None:
    """Structured event: message + key/value fields."""
    if logger.isEnabledFor(level):
        logger.log(level, message, extra={"fields": fields})
