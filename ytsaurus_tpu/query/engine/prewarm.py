"""Capture-driven prewarm (ISSUE 18 tentpole, piece c).

A restarted replica's compile cache is empty: without help, the first
occurrence of every workload shape pays an inline compile on a serving
thread — the compile storm PR 7's workload capture exists to measure.
This module replays such a capture COMPILE-ONLY: each select record is
re-parameterized (literals back into placeholders), planned against the
live schemas, prepared against the table's real resident chunks (so the
structure probes — fast-group min/max, vocabularies — make the SAME
host decisions serving traffic will, and the cache key matches
exactly), then lowered and compiled without ever executing.  Compiled
programs land in the evaluator's memory LRU and publish to the disk /
cluster AOT tiers.

Accounting discipline: prewarm compiles book through the observatory's
BACKGROUND ledger (observe_background) and the /query/tiers/
prewarm_compiles counter — NEVER through /query/compile_cache/misses —
so a full prewarm replay fires zero compile-storm alerts and leaves the
steady-state hit-rate SLO untouched (test-enforced).

Entry points: the daemon runs `prewarm_from_capture` at startup when
TieringConfig.prewarm_capture (or YT_TPU_PREWARM_CAPTURE) names a
capture file; `yt prewarm --capture FILE` drives the same path from the
CLI with an in-process client.
"""

from __future__ import annotations

import time
from typing import Mapping, Optional

import jax

from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.parameterize import plan_fingerprint
from ytsaurus_tpu.query.engine.lowering import prepare
from ytsaurus_tpu.tablet.timestamp import MAX_TIMESTAMP


def _resolve_chunks(path: str, tables, client) -> list:
    """The table's resident chunks, newest authority first: an explicit
    `tables` mapping (tests / embedded use), else the client's shard
    staging — the same chunks serving traffic dispatches over."""
    if tables is not None and path in tables:
        data = tables[path]
        return list(data) if isinstance(data, (list, tuple)) else [data]
    if client is not None:
        return list(client._query_shards(path, MAX_TIMESTAMP))
    raise YtError(f"prewarm: no chunk source for table {path!r}")


def _install(evaluator, key: tuple, fn) -> None:
    """Memory-LRU insert with the same bounded-eviction bookkeeping as
    the serving path (cache lock held only around the mutation)."""
    from ytsaurus_tpu.config import workload_config
    from ytsaurus_tpu.query.engine import evaluator as ev_mod
    cfg = workload_config()
    with evaluator._cache_lock:
        evaluator._cache[key] = fn
        evicted_keys = []
        if cfg.compile_cache_capacity:
            while len(evaluator._cache) > cfg.compile_cache_capacity:
                evicted_keys.append(
                    evaluator._cache.popitem(last=False)[0])
    for evicted_key in evicted_keys:
        ev_mod._observatory.observe_eviction(evicted_key)
        ev_mod._evictions_counter.increment()


def prewarm_from_capture(records, tables: Optional[Mapping] = None,
                         schemas: Optional[Mapping] = None,
                         client=None, evaluator=None,
                         limit: Optional[int] = None) -> dict:
    """Compile every distinct program a workload capture implies, off
    the serving path.  Returns a report dict:

      compiled        fresh lower().compile() runs (published to AOT)
      aot_hits        programs loaded from the disk/cluster AOT tiers
      already_cached  cache keys already resident in the memory LRU
      skipped         records not prewarmable (joins, non-select kinds,
                      missing tables, unparseable text) — with a bounded
                      `skip_reasons` breakdown
      seconds         total compile+load wall time
    """
    from ytsaurus_tpu.query.engine import evaluator as ev_mod
    from ytsaurus_tpu.query.engine.aot_cache import (
        get_cluster_store, get_disk_cache)
    from ytsaurus_tpu.query.workload import substitute_literals

    evaluator = evaluator or ev_mod._global_evaluator
    if schemas is None and client is not None:
        from ytsaurus_tpu.client import _SchemaResolver
        schemas = _SchemaResolver(client)
    elif schemas is None and tables is not None:
        schemas = {path: data[0].schema if isinstance(data, (list, tuple))
                   else data.schema for path, data in tables.items()}
    if schemas is None:
        raise YtError("prewarm requires schemas, tables, or a client")

    report = {"records": 0, "compiled": 0, "aot_hits": 0,
              "already_cached": 0, "skipped": 0, "seconds": 0.0}
    reasons: dict[str, int] = {}
    seen: set = set()
    chunk_cache: dict[str, list] = {}

    def _skip(why: str) -> None:
        report["skipped"] += 1
        reasons[why] = reasons.get(why, 0) + 1

    for record in records:
        if limit is not None and report["records"] >= limit:
            break
        if getattr(record, "kind", "select") != "select":
            _skip("non_select")
            continue
        report["records"] += 1
        try:
            text = substitute_literals(record.query, record.literals)
            plan = build_query(text, schemas)
        except (YtError, ValueError) as err:
            _skip(f"plan: {type(err).__name__}")
            continue
        if getattr(plan, "joins", ()):
            # Join plans dispatch over the join-widened namespace the
            # coordinator materializes per query — a shape this
            # compile-only pass cannot reconstruct faithfully.  The
            # interpreter tier doesn't cover joins either, so these
            # shapes warm on first traffic exactly as before.
            _skip("joins")
            continue
        try:
            chunks = chunk_cache.get(plan.source)
            if chunks is None:
                chunks = chunk_cache[plan.source] = _resolve_chunks(
                    plan.source, tables, client)
        except YtError:
            _skip("missing_table")
            continue
        fp = plan_fingerprint(plan)
        if fp in seen:
            # Same parameterized shape as an earlier record: every
            # chunk's program key was already handled this pass.
            continue
        seen.add(fp)
        for chunk in chunks:
            try:
                _prewarm_one(evaluator, plan, fp, chunk, seen, report,
                             get_disk_cache(), get_cluster_store())
            except Exception as err:   # noqa: BLE001 — prewarm is an
                # optimization; one unlowerable shape must not abort
                # the rest of the capture.
                _skip(f"compile: {type(err).__name__}")
    if reasons:
        report["skip_reasons"] = reasons
    return report


def _prewarm_one(evaluator, plan, fp: str, chunk, seen: set,
                 report: dict, disk, cluster) -> None:
    """Compile (or AOT-load) one (plan, chunk) program into the caches."""
    from ytsaurus_tpu.query.engine import evaluator as ev_mod
    prepared = prepare(plan, chunk)
    key = (fp, chunk.capacity, prepared.binding_shapes())
    if key in seen:
        return
    seen.add(key)
    with evaluator._cache_lock:
        if key in evaluator._cache:
            report["already_cached"] += 1
            return
    columns = {c.name: (chunk.columns[c.name].data,
                        chunk.columns[c.name].valid)
               for c in plan.schema}
    args = (columns, chunk.row_valid, tuple(prepared.bindings))
    t0 = time.perf_counter()
    fn = disk.load(key) if disk is not None else None
    if fn is not None:
        report["aot_hits"] += 1
    else:
        fn = cluster.fetch(key) if cluster is not None else None
        if fn is not None:
            report["aot_hits"] += 1
    if fn is None:
        lowered = jax.jit(prepared.run).lower(*args)
        fn = lowered.compile()
        seconds = time.perf_counter() - t0
        if disk is not None:
            disk.store(key, fn, fp, seconds)
        if cluster is not None:
            cluster.publish(key, fn, fp, seconds)
        report["compiled"] += 1
        ev_mod._prewarm_counter.increment()
    seconds = time.perf_counter() - t0
    _install(evaluator, key, fn)
    # Background ledger, NOT the miss path: a prewarm sweep must leave
    # /query/compile_cache/{hits,misses} — the storm SLO's inputs —
    # exactly where it found them.
    ev_mod._observatory.observe_background(fp, key, seconds)
    report["seconds"] += seconds


def prewarm_capture_file(path: str, tables: Optional[Mapping] = None,
                         schemas: Optional[Mapping] = None,
                         client=None, evaluator=None,
                         limit: Optional[int] = None) -> dict:
    """Load a capture file (failing loudly on schema-version mismatch)
    and prewarm it.  The daemon-startup and CLI entry point."""
    from ytsaurus_tpu.query.workload import load_capture
    records = load_capture(path)
    report = prewarm_from_capture(records, tables=tables,
                                  schemas=schemas, client=client,
                                  evaluator=evaluator, limit=limit)
    report["capture"] = path
    return report
