"""Shared unsigned-LEB128 varint helpers (single implementation for YSON,
chunk metas, and anything else host-side; the native library has its own
vectorized zigzag codec for column planes)."""

from __future__ import annotations


def write_varint_u(out: bytearray, value: int) -> None:
    if value < 0:
        raise ValueError("varint_u requires a non-negative value")
    while True:
        byte = value & 0x7F
        value >>= 7
        if value:
            out.append(byte | 0x80)
        else:
            out.append(byte)
            return


def encode_varint_u(value: int) -> bytes:
    out = bytearray()
    write_varint_u(out, value)
    return bytes(out)


def read_varint_u(data: bytes, pos: int) -> tuple[int, int]:
    """Returns (value, new_pos); raises ValueError on truncation."""
    result = 0
    shift = 0
    n = len(data)
    while True:
        if pos >= n:
            raise ValueError("truncated varint")
        byte = data[pos]
        pos += 1
        result |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long (more than 64 bits)")
