"""QL regression corpus, part 2 — regex/string/hash function family,
deeper null/edge semantics, aggregate and ordering breadth.

Together with test_ql_corpus.py this grows the harness toward the
reference suite's scale (library/query/unittests/evaluate/
ql_query_ut.cpp ~600 cases).  Cases are written from the reference's
BEHAVIOR (C++ integer semantics, RE2-compatible regex subset,
null-propagation rules), not ported text.
"""

import pytest

from tests.harness import evaluate

T = "//t"

INT_COLS = [("k", "int64", "ascending"), ("v", "int64")]
STR_COLS = [("k", "int64", "ascending"), ("s", "string")]
DBL_COLS = [("k", "int64", "ascending"), ("x", "double")]
U64_COLS = [("k", "int64", "ascending"), ("u", "uint64")]
SV_COLS = [("k", "int64", "ascending"), ("s", "string"), ("v", "int64")]


def tbl(rows, cols=INT_COLS, path=T):
    return {path: (cols, rows)}


WORDS = tbl([(1, "apple"), (2, "Banana"), (3, "cherry"), (4, None),
             (5, ""), (6, "apple pie"), (7, "a1b2c3")], STR_COLS)
NUMSTR = tbl([(1, "42"), (2, "-17"), (3, "0"), (4, "notanum"),
              (5, None), (6, " 8 "), (7, "9999999999999")], STR_COLS)
KV8 = tbl([(i, i * 7) for i in range(8)])
MIX = tbl([(1, "red", 10), (2, "blue", 20), (3, "red", 30),
           (4, None, 40), (5, "blue", None), (6, "green", 60)], SV_COLS)


def run(query, tables, expected, ordered=False):
    evaluate(query, tables, expected, ordered=ordered)


# ---------------------------------------------------------------------------
# A. regex family (RE2-compatible subset; patterns are plan-time literals)
# ---------------------------------------------------------------------------

REGEX = [
    ("full_match_hit", f"k FROM [{T}] WHERE regex_full_match('a.*e', s)",
     WORDS, [{"k": 1}, {"k": 6}]),
    ("full_match_is_anchored",
     f"k FROM [{T}] WHERE regex_full_match('pple', s)", WORDS, []),
    ("full_match_empty_pattern_matches_empty",
     f"k FROM [{T}] WHERE regex_full_match('', s)", WORDS, [{"k": 5}]),
    ("full_match_null_never_matches",
     f"k FROM [{T}] WHERE regex_full_match('.*', s)", WORDS,
     [{"k": 1}, {"k": 2}, {"k": 3}, {"k": 5}, {"k": 6}, {"k": 7}]),
    ("partial_match_substring",
     f"k FROM [{T}] WHERE regex_partial_match('pp', s)", WORDS,
     [{"k": 1}, {"k": 6}]),
    ("partial_match_case_sensitive",
     f"k FROM [{T}] WHERE regex_partial_match('banana', s)", WORDS, []),
    ("partial_match_case_insensitive_flag",
     f"k FROM [{T}] WHERE regex_partial_match('(?i)banana', s)", WORDS,
     [{"k": 2}]),
    ("partial_match_digit_class",
     f"k FROM [{T}] WHERE regex_partial_match('[0-9]', s)", WORDS,
     [{"k": 7}]),
    ("partial_match_alternation",
     f"k FROM [{T}] WHERE regex_partial_match('cherry|Banana', s)",
     WORDS, [{"k": 2}, {"k": 3}]),
    ("full_match_quantifier",
     f"k FROM [{T}] WHERE regex_full_match('[a-z0-9]+', s)", WORDS,
     [{"k": 1}, {"k": 3}, {"k": 7}]),
    ("not_partial_match",
     f"k FROM [{T}] WHERE NOT regex_partial_match('a', s)", WORDS,
     [{"k": 3}, {"k": 5}]),
    ("match_in_projection",
     f"regex_partial_match('rr', s) AS m FROM [{T}] WHERE k = 3", WORDS,
     [{"m": True}]),
    ("match_null_projects_null",
     f"regex_partial_match('x', s) AS m FROM [{T}] WHERE k = 4", WORDS,
     [{"m": None}]),
    ("replace_first_one_hit",
     f"regex_replace_first('p', s, '_') AS r FROM [{T}] WHERE k = 1",
     WORDS, [{"r": b"a_ple"}]),
    ("replace_all_every_hit",
     f"regex_replace_all('p', s, '_') AS r FROM [{T}] WHERE k = 1",
     WORDS, [{"r": b"a__le"}]),
    ("replace_all_group_backref",
     f"regex_replace_all('([0-9])', s, '<\\\\1>') AS r FROM [{T}] "
     "WHERE k = 7", WORDS, [{"r": b"a<1>b<2>c<3>"}]),
    ("replace_no_hit_identity",
     f"regex_replace_all('zz', s, '_') AS r FROM [{T}] WHERE k = 3",
     WORDS, [{"r": b"cherry"}]),
    ("replace_null_is_null",
     f"regex_replace_all('a', s, '_') AS r FROM [{T}] WHERE k = 4",
     WORDS, [{"r": None}]),
    ("escape_specials",
     f"regex_escape(s) AS r FROM [{T}] WHERE k = 6", WORDS,
     [{"r": b"apple\\ pie"}]),
    ("escape_then_match_self",
     f"k FROM [{T}] WHERE regex_full_match('apple\\\\ pie', s)", WORDS,
     [{"k": 6}]),
    ("chained_replace_then_length",
     f"length(regex_replace_all('[aeiou]', s, '')) AS r FROM [{T}] "
     "WHERE k = 1", WORDS, [{"r": 3}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in REGEX],
                         ids=[c[0] for c in REGEX])
def test_regex_family(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# B. substr / parse_int64 / hashes / sha256
# ---------------------------------------------------------------------------

STRF2 = [
    ("substr_middle", f"substr(s, 1, 3) AS r FROM [{T}] WHERE k = 1",
     WORDS, [{"r": b"ppl"}]),
    ("substr_from_only", f"substr(s, 2) AS r FROM [{T}] WHERE k = 3",
     WORDS, [{"r": b"erry"}]),
    ("substr_past_end", f"substr(s, 100) AS r FROM [{T}] WHERE k = 1",
     WORDS, [{"r": b""}]),
    ("substr_len_past_end", f"substr(s, 3, 99) AS r FROM [{T}] WHERE k = 1",
     WORDS, [{"r": b"le"}]),
    ("substr_zero_len", f"substr(s, 2, 0) AS r FROM [{T}] WHERE k = 1",
     WORDS, [{"r": b""}]),
    ("substr_null", f"substr(s, 0, 2) AS r FROM [{T}] WHERE k = 4",
     WORDS, [{"r": None}]),
    ("substr_of_empty", f"substr(s, 0, 2) AS r FROM [{T}] WHERE k = 5",
     WORDS, [{"r": b""}]),
    ("substr_in_where", f"k FROM [{T}] WHERE substr(s, 0, 1) = 'a'",
     WORDS, [{"k": 1}, {"k": 6}, {"k": 7}]),
    ("parse_int64_plain", f"parse_int64(s) AS r FROM [{T}] WHERE k = 1",
     NUMSTR, [{"r": 42}]),
    ("parse_int64_negative", f"parse_int64(s) AS r FROM [{T}] WHERE k = 2",
     NUMSTR, [{"r": -17}]),
    ("parse_int64_zero", f"parse_int64(s) AS r FROM [{T}] WHERE k = 3",
     NUMSTR, [{"r": 0}]),
    ("parse_int64_garbage_null",
     f"parse_int64(s) AS r FROM [{T}] WHERE k = 4", NUMSTR, [{"r": None}]),
    ("parse_int64_null_in_null_out",
     f"parse_int64(s) AS r FROM [{T}] WHERE k = 5", NUMSTR, [{"r": None}]),
    ("parse_int64_strips_spaces",
     f"parse_int64(s) AS r FROM [{T}] WHERE k = 6", NUMSTR, [{"r": 8}]),
    ("parse_int64_large", f"parse_int64(s) AS r FROM [{T}] WHERE k = 7",
     NUMSTR, [{"r": 9999999999999}]),
    ("parse_int64_arithmetic",
     f"parse_int64(s) * 2 AS r FROM [{T}] WHERE k = 1", NUMSTR,
     [{"r": 84}]),
    ("parse_int64_filter",
     f"k FROM [{T}] WHERE parse_int64(s) > 0", NUMSTR,
     [{"k": 1}, {"k": 6}, {"k": 7}]),
    ("sha256_len_32", f"length(sha256(s)) AS r FROM [{T}] WHERE k = 1",
     WORDS, [{"r": 32}]),
    ("sha256_distinct_inputs",
     f"k FROM [{T}] WHERE sha256(s) = sha256('apple')", WORDS,
     [{"k": 1}]),
    ("sha256_null", f"sha256(s) AS r FROM [{T}] WHERE k = 4", WORDS,
     [{"r": None}]),
    ("bigb_hash_self_equal",
     f"k FROM [{T}] WHERE bigb_hash(s) = bigb_hash(s)", WORDS,
     [{"k": 1}, {"k": 2}, {"k": 3}, {"k": 5}, {"k": 6}, {"k": 7}]),
    ("bigb_differs_from_farm",
     f"k FROM [{T}] WHERE bigb_hash(s) = farm_hash(s)", WORDS, []),
    ("substr_group_key",
     f"substr(s, 0, 1) AS c, sum(v) AS t FROM [{T}] "
     "GROUP BY substr(s, 0, 1)",
     MIX, [{"c": b"r", "t": 40}, {"c": b"b", "t": 20},
           {"c": None, "t": 40}, {"c": b"g", "t": 60}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in STRF2],
                         ids=[c[0] for c in STRF2])
def test_string_function_family(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# C. LIKE family breadth
# ---------------------------------------------------------------------------

LIKE = [
    ("like_prefix", f"k FROM [{T}] WHERE s LIKE 'apple%'", WORDS,
     [{"k": 1}, {"k": 6}]),
    ("like_suffix", f"k FROM [{T}] WHERE s LIKE '%pie'", WORDS,
     [{"k": 6}]),
    ("like_contains", f"k FROM [{T}] WHERE s LIKE '%err%'", WORDS,
     [{"k": 3}]),
    ("like_single_char", f"k FROM [{T}] WHERE s LIKE '_pple'", WORDS,
     [{"k": 1}]),
    ("like_exact", f"k FROM [{T}] WHERE s LIKE 'cherry'", WORDS,
     [{"k": 3}]),
    ("like_empty_pattern", f"k FROM [{T}] WHERE s LIKE ''", WORDS,
     [{"k": 5}]),
    ("not_like", f"k FROM [{T}] WHERE s NOT LIKE '%a%'", WORDS,
     [{"k": 3}, {"k": 5}]),
    ("ilike_case_folds", f"k FROM [{T}] WHERE s ILIKE 'banana'", WORDS,
     [{"k": 2}]),
    ("ilike_wildcard", f"k FROM [{T}] WHERE s ILIKE 'A%'", WORDS,
     [{"k": 1}, {"k": 6}, {"k": 7}]),
    ("rlike_regex", f"k FROM [{T}] WHERE s RLIKE '[ac].*'", WORDS,
     [{"k": 1}, {"k": 3}, {"k": 6}, {"k": 7}]),
    ("like_null_never", f"k FROM [{T}] WHERE s LIKE '%'", WORDS,
     [{"k": 1}, {"k": 2}, {"k": 3}, {"k": 5}, {"k": 6}, {"k": 7}]),
    ("like_escaped_percent_literal",
     f"k FROM [{T}] WHERE s LIKE 'a1b2c3'", WORDS, [{"k": 7}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in LIKE],
                         ids=[c[0] for c in LIKE])
def test_like_family(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# D. IN / BETWEEN / CASE / TRANSFORM breadth
# ---------------------------------------------------------------------------

COND2 = [
    ("in_single", f"k FROM [{T}] WHERE v IN (14)", KV8, [{"k": 2}]),
    ("in_many", f"k FROM [{T}] WHERE v IN (0, 7, 28)", KV8,
     [{"k": 0}, {"k": 1}, {"k": 4}]),
    ("in_none_match", f"k FROM [{T}] WHERE v IN (999)", KV8, []),
    ("not_in", f"k FROM [{T}] WHERE v NOT IN (0, 7)", KV8,
     [{"k": 2}, {"k": 3}, {"k": 4}, {"k": 5}, {"k": 6}, {"k": 7}]),
    ("in_strings", f"k FROM [{T}] WHERE s IN ('red', 'green')", MIX,
     [{"k": 1}, {"k": 3}, {"k": 6}]),
    ("not_in_strings_null_comparable",
     # IN is a key-tuple compare (ref CompareRowValues): null is an
     # ordinary key value, so the null row passes NOT IN ('red').
     f"k FROM [{T}] WHERE s NOT IN ('red')", MIX,
     [{"k": 2}, {"k": 4}, {"k": 5}, {"k": 6}]),
    ("between_inclusive", f"k FROM [{T}] WHERE v BETWEEN 7 AND 21", KV8,
     [{"k": 1}, {"k": 2}, {"k": 3}]),
    ("between_empty_range", f"k FROM [{T}] WHERE v BETWEEN 100 AND 90",
     KV8, []),
    ("not_between", f"k FROM [{T}] WHERE v NOT BETWEEN 1 AND 100", KV8,
     [{"k": 0}]),
    ("between_strings", f"k FROM [{T}] WHERE s BETWEEN 'blue' AND 'green'",
     MIX, [{"k": 2}, {"k": 5}, {"k": 6}]),
    ("case_value_form",
     f"CASE v WHEN 0 THEN 100 WHEN 7 THEN 200 ELSE -1 END AS r "
     f"FROM [{T}] WHERE k < 3", KV8,
     [{"r": 100}, {"r": 200}, {"r": -1}]),
    ("case_on_modulo",
     f"CASE v % 5 WHEN 0 THEN 'z' ELSE 'nz' END AS r FROM [{T}] "
     "WHERE k IN (0, 1)", KV8, [{"r": b"z"}, {"r": b"nz"}]),
    ("case_searched_form",
     f"CASE WHEN v < 10 THEN 'low' ELSE 'high' END AS r FROM [{T}] "
     "WHERE k IN (0, 3)", KV8, [{"r": b"low"}, {"r": b"high"}]),
    ("case_no_else_null",
     f"CASE WHEN v = 999 THEN 1 END AS r FROM [{T}] WHERE k = 0", KV8,
     [{"r": None}]),
    ("case_first_match_wins",
     f"CASE WHEN v >= 0 THEN 'a' WHEN v >= 10 THEN 'b' END AS r "
     f"FROM [{T}] WHERE k = 3", KV8, [{"r": b"a"}]),
    ("transform_basic",
     f"transform(s, ('red', 'blue'), ('R', 'B')) AS r FROM [{T}] "
     "WHERE k <= 2", MIX, [{"r": b"R"}, {"r": b"B"}]),
    ("transform_default_null",
     f"transform(s, ('red'), ('R')) AS r FROM [{T}] WHERE k = 6", MIX,
     [{"r": None}]),
    ("transform_ints",
     f"transform(v, (10, 20), (1, 2)) AS r FROM [{T}] WHERE k <= 2",
     MIX, [{"r": 1}, {"r": 2}]),
    ("if_nested",
     f"if(v > 15, if(v > 25, 'big', 'mid'), 'small') AS r FROM [{T}] "
     "WHERE k IN (1, 2, 3)", MIX,
     [{"r": b"small"}, {"r": b"mid"}, {"r": b"big"}]),
    ("if_null_coalesce_chain",
     f"if_null(v, 0) + if_null(v, 100) AS r FROM [{T}] WHERE k = 5",
     MIX, [{"r": 100}]),
    ("in_with_arith", f"k FROM [{T}] WHERE v % 5 IN (0)", KV8,
     [{"k": 0}, {"k": 5}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in COND2],
                         ids=[c[0] for c in COND2])
def test_conditional_breadth(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# E. aggregates: argmin/argmax, HAVING, grouped function results
# ---------------------------------------------------------------------------

AGG2 = [
    ("argmax_picks_row",
     f"argmax(s, v) AS r FROM [{T}] GROUP BY 1", MIX, [{"r": b"green"}]),
    ("argmin_picks_row",
     f"argmin(s, v) AS r FROM [{T}] GROUP BY 1", MIX, [{"r": b"red"}]),
    ("grouped_argmax",
     f"s, argmax(k, v) AS r FROM [{T}] WHERE s != '' GROUP BY s", MIX,
     [{"s": b"red", "r": 3}, {"s": b"blue", "r": 2},
      {"s": b"green", "r": 6}]),
    ("having_filters_groups",
     f"s, sum(v) AS t FROM [{T}] GROUP BY s HAVING sum(v) > 30", MIX,
     [{"s": b"red", "t": 40}, {"s": None, "t": 40},
      {"s": b"green", "t": 60}]),
    ("having_on_count",
     f"s, count(*) AS n FROM [{T}] GROUP BY s HAVING count(*) > 1", MIX,
     [{"s": b"red", "n": 2}, {"s": b"blue", "n": 2}]),
    ("count_star_vs_column",
     f"count(*) AS a, count(v) AS b FROM [{T}] GROUP BY 1", MIX,
     [{"a": 6, "b": 5}]),
    ("sum_of_expression",
     f"sum(v * 2) AS r FROM [{T}] GROUP BY 1", MIX, [{"r": 320}]),
    ("avg_is_double",
     f"avg(v) AS r FROM [{T}] WHERE s = 'red' GROUP BY 1", MIX,
     [{"r": 20.0}]),
    ("min_max_strings",
     f"min(s) AS lo, max(s) AS hi FROM [{T}] GROUP BY 1", MIX,
     [{"lo": b"blue", "hi": b"red"}]),
    ("cardinality_estimates",
     f"cardinality(s) AS c FROM [{T}] GROUP BY 1", MIX, [{"c": 3}]),
    ("group_by_function_result",
     f"v % 2 AS p, count(*) AS n FROM [{T}] WHERE v != 0 GROUP BY v % 2",
     KV8, [{"p": 0, "n": 3}, {"p": 1, "n": 4}]),
    ("group_by_regex_class",
     f"regex_partial_match('r', s) AS has_r, count(*) AS n FROM [{T}] "
     "WHERE s != '' GROUP BY regex_partial_match('r', s)", MIX,
     [{"has_r": True, "n": 3}, {"has_r": False, "n": 2}]),
    ("first_in_group",
     f"s, first(v) AS f FROM [{T}] WHERE s = 'red' GROUP BY s", MIX,
     [{"s": b"red", "f": 10}]),
    ("sum_all_null_group_is_null",
     f"s, sum(v) AS t FROM [{T}] WHERE k = 5 GROUP BY s", MIX,
     [{"s": b"blue", "t": None}]),
    ("global_aggregate_empty_input",
     f"sum(v) AS t FROM [{T}] WHERE v > 999 GROUP BY 1", MIX, []),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in AGG2],
                         ids=[c[0] for c in AGG2])
def test_aggregate_breadth(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# F. uint64 / double / boolean edges
# ---------------------------------------------------------------------------

BIG = (1 << 63) + 5          # exceeds int64: lives only in uint64
EDGE = [
    ("u64_big_roundtrip", f"u FROM [{T}] WHERE u > 0",
     tbl([(1, BIG)], U64_COLS), [{"u": BIG}]),
    ("u64_compare_large", f"k FROM [{T}] WHERE u >= {BIG}",
     tbl([(1, BIG), (2, 7)], U64_COLS), [{"k": 1}]),
    ("u64_sum", f"sum(u) AS r FROM [{T}] GROUP BY 1",
     tbl([(1, 3), (2, 4)], U64_COLS), [{"r": 7}]),
    ("u64_modulo", f"u % 10 AS r FROM [{T}]",
     tbl([(1, BIG)], U64_COLS), [{"r": BIG % 10}]),
    ("int_overflow_wraps", f"v + v AS r FROM [{T}]",
     tbl([(1, (1 << 62))]), [{"r": -(1 << 63)}]),
    ("int_min_abs_wraps", f"abs(v) AS r FROM [{T}]",
     tbl([(1, -(1 << 63))]), [{"r": -(1 << 63)}]),
    ("double_inf_compare", f"k FROM [{T}] WHERE x / 0.0 > 1e308",
     tbl([(1, 1.0), (2, -1.0)], DBL_COLS), [{"k": 1}]),
    ("double_nan_never_equal", f"k FROM [{T}] WHERE x / 0.0 = x / 0.0",
     tbl([(1, 0.0)], DBL_COLS), []),
    ("double_neg_zero_equals_zero", f"k FROM [{T}] WHERE x = 0.0",
     tbl([(1, -0.0)], DBL_COLS), [{"k": 1}]),
    ("double_precise_small", f"x * 3.0 AS r FROM [{T}]",
     tbl([(1, 0.5)], DBL_COLS), [{"r": 1.5}]),
    ("bool_and_or",
     f"k FROM [{T}] WHERE boolean(v) AND NOT boolean(v - v)",
     tbl([(1, 2), (2, 0)]), [{"k": 1}]),
    ("int64_cast_truncates_toward_zero", f"int64(x) AS r FROM [{T}]",
     tbl([(1, -3.9)], DBL_COLS), [{"r": -3}]),
    ("double_cast_of_u64", f"double(u) AS r FROM [{T}]",
     tbl([(1, 4)], U64_COLS), [{"r": 4.0}]),
    ("uint64_of_negative_wraps", f"uint64(v) AS r FROM [{T}]",
     tbl([(1, -1)]), [{"r": (1 << 64) - 1}]),
    ("shift_by_63", f"v << 62 AS r FROM [{T}]", tbl([(1, 1)]),
     [{"r": 1 << 62}]),
    ("xor_self_is_zero", f"v ^ v AS r FROM [{T}]", tbl([(1, 12345)]),
     [{"r": 0}]),
    ("division_by_nonzero_after_filter",
     f"v / k AS r FROM [{T}] WHERE k != 0", tbl([(0, 5), (2, 10)]),
     [{"r": 5}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in EDGE],
                         ids=[c[0] for c in EDGE])
def test_numeric_edges(query, tables, expected):
    run(query, tables, expected)


# ---------------------------------------------------------------------------
# G. ORDER BY / LIMIT / OFFSET combinations
# ---------------------------------------------------------------------------

ORD = [
    ("order_limit", f"v FROM [{T}] ORDER BY v ASC LIMIT 3", KV8,
     [{"v": 0}, {"v": 7}, {"v": 14}]),
    ("order_desc_limit", f"v FROM [{T}] ORDER BY v DESC LIMIT 2", KV8,
     [{"v": 49}, {"v": 42}]),
    ("order_offset", f"v FROM [{T}] ORDER BY v ASC, k ASC "
     "OFFSET 2 LIMIT 2", KV8, [{"v": 14}, {"v": 21}]),
    ("order_by_two_keys",
     # Null string sorts first ascending.
     f"s, v FROM [{T}] WHERE v != 0 ORDER BY s ASC, v DESC LIMIT 3",
     MIX, [{"s": None, "v": 40}, {"s": b"blue", "v": 20},
           {"s": b"green", "v": 60}]),
    ("order_by_expression",
     f"k FROM [{T}] ORDER BY v % 5 ASC, k ASC LIMIT 2", KV8,
     [{"k": 0}, {"k": 5}]),
    ("order_nulls_first_asc",
     f"k FROM [{T}] ORDER BY v ASC LIMIT 2", MIX,
     [{"k": 5}, {"k": 1}]),
    ("limit_larger_than_input", f"k FROM [{T}] ORDER BY k ASC LIMIT 99",
     tbl([(1, 1), (2, 2)]), [{"k": 1}, {"k": 2}]),
    ("offset_past_end", f"k FROM [{T}] ORDER BY k ASC OFFSET 99 LIMIT 5",
     tbl([(1, 1)]), []),
    ("order_strings_desc",
     f"s FROM [{T}] WHERE s != '' ORDER BY s DESC LIMIT 2", MIX,
     [{"s": b"red"}, {"s": b"red"}]),
    ("distinct_then_order",
     f"v % 5 AS m FROM [{T}] GROUP BY v % 5 ORDER BY v % 5 ASC LIMIT 10",
     KV8, [{"m": 0}, {"m": 1}, {"m": 2}, {"m": 3}, {"m": 4}],
     True),
]


@pytest.mark.parametrize("query,tables,expected,ordered",
                         [(c[1], c[2], c[3],
                           c[4] if len(c) > 4 else True)
                          for c in ORD],
                         ids=[c[0] for c in ORD])
def test_ordering_breadth(query, tables, expected, ordered):
    run(query, tables, expected, ordered=ordered)


# ---------------------------------------------------------------------------
# H. composition: concat/upper/lower/length/timestamps interplay
# ---------------------------------------------------------------------------

TS = tbl([(1, 0), (2, 3_600), (3, 90_061), (4, 694_861),
          (5, 31_536_000), (6, None)],
         [("k", "int64", "ascending"), ("t", "int64")])

COMPOSE = [
    ("upper_lower_roundtrip",
     f"lower(upper(s)) AS r FROM [{T}] WHERE k = 2", WORDS,
     [{"r": b"banana"}]),
    ("concat_columns", f"concat(s, s) AS r FROM [{T}] WHERE k = 1",
     WORDS, [{"r": b"appleapple"}]),
    ("concat_literal", f"concat(s, '!') AS r FROM [{T}] WHERE k = 3",
     WORDS, [{"r": b"cherry!"}]),
    ("concat_null_is_null", f"concat(s, 'x') AS r FROM [{T}] WHERE k = 4",
     WORDS, [{"r": None}]),
    ("length_empty", f"length(s) AS r FROM [{T}] WHERE k = 5", WORDS,
     [{"r": 0}]),
    ("length_null", f"length(s) AS r FROM [{T}] WHERE k = 4", WORDS,
     [{"r": None}]),
    ("length_of_upper", f"length(upper(s)) AS r FROM [{T}] WHERE k = 6",
     WORDS, [{"r": 9}]),
    ("upper_in_where", f"k FROM [{T}] WHERE upper(s) = 'BANANA'", WORDS,
     [{"k": 2}]),
    ("lower_group_by",
     f"lower(substr(s, 0, 1)) AS c, count(*) AS n FROM [{T}] "
     "WHERE s != '' GROUP BY lower(substr(s, 0, 1))", WORDS,
     [{"c": b"a", "n": 3}, {"c": b"b", "n": 1}, {"c": b"c", "n": 1}]),
    ("is_prefix_literal", f"k FROM [{T}] WHERE is_prefix('app', s)",
     WORDS, [{"k": 1}, {"k": 6}]),
    ("is_substr_literal", f"k FROM [{T}] WHERE is_substr('err', s)",
     WORDS, [{"k": 3}]),
    ("ts_floor_hour", f"timestamp_floor_hour(t) AS r FROM [{T}] "
     "WHERE k = 3", TS, [{"r": 90_000}]),
    ("ts_floor_day", f"timestamp_floor_day(t) AS r FROM [{T}] "
     "WHERE k = 4", TS, [{"r": 691_200}]),
    ("ts_floor_year", f"timestamp_floor_year(t) AS r FROM [{T}] "
     "WHERE k = 5", TS, [{"r": 31_536_000}]),
    ("ts_floor_null", f"timestamp_floor_day(t) AS r FROM [{T}] "
     "WHERE k = 6", TS, [{"r": None}]),
    ("ts_floor_zero", f"timestamp_floor_week(t) AS r FROM [{T}] "
     "WHERE k = 1", TS, [{"r": -259_200}]),
    ("ts_group_by_hour",
     f"timestamp_floor_hour(t) AS h, count(*) AS n FROM [{T}] "
     "WHERE t != 0 GROUP BY timestamp_floor_hour(t)", TS,
     [{"h": 3_600, "n": 1}, {"h": 90_000, "n": 1},
      {"h": 694_800, "n": 1}, {"h": 31_536_000, "n": 1}]),
    ("farm_hash_of_int", f"k FROM [{T}] WHERE farm_hash(v) != 0",
     tbl([(1, 5)]), [{"k": 1}]),
    ("farm_hash_multi_arg",
     f"k FROM [{T}] WHERE farm_hash(k, v) = farm_hash(k, v)",
     tbl([(1, 5)]), [{"k": 1}]),
    ("hash_distributes",
     f"farm_hash(v) % 4 AS b, count(*) AS n FROM [{T}] "
     "GROUP BY farm_hash(v) % 4 HAVING count(*) > 0",
     tbl([(i, i) for i in range(40)]),
     None),
    ("min_of_mixed_null",
     f"min_of(v, if_null(v, 99)) AS r FROM [{T}]",
     tbl([(1, None)]), [{"r": 99}]),
    ("concat_of_substr",
     f"concat(substr(s, 0, 3), '...') AS r FROM [{T}] WHERE k = 2",
     WORDS, [{"r": b"Ban..."}]),
    ("regex_on_upper",
     f"k FROM [{T}] WHERE regex_full_match('[A-Z ]+', upper(s))",
     WORDS, [{"k": 1}, {"k": 2}, {"k": 3}, {"k": 6}]),
    ("nested_if_null_strings",
     f"if_null(s, 'missing') AS r FROM [{T}] WHERE k = 4", WORDS,
     [{"r": b"missing"}]),
    ("case_over_length",
     f"CASE WHEN length(s) > 5 THEN 'long' ELSE 'short' END AS r "
     f"FROM [{T}] WHERE k IN (1, 3)", WORDS,
     [{"r": b"short"}, {"r": b"long"}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in COMPOSE],
                         ids=[c[0] for c in COMPOSE])
def test_composition(query, tables, expected):
    if expected is None:
        rows = evaluate(query, tables)
        assert sum(r["n"] for r in rows) == 40    # partitions cover all
    else:
        run(query, tables, expected)


# ---------------------------------------------------------------------------
# I. SPMD dual-check: the same queries through the 8-device mesh
# ---------------------------------------------------------------------------

SPMD_SCHEMA = [("k", "int64", "ascending"), ("s", "string"),
               ("v", "int64"), ("x", "double")]


def _spmd_fixture():
    import numpy as np

    from ytsaurus_tpu.chunks import ColumnarChunk
    from ytsaurus_tpu.parallel.mesh import make_mesh
    from ytsaurus_tpu.schema import TableSchema

    rng = np.random.default_rng(7)
    words = np.array([b"alpha", b"beta", b"gamma", b"delta", b""],
                     dtype=object)
    schema = TableSchema.make(SPMD_SCHEMA)
    chunks = []
    base = 0
    for shard in range(8):
        n = 40 + shard * 7
        rows = []
        for i in range(n):
            w = words[int(rng.integers(0, len(words)))]
            rows.append((base + i,
                         None if i % 11 == 0 else w,
                         None if i % 13 == 0 else int(rng.integers(0, 50)),
                         float(rng.uniform(-5, 5))))
        base += n
        chunks.append(ColumnarChunk.from_rows(schema, rows))
    return make_mesh(8), schema, chunks


SPMD_QUERIES = [
    "regex_spmd_filter",
    "regex_replace_spmd",
    "substr_spmd_group",
    "parse_like_spmd",
    "sha_len_spmd",
    "bigb_spmd_group",
    "upper_spmd",
    "case_spmd",
    "in_spmd",
    "between_spmd",
    "hash_mod_spmd",
    "minmax_spmd",
    "having_spmd",
    "ts_floor_spmd",
    "ilike_spmd",
    "tuple_in_spmd",
    "like_escape_spmd",
    "order_two_dirs_spmd",
]

_SPMD_SQL = {
    "regex_spmd_filter":
        f"k FROM [{T}] WHERE regex_partial_match('a', s)",
    "regex_replace_spmd":
        f"regex_replace_all('a', s, '_') AS r, count(*) AS n FROM [{T}] "
        "GROUP BY regex_replace_all('a', s, '_')",
    "substr_spmd_group":
        f"substr(s, 0, 1) AS c, count(*) AS n FROM [{T}] "
        "GROUP BY substr(s, 0, 1)",
    "parse_like_spmd":
        f"k FROM [{T}] WHERE s LIKE '%eta'",
    "sha_len_spmd":
        f"length(sha256(s)) AS l, count(*) AS n FROM [{T}] "
        "GROUP BY length(sha256(s))",
    "bigb_spmd_group":
        f"bigb_hash(s) % 4 AS b, count(*) AS n FROM [{T}] "
        "WHERE s != '' GROUP BY bigb_hash(s) % 4",
    "upper_spmd":
        f"upper(s) AS u, count(*) AS n FROM [{T}] GROUP BY upper(s)",
    "case_spmd":
        f"CASE WHEN v < 25 THEN 'lo' ELSE 'hi' END AS c, count(*) AS n "
        f"FROM [{T}] WHERE v != 0 GROUP BY "
        "CASE WHEN v < 25 THEN 'lo' ELSE 'hi' END",
    "in_spmd":
        f"k FROM [{T}] WHERE s IN ('alpha', 'gamma') AND v IN "
        "(1, 2, 3, 4, 5, 6, 7)",
    "between_spmd":
        f"k FROM [{T}] WHERE v BETWEEN 10 AND 20 AND s BETWEEN "
        "'beta' AND 'delta'",
    "hash_mod_spmd":
        f"farm_hash(v) % 8 AS b, count(*) AS n FROM [{T}] "
        "GROUP BY farm_hash(v) % 8",
    "minmax_spmd":
        f"min_of(v, 25) AS m, count(*) AS n FROM [{T}] "
        "GROUP BY min_of(v, 25)",
    "having_spmd":
        f"s, sum(v) AS t FROM [{T}] GROUP BY s HAVING sum(v) > 100",
    "ts_floor_spmd":
        f"timestamp_floor_hour(v * 600) AS h, count(*) AS n FROM [{T}] "
        "GROUP BY timestamp_floor_hour(v * 600)",
    "ilike_spmd":
        f"k FROM [{T}] WHERE s ILIKE 'ALPHA'",
    "tuple_in_spmd":
        f"k FROM [{T}] WHERE (s, v) IN (('alpha', 1), ('beta', 2), "
        "('gamma', 3))",
    "like_escape_spmd":
        f"k FROM [{T}] WHERE s LIKE '%a' AND s NOT LIKE 'a\\\\_%'",
    "order_two_dirs_spmd":
        f"k, v FROM [{T}] WHERE v != 0 ORDER BY v DESC, k ASC LIMIT 9",
}


@pytest.fixture(scope="module")
def spmd_env():
    return _spmd_fixture()


@pytest.mark.parametrize("case", SPMD_QUERIES)
def test_spmd_matches_local(case, spmd_env):
    """Every new-function query family answers IDENTICALLY on the local
    single-chunk path and the 8-shard SPMD path (the dual-check the
    original corpus established, extended to the new registry tail)."""
    from ytsaurus_tpu.chunks.columnar import concat_chunks
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        ShardedTable,
    )
    from ytsaurus_tpu.query.builder import build_query

    mesh, schema, chunks = spmd_env
    query = _SPMD_SQL[case]
    local = evaluate(query, {T: concat_chunks(chunks)})
    plan = build_query(query, {T: schema})
    table = ShardedTable.from_chunks(mesh, chunks)
    spmd = DistributedEvaluator(mesh).run(plan, table).to_rows()
    if "ORDER BY" in query:
        # Deterministic order (unique tiebreak): the SEQUENCE is the
        # contract — canonicalizing would let a lost merge re-sort
        # slip through.
        assert spmd == local, f"SPMD order diverged for: {query}"
        return

    def canon(rows):
        return sorted(
            (tuple(sorted((k, repr(v)) for k, v in r.items()))
             for r in rows))
    assert canon(spmd) == canon(local), \
        f"SPMD diverged from local for: {query}"


# ---------------------------------------------------------------------------
# J. join + subquery breadth with the new functions
# ---------------------------------------------------------------------------

D = "//d"
DIM_COLS = [("g", "int64", "ascending"), ("name", "string")]


def _two(rows_f, rows_d):
    return {T: ([("k", "int64", "ascending"), ("g", "int64"),
                 ("v", "int64")], rows_f),
            D: (DIM_COLS, rows_d)}


FACTS = [(1, 0, 10), (2, 1, 20), (3, 0, 30), (4, 2, 40), (5, 1, 50)]
DIMS = [(0, "zero"), (1, "one"), (3, "three")]

JOIN2 = [
    ("join_then_regex",
     f"k FROM [{T}] JOIN [{D}] USING g "
     "WHERE regex_partial_match('o', name)",
     _two(FACTS, DIMS), [{"k": 1}, {"k": 2}, {"k": 3}, {"k": 5}]),
    ("join_project_upper",
     f"k, upper(name) AS u FROM [{T}] JOIN [{D}] USING g WHERE k = 2",
     _two(FACTS, DIMS), [{"k": 2, "u": b"ONE"}]),
    ("join_group_by_dim",
     f"name, sum(v) AS t FROM [{T}] JOIN [{D}] USING g GROUP BY name",
     _two(FACTS, DIMS),
     [{"name": b"zero", "t": 40}, {"name": b"one", "t": 70}]),
    ("join_unmatched_dropped",
     f"k FROM [{T}] JOIN [{D}] USING g WHERE g = 2",
     _two(FACTS, DIMS), []),
    ("left_join_keeps_unmatched",
     f"k, name FROM [{T}] LEFT JOIN [{D}] USING g WHERE k = 4",
     _two(FACTS, DIMS), [{"k": 4, "name": None}]),
    ("join_substr_on_dim",
     f"substr(name, 0, 1) AS c, count(*) AS n FROM [{T}] "
     f"JOIN [{D}] USING g GROUP BY substr(name, 0, 1)",
     _two(FACTS, DIMS), [{"c": b"z", "n": 2}, {"c": b"o", "n": 2}]),
    ("join_having",
     f"name, count(*) AS n FROM [{T}] JOIN [{D}] USING g "
     "GROUP BY name HAVING count(*) >= 2",
     _two(FACTS, DIMS),
     [{"name": b"zero", "n": 2}, {"name": b"one", "n": 2}]),
    ("join_where_both_sides",
     f"k FROM [{T}] JOIN [{D}] USING g WHERE v > 15 AND name != 'zero'",
     _two(FACTS, DIMS), [{"k": 2}, {"k": 5}]),
    ("join_order_by_dim",
     f"k FROM [{T}] JOIN [{D}] USING g ORDER BY name ASC, k ASC LIMIT 3",
     _two(FACTS, DIMS), [{"k": 2}, {"k": 5}, {"k": 1}]),
    ("join_then_in",
     f"k FROM [{T}] JOIN [{D}] USING g WHERE name IN ('one')",
     _two(FACTS, DIMS), [{"k": 2}, {"k": 5}]),
    ("join_if_null_dim",
     f"k, if_null(name, '?') AS n FROM [{T}] LEFT JOIN [{D}] USING g "
     "WHERE k = 4", _two(FACTS, DIMS), [{"k": 4, "n": b"?"}]),
    ("join_empty_dim_table",
     f"k FROM [{T}] JOIN [{D}] USING g", _two(FACTS, []), []),
    ("join_count_star",
     f"count(*) AS n FROM [{T}] JOIN [{D}] USING g GROUP BY 1",
     _two(FACTS, DIMS), [{"n": 4}]),
    ("self_like_filter_both",
     f"k FROM [{T}] JOIN [{D}] USING g WHERE name LIKE '%e%' AND "
     "v BETWEEN 10 AND 30", _two(FACTS, DIMS),
     [{"k": 1}, {"k": 2}, {"k": 3}]),
    ("join_transform_dim",
     f"transform(name, ('zero', 'one'), ('Z', 'O')) AS c, "
     f"count(*) AS n FROM [{T}] JOIN [{D}] USING g "
     "GROUP BY transform(name, ('zero', 'one'), ('Z', 'O'))",
     _two(FACTS, DIMS), [{"c": b"Z", "n": 2}, {"c": b"O", "n": 2}]),
    ("join_bigb_group",
     f"bigb_hash(name) % 2 AS b, count(*) AS n FROM [{T}] "
     f"JOIN [{D}] USING g GROUP BY bigb_hash(name) % 2 "
     "HAVING count(*) > 0", _two(FACTS, DIMS), None),
    ("order_by_length_of_name",
     f"name FROM [{T}] JOIN [{D}] USING g "
     "ORDER BY length(name) ASC, name ASC LIMIT 2",
     _two(FACTS, DIMS), [{"name": b"one"}, {"name": b"one"}]),
    ("where_parse_int64_of_concat",
     f"k FROM [{T}] WHERE parse_int64(concat('1', '0')) = 10",
     tbl([(1, 1)]), [{"k": 1}]),
    ("aggregate_of_regex_replace",
     f"count(*) AS n FROM [{T}] WHERE "
     "length(regex_replace_all('0', '100', 'x')) = 3 GROUP BY 1",
     tbl([(1, 1)]), [{"n": 1}]),
    ("substr_out_of_order_args_error_free",
     f"substr('hello', 1, 2) AS r FROM [{T}]", tbl([(1, 1)]),
     [{"r": b"el"}]),
]


@pytest.mark.parametrize("query,tables,expected",
                         [c[1:] for c in JOIN2],
                         ids=[c[0] for c in JOIN2])
def test_join_breadth(query, tables, expected):
    if expected is None:
        rows = evaluate(query, tables)
        assert sum(r["n"] for r in rows) == 4
        return
    ordered = "ORDER BY" in query
    run(query, tables, expected, ordered=ordered)
