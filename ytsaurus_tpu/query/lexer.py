"""QL lexer.

Tokenizes the YT query language surface (ref grammar: library/query/base/
lexer.rl6): case-insensitive keywords, int literals (with `u` suffix for
uint64), doubles, single/double-quoted strings with escapes, identifiers
(dotted for join-qualified columns, `[...]`-bracketed for exotic names), and
the operator set used by expressions.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

from ytsaurus_tpu.errors import EErrorCode, YtError


class TokenKind(enum.Enum):
    IDENT = "ident"
    INT = "int"
    UINT = "uint"
    DOUBLE = "double"
    STRING = "string"
    KEYWORD = "keyword"
    OP = "op"
    EOF = "eof"


KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "join", "left", "on", "using", "as", "and", "or", "not", "in",
    "between", "transform", "case", "when", "then", "else", "end", "if",
    "asc", "desc", "false", "true", "null", "with", "totals", "like", "ilike",
    "escape", "rlike", "regexp", "is", "array", "unnest",
}

# Multi-char operators first (longest match wins).
OPERATORS = [
    "<<", ">>", "!=", "<>", "<=", ">=", "=", "<", ">", "(", ")", ",", "+",
    "-", "*", "/", "%", "|", "&", "~", "^", ".", "[", "]", "#", "?",
]


@dataclass(frozen=True)
class Token:
    kind: TokenKind
    value: object           # str for ident/op/keyword/string; int/float for numbers
    pos: int

    def is_keyword(self, *names: str) -> bool:
        return self.kind is TokenKind.KEYWORD and self.value in names

    def is_op(self, *ops: str) -> bool:
        return self.kind is TokenKind.OP and self.value in ops


def _lex_error(source: str, pos: int, message: str) -> YtError:
    context = source[max(0, pos - 20):pos + 20]
    return YtError(f"{message} at position {pos}: ...{context!r}...",
                   code=EErrorCode.QueryParseError)


def tokenize(source: str) -> list[Token]:
    tokens: list[Token] = []
    i, n = 0, len(source)
    while i < n:
        c = source[i]
        if c.isspace():
            i += 1
            continue
        start = i
        # Comments: -- to end of line.
        if source.startswith("--", i):
            while i < n and source[i] != "\n":
                i += 1
            continue
        # Numbers.
        if c.isdigit() or (c == "." and i + 1 < n and source[i + 1].isdigit()):
            j = i
            is_double = False
            while j < n and (source[j].isdigit() or source[j] in "._eE+-xXabcdefABCDEF"):
                ch = source[j]
                if ch in "+-" and source[j - 1] not in "eE":
                    break
                if ch == "." or ((ch in "eE") and not source.startswith("0x", i)):
                    is_double = True
                j += 1
            text = source[i:j].rstrip("uU")
            suffix_u = source[i:j][len(text):] != ""
            try:
                if is_double and not suffix_u:
                    tokens.append(Token(TokenKind.DOUBLE, float(text), start))
                else:
                    value = int(text, 0)
                    kind = TokenKind.UINT if suffix_u else TokenKind.INT
                    tokens.append(Token(kind, value, start))
            except ValueError:
                raise _lex_error(source, i, f"Bad numeric literal {source[i:j]!r}")
            i = j
            continue
        # Strings.
        if c in "'\"":
            quote = c
            j = i + 1
            buf = []
            while j < n and source[j] != quote:
                if source[j] == "\\" and j + 1 < n:
                    esc = source[j + 1]
                    mapping = {"n": "\n", "t": "\t", "r": "\r", "\\": "\\",
                               "'": "'", '"': '"', "0": "\0"}
                    if esc in mapping:
                        buf.append(mapping[esc])
                        j += 2
                        continue
                    if esc == "x" and j + 3 < n:
                        buf.append(chr(int(source[j + 2:j + 4], 16)))
                        j += 4
                        continue
                buf.append(source[j])
                j += 1
            if j >= n:
                raise _lex_error(source, i, "Unterminated string literal")
            tokens.append(Token(TokenKind.STRING, "".join(buf), start))
            i = j + 1
            continue
        # Bracketed identifiers: [path with anything].
        if c == "[":
            j = source.find("]", i + 1)
            if j != -1 and _expects_identifier(tokens):
                tokens.append(Token(TokenKind.IDENT, source[i + 1:j], start))
                i = j + 1
                continue
        # Identifiers / keywords.
        if c.isalpha() or c in "_$":
            j = i
            while j < n and (source[j].isalnum() or source[j] in "_$"):
                j += 1
            word = source[i:j]
            low = word.lower()
            if low in KEYWORDS:
                tokens.append(Token(TokenKind.KEYWORD, low, start))
            else:
                tokens.append(Token(TokenKind.IDENT, word, start))
            i = j
            continue
        # Operators.
        for op in OPERATORS:
            if source.startswith(op, i):
                tokens.append(Token(TokenKind.OP, op, start))
                i += len(op)
                break
        else:
            raise _lex_error(source, i, f"Unexpected character {c!r}")
    tokens.append(Token(TokenKind.EOF, None, n))
    return tokens


def _expects_identifier(tokens: list[Token]) -> bool:
    """Heuristic: after FROM/JOIN/start, `[` opens a bracketed path/name."""
    if not tokens:
        return True
    last = tokens[-1]
    return last.is_keyword("from", "join") or last.is_op(",", "(") or \
        last.is_keyword("select", "by", "on", "using", "where", "and", "or")
