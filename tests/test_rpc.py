"""Bus/RPC plane tests: framing, services, errors, concurrency, retries.

Mirrors the reference's core/rpc/unittests coverage shape (in-process TCP
loopback service, error propagation, method limits) against the redesigned
asyncio bus.
"""

import threading
import time

import pytest

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.rpc import Channel, RetryingChannel, RpcServer, Service, \
    rpc_method
from ytsaurus_tpu.rpc.packet import PacketError, encode_packet


class EchoService(Service):
    name = "echo"

    @rpc_method()
    def echo(self, body, attachments):
        return {"echo": body.get("value")}, [bytes(a) for a in attachments]

    @rpc_method()
    def fail(self, body, attachments):
        raise YtError("intentional", code=EErrorCode.NoSuchNode,
                      attributes={"path": "//tmp/x"})

    @rpc_method()
    def crash(self, body, attachments):
        raise RuntimeError("boom")

    @rpc_method(concurrency=2)
    def slow(self, body, attachments):
        time.sleep(float(body.get("delay", 0.2)))
        return {"done": True}


@pytest.fixture(scope="module")
def server():
    srv = RpcServer([EchoService()])
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture()
def channel(server):
    ch = Channel(server.address, timeout=30)
    yield ch
    ch.close()


def test_echo_roundtrip(channel):
    body, attachments = channel.call(
        "echo", "echo", {"value": 42}, [b"blob-one", b"\x00" * 1024])
    assert body["echo"] == 42
    assert attachments == [b"blob-one", b"\x00" * 1024]


def test_error_propagates_code_and_attributes(channel):
    with pytest.raises(YtError) as ei:
        channel.call("echo", "fail", {})
    assert ei.value.code == EErrorCode.NoSuchNode
    assert ei.value.attributes["path"] == b"//tmp/x"
    assert "intentional" in ei.value.message


def test_unhandled_exception_wrapped(channel):
    with pytest.raises(YtError) as ei:
        channel.call("echo", "crash", {})
    assert "boom" in ei.value.message


def test_no_such_method(channel):
    with pytest.raises(YtError) as ei:
        channel.call("echo", "nope", {})
    assert ei.value.code == EErrorCode.NoSuchMethod
    with pytest.raises(YtError) as ei:
        channel.call("ghost", "echo", {})
    assert ei.value.code == EErrorCode.NoSuchService


def test_concurrent_calls_multiplex(channel):
    results = {}
    def worker(i):
        body, _ = channel.call("echo", "echo", {"value": i})
        results[i] = body["echo"]
    threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == {i: i for i in range(16)}


def test_slow_calls_do_not_block_fast_ones(server):
    ch = Channel(server.address, timeout=30)
    done = []
    t = threading.Thread(
        target=lambda: (ch.call("echo", "slow", {"delay": 1.0}),
                        done.append("slow")))
    t.start()
    t0 = time.monotonic()
    ch.call("echo", "echo", {"value": 1})
    assert time.monotonic() - t0 < 0.9        # not serialized behind slow
    t.join()
    assert done == ["slow"]
    ch.close()


def test_large_attachment(channel):
    blob = bytes(range(256)) * (1 << 14)      # 4 MiB
    body, attachments = channel.call("echo", "echo", {"value": 0}, [blob])
    assert attachments[0] == blob


def test_packet_corruption_detected():
    import asyncio
    from ytsaurus_tpu.rpc.packet import read_packet
    raw = bytearray(encode_packet([b"hello", b"world"]))
    raw[-1] ^= 0xFF                           # flip a byte in the last part

    async def run():
        reader = asyncio.StreamReader()
        reader.feed_data(bytes(raw))
        reader.feed_eof()
        await read_packet(reader)

    with pytest.raises(PacketError, match="checksum"):
        asyncio.new_event_loop().run_until_complete(run())


def test_retrying_channel_survives_server_restart():
    svc = EchoService()
    srv = RpcServer([svc])
    srv.start()
    port = srv.port
    ch = RetryingChannel(Channel(srv.address, timeout=10))
    assert ch.call("echo", "echo", {"value": 1})[0]["echo"] == 1
    srv.stop()
    # Restart on the same port.
    srv2 = RpcServer([svc], port=port)
    srv2.start()
    assert ch.call("echo", "echo", {"value": 2})[0]["echo"] == 2
    ch.close()
    srv2.stop()


def test_dead_peer_raises_peer_unavailable():
    ch = RetryingChannel(Channel("127.0.0.1:1", timeout=2), attempts=2,
                         backoff=0.05)
    with pytest.raises(YtError) as ei:
        ch.call("echo", "echo", {})
    assert ei.value.code == EErrorCode.PeerUnavailable


class _LatencyChannel:
    """Deterministic fake peer with a fixed response latency."""

    def __init__(self, latency: float, tag: str, fail: bool = False):
        self.latency = latency
        self.tag = tag
        self.fail = fail
        self.calls = 0
        self.address = tag

    def call(self, service, method, body=None, attachments=(), *a, **kw):
        self.calls += 1
        time.sleep(self.latency)
        if self.fail:
            raise YtError(f"{self.tag} down",
                          code=EErrorCode.TransportError)
        return {"from": self.tag}, []

    def close(self):
        pass


def test_hedging_channel_bounds_tail_latency():
    """VERDICT r2 #7: with one slow peer, p99 is bounded by the hedging
    delay + the healthy peer's latency, not the slow peer's latency."""
    from ytsaurus_tpu.rpc import HedgingChannel

    slow = _LatencyChannel(1.5, "slow")
    fast = _LatencyChannel(0.01, "fast")
    ch = HedgingChannel(slow, fast, hedging_delay=0.05)
    latencies = []
    for _ in range(10):
        t0 = time.monotonic()
        body, _ = ch.call("echo", "echo", {})
        latencies.append(time.monotonic() - t0)
        assert body["from"] == "fast"
    assert max(latencies) < 1.0, f"tail not bounded: {max(latencies):.3f}s"
    ch.close()


def test_hedging_channel_primary_fast_path_and_failure():
    from ytsaurus_tpu.rpc import HedgingChannel

    fast = _LatencyChannel(0.0, "primary")
    backup = _LatencyChannel(0.0, "backup")
    ch = HedgingChannel(fast, backup, hedging_delay=0.2)
    assert ch.call("e", "e", {})[0]["from"] == "primary"
    assert backup.calls == 0                   # healthy primary: no hedge
    ch.close()
    # Fast primary failure hedges immediately (no delay wait).
    broken = _LatencyChannel(0.0, "broken", fail=True)
    backup2 = _LatencyChannel(0.0, "backup2")
    ch2 = HedgingChannel(broken, backup2, hedging_delay=5.0)
    t0 = time.monotonic()
    assert ch2.call("e", "e", {})[0]["from"] == "backup2"
    assert time.monotonic() - t0 < 1.0
    ch2.close()


def test_hedging_channel_never_hedges_mutations():
    from ytsaurus_tpu.rpc import HedgingChannel

    slow = _LatencyChannel(0.3, "slow")
    backup = _LatencyChannel(0.0, "backup")
    ch = HedgingChannel(slow, backup, hedging_delay=0.01)
    body, _ = ch.call("e", "e", {}, idempotent=False)
    assert body["from"] == "slow" and backup.calls == 0
    ch.close()


def test_nonidempotent_retries_connect_failure():
    """A connect-refused transport failure provably never dispatched, so
    even a non-idempotent call retries it (ADVICE r3: only a mid-call
    drop is ambiguous and must surface)."""
    ch = RetryingChannel(Channel("127.0.0.1:1", timeout=2), attempts=2,
                         backoff=0.05)
    with pytest.raises(YtError) as ei:
        ch.call("echo", "echo", {}, idempotent=False)
    # Exhausted retries (not surfaced on attempt 1): PeerUnavailable.
    assert ei.value.code == EErrorCode.PeerUnavailable
    ch.close()


def test_nonidempotent_midcall_drop_surfaces():
    """A connection that dies AFTER the request was dispatched must
    surface to a non-idempotent caller instead of being resent (the
    mutation may have executed on the dying peer).  Emulated
    deterministically at the channel layer: a dispatched TransportError
    (no dispatched=False marker) must not be retried."""
    from ytsaurus_tpu.rpc.channel import _never_dispatched
    dispatched_err = YtError("conn dropped mid-call",
                             code=EErrorCode.TransportError)
    undispatched_err = YtError("connect refused",
                               code=EErrorCode.TransportError,
                               attributes={"dispatched": False})
    assert not _never_dispatched(dispatched_err)
    assert _never_dispatched(undispatched_err)

    class OneShotChannel:
        address = "fake"
        calls = 0

        def call(self, *a, **kw):
            OneShotChannel.calls += 1
            raise dispatched_err

        def close(self):
            pass

    ch = RetryingChannel(OneShotChannel(), attempts=3, backoff=0.01)
    with pytest.raises(YtError) as ei:
        ch.call("echo", "echo", {}, idempotent=False)
    assert ei.value.code == EErrorCode.TransportError
    assert OneShotChannel.calls == 1          # surfaced, not retried
