"""Client channels: synchronous facade over the asyncio bus.

One shared background event-loop thread per process frames packets for all
channels (the analog of the reference's shared bus thread pool); callers
block on concurrent.futures handed across the loop boundary.  A
RetryingChannel wraps transport failures (never application YtErrors) with
reconnect + backoff, like core/rpc/retrying_channel.h.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import itertools
import threading
import time

from ytsaurus_tpu import yson
from ytsaurus_tpu.config import retry_policy
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.rpc.packet import PacketError, read_packet, write_packet
from ytsaurus_tpu.rpc.server import error_from_wire
from ytsaurus_tpu.rpc.wire import decode_body, encode_body
from ytsaurus_tpu.utils import failpoints
from ytsaurus_tpu.utils.logging import get_logger
from ytsaurus_tpu.utils import sanitizers

logger = get_logger("rpc")

# Injected send failures look exactly like a dropped connection (a
# dispatched transport error), so the retrying/failover/hedging wrappers
# exercise their real mid-call recovery ladders.
_FP_SEND = failpoints.register_site(
    "rpc.channel.send",
    error=lambda s: YtError(f"injected transport failure at {s}",
                            code=EErrorCode.TransportError))
# Raises ConnectionError, NOT YtError: the injected fault must walk the
# same never-dispatched path a real refused connect takes (the caller
# wraps it with dispatched=False, so even non-idempotent calls resend).
_FP_CONNECT = failpoints.register_site(
    "rpc.channel.connect",
    error=lambda s: ConnectionError(f"injected connect failure at {s}"))

# guards: _loop
_loop_lock = sanitizers.register_lock("channel._loop_lock", hot=False)
_loop: asyncio.AbstractEventLoop | None = None


def _shared_loop() -> asyncio.AbstractEventLoop:
    global _loop
    with _loop_lock:
        if _loop is None or _loop.is_closed():
            loop = asyncio.new_event_loop()
            thread = threading.Thread(
                target=loop.run_forever, daemon=True, name="rpc-client-loop")
            thread.start()
            _loop = loop
        return _loop


class _ConnState:
    """One live TCP connection: reader pump + pending request futures."""

    def __init__(self, reader, writer):
        self.reader = reader
        self.writer = writer
        self.pending: dict[int, asyncio.Future] = {}
        self.task: asyncio.Future | None = None
        self.alive = True


class Channel:
    """A connection to one RPC endpoint ("host:port")."""

    def __init__(self, address: str, timeout: float = 60.0):
        self.address = address
        host, port = address.rsplit(":", 1)
        self._host, self._port = host, int(port)
        self.timeout = timeout
        self._rid = itertools.count(1)
        # guards: _conn
        self._lock = sanitizers.register_lock("channel.Channel._lock")
        self._connect_lock: asyncio.Lock | None = None
        self._conn: _ConnState | None = None

    # -- wire ------------------------------------------------------------------

    async def _connect(self) -> "_ConnState":
        _FP_CONNECT.hit()
        reader, writer = await asyncio.open_connection(self._host, self._port)
        state = _ConnState(reader=reader, writer=writer)

        async def pump():
            try:
                while True:
                    parts = await read_packet(reader)
                    envelope = yson.loads(parts[0], encoding=None)
                    rid = int(envelope["rid"])
                    fut = state.pending.pop(rid, None)
                    if fut is not None and not fut.done():
                        fut.set_result((envelope, parts))
            except (asyncio.IncompleteReadError, ConnectionError,
                    PacketError, asyncio.CancelledError) as exc:
                state.alive = False
                for fut in state.pending.values():
                    if not fut.done():
                        fut.set_exception(ConnectionError(str(exc)))
                state.pending.clear()
                writer.close()

        state.task = asyncio.ensure_future(pump())
        return state

    async def _call_async(self, service: str, method: str, body,
                          attachments, timeout: float, trace_wire=None):
        if self._connect_lock is None:
            self._connect_lock = asyncio.Lock()
        async with self._connect_lock:
            # Serialized: concurrent first calls must share ONE connection
            # (unserialized, each would open and leak its own socket+pump).
            with self._lock:
                state = self._conn
            if state is None or not state.alive:
                try:
                    state = await self._connect()
                except (ConnectionError, OSError) as exc:
                    # The request was never sent: safe to resend even a
                    # non-idempotent call (see RetryingChannel).
                    raise YtError(
                        f"cannot connect to {self.address}: {exc}",
                        code=EErrorCode.TransportError,
                        attributes={"dispatched": False}) from exc
                with self._lock:
                    self._conn = state
        rid = next(self._rid)
        fut = asyncio.get_event_loop().create_future()
        state.pending[rid] = fut
        # No await between registration and this check, so the pump cannot
        # have died without either failing our future or being seen here.
        if not state.alive:
            state.pending.pop(rid, None)
            raise YtError(
                f"connection to {self.address} lost before dispatch",
                code=EErrorCode.TransportError,
                attributes={"dispatched": False})
        req = {"rid": rid, "kind": "req", "service": service,
               "method": method}
        if trace_wire is not None:
            req["trace"] = trace_wire
        envelope = yson.dumps(req, binary=True)
        wire_body = yson.dumps(encode_body(body if body is not None else {}),
                               binary=True)
        try:
            await write_packet(state.writer, [envelope, wire_body,
                                              *attachments])
            envelope, parts = await asyncio.wait_for(fut, timeout)
        except (ConnectionError, asyncio.IncompleteReadError):
            with self._lock:
                if self._conn is state:
                    self._conn = None
            raise
        except asyncio.TimeoutError:
            # A timed-out connection is suspect (half-dead peer, stopped
            # server loop) — drop it so the next attempt reconnects.
            state.pending.pop(rid, None)
            state.alive = False
            state.writer.close()
            with self._lock:
                if self._conn is state:
                    self._conn = None
            raise YtError(
                f"RPC {service}.{method} to {self.address} timed out "
                f"after {timeout}s", code=EErrorCode.RpcTimeout) from None
        kind = envelope["kind"]
        if kind == b"err":
            raise error_from_wire(yson.loads(parts[1], encoding=None))
        body = decode_body(yson.loads(parts[1], encoding=None)) \
            if len(parts) > 1 else {}
        return body, list(parts[2:])

    # -- public sync API -------------------------------------------------------

    def call(self, service: str, method: str, body=None,
             attachments=(), timeout: float | None = None,
             idempotent: bool = True):
        """Returns (body: dict, attachments: list[bytes]); raises YtError.
        `idempotent` is accepted (and ignored) so every channel shares
        one call signature — a bare Channel never resends, so the flag
        only matters to the retrying/failover/hedging wrappers."""
        timeout = timeout if timeout is not None else self.timeout
        _FP_SEND.hit()
        # Trace context is captured HERE, on the calling thread — contextvars
        # do not flow into the shared loop thread.
        from ytsaurus_tpu.utils.tracing import current_trace
        ambient = current_trace()
        trace_wire = ambient.to_wire() if ambient is not None else None
        loop = _shared_loop()
        fut = asyncio.run_coroutine_threadsafe(
            self._call_async(service, method, body, list(attachments),
                             timeout, trace_wire), loop)
        try:
            return fut.result(timeout=timeout + 15)
        except concurrent.futures.TimeoutError as exc:
            fut.cancel()
            raise YtError(
                f"RPC {service}.{method} to {self.address} stalled on the "
                "client loop", code=EErrorCode.RpcTimeout) from exc
        except (ConnectionError, OSError, asyncio.IncompleteReadError) as exc:
            raise YtError(
                f"transport failure calling {service}.{method} on "
                f"{self.address}: {exc}",
                code=EErrorCode.TransportError) from exc

    def close(self) -> None:
        with self._lock:
            state, self._conn = self._conn, None
        if state is not None:
            loop = _shared_loop()
            if state.task is not None:
                loop.call_soon_threadsafe(state.task.cancel)
            loop.call_soon_threadsafe(state.writer.close)


def _never_dispatched(err: "YtError") -> bool:
    """True when a transport failure provably happened BEFORE the request
    was sent (connection refused), making even a non-idempotent resend
    safe.  A mid-call drop proves nothing — the peer may have executed
    the mutation before dying."""
    return err.code == EErrorCode.TransportError and \
        err.attributes.get("dispatched") is False


class _RetryBudget:
    """Token-bucket retry budget (ISSUE 17): each retry SPENDS one
    token; each successful call DEPOSITS `refill` tokens (capped at
    `capacity`); a throttled outcome deposits nothing — the budget is
    admission-aware, so a cluster that is shedding load watches retry
    traffic decay to the deposit rate instead of multiplying.

    Thread-safe; one budget per RetryingChannel instance, shared by
    every call through it (the budget models the CHANNEL's standing
    with the peer, not one request's patience)."""

    __slots__ = ("capacity", "refill", "_tokens", "_lock",
                 "spent_n", "exhausted_n")

    def __init__(self, capacity: int, refill: float):
        self.capacity = float(capacity)
        self.refill = refill
        self._tokens = float(capacity)     # starts full: first failures
        self._lock = threading.Lock()      # may retry immediately
        self.spent_n = 0
        self.exhausted_n = 0

    def try_spend(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.spent_n += 1
                return True
            self.exhausted_n += 1
            return False

    def deposit(self) -> None:
        with self._lock:
            self._tokens = min(self._tokens + self.refill, self.capacity)

    def snapshot(self) -> dict:
        with self._lock:
            return {"tokens": round(self._tokens, 3),
                    "capacity": self.capacity,
                    "spent": self.spent_n,
                    "exhausted": self.exhausted_n}


class RetryingChannel:
    """Retries TRANSPORT failures (peer restarting, dropped connection);
    application YtErrors pass through untouched.

    Attempts/backoff default to the process-wide retry policy
    (`config.retry_policy(policy)`) instead of per-call-site constants;
    backoff is exponential with a cap and decorrelating jitter
    (RetryPolicyConfig.delay).

    Serving-plane codes (ISSUE 3 satellite): RequestThrottled is
    retried even for non-idempotent calls — admission rejection means
    the request was NEVER executed — and the wait honors the error's
    `retry_after` hint instead of the generic backoff curve.
    DeadlineExceeded is TERMINAL: the deadline belongs to the caller's
    query, and a retry could not possibly land inside it.

    ISSUE 17: when the policy declares `retry_budget > 0`, retries draw
    from a token bucket refilled only by SUCCESSFUL calls (throttled
    outcomes refund nothing) — an exhausted budget fails fast, shedding
    load instead of feeding a retry storm.  Backoff sleeps are capped
    at the caller's `token.remaining()` (CancellationToken), and an
    already-expired deadline surfaces as DeadlineExceeded promptly
    instead of sleeping through it."""

    def __init__(self, channel: Channel, attempts: int | None = None,
                 backoff: float | None = None, policy: str = "rpc"):
        from ytsaurus_tpu.config import RetryPolicyConfig
        cfg = retry_policy(policy)
        if attempts is not None or backoff is not None:
            # Caller overrides ride on a copy; the shared policy object
            # stays untouched.
            cfg = RetryPolicyConfig(
                attempts=attempts if attempts is not None else cfg.attempts,
                backoff=backoff if backoff is not None else cfg.backoff,
                backoff_cap=cfg.backoff_cap, jitter=cfg.jitter,
                retry_budget=cfg.retry_budget,
                retry_budget_refill=cfg.retry_budget_refill)
        self.channel = channel
        self._policy = cfg
        self.retry_budget: _RetryBudget | None = \
            _RetryBudget(cfg.retry_budget, cfg.retry_budget_refill) \
            if cfg.retry_budget > 0 else None

    @property
    def address(self) -> str:
        return self.channel.address

    @property
    def attempts(self) -> int:
        return self._policy.attempts

    def call(self, service: str, method: str, body=None,
             attachments=(), timeout: float | None = None,
             idempotent: bool = True, token=None):
        from ytsaurus_tpu.errors import retry_after_hint
        from ytsaurus_tpu.utils.tracing import child_span
        last: YtError | None = None
        budget = self.retry_budget
        for attempt in range(self._policy.attempts):
            if token is not None:
                # Surface an expired caller deadline NOW — before
                # dispatching (or sleeping toward) an attempt that
                # cannot possibly land inside it.
                token.check()
            try:
                # Fresh span PER ATTEMPT on the SAME trace (ISSUE 5
                # satellite): the wire then carries a distinct parent
                # span id for each try, so retried server work nests
                # under its own attempt instead of aliasing the first.
                with child_span("rpc.call", service=service,
                                method=method, attempt=attempt):
                    result = self.channel.call(service, method, body,
                                               attachments, timeout)
                if budget is not None:
                    budget.deposit()
                return result
            except YtError as err:
                if err.contains(EErrorCode.DeadlineExceeded):
                    # Terminal: the caller's query deadline already
                    # passed on the server; a retry cannot beat it.
                    raise
                throttled = err.code == EErrorCode.RequestThrottled or \
                    err.contains(EErrorCode.RequestThrottled)
                # Neither a timeout NOR a dropped connection proves
                # non-execution (the mutation may have run on a dying
                # peer): a non-idempotent call is resent only when the
                # transport failure happened before dispatch (connect
                # refused — the request never left this process).  A
                # THROTTLE is always safe to resend: admission rejected
                # the request before anything executed.
                if throttled:
                    retryable = True
                elif idempotent:
                    retryable = err.code in (EErrorCode.TransportError,
                                             EErrorCode.RpcTimeout)
                else:
                    retryable = _never_dispatched(err)
                if not retryable:
                    raise
                last = err
                if attempt + 1 < self._policy.attempts:
                    # No sleep after the FINAL attempt: the failure is
                    # already decided, the caller shouldn't wait for it.
                    if budget is not None and not budget.try_spend():
                        # Budget dry: fail FAST — the cluster is
                        # already struggling, and N clients x M retries
                        # is exactly the storm the bucket caps.
                        raise YtError(
                            f"RPC to {self.channel.address}: retry "
                            f"budget exhausted after attempt "
                            f"{attempt + 1}",
                            code=EErrorCode.PeerUnavailable,
                            attributes={"retry_budget_exhausted": True},
                            inner_errors=[last])
                    hint = retry_after_hint(err) if throttled else None
                    delay = min(hint, self._policy.backoff_cap) \
                        if hint is not None \
                        else self._policy.delay(attempt)
                    if token is not None:
                        remaining = token.remaining()
                        if remaining is not None:
                            # Cap the backoff at the caller's deadline:
                            # sleeping past it only delays the
                            # DeadlineExceeded the next check raises.
                            delay = min(delay, remaining)
                    time.sleep(delay)
        if token is not None:
            token.check()
        raise YtError(
            f"RPC to {self.channel.address} failed after "
            f"{self._policy.attempts} attempts",
            code=EErrorCode.PeerUnavailable, inner_errors=[last])

    def close(self) -> None:
        self.channel.close()


# Cluster-wide cap on in-flight hedge threads: losing (slow) attempts
# park a daemon thread until they finish, so the cap must cover
# request_rate x slow_latency.  Past it, hedged_race degrades to running
# attempts sequentially in the caller's thread — safe, just unhedged.
_HEDGE_SLOTS = threading.BoundedSemaphore(64)


def hedged_race(attempts: "list", delay: float):
    """First-success race with staggered arming (ref
    core/rpc/hedging_channel.h generalized to N attempts): attempt 0
    starts immediately; attempt i+1 is armed after `delay` with no
    answer, or IMMEDIATELY when attempt i fails.  Raises the last
    YtError when every attempt fails; a NON-YtError from any attempt
    propagates immediately (a programming error must never be swallowed
    into a silent hang).  Losing attempts run on abandoned daemon
    threads — a wedged loser cannot block the caller or interpreter
    exit."""
    import queue as _queue

    if not attempts:
        raise YtError("hedged race with no attempts",
                      code=EErrorCode.PeerUnavailable)
    results: "_queue.SimpleQueue" = _queue.SimpleQueue()

    def run(fn):
        try:
            try:
                results.put(("ok", fn()))
            except BaseException as err:  # noqa: BLE001 — relayed below
                results.put(("err", err))
        finally:
            _HEDGE_SLOTS.release()

    started = 0
    pending = 0
    last: YtError | None = None
    while True:
        if started < len(attempts):
            fn = attempts[started]
            if _HEDGE_SLOTS.acquire(blocking=False):
                started += 1
                try:
                    threading.Thread(target=run, args=(fn,), daemon=True,
                                     name=f"hedge-{started}").start()
                except BaseException:
                    # Thread spawn failed (fd/thread exhaustion): the
                    # slot must not leak out of the global pool.
                    _HEDGE_SLOTS.release()
                    raise
                pending += 1
            elif pending == 0:
                # Saturated with nothing in flight: run inline
                # (sequential fallback) rather than spawning unboundedly.
                started += 1
                try:
                    return fn()
                except YtError as err:
                    last = err
                    continue
            # Saturated with attempts in flight: fall through and wait
            # on their results (a fast success must win over blocking
            # inline on the next attempt); arming retries next loop.
        if pending == 0 and started >= len(attempts):
            raise last
        try:
            kind, value = results.get(
                timeout=delay if started < len(attempts) else None)
        except _queue.Empty:
            continue                # stagger elapsed: arm the next
        pending -= 1
        if kind == "ok":
            return value
        if not isinstance(value, YtError):
            raise value             # programming error: surface, loudly
        last = value                # failure: arm the next immediately


class HedgingChannel:
    """Race a DELAYED backup request against the primary (ref
    core/rpc/hedging_channel.h): when the primary has not answered
    within `hedging_delay`, the same request is sent to the backup and
    the first success wins — tail latency of one slow peer is bounded by
    hedging_delay + the healthy peer's latency, instead of the slow
    peer's timeout.  A fast primary FAILURE hedges immediately.

    Hedging applies only to idempotent calls: a duplicated mutation
    would double-execute, so non-idempotent calls go primary-only."""

    def __init__(self, primary, backup, hedging_delay: float = 0.05):
        self.primary = primary
        self.backup = backup
        self.hedging_delay = hedging_delay

    @property
    def address(self) -> str:
        return self.primary.address

    def call(self, service: str, method: str, body=None,
             attachments=(), timeout: float | None = None,
             idempotent: bool = True):
        if not idempotent:
            # The flag must reach a wrapped RetryingChannel/Failover
            # channel too, or IT would resend the mutation.
            return self.primary.call(service, method, body, attachments,
                                     timeout, idempotent=False)
        return hedged_race(
            [lambda: self.primary.call(service, method, body, attachments,
                                       timeout),
             lambda: self.backup.call(service, method, body, attachments,
                                      timeout)],
            self.hedging_delay)

    def close(self) -> None:
        self.primary.close()
        self.backup.close()


class FailoverChannel:
    """Channel over several master addresses: sticks to the one that
    last answered, rotates on errors that mean "this peer cannot serve
    me" (dead process, or a follower without the service), and keeps
    cycling with backoff until the failover window closes — which is
    what rides out a leader election.

    Retry semantics extend RetryingChannel's: NoSuchService (follower —
    the call never dispatched) and never-dispatched connect failures
    rotate always; dispatched TransportError / RpcTimeout /
    PeerUnavailable rotate only for idempotent calls (the mutation may
    have executed on the dying peer).
    Ref: dynamic channel pools + peer rediscovery
    (yt/yt/core/rpc/dynamic_channel_pool.h)."""

    def __init__(self, addresses: "list[str]", timeout: float = 120.0,
                 failover_window: float = 45.0, backoff: float = 0.3):
        if not addresses:
            raise ValueError("FailoverChannel needs at least one address")
        self._channels = [Channel(a, timeout=timeout) for a in addresses]
        self._current = 0
        self.failover_window = failover_window
        self.backoff = backoff

    @property
    def address(self) -> str:
        return self._channels[self._current].address

    def call(self, service: str, method: str, body=None,
             attachments=(), timeout: float | None = None,
             idempotent: bool = True):
        deadline = time.monotonic() + self.failover_window
        last: YtError | None = None
        cycle = 0
        while True:
            channel = self._channels[self._current]
            try:
                return channel.call(service, method, body, attachments,
                                    timeout)
            except YtError as err:
                if idempotent:
                    rotatable = err.code in (
                        EErrorCode.NoSuchService, EErrorCode.TransportError,
                        EErrorCode.RpcTimeout, EErrorCode.PeerUnavailable)
                else:
                    # NoSuchService is the follower's answer — the call
                    # never executed there.  A dropped connection only
                    # rotates when the request provably never left this
                    # process; otherwise the mutation may have committed
                    # on the dying leader and a resend would double-run
                    # it (no server-side mutation-id dedup).
                    rotatable = err.code == EErrorCode.NoSuchService or \
                        _never_dispatched(err)
                if not rotatable:
                    raise
                last = err
                self._current = (self._current + 1) % len(self._channels)
                cycle += 1
                if time.monotonic() > deadline:
                    raise YtError(
                        "no master answered within the failover window "
                        f"({self.failover_window:.0f}s)",
                        code=EErrorCode.PeerUnavailable,
                        inner_errors=[last])
                if cycle % len(self._channels) == 0:
                    time.sleep(min(self.backoff *
                                   (2 ** (cycle // len(self._channels))),
                                   3.0))

    def close(self) -> None:
        for channel in self._channels:
            channel.close()
