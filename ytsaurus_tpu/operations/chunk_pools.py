"""Chunk pools: slice operation inputs into job-sized stripes.

Ref shape: server/lib/chunk_pools/chunk_pool.h:27-261 — controllers feed
input chunks into a pool; the pool hands back "joblets" (stripes of chunk
slices sized by data weight / row count), so inputs far larger than one
worker's memory stream through bounded jobs.

Redesign: chunks are columnar with static capacities; a stripe is a list
of (chunk, row_range) slices.  The unordered pool greedily bin-packs
whole chunks (splitting oversized ones); the ordered pool keeps input
order and only cuts on size boundaries (ordered map/merge semantics).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

from ytsaurus_tpu.chunks.columnar import ColumnarChunk, concat_chunks

DEFAULT_DATA_WEIGHT_PER_JOB = 256 << 20      # bytes
DEFAULT_ROWS_PER_JOB = 4_000_000


def chunk_data_weight(chunk: ColumnarChunk) -> int:
    """Approximate payload bytes (plane bytes pro-rated to live rows).
    Uses .nbytes metadata only — never forces a device-to-host copy."""
    if chunk.capacity == 0:
        return 0
    total = sum(col.data.nbytes for col in chunk.columns.values())
    return int(total * (chunk.row_count / chunk.capacity))


@dataclass
class Stripe:
    """One job's input: chunk slices materialized lazily."""

    slices: list[tuple[ColumnarChunk, int, int]] = field(default_factory=list)
    row_count: int = 0
    data_weight: int = 0

    def add(self, chunk: ColumnarChunk, start: int, end: int,
            chunk_weight: "int | None" = None) -> None:
        self.slices.append((chunk, start, end))
        rows = end - start
        self.row_count += rows
        if chunk.row_count:
            if chunk_weight is None:
                chunk_weight = chunk_data_weight(chunk)
            self.data_weight += int(chunk_weight * rows / chunk.row_count)

    def materialize(self) -> ColumnarChunk:
        parts = []
        for chunk, start, end in self.slices:
            if start == 0 and end == chunk.row_count:
                parts.append(chunk)
            else:
                parts.append(chunk.slice_rows(start, end))
        return concat_chunks(parts) if len(parts) > 1 else parts[0]


def split_stripe(stripe: Stripe) -> "list[Stripe]":
    """Halve a stripe by rows (ref job_splitter.h: an interrupted long job
    hands its remaining input to smaller jobs).  Returns [stripe] when it
    cannot be split (single row)."""
    if stripe.row_count < 2:
        return [stripe]
    target = stripe.row_count // 2
    first, second = Stripe(), Stripe()
    taken = 0
    for chunk, start, end in stripe.slices:
        rows = end - start
        if taken >= target:
            second.add(chunk, start, end)
        elif taken + rows <= target:
            first.add(chunk, start, end)
            taken += rows
        else:
            cut = start + (target - taken)
            first.add(chunk, start, cut)
            second.add(chunk, cut, end)
            taken = target
    return [s for s in (first, second) if s.slices]


def _split_oversized(chunk: ColumnarChunk, max_rows: int):
    """Yield (start, end) ranges of at most max_rows."""
    start = 0
    while start < chunk.row_count:
        end = min(start + max_rows, chunk.row_count)
        yield start, end
        start = end


def build_stripes(chunks: Sequence[ColumnarChunk],
                  data_weight_per_job: int = DEFAULT_DATA_WEIGHT_PER_JOB,
                  rows_per_job: int = DEFAULT_ROWS_PER_JOB,
                  ordered: bool = False,
                  max_job_count: "int | None" = None) -> list[Stripe]:
    """Slice input chunks into job stripes bounded by rows AND bytes.

    ordered=True keeps rows in input order across stripes (ordered map /
    merge); unordered may pack any chunks together.  max_job_count caps
    the stripe count by scaling the per-job budgets up (the reference's
    job-size adjuster, chunk_pool.h job size constraints).
    """
    chunks = [c for c in chunks if c.row_count > 0]
    if not chunks:
        return []
    weights = {id(c): chunk_data_weight(c) for c in chunks}
    if max_job_count:
        total_rows = sum(c.row_count for c in chunks)
        total_weight = sum(weights.values())
        rows_per_job = max(rows_per_job,
                           -(-total_rows // max_job_count))
        data_weight_per_job = max(data_weight_per_job,
                                  -(-total_weight // max_job_count))

    stripes: list[Stripe] = []
    current = Stripe()

    def flush():
        nonlocal current
        if current.slices:
            stripes.append(current)
            current = Stripe()

    # Unordered: sort descending by weight for tighter packing.
    pending = list(chunks) if ordered else sorted(
        chunks, key=lambda c: weights[id(c)], reverse=True)
    for chunk in pending:
        weight = weights[id(chunk)]
        bytes_per_row = max(weight // max(chunk.row_count, 1), 1)
        max_rows_by_weight = max(data_weight_per_job // bytes_per_row, 1)
        max_rows = min(rows_per_job, max_rows_by_weight)
        for start, end in _split_oversized(chunk, max_rows):
            rows = end - start
            fits = (current.row_count + rows <= rows_per_job and
                    current.data_weight + rows * bytes_per_row
                    <= data_weight_per_job)
            if current.slices and not fits:
                flush()
            current.add(chunk, start, end, chunk_weight=weight)
            if current.row_count >= rows_per_job or \
                    current.data_weight >= data_weight_per_job:
                flush()
    flush()
    # max_job_count is a HARD cap: greedy packing can overshoot on
    # multi-chunk inputs, so fold the smallest stripes together (adjacent
    # ones when ordered, to preserve row order).
    while max_job_count and len(stripes) > max_job_count:
        if ordered:
            i = min(range(len(stripes) - 1),
                    key=lambda j: stripes[j].row_count +
                    stripes[j + 1].row_count)
            j = i + 1
        else:
            by_rows = sorted(range(len(stripes)),
                             key=lambda j: stripes[j].row_count)
            i, j = sorted(by_rows[:2])
        for args in stripes[j].slices:
            stripes[i].add(*args)
        del stripes[j]
    return stripes
