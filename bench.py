"""Benchmark: TPC-H Q1 rows/sec on the query engine (BASELINE.md config 1).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's LLVM-JIT evaluator on a modern x86 core sustains
roughly 5e7 rows/s on Q1-shaped scan+filter+group (order-of-magnitude from
vectorized-engine literature; the reference repo publishes no absolute
numbers — see BASELINE.md).  vs_baseline = ours / 5e7.

Usage: python bench.py [--smoke] [--rows N] [--iters K]
"""

import argparse
import json
import sys
import time


BASELINE_ROWS_PER_SEC = 5.0e7


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--smoke", action="store_true",
                        help="small row count, CPU-friendly")
    parser.add_argument("--rows", type=int, default=None)
    parser.add_argument("--iters", type=int, default=5)
    args = parser.parse_args()

    import jax

    from ytsaurus_tpu.models import tpch
    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.engine.lowering import prepare

    n_rows = args.rows or (100_000 if args.smoke else 64_000_000)
    chunk = tpch.generate_lineitem(n_rows)
    plan = build_query(tpch.Q1, {"//tpch/lineitem": tpch.LINEITEM_SCHEMA})
    prepared = prepare(plan, chunk)
    columns = {c.name: (chunk.columns[c.name].data,
                        chunk.columns[c.name].valid)
               for c in plan.schema}
    bindings = tuple(prepared.bindings)
    row_valid = chunk.row_valid
    jax.block_until_ready(row_valid)
    fn = jax.jit(prepared.run)

    # Warm-up / compile.
    planes, count = fn(columns, row_valid, bindings)
    jax.block_until_ready(planes)
    n_groups = int(count)
    assert 1 <= n_groups <= 6, f"Q1 produced {n_groups} groups"

    times = []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        planes, count = fn(columns, row_valid, bindings)
        jax.block_until_ready(planes)
        times.append(time.perf_counter() - t0)
    best = min(times)
    rows_per_sec = n_rows / best

    print(json.dumps({
        "metric": "tpch_q1_rows_per_sec",
        "value": round(rows_per_sec, 1),
        "unit": "rows/s",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
    }))
    print(f"# n_rows={n_rows} best={best*1e3:.2f}ms groups={n_groups} "
          f"device={jax.devices()[0].platform}", file=sys.stderr)


if __name__ == "__main__":
    main()
