"""Runtime concurrency sanitizer: the dynamic complement of `yt analyze
--pass guards` (tools/analyze/guard_inference.py).

The reference platform's correctness story leans on TSAN builds and
strict lock hierarchies (Hydra automaton thread affinity, tablet lock
ordering).  A Python serving stack has no TSAN, so this module provides
the piece that carries over: an OPT-IN instrumented lock layer over the
tree's ~30 hot locks that records, live,

  * per-thread held-lock sets and the acquisition-order edges they
    imply (every held lock → the lock being acquired),
  * lock-order INVERSIONS — acquiring B while holding A after some
    thread acquired A while holding B (the two-thread deadlock shape),
    with both acquisition stacks attached,
  * hold-time budget violations (a hot lock held longer than
    `hold_budget_seconds` serializes the serving plane),
  * host syncs / blocking I/O UNDER a registered hot-path lock — the
    failpoint I/O sites (`utils/failpoints.py`, the statically-enforced
    I/O boundary list) and the jax-pass sync points (`finish`,
    `_read_counts`) call `note_blocking(...)`, and the sanitizer flags
    any that run while a hot lock is held.

The observed edge set exports via `edge_snapshot()`, and tier-1 asserts
it is a SUBGRAPH of the static reconciliation graph
(`guard_inference.reconciliation_graph`) — a dynamic edge the AST
propagation cannot derive fails the build with stacks attached, keeping
the static analysis honest against runtime reality.

Gating: `YT_TPU_SANITIZE=1` (tests/conftest.py arms it suite-wide, the
same pattern as YT_TPU_INVARIANTS) or `config.SanitizerConfig.enabled`
via `configure()`.  DISABLED is the default and costs nothing:
`register_lock()` returns the plain `threading.Lock` unwrapped — zero
wrapper objects, zero per-acquire overhead (asserted by `bench.py
--config sanitizer_overhead`).  Locks created before enablement stay
plain; enable before constructing the daemons you want watched.

Registration names are stable SITE ids (`profiling.Counter._lock`):
every instance of a class shares its site's name, matching the static
graph's node granularity.  `guard_inference.registered_site_map()`
reads the name → static-node mapping straight off these call sites.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Optional

_ENV = "YT_TPU_SANITIZE"

# Bounded-report defaults (events beyond the cap still COUNT, they just
# stop accumulating stacks — the report must never grow unbounded under
# a pathological workload).
DEFAULT_HOLD_BUDGET = 0.25          # seconds a hot lock may be held
MAX_EDGES = 4096
MAX_EVENTS = 64
_STACK_LIMIT = 12


def enabled() -> bool:
    if os.environ.get(_ENV, "") not in ("", "0"):
        return True
    return _config_enabled


_config_enabled = False


def _short_stack() -> "list[str]":
    """A compact acquisition stack: repo frames preferred, innermost
    last; falls back to the raw innermost frames when the acquisition
    came entirely from user code outside the tree (a report with no
    stack is undebuggable)."""
    frames = traceback.extract_stack(limit=_STACK_LIMIT + 8)[:-3]
    out = []
    for frame in frames:
        name = frame.filename.replace(os.sep, "/")
        if "ytsaurus_tpu/" in name or "/tests/" in name or \
                "/tools/" in name:
            short = name.split("ytsaurus_tpu/")[-1] \
                if "ytsaurus_tpu/" in name else name.rsplit("/", 2)[-1]
            out.append(f"{short}:{frame.lineno} in {frame.name}")
    if not out:
        out = [f"{f.filename.rsplit('/', 1)[-1]}:{f.lineno} "
               f"in {f.name}" for f in frames[-4:]]
    return out[-_STACK_LIMIT:]


class _Held:
    """One per-thread held-lock frame."""

    __slots__ = ("name", "t0", "hot")

    def __init__(self, name: str, t0: float, hot: bool):
        self.name = name
        self.t0 = t0
        self.hot = hot


class LockSanitizer:
    """The event collector.  One process-global instance backs the
    registered locks; unit tests construct their own so deliberate
    inversions don't pollute the tier-1 reconciliation gate."""

    def __init__(self, hold_budget: float = DEFAULT_HOLD_BUDGET,
                 max_edges: int = MAX_EDGES,
                 max_events: int = MAX_EVENTS):
        self.hold_budget = hold_budget
        self.max_edges = max_edges
        self.max_events = max_events
        self._tl = threading.local()
        # Internal metadata lock: a LEAF by construction (never acquires
        # anything) and deliberately NOT registered with itself.
        self._meta = threading.Lock()
        self.edges: dict[tuple, dict] = {}     # (a, b) -> first sighting
        self.inversions: list[dict] = []
        self.hold_violations: list[dict] = []
        self.sync_under_lock: list[dict] = []
        # Tallies keep counting past the bounded report caps.  They are
        # DELIBERATELY lock-free int bumps: the sanitizer must not add a
        # global lock acquisition to every instrumented acquire, and an
        # occasionally-lost increment in telemetry is an acceptable
        # trade (the bounded event lists, which feed the reconciliation
        # gate, DO ride _meta).
        self.inversions_n = 0
        self.hold_violations_n = 0
        self.sync_under_lock_n = 0
        self.acquires_n = 0

    # -- per-thread stack ------------------------------------------------------

    def _stack(self) -> "list[_Held]":
        stack = getattr(self._tl, "stack", None)
        if stack is None:
            stack = self._tl.stack = []
        return stack

    def held_names(self) -> "list[str]":
        return [h.name for h in self._stack()]

    # -- events ----------------------------------------------------------------

    def on_acquire(self, name: str, hot: bool) -> None:
        stack = self._stack()
        self.acquires_n += 1
        t0 = time.monotonic()
        if stack:
            new_edges = []
            inversions = []
            for held in stack:
                if held.name == name:
                    continue        # re-entrant / sibling instance
                pair = (held.name, name)
                if pair not in self.edges:
                    new_edges.append(pair)
                if (name, held.name) in self.edges:
                    inversions.append(pair)
            if new_edges or inversions:
                frames = _short_stack()
                self.inversions_n += len(inversions)
                with self._meta:
                    for pair in new_edges:
                        if len(self.edges) < self.max_edges and \
                                pair not in self.edges:
                            self.edges[pair] = {
                                "thread": threading.current_thread().name,
                                "stack": frames,
                            }
                    for pair in inversions:
                        if len(self.inversions) < self.max_events:
                            prior = self.edges.get((pair[1], pair[0]))
                            self.inversions.append({
                                "acquiring": pair[1],
                                "holding": pair[0],
                                "stack": frames,
                                "prior_order_stack":
                                    (prior or {}).get("stack"),
                            })
        stack.append(_Held(name, t0, hot))

    def on_release(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].name == name:
                held = stack.pop(i)
                break
        else:
            return
        elapsed = time.monotonic() - held.t0
        if held.hot and elapsed > self.hold_budget:
            self.hold_violations_n += 1
            # analyze: allow(guard-read): approximate lock-free cap probe by design — the append below re-rides _meta
            if len(self.hold_violations) < self.max_events:
                with self._meta:
                    self.hold_violations.append({
                        "lock": name,
                        "held_seconds": round(elapsed, 4),
                        "budget_seconds": self.hold_budget,
                        "thread": threading.current_thread().name,
                        "stack": _short_stack(),
                    })

    def note_blocking(self, kind: str, detail: str) -> None:
        """A blocking operation (failpoint I/O site, host sync) is about
        to run on this thread; flag it if a registered HOT lock is
        held."""
        hot = [h.name for h in self._stack() if h.hot]
        if not hot:
            return
        self.sync_under_lock_n += 1
        # analyze: allow(guard-read): approximate lock-free cap probe by design — the append below re-rides _meta
        if len(self.sync_under_lock) < self.max_events:
            with self._meta:
                self.sync_under_lock.append({
                    "kind": kind,
                    "detail": detail,
                    "locks_held": hot,
                    "thread": threading.current_thread().name,
                    "stack": _short_stack(),
                })

    # -- reporting -------------------------------------------------------------

    def edge_snapshot(self) -> "dict[tuple, dict]":
        with self._meta:
            return dict(self.edges)

    def counters(self) -> dict:
        return {
            "inversions": self.inversions_n,
            "hold_violations": self.hold_violations_n,
            "sync_under_lock": self.sync_under_lock_n,
            "edges_observed": len(self.edges),
            "acquires": self.acquires_n,
        }

    def snapshot(self) -> dict:
        """The bounded report (monitoring /sanitizer + orchid)."""
        with self._meta:
            edges = sorted(f"{a} -> {b}" for a, b in self.edges)
            report = {
                "enabled": True,
                "hold_budget_seconds": self.hold_budget,
                "counters": self.counters(),
                "edges": edges,
                "inversions": list(self.inversions),
                "hold_violations": list(self.hold_violations),
                "sync_under_lock": list(self.sync_under_lock),
                "registered_sites": sorted(_registered),
            }
        _publish_sensors(self)
        return report

    def reset(self) -> None:
        with self._meta:
            self.edges.clear()
            self.inversions.clear()
            self.hold_violations.clear()
            self.sync_under_lock.clear()
        # Lock-free like their bumps (see __init__) — zeroing them under
        # _meta would manufacture guard evidence the hot path never has.
        self.inversions_n = 0
        self.hold_violations_n = 0
        self.sync_under_lock_n = 0
        self.acquires_n = 0


# -- instrumented lock types ---------------------------------------------------


class InstrumentedLock:
    """`threading.Lock` + sanitizer events.  Only constructed when the
    sanitizer is enabled; the disabled path hands out plain locks."""

    __slots__ = ("_lock", "_name", "_san", "_hot")

    def __init__(self, san: LockSanitizer, name: str,
                 lock=None, hot: bool = True):
        self._san = san
        self._name = name
        self._lock = lock if lock is not None else threading.Lock()
        self._hot = hot

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._san.on_acquire(self._name, self._hot)
        return got

    def release(self) -> None:
        self._san.on_release(self._name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class InstrumentedRLock:
    """Re-entrant variant: only the OUTERMOST acquire/release emit
    sanitizer events (nested re-acquisition is not an ordering edge)."""

    __slots__ = ("_lock", "_name", "_san", "_hot", "_depth")

    def __init__(self, san: LockSanitizer, name: str,
                 lock=None, hot: bool = True):
        self._san = san
        self._name = name
        self._lock = lock if lock is not None else threading.RLock()
        self._hot = hot
        self._depth = threading.local()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            depth = getattr(self._depth, "n", 0)
            if depth == 0:
                self._san.on_acquire(self._name, self._hot)
            self._depth.n = depth + 1
        return got

    def release(self) -> None:
        depth = getattr(self._depth, "n", 1) - 1
        self._depth.n = depth
        if depth == 0:
            self._san.on_release(self._name)
        self._lock.release()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class InstrumentedCondition:
    """`threading.Condition` + sanitizer events.  `wait()` RELEASES the
    underlying lock until wakeup — the held-set bookkeeping mirrors
    that, so hold budgets exclude the wait and edges observed by a woken
    thread attribute correctly."""

    __slots__ = ("_cond", "_name", "_san", "_hot")

    def __init__(self, san: LockSanitizer, name: str,
                 cond=None, hot: bool = True):
        self._san = san
        self._name = name
        self._cond = cond if cond is not None else threading.Condition()
        self._hot = hot

    def acquire(self, *args, **kwargs) -> bool:
        got = self._cond.acquire(*args, **kwargs)
        if got:
            self._san.on_acquire(self._name, self._hot)
        return got

    def release(self) -> None:
        self._san.on_release(self._name)
        self._cond.release()

    def __enter__(self) -> bool:
        got = self._cond.__enter__()
        self._san.on_acquire(self._name, self._hot)
        return got

    def __exit__(self, *exc) -> None:
        self._san.on_release(self._name)
        return self._cond.__exit__(*exc)

    def wait(self, timeout: Optional[float] = None):
        self._san.on_release(self._name)
        try:
            return self._cond.wait(timeout)
        finally:
            self._san.on_acquire(self._name, self._hot)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        self._san.on_release(self._name)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            self._san.on_acquire(self._name, self._hot)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


# -- registration (the one helper the ~30 hot-lock sites call) -----------------

_global: Optional[LockSanitizer] = None
_global_lock = threading.Lock()
_registered: "dict[str, int]" = {}      # site name -> instance count


def get_sanitizer() -> Optional[LockSanitizer]:
    """The process-global sanitizer, or None when disabled."""
    return _global


def _get_or_create() -> LockSanitizer:
    global _global
    if _global is None:
        with _global_lock:
            if _global is None:
                _global = LockSanitizer()
    return _global


def _register(name: str):
    with _global_lock:
        _registered[name] = _registered.get(name, 0) + 1


def register_lock(name: str, lock=None, *, hot: bool = True):
    """The registration helper every hot-lock site calls:

        self._lock = sanitizers.register_lock("serving.Batcher._lock")

    Disabled (the default): returns the plain `threading.Lock` (or the
    one passed in) — no wrapper, no overhead.  Enabled: returns an
    InstrumentedLock feeding the global sanitizer.  `hot=False`
    registers for ordering/edges but exempts the lock from the
    hold-budget and sync-under-lock rules (locks that intentionally
    cover I/O, e.g. the AOT disk tier's)."""
    if not enabled():
        return lock if lock is not None else threading.Lock()
    _register(name)
    return InstrumentedLock(_get_or_create(), name, lock, hot=hot)


def register_rlock(name: str, lock=None, *, hot: bool = True):
    if not enabled():
        return lock if lock is not None else threading.RLock()
    _register(name)
    return InstrumentedRLock(_get_or_create(), name, lock, hot=hot)


def register_condition(name: str, cond=None, *, hot: bool = True):
    if not enabled():
        return cond if cond is not None else threading.Condition()
    _register(name)
    return InstrumentedCondition(_get_or_create(), name, cond, hot=hot)


def registered_sites() -> "list[str]":
    return sorted(_registered)


# -- blocking-operation probes (failpoints + jax sync points call these) -------


def note_blocking(kind: str, detail: str) -> None:
    """Called at the statically-known blocking boundaries: failpoint
    I/O sites (`FailpointSite.hit`/`write_hit` — the same list the
    coverage pass enforces) and the jax-pass host-sync points.  A no-op
    (one global read) when the sanitizer is off."""
    san = _global
    if san is not None:
        san.note_blocking(kind, detail)


def note_host_sync(detail: str) -> None:
    note_blocking("host-sync", detail)


# -- config + reporting surfaces -----------------------------------------------


def configure(config) -> None:
    """Apply a `config.SanitizerConfig`: enablement for locks created
    AFTER this call, plus budgets on the live sanitizer."""
    global _config_enabled
    _config_enabled = bool(getattr(config, "enabled", False))
    san = _get_or_create() if _config_enabled else _global
    if san is not None:
        budget = getattr(config, "hold_budget_seconds", None)
        if budget is not None:
            # 0.0 is a legal (maximally strict) budget — config
            # validates ge=0, so apply whatever it accepted.
            san.hold_budget = float(budget)


def snapshot() -> dict:
    """Monitoring /sanitizer + orchid producer (bounded)."""
    san = _global
    if san is None:
        return {"enabled": False, "registered_sites": sorted(_registered)}
    return san.snapshot()


def edge_snapshot() -> "dict[tuple, dict]":
    san = _global
    return san.edge_snapshot() if san is not None else {}


def counters() -> dict:
    san = _global
    if san is None:
        return {"inversions": 0, "hold_violations": 0,
                "sync_under_lock": 0, "edges_observed": 0, "acquires": 0}
    return san.counters()


def _publish_sensors(san: LockSanitizer) -> None:
    """Mirror the counters onto /metrics (pull-time, never in the
    per-acquire path)."""
    from ytsaurus_tpu.utils.profiling import Profiler
    prof = Profiler("/sanitizer")
    stats = san.counters()
    prof.gauge("inversions").set(stats["inversions"])
    prof.gauge("hold_violations").set(stats["hold_violations"])
    prof.gauge("sync_under_lock").set(stats["sync_under_lock"])
    prof.gauge("edges_observed").set(stats["edges_observed"])


# -- reconciliation against the static graph -----------------------------------


def reconcile(static_edges, site_map, observed=None) -> "list[str]":
    """Dynamic ⊆ static: every OBSERVED acquisition edge between two
    registered sites must exist in the static reconciliation graph.

    `static_edges`: [a_node, b_node, site] triples (guard_inference.
    reconciliation_graph()["edges"]); `site_map`: registration name →
    static node id (same snapshot's "site_map").  Returns one violation
    string per missing edge, acquisition stacks attached — empty means
    the static analysis models runtime reality."""
    observed = observed if observed is not None else edge_snapshot()
    static = {(a, b) for a, b, _site in static_edges}
    violations = []
    for (a, b), info in sorted(observed.items()):
        node_a = site_map.get(a)
        node_b = site_map.get(b)
        if node_a is None or node_b is None:
            continue        # unregistered site: not part of the gate
        if node_a == node_b:
            continue        # sibling instances of one site
        if (node_a, node_b) in static:
            continue
        stack = "\n    ".join(info.get("stack") or ["<no stack>"])
        violations.append(
            f"dynamic lock-order edge {a} -> {b} "
            f"({node_a} -> {node_b}) is MISSING from the static "
            f"graph — teach tools/analyze (accessor/index resolution) "
            f"or restructure the locking; observed on thread "
            f"{info.get('thread')} at:\n    {stack}")
    return violations
