"""Observability: sensors, tracing, Orchid, monitoring endpoint, RPC wiring."""

import json
import re
import urllib.request

import pytest

from ytsaurus_tpu.server.monitoring import MonitoringServer
from ytsaurus_tpu.server.orchid import OrchidService, OrchidTree, default_orchid
from ytsaurus_tpu.utils.profiling import (
    Histogram,
    Profiler,
    ProfilerRegistry,
)
from ytsaurus_tpu.utils.tracing import (
    TraceContext,
    current_trace,
    get_collector,
    start_span,
)


# -- sensors -------------------------------------------------------------------

def test_counter_gauge_summary():
    reg = ProfilerRegistry()
    prof = Profiler("/test", registry=reg)
    prof.counter("requests").increment()
    prof.counter("requests").increment(2)
    prof.gauge("depth").set(7)
    prof.summary("latency").record(0.5)
    prof.summary("latency").record(1.5)

    assert prof.counter("requests").get() == 3
    assert prof.gauge("depth").get() == 7
    s = prof.summary("latency")
    assert s.count == 2 and s.sum == 2.0 and s.min == 0.5 and s.max == 1.5

    text = reg.render_prometheus()
    assert "test_requests 3" in text
    assert "test_depth 7" in text
    assert "test_latency_sum 2.0" in text


def test_tags_make_distinct_sensors():
    reg = ProfilerRegistry()
    prof = Profiler("/q", registry=reg)
    prof.with_tags(pool="a").counter("n").increment()
    prof.with_tags(pool="b").counter("n").increment(5)
    text = reg.render_prometheus()
    assert 'q_n{pool="a"} 1' in text
    assert 'q_n{pool="b"} 5' in text


def test_histogram_buckets():
    h = Histogram(bounds=(1.0, 10.0))
    for v in (0.5, 5.0, 50.0):
        h.record(v)
    samples = dict((suffix, val) for _k, suffix, val in h.samples())
    assert samples['.bucket{le="1.0"}'] == 1
    assert samples['.bucket{le="10.0"}'] == 2
    assert samples['.bucket{le="+Inf"}'] == 3
    assert samples[".count"] == 3


def test_registry_collect_snapshot():
    reg = ProfilerRegistry()
    Profiler("/x", registry=reg).counter("c").increment(4)
    snap = reg.collect()
    assert snap["/x/c"] == 4


# -- tracing -------------------------------------------------------------------

def test_span_nesting_and_collection():
    with TraceContext("root") as root:
        assert current_trace() is root
        with start_span("child", table="//t") as child:
            assert child.trace_id == root.trace_id
            assert child.parent_span_id == root.span_id
    assert current_trace() is None
    spans = get_collector().find(root.trace_id)
    names = {s.name for s in spans}
    assert names == {"root", "child"}
    child_rec = next(s for s in spans if s.name == "child")
    assert child_rec.tags["table"] == "//t"


def test_trace_wire_round_trip():
    ctx = TraceContext("a", sampled=True)
    ctx.set_baggage("user", "alice")
    wire = ctx.to_wire()
    # Simulate YSON transport byte-keys.
    wire = {k.encode(): v for k, v in wire.items()}
    remote = TraceContext.from_wire(wire, "server_side")
    assert remote.trace_id == ctx.trace_id
    assert remote.parent_span_id == ctx.span_id
    assert remote.baggage == {"user": "alice"}


def test_unsampled_spans_not_collected():
    ctx = TraceContext("quiet", sampled=False)
    with ctx:
        pass
    assert not get_collector().find(ctx.trace_id)


# -- prometheus exposition validator (ISSUE 5 satellite) -----------------------

_METRIC_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*\Z")


def parse_prometheus_exposition(text: str) -> list:
    """STRICT parse of the text exposition format: returns
    [(metric, labels_dict, value)] or raises ValueError on any grammar
    violation (bad names, unescaped label values, trailing garbage,
    duplicate series).  New sensors that would break a Prometheus scrape
    must fail HERE, in tests, not in production scrapes."""
    series = []
    seen = set()
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line or line.startswith("#"):
            continue

        def fail(reason):
            raise ValueError(f"line {lineno}: {reason}: {line!r}")

        i = line.find("{")
        labels = {}
        if i == -1:
            name, _, value_str = line.partition(" ")
        else:
            name = line[:i]
            # Label block: char-by-char so escapes inside quoted values
            # are honored (\\ \" \n are the ONLY legal escapes).
            pos = i + 1
            while True:
                j = line.find("=", pos)
                if j == -1:
                    fail("label without '='")
                label_name = line[pos:j]
                if not _LABEL_NAME_RE.match(label_name):
                    fail(f"bad label name {label_name!r}")
                if line[j + 1] != '"':
                    fail("unquoted label value")
                value_chars = []
                k = j + 2
                while k < len(line) and line[k] != '"':
                    ch = line[k]
                    if ch == "\\":
                        esc = line[k + 1] if k + 1 < len(line) else ""
                        if esc not in ("\\", '"', "n"):
                            fail(f"illegal escape \\{esc}")
                        value_chars.append(
                            {"\\": "\\", '"': '"', "n": "\n"}[esc])
                        k += 2
                    else:
                        value_chars.append(ch)
                        k += 1
                if k >= len(line):
                    fail("unterminated label value")
                if label_name in labels:
                    fail(f"duplicate label {label_name!r}")
                labels[label_name] = "".join(value_chars)
                k += 1
                if k < len(line) and line[k] == ",":
                    pos = k + 1
                    continue
                if k < len(line) and line[k] == "}":
                    break
                fail("expected ',' or '}' after label value")
            rest = line[k + 1:]
            if not rest.startswith(" "):
                fail("missing space before value")
            value_str = rest[1:]
        if not _METRIC_NAME_RE.match(name):
            fail(f"bad metric name {name!r}")
        if " " in value_str:
            fail("trailing garbage after value")
        try:
            value = float(value_str)
        except ValueError:
            if value_str not in ("+Inf", "-Inf", "NaN"):
                fail(f"bad sample value {value_str!r}")
            value = float(value_str.replace("Inf", "inf"))
        key = (name, tuple(sorted(labels.items())))
        if key in seen:
            fail(f"duplicate series {key!r}")
        seen.add(key)
        series.append((name, labels, value))
    return series


def test_exposition_validator_rejects_bad_lines():
    for bad in ("1metric 2", "m{x=1} 2", 'm{x="a} 2', 'm{x="a\\q"} 2',
                'm{x="a"}2', "m two", "m 1 extra", 'm{x="a",} 2',
                "m 1\nm 1"):
        with pytest.raises(ValueError):
            parse_prometheus_exposition(bad)
    ok = parse_prometheus_exposition('m{x="a\\"b\\\\c\\nd"} 1.5')
    assert ok == [("m", {"x": 'a"b\\c\nd'}, 1.5)]


def test_render_prometheus_survives_hostile_label_values():
    reg = ProfilerRegistry()
    prof = Profiler("/evil", registry=reg)
    prof.with_tags(q='say "hi"\nback\\slash').counter("n").increment()
    prof.with_tags(name="a.b/c-d").histogram(
        "lat", bounds=(0.1, 1.0)).record(0.5)
    prof.summary("s").record(2.0)
    series = parse_prometheus_exposition(reg.render_prometheus())
    (evil,) = [(n, l, v) for n, l, v in series if n == "evil_n"]
    assert evil[1] == {"q": 'say "hi"\nback\\slash'} and evil[2] == 1
    buckets = {l["le"]: v for n, l, v in series
               if n == "evil_lat_bucket"}
    assert buckets == {"0.1": 0, "1.0": 1, "+Inf": 1}


def test_live_registry_exposition_is_valid():
    """The GLOBAL registry — after real spans/sensors from other tests
    have landed in it — must render a grammatically valid exposition
    with no duplicate series."""
    from ytsaurus_tpu.utils.profiling import get_registry
    from ytsaurus_tpu.utils.tracing import TraceContext

    # Make sure at least one span-duration histogram (dotted span name
    # as a label value) is present.
    with TraceContext("exposition.check"):
        pass
    series = parse_prometheus_exposition(get_registry().render_prometheus())
    assert any(n == "tracing_span_seconds_count" and
               l.get("name") == "exposition.check"
               for n, l, v in series)


# -- orchid --------------------------------------------------------------------

def test_orchid_get_descends_into_producer_output():
    tree = OrchidTree()
    tree.register("/tablets", lambda: {"t1": {"rows": 10}, "t2": {"rows": 3}})
    tree.register_value("/version", "1.0")
    assert tree.get("/tablets/t1/rows") == 10
    assert tree.get("/version") == "1.0"
    assert tree.list("/tablets") == ["t1", "t2"]
    assert tree.list("/") == ["tablets", "version"]


def test_orchid_missing_path():
    from ytsaurus_tpu.errors import YtError
    tree = OrchidTree()
    tree.register("/a", lambda: {"b": 1})
    with pytest.raises(YtError):
        tree.get("/a/nope")
    with pytest.raises(YtError):
        tree.get("/zzz")


def test_default_orchid_has_sensors_and_spans():
    tree = default_orchid()
    assert isinstance(tree.get("/monitoring/sensors"), dict)
    assert isinstance(tree.get("/tracing/recent_spans"), list)


# -- monitoring http -----------------------------------------------------------

def test_monitoring_endpoints():
    reg = ProfilerRegistry()
    Profiler("/mon", registry=reg).counter("hits").increment(2)
    tree = OrchidTree()
    tree.register("/state", lambda: {"phase": "leading", "peers": [1, 2]})
    server = MonitoringServer(tree, reg)
    server.start()
    try:
        base = f"http://{server.address}"
        assert urllib.request.urlopen(f"{base}/healthz").read() == b"ok"
        metrics = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert "mon_hits 2" in metrics
        state = json.loads(
            urllib.request.urlopen(f"{base}/orchid/state").read())
        assert state == {"phase": "leading", "peers": [1, 2]}
        phase = json.loads(
            urllib.request.urlopen(f"{base}/orchid/state/phase").read())
        assert phase == "leading"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/orchid/zzz")
    finally:
        server.stop()


# -- rpc propagation -----------------------------------------------------------

def test_rpc_propagates_trace_and_counts_requests():
    from ytsaurus_tpu.rpc import Channel, RpcServer
    from ytsaurus_tpu.rpc.server import Service, rpc_method
    from ytsaurus_tpu.utils import profiling

    seen = {}

    class Echo(Service):
        name = "echo"

        @rpc_method()
        def ping(self, body, attachments):
            ctx = current_trace()
            seen["trace_id"] = ctx.trace_id if ctx else None
            seen["baggage"] = dict(ctx.baggage) if ctx else {}
            return {"pong": True}

    server = RpcServer([Echo()])
    server.start()
    channel = Channel(server.address, timeout=10)
    try:
        with TraceContext("client_op") as root:
            root.set_baggage("user", "bob")
            body, _ = channel.call("echo", "ping", {})
        assert body["pong"] is True
        assert seen["trace_id"] == root.trace_id
        assert seen["baggage"].get("user") in ("bob", b"bob")
        # Server span was exported with the same trace id.
        names = {s.name for s in get_collector().find(root.trace_id)}
        assert "echo.ping" in names
        # Request sensor ticked.
        counter = profiling.Profiler("/rpc/server").with_tags(
            service="echo", method="ping").counter("request_count")
        assert counter.get() >= 1
    finally:
        channel.close()
        server.stop()


def test_orchid_service_over_rpc():
    from ytsaurus_tpu.rpc import Channel, RpcServer

    tree = OrchidTree()
    tree.register("/live", lambda: {"n": 42})
    server = RpcServer([OrchidService(tree)])
    server.start()
    channel = Channel(server.address, timeout=10)
    try:
        body, _ = channel.call("orchid", "get", {"path": "/live/n"})
        assert body["value"] == 42
        body, _ = channel.call("orchid", "list", {"path": "/"})
        names = [n.decode() if isinstance(n, bytes) else n
                 for n in body["names"]]
        assert names == ["live"]
    finally:
        channel.close()
        server.stop()


# -- log rotation (ref core/logging's compressed rotating writer) --------------

def test_rotating_log_handler_gzips_history(tmp_path):
    import gzip
    import json as _json
    import logging as _logging

    from ytsaurus_tpu.utils.logging import (
        StructuredFormatter,
        make_rotating_handler,
    )

    path = str(tmp_path / "daemon.log")
    handler = make_rotating_handler(path, max_bytes=2000, backups=2)
    logger = _logging.getLogger("rotation-test")
    logger.setLevel(_logging.INFO)
    logger.addHandler(handler)
    logger.propagate = False
    for i in range(200):
        logger.info("event %d with some padding to fill bytes", i)
    handler.close()
    live = open(path).read().splitlines()
    assert live and all(_json.loads(line)["category"] == "rotation-test"
                        for line in live)
    import os as _os
    rotated = [f for f in _os.listdir(tmp_path)
               if f.startswith("daemon.log.") and f.endswith(".gz")]
    assert 1 <= len(rotated) <= 2            # history capped at backups
    with gzip.open(tmp_path / rotated[0], "rt") as f:
        row = _json.loads(f.readline())
    assert "event" in row["message"]
    # The live file respects the size cap (plus at most one record).
    assert _os.path.getsize(path) < 4000


def test_env_wired_file_logging(tmp_path, monkeypatch):
    """YTSAURUS_TPU_LOG_FILE adds the rotating file sink at configure
    time (fresh interpreter via subprocess: _configure is once-only)."""
    import subprocess
    import sys

    log_path = tmp_path / "wired.log"
    code = (
        "from ytsaurus_tpu.utils.logging import get_logger, log_event\n"
        "import logging\n"
        "log_event(get_logger('Wired'), logging.WARNING, 'hello',"
        " k=1)\n")
    import pathlib
    repo_root = str(pathlib.Path(__file__).resolve().parents[1])
    env = {"YTSAURUS_TPU_LOG_FILE": str(log_path),
           "JAX_PLATFORMS": "cpu", "PATH": "/usr/bin:/bin",
           "PYTHONPATH": repo_root}
    subprocess.run([sys.executable, "-c", code], check=True, env=env,
                   timeout=120)
    import json as _json
    # Per-process disambiguation: the actual file carries the child pid.
    (actual,) = list(log_path.parent.glob("wired-*.log"))
    lines = [_json.loads(line) for line in open(actual)]
    assert lines[0]["message"] == "hello" and lines[0]["k"] == 1
