"""Sequoia groundwork: the resolve ground-table stays consistent with
the master tree through the mutation stream (ref sequoia_server +
sequoia_client ground tables)."""

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.cypress.sequoia import RESOLVE_PATH, SequoiaResolver


@pytest.fixture
def resolver(tmp_path):
    client = connect(str(tmp_path / "c"))
    client.create("map_node", "//pre/existing", recursive=True)
    return client, SequoiaResolver(client).enable()


def test_bootstrap_full_sync(resolver):
    client, seq = resolver
    hit = seq.resolve("//pre/existing")
    assert hit is not None
    assert hit["node_type"] == "map_node"
    assert seq.verify() == []


def test_mutations_maintain_resolve_table(resolver):
    client, seq = resolver
    client.create("document", "//a/b/c", recursive=True)
    assert seq.resolve("//a/b/c")["node_type"] == "document"
    # Recursive creates materialize ancestor records too.
    assert seq.resolve("//a")["node_type"] == "map_node"
    assert seq.resolve("//a/b")["node_type"] == "map_node"

    client.write_table("//a/t", [{"x": 1}])
    assert seq.resolve("//a/t")["node_type"] == "table"

    client.copy("//a", "//a2", recursive=True)
    assert seq.resolve("//a2/b/c") is not None
    client.move("//a2", "//a3")
    assert seq.resolve("//a2") is None
    assert seq.resolve("//a3/b/c") is not None

    client.remove("//a")
    assert seq.resolve("//a") is None
    assert seq.resolve("//a/b/c") is None
    assert seq.verify() == []


def test_resolve_matches_tree_ids(resolver):
    client, seq = resolver
    client.create("document", "//idcheck", recursive=True)
    node = client.cluster.master.tree.resolve("//idcheck")
    assert seq.resolve("//idcheck")["node_id"] == node.id


def test_verify_detects_and_full_sync_repairs(resolver):
    client, seq = resolver
    client.create("document", "//d/x", recursive=True)
    assert seq.verify() == []
    # Sabotage: drop one record behind the maintainer's back.
    client.delete_rows(RESOLVE_PATH, [("//d/x",)])
    assert "//d/x" in seq.verify()
    seq.full_sync()
    assert seq.verify() == []
    assert seq.resolve("//d/x") is not None


def test_resolve_excludes_own_subtree(resolver):
    client, seq = resolver
    # The resolve table does not mirror itself (no recursion).
    assert seq.resolve(RESOLVE_PATH) is None
    assert all(not p.startswith("//sys/sequoia") for p in seq.verify())


def test_set_creates_and_replaces_children(resolver):
    client, seq = resolver
    # set can CREATE a node outright...
    client.set("//brandnew", 5)
    assert seq.resolve("//brandnew") is not None
    # ...and replace a map_node's entire child set.
    client.create("document", "//m/old", recursive=True)
    client.set("//m", {"fresh": 1})
    assert seq.resolve("//m/old") is None
    assert seq.resolve("//m/fresh") is not None
    assert seq.verify() == []


def test_tx_abort_resyncs(resolver):
    client, seq = resolver
    tx = client.start_tx()
    client.create("document", "//txnode", recursive=True, tx=tx)
    assert seq.resolve("//txnode") is not None
    client.abort_tx(tx)
    assert seq.resolve("//txnode") is None      # no phantom node
    assert seq.verify() == []


def test_links_resolve_consistently(resolver):
    """Rows record the RAW node: a link row is the link itself (type
    'link'), so the incremental path, full_sync, and verify agree — and
    removing the TARGET never strands the link's row."""
    client, seq = resolver
    client.create("document", "//tgt", recursive=True)
    client.link("//tgt", "//lnk")
    link_id = client.cluster.master.tree.resolve(
        "//lnk", follow_links=False).id
    hit = seq.resolve("//lnk")
    assert hit == {"node_id": link_id, "node_type": "link"}
    assert seq.verify() == []
    seq.full_sync()
    assert seq.resolve("//lnk") == hit
    assert seq.verify() == []
    # Target removal: the link row stays valid (it records the link).
    client.remove("//tgt")
    assert seq.resolve("//lnk") == hit
    assert seq.verify() == []


def test_noncanonical_paths_share_one_row(resolver):
    client, seq = resolver
    client.create("document", "//x//y", recursive=True)
    assert seq.resolve("//x/y") is not None
    assert seq.verify() == []
    client.remove("//x//y")
    assert seq.resolve("//x/y") is None
    assert seq.verify() == []


def test_quoted_path_removal(resolver):
    client, seq = resolver
    client.create("map_node", "//data/it's", recursive=True)
    client.create("document", "//data/it's/leaf")
    assert seq.resolve("//data/it's/leaf") is not None
    client.remove("//data/it's")
    assert seq.resolve("//data/it's") is None
    assert seq.resolve("//data/it's/leaf") is None
    assert seq.verify() == []


def test_excluded_prefix_is_segment_aware(resolver):
    client, seq = resolver
    client.create("document", "//sys/sequoia_backup", recursive=True)
    assert seq.resolve("//sys/sequoia_backup") is not None
    assert seq.verify() == []


def test_under_mutation_load_stays_consistent(resolver):
    client, seq = resolver
    for i in range(40):
        client.create("document", f"//load/d{i}", recursive=True)
        if i % 3 == 0:
            client.set(f"//load/d{i}", {"v": i})
        if i % 7 == 0 and i:
            client.remove(f"//load/d{i - 1}")
    assert seq.verify() == []
    assert seq.resolve("//load/d2") is not None
    assert seq.resolve("//load/d6") is None       # removed at i=7