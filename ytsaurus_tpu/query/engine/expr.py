"""Expression compilation: typed IR → XLA, with host-side vocabulary binding.

Architecture (TPU-first redesign of the reference's LLVM expression codegen,
library/query/engine/cg_fragment_compiler.cpp):

  * Device planes are (data, valid) pairs; all null logic is three-valued and
    vectorized (the reference branches per row; we mask).
  * String work is split: per-row compute stays on device over int32
    dictionary codes; anything that inspects string BYTES (LIKE, lower,
    comparisons against literals, cross-vocabulary equality) is evaluated
    host-side over the chunk vocabulary — O(|vocab|), usually ≪ O(rows) —
    and shipped to the device as small bound arrays consumed by gathers.

  Two phases walk the IR in IDENTICAL order:
    - bind phase (per chunk, host): resolves vocabularies, computes remap /
      predicate tables and literal codes, appending them to a bindings list.
    - emit phase (once per compile-cache entry, at jit trace time): builds the
      jnp computation, pulling bound values positionally from the traced
      bindings tuple.
  Emit control flow depends only on IR structure and binding SHAPES, never on
  binding VALUES, so one traced program serves every chunk whose bindings
  have the same shapes.
"""

from __future__ import annotations

import hashlib
import re
from dataclasses import dataclass, field
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.schema import EValueType, device_dtype

_EMPTY_VOCAB = np.array([], dtype=object)


def _dtype_for(ty: EValueType):
    return device_dtype(ty)


# --- bind-phase context -------------------------------------------------------


@dataclass
class ColumnBinding:
    """Host view of one input column at bind time."""
    type: EValueType
    vocab: Optional[np.ndarray]  # for string columns


@dataclass
class BindContext:
    """Per-chunk bind state: column vocabs in, bound host arrays out.

    `structure` is the bind-phase STRUCTURE NOTEBOOK: any host constant
    a bind method bakes into the traced program (concat's pair-table
    width, order-key bit widths, ...) must be noted here — it folds into
    PreparedQuery.structure_key and hence the compile-cache key, so two
    plans that share a (parameterized) fingerprint and binding shapes
    but differ in a baked constant can never share a program."""
    columns: dict[str, ColumnBinding]
    bindings: list = field(default_factory=list)
    structure: list = field(default_factory=list)

    def add(self, value) -> int:
        self.bindings.append(value)
        return len(self.bindings) - 1

    def note(self, *entry) -> None:
        self.structure.append(entry)


@dataclass
class EmitContext:
    """Trace-time state: column planes + the traced bindings tuple."""
    columns: dict[str, tuple[jax.Array, jax.Array]]
    bindings: tuple
    capacity: int


@dataclass
class BoundExpr:
    """Result of binding one IR node for one chunk."""
    type: EValueType
    vocab: Optional[np.ndarray]          # result vocabulary if string-typed
    emit: Callable[[EmitContext], tuple[jax.Array, jax.Array]]


def _vocab_bucket(n: int) -> int:
    """Pad vocab-indexed bound arrays to power-of-two buckets ≥ 8 so binding
    shapes (and hence compiled programs) are reused across chunks."""
    from ytsaurus_tpu.chunks.columnar import next_pow2
    return next_pow2(n, floor=8)


def _pad_np(arr: np.ndarray, size: int, fill) -> np.ndarray:
    out = np.full(size, fill, dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def _vocab_code(vocab: np.ndarray, value: bytes) -> int:
    """Code of `value` in sorted vocab, or -1 if absent."""
    idx = np.searchsorted(vocab, value) if len(vocab) else 0
    if idx < len(vocab) and vocab[idx] == value:
        return int(idx)
    return -1


def _range_code(vocab: np.ndarray, value: bytes) -> int:
    """Order-preserving encoding of `value` against a sorted vocab in the
    doubled space where row code c sits at 2c+1: a present value lands
    exactly on its row encoding, an absent one on the even insertion
    point between its neighbors (comparable, never equal)."""
    idx = int(np.searchsorted(vocab, value)) if len(vocab) else 0
    if idx < len(vocab) and vocab[idx] == value:
        return 2 * idx + 1
    return 2 * idx


def _remap_table(old_vocab: np.ndarray, new_vocab: np.ndarray) -> np.ndarray:
    lookup = {v: i for i, v in enumerate(new_vocab)}
    table = np.array([lookup[v] for v in old_vocab], dtype=np.int32)
    if len(table) == 0:
        table = np.zeros(1, dtype=np.int32)
    return table


def _merge_vocabs(*vocabs: Optional[np.ndarray]) -> np.ndarray:
    values = set()
    for v in vocabs:
        if v is not None:
            values.update(v)
    return np.array(sorted(values), dtype=object)


def _gather_binding(slot: int):
    """Emit helper: codes -> bound table lookup (clipped; -1-safe callers
    must mask validity themselves)."""
    def gather(ctx: EmitContext, codes: jax.Array) -> jax.Array:
        table = ctx.bindings[slot]
        return table[jnp.clip(codes, 0, table.shape[0] - 1)]
    return gather


class ExprBinder:
    """Binds a typed IR expression for one chunk (host phase)."""

    def __init__(self, bind_ctx: BindContext):
        self.ctx = bind_ctx

    def bind(self, node: ir.TExpr) -> BoundExpr:
        method = getattr(self, f"_bind_{type(node).__name__}", None)
        if method is None:
            raise YtError(f"Cannot lower {type(node).__name__}",
                          code=EErrorCode.QueryUnsupported)
        return method(node)

    # -- leaves ---------------------------------------------------------------

    def _bind_TLiteral(self, node: ir.TLiteral) -> BoundExpr:
        ty = node.type
        if ty is EValueType.null:
            def emit_null(ctx: EmitContext):
                zeros = jnp.zeros(ctx.capacity, dtype=jnp.int8)
                return zeros, jnp.zeros(ctx.capacity, dtype=bool)
            return BoundExpr(type=ty, vocab=None, emit=emit_null)
        if ty is EValueType.string:
            # Value-independent on device already: the literal is code 0
            # of its own one-entry vocabulary; every consumer reads the
            # actual bytes through bound remap/predicate tables.
            vocab = np.array([node.value], dtype=object)

            def emit_str(ctx: EmitContext):
                return (jnp.zeros(ctx.capacity, dtype=jnp.int32),
                        jnp.ones(ctx.capacity, dtype=bool))
            return BoundExpr(type=ty, vocab=vocab, emit=emit_str)
        if not isinstance(ty, EValueType):
            # Vector literal (the NEAREST query vector): a (dim,) float32
            # runtime BINDING.  The binding SHAPE keys the compile cache
            # per dim; the component values never enter the traced
            # program, so one program serves every query vector.
            # analyze: allow(host-sync): node.value is a host python tuple (bind phase), not a device plane
            slot = self.ctx.add(jnp.asarray(np.asarray(node.value,
                                                       dtype=np.float32)))

            def emit_vec(ctx: EmitContext):
                return (ctx.bindings[slot].astype(jnp.float32),
                        jnp.ones(ctx.capacity, dtype=bool))
            return BoundExpr(type=ty, vocab=None, emit=emit_vec)
        value = node.value
        dt = _dtype_for(ty)
        if ty is EValueType.boolean:
            # Static residue: true/false are keywords to the lexer and
            # stay in the (parameterized) fingerprint, so baking the
            # value cannot grow a shape spectrum.
            def emit_bool(ctx: EmitContext):
                return (jnp.full(ctx.capacity, bool(value), dtype=dt),
                        jnp.ones(ctx.capacity, dtype=bool))
            return BoundExpr(type=ty, vocab=None, emit=emit_bool)
        # Numeric literals ride as a 0-d BINDING, not a trace constant:
        # the compiled program is literal-value-independent, which is
        # what lets the parameterized fingerprint (ir.fingerprint with
        # omit_values=True) key one program for every constant.
        # analyze: allow(host-sync): `value` is a host python scalar (bind phase), not a device plane
        slot = self.ctx.add(jnp.asarray(np.asarray(value, dtype=dt)))

        def emit(ctx: EmitContext):
            return (jnp.broadcast_to(ctx.bindings[slot].astype(dt),
                                     (ctx.capacity,)),
                    jnp.ones(ctx.capacity, dtype=bool))
        return BoundExpr(type=ty, vocab=None, emit=emit)

    def _bind_TReference(self, node: ir.TReference) -> BoundExpr:
        binding = self.ctx.columns.get(node.name)
        if binding is None:
            raise YtError(f"Unbound column {node.name!r}",
                          code=EErrorCode.QueryExecutionError)
        name = node.name

        def emit(ctx: EmitContext):
            return ctx.columns[name]
        return BoundExpr(type=node.type, vocab=binding.vocab, emit=emit)

    # -- operators ------------------------------------------------------------

    def _bind_TUnary(self, node: ir.TUnary) -> BoundExpr:
        operand = self.bind(node.operand)
        op = node.op

        def emit(ctx: EmitContext):
            data, valid = operand.emit(ctx)
            if op == "not":
                return ~data.astype(bool), valid
            if op == "-":
                return -data, valid
            if op == "~":
                return ~data, valid
            raise AssertionError(op)
        return BoundExpr(type=node.type, vocab=None, emit=emit)

    def _bind_TBinary(self, node: ir.TBinary) -> BoundExpr:
        op = node.op
        lhs_b = self.bind(node.lhs)
        rhs_b = self.bind(node.rhs)

        if op in ("and", "or"):
            def emit_logical(ctx: EmitContext):
                ld, lv = lhs_b.emit(ctx)
                rd, rv = rhs_b.emit(ctx)
                ld, rd = ld.astype(bool), rd.astype(bool)
                if op == "and":
                    known_false = (lv & ~ld) | (rv & ~rd)
                    valid = (lv & rv) | known_false
                    data = jnp.where(lv, ld, True) & jnp.where(rv, rd, True)
                else:
                    known_true = (lv & ld) | (rv & rd)
                    valid = (lv & rv) | known_true
                    data = jnp.where(lv, ld, False) | jnp.where(rv, rd, False)
                return data & valid if op == "and" else data, valid
            return BoundExpr(type=EValueType.boolean, vocab=None,
                             emit=emit_logical)

        # String comparison: encoded-plane fast path first (ISSUE 19) —
        # a literal against a dict-encoded side compares CODES against
        # one host-bound code, skipping the merged-vocab remap tables
        # and their two per-row gathers entirely.
        if EValueType.string in (lhs_b.type, rhs_b.type) and \
                lhs_b.type is not EValueType.null and rhs_b.type is not EValueType.null:
            encoded = self._bind_string_literal_cmp(node, op, lhs_b, rhs_b)
            if encoded is not None:
                return encoded
            # Decoded fallback: the remap-table path.  Note it in the
            # structure notebook so the dispatcher can book the
            # /query/kernels/decoded_fallbacks sensor and EXPLAIN
            # ANALYZE can say which execution mode actually ran.
            self.ctx.note("str-decoded", op)
            merged = _merge_vocabs(lhs_b.vocab, rhs_b.vocab)
            l_vocab = lhs_b.vocab if lhs_b.vocab is not None else _EMPTY_VOCAB
            r_vocab = rhs_b.vocab if rhs_b.vocab is not None else _EMPTY_VOCAB
            l_slot = self.ctx.add(jnp.asarray(_pad_np(
                _remap_table(l_vocab, merged),
                _vocab_bucket(max(len(l_vocab), 1)), 0)))
            r_slot = self.ctx.add(jnp.asarray(_pad_np(
                _remap_table(r_vocab, merged),
                _vocab_bucket(max(len(r_vocab), 1)), 0)))
            l_gather = _gather_binding(l_slot)
            r_gather = _gather_binding(r_slot)

            def emit_strcmp(ctx: EmitContext):
                ld, lv = lhs_b.emit(ctx)
                rd, rv = rhs_b.emit(ctx)
                lm = l_gather(ctx, ld)
                rm = r_gather(ctx, rd)
                data = _compare(op, lm, rm)
                return data, lv & rv
            return BoundExpr(type=EValueType.boolean, vocab=None,
                             emit=emit_strcmp)

        target = node.type if op not in _CMP_OPS else None

        def emit(ctx: EmitContext):
            ld, lv = lhs_b.emit(ctx)
            rd, rv = rhs_b.emit(ctx)
            valid = lv & rv
            if op in _CMP_OPS:
                ld, rd = _promote_pair(ld, rd)
                return _compare(op, ld, rd), valid
            dt = _dtype_for(target)
            ld = ld.astype(dt)
            rd = rd.astype(dt)
            if op == "+":
                data = ld + rd
            elif op == "-":
                data = ld - rd
            elif op == "*":
                data = ld * rd
            elif op == "/":
                if jnp.issubdtype(dt, jnp.integer):
                    safe = jnp.where(rd == 0, jnp.ones_like(rd), rd)
                    data = jax.lax.div(ld, safe)   # C++ trunc semantics
                    valid = valid & (rd != 0)
                else:
                    data = ld / rd
            elif op == "%":
                if jnp.issubdtype(dt, jnp.integer):
                    safe = jnp.where(rd == 0, jnp.ones_like(rd), rd)
                    data = jax.lax.rem(ld, safe)
                    valid = valid & (rd != 0)
                else:
                    data = jnp.fmod(ld, rd)
            elif op == "|":
                data = ld | rd
            elif op == "&":
                data = ld & rd
            elif op == "^":
                data = ld ^ rd
            elif op == "<<":
                data = jnp.left_shift(ld, rd)
            elif op == ">>":
                data = jnp.right_shift(ld, rd)
            else:
                raise AssertionError(op)
            return data, valid
        return BoundExpr(type=node.type, vocab=None, emit=emit)

    def _bind_string_literal_cmp(self, node: ir.TBinary, op: str,
                                 lhs_b: BoundExpr,
                                 rhs_b: BoundExpr) -> Optional[BoundExpr]:
        """Encoded-plane string comparison (ISSUE 19): literal vs a
        dict-encoded expression compares CODES, not remapped vocabs.

        The binding carries the literal's position in the COLUMN side's
        own sorted vocabulary: =/!= bind the exact code (-1 when absent —
        equal to no row code), range ops bind in the doubled space where
        row code c sits at 2c+1 and an absent literal lands on its even
        insertion point (strictly between neighboring codes, equal to
        none — see _range_code).  Order preservation of the encode makes
        the integer compare the byte compare.  Bit-identical to the
        merged remap-table path on valid lanes; that path remains the
        decoded oracle (compile_config().encoded_predicates=False).

        NOTE: interp.NumpyBinder mirrors this decision AND these
        formulas — change both or tier bit-identity breaks."""
        from ytsaurus_tpu.config import compile_config
        if op not in _CMP_OPS or not compile_config().encoded_predicates:
            return None
        if not (lhs_b.type is EValueType.string
                and rhs_b.type is EValueType.string):
            return None
        if isinstance(node.rhs, ir.TLiteral) and lhs_b.vocab is not None:
            col_b, lit, lit_on_right = lhs_b, node.rhs.value, True
        elif isinstance(node.lhs, ir.TLiteral) and rhs_b.vocab is not None:
            col_b, lit, lit_on_right = rhs_b, node.lhs.value, False
        else:
            return None
        if lit is None:
            return None
        from ytsaurus_tpu.chunks.columnar import vocab_digest
        vocab = col_b.vocab
        # The bound code is only meaningful against THIS vocab
        # generation: fold its content digest into the structure notebook
        # (-> structure_key -> compile cache key) so a chunk re-encode
        # after compaction can never pair a stale code binding with a
        # cached program, even if a future layer memoizes bind output.
        self.ctx.note("strlit", op, vocab_digest(vocab))
        if op in ("=", "!="):
            slot = self.ctx.add(jnp.asarray(
                np.int32(_vocab_code(vocab, lit))))

            def emit_eq(ctx: EmitContext):
                data, valid = col_b.emit(ctx)
                code = ctx.bindings[slot]
                out = (data == code) if op == "=" else (data != code)
                return out, valid
            return BoundExpr(type=EValueType.boolean, vocab=None,
                             emit=emit_eq)
        slot = self.ctx.add(jnp.asarray(np.int32(_range_code(vocab, lit))))

        def emit_rng(ctx: EmitContext):
            data, valid = col_b.emit(ctx)
            doubled = data.astype(jnp.int32) * 2 + 1
            code = ctx.bindings[slot]
            out = _compare(op, doubled, code) if lit_on_right \
                else _compare(op, code, doubled)
            return out, valid
        return BoundExpr(type=EValueType.boolean, vocab=None,
                         emit=emit_rng)

    # -- functions ------------------------------------------------------------

    def _bind_TFunction(self, node: ir.TFunction) -> BoundExpr:
        name = node.name
        args = [self.bind(a) for a in node.args]

        if name == "if":
            return self._bind_if(node, args)
        if name in ("l2_distance", "distance", "cosine_distance",
                    "dot_product"):
            a, b = args[0], args[1]
            metric = name

            def emit_dist(ctx: EmitContext):
                da, va = a.emit(ctx)
                db, vb = b.emit(ctx)
                da = da.astype(jnp.float32)
                db = db.astype(jnp.float32)
                if da.ndim == 1 and db.ndim == 2:
                    da, db = db, da
                    va, vb = vb, va
                if da.ndim == 2 and db.ndim == 1:
                    # THE tiled distance pass: (capacity, dim) @ (dim,)
                    # — one MXU matmul over the contiguous plane.
                    dot = da @ db
                elif da.ndim == 2:
                    dot = (da * db).sum(axis=1)   # row-wise col vs col
                else:
                    dot = da @ db                 # two literals: scalar
                na2 = (da * da).sum(axis=-1)
                nb2 = (db * db).sum(axis=-1)
                if metric == "dot_product":
                    out = dot
                elif metric == "cosine_distance":
                    denom = jnp.sqrt(na2) * jnp.sqrt(nb2)
                    out = jnp.where(denom > 0.0, 1.0 - dot / denom, 1.0)
                else:
                    # L2 via the norm trick off the shared dot pass.
                    out = jnp.sqrt(jnp.maximum(na2 - 2.0 * dot + nb2, 0.0))
                out = jnp.broadcast_to(out, (ctx.capacity,))
                return out.astype(jnp.float64), va & vb
            return BoundExpr(type=EValueType.double, vocab=None,
                             emit=emit_dist)
        if name == "is_null":
            a = args[0]

            def emit_is_null(ctx):
                _, valid = a.emit(ctx)
                return ~valid, jnp.ones_like(valid)
            return BoundExpr(type=EValueType.boolean, vocab=None,
                             emit=emit_is_null)
        if name == "if_null":
            return self._bind_merge_select(
                node, [args[0], args[1]],
                lambda ctx, planes: (
                    jnp.where(planes[0][1], planes[0][0], planes[1][0]),
                    planes[0][1] | planes[1][1]))
        if name in ("int64", "uint64", "double", "boolean"):
            a = args[0]
            dt = _dtype_for(node.type)

            def emit_cast(ctx):
                data, valid = a.emit(ctx)
                if data.dtype == jnp.bool_ or node.type is EValueType.boolean:
                    return data.astype(dt) if node.type is not EValueType.boolean \
                        else (data != 0), valid
                return data.astype(dt), valid
            return BoundExpr(type=node.type, vocab=None, emit=emit_cast)
        if name == "abs":
            a = args[0]

            def emit_abs(ctx):
                data, valid = a.emit(ctx)
                if jnp.issubdtype(data.dtype, jnp.unsignedinteger):
                    return data, valid
                return jnp.abs(data), valid
            return BoundExpr(type=node.type, vocab=None, emit=emit_abs)
        if name in ("floor", "ceil", "sqrt"):
            a = args[0]
            fn = {"floor": jnp.floor, "ceil": jnp.ceil, "sqrt": jnp.sqrt}[name]

            def emit_math(ctx):
                data, valid = a.emit(ctx)
                return fn(data.astype(jnp.float64)), valid
            return BoundExpr(type=node.type, vocab=None, emit=emit_math)
        if name in ("lower", "upper"):
            return self._bind_string_map(
                args[0], (lambda v: v.lower()) if name == "lower" else
                (lambda v: v.upper()))
        if name == "concat":
            return self._bind_concat(args[0], args[1])
        if name.startswith("timestamp_floor_"):
            unit = name[len("timestamp_floor_"):]
            a = args[0]

            def emit_ts_floor(ctx):
                data, valid = a.emit(ctx)
                return _timestamp_floor(data.astype(jnp.int64), unit), valid
            return BoundExpr(type=EValueType.int64, vocab=None,
                             emit=emit_ts_floor)
        if name in ("is_finite", "is_nan"):
            a = args[0]
            fn = jnp.isfinite if name == "is_finite" else jnp.isnan

            def emit_fpred(ctx):
                data, valid = a.emit(ctx)
                return fn(data.astype(jnp.float64)), valid
            return BoundExpr(type=EValueType.boolean, vocab=None,
                             emit=emit_fpred)
        if name == "length":
            return self._bind_vocab_table(args[0], EValueType.int64,
                                          np.int64, len)
        if name in ("is_prefix", "is_substr"):
            # Non-literal pattern path comes through here; only literal
            # patterns (TStringPredicate) are supported for now.
            raise YtError(f"{name} requires a literal pattern",
                          code=EErrorCode.QueryUnsupported)
        if name == "farm_hash":
            return self._bind_hash(args)
        if name in ("regex_full_match", "regex_partial_match"):
            # Pattern compiles at PLAN time against the vocabulary (ref
            # regex_* builtins run RE2 per row; here the match set is a
            # host-computed table consumed by one device gather).
            rx = _compile_regex(_literal_bytes(node.args[0], name), name)
            return self._bind_vocab_table(
                args[1], EValueType.boolean, np.bool_,
                (lambda v: rx.fullmatch(v) is not None)
                if name == "regex_full_match"
                else (lambda v: rx.search(v) is not None))
        if name in ("regex_replace_first", "regex_replace_all"):
            rx = _compile_regex(_literal_bytes(node.args[0], name), name)
            rewrite = _literal_bytes(node.args[2], name)
            count = 1 if name == "regex_replace_first" else 0
            try:
                return self._bind_string_map(
                    args[1], lambda v: rx.sub(rewrite, v, count=count))
            except re.error as exc:
                raise YtError(f"{name}: invalid rewrite "
                              f"{rewrite!r}: {exc}",
                              code=EErrorCode.QueryParseError)
        if name == "regex_escape":
            return self._bind_string_map(args[0], re.escape)
        if name == "sha256":
            return self._bind_string_map(
                args[0], lambda v: hashlib.sha256(v).digest())
        if name == "bigb_hash":
            # A farm_hash-class string hash with its own mix (ref
            # bigb_hash over uids) — domain-separated from farm_hash.
            return self._bind_vocab_table(
                args[0], EValueType.uint64, np.uint64,
                lambda v: _bytes_hash(b"bigb:" + v))
        if name == "parse_int64":
            s = args[0]
            vocab = s.vocab if s.vocab is not None else _EMPTY_VOCAB

            def _try_parse(v: bytes):
                # Reference FromString semantics: optional sign + digits
                # only (Python int() would also take '1_2'), and the
                # value must FIT int64 (overflow → null, not a bind-time
                # OverflowError from np.int64).
                try:
                    text = v.strip()
                except AttributeError:
                    return 0, False
                if not re.fullmatch(rb"[+-]?[0-9]+", text):
                    return 0, False
                value = int(text)
                if not (-(1 << 63) <= value < (1 << 63)):
                    return 0, False
                return value, True
            parsed = [_try_parse(v) for v in vocab]
            val_t = np.array([p[0] for p in parsed] or [0],
                             dtype=np.int64)
            ok_t = np.array([p[1] for p in parsed] or [False],
                            dtype=np.bool_)
            val_slot = self.ctx.add(jnp.asarray(
                _pad_np(val_t, _vocab_bucket(len(val_t)), 0)))
            ok_slot = self.ctx.add(jnp.asarray(
                _pad_np(ok_t, _vocab_bucket(len(ok_t)), 0)))
            g_val = _gather_binding(val_slot)
            g_ok = _gather_binding(ok_slot)

            def emit_parse(ctx):
                data, valid = s.emit(ctx)
                # Unparseable strings yield null (ref parse_int64
                # error→null semantics for the non-throwing variant).
                return g_val(ctx, data), valid & g_ok(ctx, data)
            return BoundExpr(type=EValueType.int64, vocab=None,
                             emit=emit_parse)
        if name == "substr":
            start = int(_literal_int(node.args[1], name))
            length = int(_literal_int(node.args[2], name)) \
                if len(node.args) > 2 else None
            if start < 0 or (length is not None and length < 0):
                raise YtError("substr: start/length must be >= 0",
                              code=EErrorCode.QueryTypeError)
            end = None if length is None else start + length
            return self._bind_string_map(
                args[0], lambda v: v[start:end])
        if name in ("min_of", "max_of"):
            pick_min = name == "min_of"

            def emit_minmax(ctx):
                planes = [a.emit(ctx) for a in args]
                data, valid = planes[0]
                for d, v in planes[1:]:
                    d, data2 = _promote_pair(d, data)
                    better = (d < data2) if pick_min else (d > data2)
                    take = v & (~valid | better)
                    data = jnp.where(take, d, data2)
                    valid = valid | v
                return data, valid
            return BoundExpr(type=node.type, vocab=None, emit=emit_minmax)
        raise YtError(f"Function {name!r} has no lowering",
                      code=EErrorCode.QueryUnsupported)

    def _bind_if(self, node: ir.TFunction, args: list[BoundExpr]) -> BoundExpr:
        cond, then_b, else_b = args

        def select(ctx, planes):
            cd, cv = planes[0]
            td, tv = planes[1]
            ed, ev = planes[2]
            take_then = cv & cd.astype(bool)
            take_else = cv & ~cd.astype(bool)
            td2, ed2 = _promote_pair(td, ed)
            data = jnp.where(take_then, td2, ed2)
            valid = jnp.where(take_then, tv, take_else & ev)
            return data, valid
        return self._bind_merge_select(node, [cond, then_b, else_b], select,
                                       string_operands=(1, 2))

    def _bind_merge_select(self, node, args: list[BoundExpr], select,
                           string_operands: tuple[int, ...] = (0, 1)) -> BoundExpr:
        """Shared lowering for if/if_null: merges string vocabs of the
        value-producing operands when the result is string-typed."""
        if node.type is EValueType.string:
            value_args = [args[i] for i in string_operands]
            merged = _merge_vocabs(*[a.vocab for a in value_args])
            remap_gathers = {}
            for i in string_operands:
                a = args[i]
                vocab = a.vocab if a.vocab is not None else _EMPTY_VOCAB
                slot = self.ctx.add(jnp.asarray(_pad_np(
                    _remap_table(vocab, merged),
                    _vocab_bucket(max(len(vocab), 1)), 0)))
                remap_gathers[i] = _gather_binding(slot)

            def emit_str(ctx):
                planes = []
                for i, a in enumerate(args):
                    d, v = a.emit(ctx)
                    if i in remap_gathers and a.type is EValueType.string:
                        d = remap_gathers[i](ctx, d)
                    planes.append((d, v))
                return select(ctx, planes)
            return BoundExpr(type=node.type, vocab=merged, emit=emit_str)

        def emit(ctx):
            planes = [a.emit(ctx) for a in args]
            return select(ctx, planes)
        return BoundExpr(type=node.type, vocab=None, emit=emit)

    def _bind_concat(self, a: BoundExpr, b: BoundExpr) -> BoundExpr:
        """String concatenation at the vocabulary level: the result vocab is
        the (sorted, deduped) cross product of operand vocabs; the device
        computes pair index c_a * |v_b| + c_b and gathers through a bound
        remap.  Guarded by a cross-product cap."""
        va = a.vocab if a.vocab is not None else _EMPTY_VOCAB
        vb = b.vocab if b.vocab is not None else _EMPTY_VOCAB
        na, nb = max(len(va), 1), max(len(vb), 1)
        # nb bakes into the pair-index arithmetic below (a trace
        # constant the padded table shape alone cannot distinguish).
        self.ctx.note("concat", na, nb)
        if na * nb > 1 << 16:
            raise YtError(
                f"concat() vocabulary cross product too large "
                f"({len(va)}x{len(vb)}); reduce distinct values",
                code=EErrorCode.QueryUnsupported)
        pairs = [bytes(x) + bytes(y)
                 for x in (va if len(va) else [b""])
                 for y in (vb if len(vb) else [b""])]
        merged = np.array(sorted(set(pairs)), dtype=object)
        lookup = {v: i for i, v in enumerate(merged)}
        table = np.array([lookup[p] for p in pairs], dtype=np.int32)
        slot = self.ctx.add(jnp.asarray(
            _pad_np(table, _vocab_bucket(len(table)), 0)))
        gather = _gather_binding(slot)
        nb_const = nb

        def emit(ctx):
            da, valid_a = a.emit(ctx)
            db, valid_b = b.emit(ctx)
            pair = da.astype(jnp.int32) * nb_const + db.astype(jnp.int32)
            return gather(ctx, pair), valid_a & valid_b
        return BoundExpr(type=EValueType.string, vocab=merged, emit=emit)

    def _bind_vocab_table(self, a: BoundExpr, result_type: EValueType,
                          np_dtype, fn) -> BoundExpr:
        """String → scalar via a host-computed per-vocabulary table and
        one device gather (the length/regex/hash shape)."""
        vocab = a.vocab if a.vocab is not None else _EMPTY_VOCAB
        table = np.array([fn(v) for v in vocab] or [np_dtype()],
                         dtype=np_dtype)
        slot = self.ctx.add(jnp.asarray(
            _pad_np(table, _vocab_bucket(len(table)), 0)))
        gather = _gather_binding(slot)

        def emit(ctx):
            data, valid = a.emit(ctx)
            return gather(ctx, data), valid
        return BoundExpr(type=result_type, vocab=None, emit=emit)

    def _bind_string_map(self, a: BoundExpr, fn) -> BoundExpr:
        """Vocabulary-level string→string transform (lower/upper/…)."""
        vocab = a.vocab if a.vocab is not None else _EMPTY_VOCAB
        new_values = [fn(v) for v in vocab]
        new_vocab = np.array(sorted(set(new_values)), dtype=object)
        lookup = {v: i for i, v in enumerate(new_vocab)}
        table = np.array([lookup[v] for v in new_values], dtype=np.int32)
        if len(table) == 0:
            table = np.zeros(1, dtype=np.int32)
        slot = self.ctx.add(jnp.asarray(
            _pad_np(table, _vocab_bucket(len(table)), 0)))
        gather = _gather_binding(slot)

        def emit(ctx):
            data, valid = a.emit(ctx)
            return gather(ctx, data), valid
        return BoundExpr(type=EValueType.string, vocab=new_vocab, emit=emit)

    def _bind_hash(self, args: list[BoundExpr]) -> BoundExpr:
        hashed_args = []
        for a in args:
            if a.type is EValueType.string:
                vocab = a.vocab if a.vocab is not None else _EMPTY_VOCAB
                table = np.array(
                    [_bytes_hash(v) for v in vocab], dtype=np.uint64)
                if len(table) == 0:
                    table = np.zeros(1, dtype=np.uint64)
                slot = self.ctx.add(jnp.asarray(
                    _pad_np(table, _vocab_bucket(len(table)), 0)))
                hashed_args.append((a, _gather_binding(slot)))
            else:
                hashed_args.append((a, None))

        def emit(ctx):
            # Hash of a null value is defined (contributes 0), so the result
            # is always valid.
            acc = jnp.full(ctx.capacity, np.uint64(0x9E3779B97F4A7C15),
                           dtype=jnp.uint64)
            for a, gather in hashed_args:
                data, valid = a.emit(ctx)
                if gather is not None:
                    h = gather(ctx, data)
                else:
                    h = _mix_u64(data)
                h = jnp.where(valid, h, jnp.zeros_like(h))
                acc = _combine_u64(acc, h)
            return acc, jnp.ones(ctx.capacity, dtype=bool)
        return BoundExpr(type=EValueType.uint64, vocab=None, emit=emit)

    # -- membership / ranges / transform --------------------------------------

    def _bind_TIn(self, node: ir.TIn) -> BoundExpr:
        from ytsaurus_tpu.chunks.columnar import next_pow2
        operands = [self.bind(o) for o in node.operands]
        # IN lists trace a membership loop per tuple, so the list LENGTH
        # bakes into the program.  Bucket it pow2 (same discipline as
        # chunk capacities / lookup needles): padded slots carry
        # present=False so they match nothing, and `user_id IN (...)`
        # traffic with drifting list sizes compiles O(log max) programs
        # instead of one per length.
        n_bucket = next_pow2(len(node.values))
        self.ctx.note("in", n_bucket)
        value_planes, value_valids = self._bind_value_tuples(
            operands, node.values, pad_to=n_bucket)
        present_np = np.zeros(n_bucket, dtype=bool)
        present_np[: len(node.values)] = True
        present_slot = self.ctx.add(jnp.asarray(present_np))

        def emit(ctx):
            op_planes = [o.emit(ctx) for o in operands]
            match_any = jnp.zeros(ctx.capacity, dtype=bool)
            present = ctx.bindings[present_slot]
            for vi in range(n_bucket):
                row_match = jnp.ones(ctx.capacity, dtype=bool)
                for oi, (data, valid) in enumerate(op_planes):
                    const = ctx.bindings[value_planes[oi]][vi]
                    cvalid = ctx.bindings[value_valids[oi]][vi]
                    # null element matches null rows; non-null matches equal
                    # valid rows (null == null per CompareRowValues).
                    row_match = row_match & jnp.where(
                        cvalid, valid & (data == const), ~valid)
                match_any = match_any | (row_match & present[vi])
            return match_any, jnp.ones(ctx.capacity, dtype=bool)
        return BoundExpr(type=EValueType.boolean, vocab=None, emit=emit)

    def _bind_TBetween(self, node: ir.TBetween) -> BoundExpr:
        operands = [self.bind(o) for o in node.operands]
        string_ops = [o.type is EValueType.string for o in operands]
        bound_ranges = []
        for lower, upper in node.ranges:
            lo = self._bind_value_tuples(operands[: len(lower)], [lower],
                                         range_encode=True)
            up = self._bind_value_tuples(operands[: len(upper)], [upper],
                                         range_encode=True)
            bound_ranges.append((len(lower), lo, len(upper), up))

        def emit(ctx):
            op_planes = []
            for operand, is_str in zip(operands, string_ops):
                data, valid = operand.emit(ctx)
                if is_str:
                    # Doubled space: see _range_code.
                    data = data.astype(jnp.int32) * 2 + 1
                op_planes.append((data, valid))
            in_any = jnp.zeros(ctx.capacity, dtype=bool)
            for lo_len, lo_slots, up_len, up_slots in bound_ranges:
                ge = _lex_compare(ctx, op_planes[:lo_len], lo_slots, 0, ">=")
                le = _lex_compare(ctx, op_planes[:up_len], up_slots, 0, "<=")
                in_any = in_any | (ge & le)
            result = in_any
            if node.negated:
                result = ~result
            return result, jnp.ones(ctx.capacity, dtype=bool)
        return BoundExpr(type=EValueType.boolean, vocab=None, emit=emit)

    def _bind_TTransform(self, node: ir.TTransform) -> BoundExpr:
        operands = [self.bind(o) for o in node.operands]
        from_slots, from_valids = self._bind_value_tuples(
            operands, node.from_values)
        default = self.bind(node.default) if node.default is not None else None

        # Output values (may be strings → need an output vocab).
        out_vocab = None
        if node.type is EValueType.string:
            out_vocab = _merge_vocabs(
                np.array([v for v in node.to_values if v is not None],
                         dtype=object),
                default.vocab if default is not None else None)
            to_codes = np.array(
                [_vocab_code(out_vocab, v) if v is not None else 0
                 for v in node.to_values], dtype=np.int32)
            to_valid = np.array([v is not None for v in node.to_values])
            to_slot = self.ctx.add(jnp.asarray(to_codes if len(to_codes) else
                                               np.zeros(1, dtype=np.int32)))
            default_gather = None
            if default is not None and default.type is EValueType.string:
                vocab = default.vocab if default.vocab is not None else _EMPTY_VOCAB
                slot = self.ctx.add(jnp.asarray(_pad_np(
                    _remap_table(vocab, out_vocab),
                    _vocab_bucket(max(len(vocab), 1)), 0)))
                default_gather = _gather_binding(slot)
        else:
            dt = _dtype_for(node.type)
            to_np = np.array(
                [v if v is not None else 0 for v in node.to_values], dtype=dt)
            to_valid = np.array([v is not None for v in node.to_values])
            to_slot = self.ctx.add(jnp.asarray(to_np if len(to_np) else
                                               np.zeros(1, dtype=dt)))
            default_gather = None
        to_valid_slot = self.ctx.add(jnp.asarray(
            to_valid if len(to_valid) else np.zeros(1, dtype=bool)))

        def emit(ctx):
            op_planes = [o.emit(ctx) for o in operands]
            n_values = len(node.from_values)
            # Find first matching from-tuple per row.
            match_idx = jnp.full(ctx.capacity, n_values, dtype=jnp.int32)
            for vi in range(n_values - 1, -1, -1):
                row_match = jnp.ones(ctx.capacity, dtype=bool)
                for oi, (data, valid) in enumerate(op_planes):
                    const = ctx.bindings[from_slots[oi]][vi]
                    cvalid = ctx.bindings[from_valids[oi]][vi]
                    row_match = row_match & jnp.where(
                        cvalid, valid & (data == const), ~valid)
                match_idx = jnp.where(row_match, vi, match_idx)
            matched = match_idx < n_values
            safe_idx = jnp.clip(match_idx, 0, max(n_values - 1, 0))
            to_table = ctx.bindings[to_slot]
            to_valid_tab = ctx.bindings[to_valid_slot]
            data = to_table[safe_idx]
            valid = matched & to_valid_tab[safe_idx]
            if default is not None:
                dd, dv = default.emit(ctx)
                if default_gather is not None:
                    dd = default_gather(ctx, dd)
                dd = dd.astype(data.dtype)
                data = jnp.where(matched, data, dd)
                valid = jnp.where(matched, valid, dv)
            return data, valid
        return BoundExpr(type=node.type, vocab=out_vocab, emit=emit)

    def _bind_value_tuples(self, operands: list[BoundExpr],
                           values, range_encode: bool = False,
                           pad_to: Optional[int] = None
                           ) -> tuple[list[int], list[int]]:
        """Bind literal tuples column-wise; returns (value_slots, valid_slots)
        — one binding slot per operand holding the per-tuple constants
        (strings → codes) plus one holding the per-tuple element validity
        (False where the literal is null), so null tuple elements match null
        rows and nothing else (CompareRowValues semantics: null == null).

        range_encode=True (BETWEEN bounds): string literals ABSENT from
        the column's vocabulary must still order correctly against row
        codes, not collapse to -1 (which made `s BETWEEN 'a' AND 'b'`
        empty whenever the bounds were not column values).  Rows compare
        in a DOUBLED space (code*2+1, see _bind_TBetween); a present
        literal binds exactly (idx*2+1, equality preserved) and an
        absent one binds at its even insertion point (idx*2), which
        orders strictly between the neighboring codes and can equal no
        row — exactly the semantics of a value missing from the sorted
        vocabulary."""
        slots = []
        valid_slots = []
        for oi, operand in enumerate(operands):
            col = [tup[oi] if oi < len(tup) else None for tup in values]
            if operand.type is EValueType.string:
                vocab = operand.vocab if operand.vocab is not None else _EMPTY_VOCAB
                if range_encode:
                    arr = np.array(
                        [_range_code(vocab, v) if v is not None else 0
                         for v in col], dtype=np.int32)
                else:
                    arr = np.array(
                        [_vocab_code(vocab, v) if v is not None else -2
                         for v in col], dtype=np.int32)
            else:
                dt = _dtype_for(operand.type) if operand.type is not EValueType.null \
                    else np.int64
                arr = np.array([v if v is not None else 0 for v in col],
                               dtype=dt)
            ok = np.array([v is not None for v in col], dtype=bool)
            if len(arr) == 0:
                arr = np.zeros(1, dtype=arr.dtype)
                ok = np.zeros(1, dtype=bool)
            if pad_to is not None and len(arr) < pad_to:
                # pow2-bucketed value list (TIn): padded slots are
                # masked off by the caller's `present` binding.
                arr = _pad_np(arr, pad_to, 0)
                ok = _pad_np(ok, pad_to, False)
            slots.append(self.ctx.add(jnp.asarray(arr)))
            valid_slots.append(self.ctx.add(jnp.asarray(ok)))
        return slots, valid_slots

    # -- string predicates -----------------------------------------------------

    def _bind_TStringPredicate(self, node: ir.TStringPredicate) -> BoundExpr:
        operand = self.bind(node.operand)
        vocab = operand.vocab if operand.vocab is not None else _EMPTY_VOCAB
        matcher = _string_matcher(node)
        table = np.array([matcher(v) for v in vocab], dtype=bool)
        if len(table) == 0:
            table = np.zeros(1, dtype=bool)
        if node.negated:
            table = ~table
        slot = self.ctx.add(jnp.asarray(
            _pad_np(table, _vocab_bucket(len(table)), False)))
        gather = _gather_binding(slot)

        def emit(ctx):
            data, valid = operand.emit(ctx)
            return gather(ctx, data), valid
        return BoundExpr(type=EValueType.boolean, vocab=None, emit=emit)


_CMP_OPS = ("=", "!=", "<", "<=", ">", ">=")


def _compare(op: str, lhs: jax.Array, rhs: jax.Array) -> jax.Array:
    if op == "=":
        return lhs == rhs
    if op == "!=":
        return lhs != rhs
    if op == "<":
        return lhs < rhs
    if op == "<=":
        return lhs <= rhs
    if op == ">":
        return lhs > rhs
    if op == ">=":
        return lhs >= rhs
    raise AssertionError(op)


def _promote_pair(a: jax.Array, b: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Promote two numeric planes to a common dtype for comparison/select."""
    if a.dtype == b.dtype:
        return a, b
    target = jnp.promote_types(a.dtype, b.dtype)
    return a.astype(target), b.astype(target)


def _lex_compare(ctx: EmitContext, op_planes, slots, vi: int,
                 op: str) -> jax.Array:
    """Lexicographic tuple comparison against bound constants (tuple index vi).
    Null-aware: null sorts before every value and equals null (the
    CompareRowValues total order)."""
    value_slots, valid_slots = slots
    cap = ctx.capacity
    result = jnp.full(cap, op in ("<=", ">="), dtype=bool)
    # Build from least-significant operand backwards:
    for oi in range(len(op_planes) - 1, -1, -1):
        data, valid = op_planes[oi]
        const = ctx.bindings[value_slots[oi]][vi]
        cvalid = ctx.bindings[valid_slots[oi]][vi]
        eq = jnp.where(cvalid, valid & (data == const), ~valid)
        if op in ("<=", "<"):
            lt = jnp.where(cvalid, (~valid) | (data < const),
                           jnp.zeros(cap, dtype=bool))
            result = lt | (eq & result)
        else:
            gt = jnp.where(cvalid, valid & (data > const), valid)
            result = gt | (eq & result)
    return result


def _string_matcher(node: ir.TStringPredicate):
    pattern = node.pattern
    if node.kind == "prefix":
        return lambda v: v.startswith(pattern)
    if node.kind == "substr":
        return lambda v: pattern in v
    if node.kind == "regex":
        rx = _compile_regex(pattern, "regex predicate")
        return lambda v: rx.fullmatch(v) is not None
    if node.kind == "like":
        rx = _like_to_regex(pattern, node.case_insensitive)
        return lambda v: rx.fullmatch(v) is not None
    raise YtError(f"Unknown string predicate {node.kind!r}")


def _like_to_regex(pattern: bytes, case_insensitive: bool):
    """SQL LIKE → regex: % and _ wildcard; backslash escapes the next
    character (\\% and \\_ match literally, \\\\ is a backslash — the
    standard ESCAPE '\\' semantics the reference's LIKE applies)."""
    out = []
    chars = pattern.decode("utf-8", errors="surrogateescape")
    i = 0
    while i < len(chars):
        ch = chars[i]
        if ch == "\\":
            # Standard ESCAPE: only %, _, and \ may follow; anything
            # else (incl. a trailing lone backslash) is a pattern error,
            # not a silent guess.
            if i + 1 >= len(chars) or chars[i + 1] not in "%_\\":
                raise YtError(
                    f"LIKE: invalid escape in pattern {pattern!r} "
                    f"(backslash must precede %, _ or \\)",
                    code=EErrorCode.QueryParseError)
            out.append(re.escape(chars[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    flags = re.DOTALL | (re.IGNORECASE if case_insensitive else 0)
    return re.compile("".join(out).encode("utf-8", errors="surrogateescape"),
                      flags)


def _days_to_civil(days: jax.Array):
    """Vectorized days-since-epoch → (year, month, day), proleptic Gregorian
    (the civil-from-days algorithm as pure integer device ops)."""
    z = days + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(
        doe - doe // 1460 + doe // 36524 - doe // 146096, 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = y + (m <= 2)
    return y, m, d


def _civil_to_days(y: jax.Array, m: jax.Array, d: jax.Array) -> jax.Array:
    y = y - (m <= 2)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.mod(m + 9, 12)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


def _timestamp_floor(ts: jax.Array, unit: str) -> jax.Array:
    """Floor unix seconds to a calendar boundary (weeks start Monday)."""
    if unit == "hour":
        return ts - jnp.mod(ts, 3600)
    if unit == "day":
        return ts - jnp.mod(ts, 86400)
    days = jnp.floor_divide(ts, 86400)
    if unit == "week":
        dow = jnp.mod(days + 3, 7)       # epoch day was a Thursday
        return (days - dow) * 86400
    y, m, _ = _days_to_civil(days)
    if unit == "month":
        return _civil_to_days(y, m, jnp.ones_like(m)) * 86400
    if unit == "year":
        one = jnp.ones_like(y)
        return _civil_to_days(y, one, one) * 86400
    raise YtError(f"Unknown timestamp unit {unit!r}",
                  code=EErrorCode.QueryUnsupported)


def _compile_regex(pattern: bytes, what: str):
    try:
        return re.compile(pattern)
    except re.error as exc:
        raise YtError(f"{what}: invalid regex {pattern!r}: {exc}",
                      code=EErrorCode.QueryParseError)


def _literal_bytes(arg, what: str) -> bytes:
    """Plan-time literal string (patterns/rewrites compile against the
    vocabulary at bind time; a computed pattern has no vocabulary-sized
    table)."""
    if not isinstance(arg, ir.TLiteral) or not isinstance(arg.value,
                                                          (bytes, str)):
        raise YtError(f"{what} requires a literal string argument",
                      code=EErrorCode.QueryUnsupported)
    value = arg.value
    return value.encode() if isinstance(value, str) else value


def _literal_int(arg, what: str) -> int:
    if not isinstance(arg, ir.TLiteral) or not isinstance(arg.value, int):
        raise YtError(f"{what} requires a literal integer argument",
                      code=EErrorCode.QueryUnsupported)
    return arg.value


def _bytes_hash(v: bytes) -> np.uint64:
    """Deterministic 64-bit FNV-1a (stands in for FarmHash; stable across
    runs, which is all sharding/sampling needs)."""
    h = np.uint64(0xCBF29CE484222325)
    for b in v:
        h = np.uint64((int(h) ^ b) * 0x100000001B3 % (1 << 64))
    return h


def _mix_u64(data: jax.Array) -> jax.Array:
    x = data.astype(jnp.uint64) if data.dtype != jnp.float64 else \
        jax.lax.bitcast_convert_type(data, jnp.uint64)
    x = x ^ (x >> np.uint64(33))
    x = x * np.uint64(0xFF51AFD7ED558CCD)
    x = x ^ (x >> np.uint64(33))
    return x


def _combine_u64(a: jax.Array, b: jax.Array) -> jax.Array:
    return (a ^ b) * np.uint64(0x9E3779B97F4A7C15) + (a << np.uint64(6))
