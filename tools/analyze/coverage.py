"""Failpoint & span coverage pass (`yt analyze --pass coverage`).

Two disciplines established by PR 2 (deterministic failpoints) and PR 5
(span-site rules), enforced statically:

  failpoint-coverage   a function in the server/chunk/rpc planes that
                       performs REAL I/O (file open/replace/remove,
                       socket connect) must contain a failpoint probe
                       (`<site>.hit()` / `.write_hit()` / `.fire()`) —
                       or carry an explicit waiver
                       (`# analyze: allow(failpoint): reason`) on its
                       def line.  The chaos soak can only prove recovery
                       for faults it can inject.
  span-discipline      root-span creation (`start_span`,
                       `start_query_span`, bare `TraceContext(...)`)
                       is allowed ONLY at the declared entry points; an
                       interior site that roots a fresh trace orphans
                       itself from the caller's flight recording —
                       interior code uses `child_span` (PR 5 rule).
"""

from __future__ import annotations

import ast

from tools.analyze.core import (
    Finding,
    SourceFile,
    dotted_name,
    walk_functions,
)

PASS_NAME = "coverage"

# Planes whose I/O functions must be injectable.
FAILPOINT_PREFIXES = (
    "ytsaurus_tpu/chunks/",
    "ytsaurus_tpu/rpc/",
    "ytsaurus_tpu/server/",
)

# Call shapes that constitute REAL I/O for coverage purposes.  Curated
# to state-bearing operations (durability/wire boundaries), not every
# os.path probe.
_IO_CALLS = {
    "open",
    "os.replace", "os.rename", "os.remove", "os.unlink", "os.fsync",
    "socket.create_connection", "asyncio.open_connection",
}

# Failpoint probe shapes: a call whose attribute is one of these on any
# receiver (`_FP_READ.hit()`, `site.write_hit(blob)`, `_FP.fire()`).
_PROBE_ATTRS = {"hit", "write_hit", "fire"}

# Modules allowed to root traces (the PR 5 entry points) — everything
# else must use child_span.
SPAN_ENTRY_FILES = {
    "ytsaurus_tpu/client.py",           # gateway select/lookup roots
    "ytsaurus_tpu/operations/scheduler.py",   # operation roots
    "ytsaurus_tpu/server/http_proxy.py",      # X-YT-Trace-Id ingress
    "ytsaurus_tpu/utils/tracing.py",          # the substrate itself
    "ytsaurus_tpu/rpc/server.py",             # wire-context restore
}

_ROOT_SPAN_CALLS = {"start_span", "start_query_span",
                    "tracing.start_span", "tracing.start_query_span"}


def _is_io_call(call: ast.Call) -> "str | None":
    name = dotted_name(call.func)
    if name in _IO_CALLS:
        return name
    return None


def _has_probe(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _PROBE_ATTRS:
            return True
    return False


def _check_failpoints(f: SourceFile, findings: "list[Finding]") -> None:
    for cls, fn in walk_functions(f.tree):
        io_sites = []
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = _is_io_call(node)
                if name is not None and \
                        not f.waived("failpoint", node.lineno):
                    io_sites.append((name, node.lineno))
        if not io_sites or _has_probe(fn):
            continue
        if f.function_waived("failpoint", fn):
            continue
        names = ", ".join(sorted({n for n, _ in io_sites}))
        qual = f"{cls}.{fn.name}" if cls else fn.name
        findings.append(Finding(
            PASS_NAME, "failpoint", f.path, fn.lineno,
            f"{qual} performs I/O ({names} at line"
            f"{'s' if len(io_sites) > 1 else ''} "
            f"{', '.join(str(l) for _, l in io_sites)}) but contains "
            f"no failpoints probe — register a site "
            f"(utils/failpoints.register_site) and call `.hit()` at "
            f"the boundary, or waive with `# analyze: "
            f"allow(failpoint): reason`"))


def _check_spans(f: SourceFile, findings: "list[Finding]") -> None:
    if f.path in SPAN_ENTRY_FILES:
        return
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        rooted = None
        if name in _ROOT_SPAN_CALLS:
            rooted = name
        elif name == "TraceContext" or name.endswith(".TraceContext"):
            rooted = "TraceContext(...)"
        if rooted is None or f.waived("span-root", node.lineno):
            continue
        findings.append(Finding(
            PASS_NAME, "span-root", f.path, node.lineno,
            f"{rooted} roots a fresh trace outside the declared entry "
            f"points ({', '.join(sorted(SPAN_ENTRY_FILES))}) — interior "
            f"sites use child_span so the work stays inside the "
            f"caller's trace"))


def run(files: "list[SourceFile]") -> "list[Finding]":
    findings: list[Finding] = []
    for f in files:
        if any(f.path.startswith(p) for p in FAILPOINT_PREFIXES):
            _check_failpoints(f, findings)
        _check_spans(f, findings)
    return findings
