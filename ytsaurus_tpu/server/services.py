"""RPC services hosted by the cluster daemons.

DataNodeService   — blob-level chunk storage + journal (quorum WAL) records.
NodeTrackerService — data-node registration/heartbeats on the primary.
DriverService     — the full driver command registry over RPC, plus
                    tx-id-based transactions and chunk location metadata
                    (the proxy pattern: the client stays thin).
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.rpc import Service, rpc_method
from ytsaurus_tpu.rpc.wire import wire_text as _text
from ytsaurus_tpu.utils import failpoints
from ytsaurus_tpu.utils.logging import get_logger

# Injects a disk fault into the data node's DURABLE-state publishes
# (journal membership + replicated snapshots, both tmp+fsync+rename):
# the writer sees a failed put and the quorum ladder must ride it out.
_FP_STATE_WRITE = failpoints.register_site(
    "server.state.write",
    error=lambda s: OSError(f"injected state write failure at {s}"))

logger = get_logger("server")


def chunk_push_request(store, chunk_id: str) -> "tuple[dict, bytes]":
    """(body, blob) for a node-to-node chunk push — ONE protocol shared
    by the replicator's replicate_chunk job and P2P seeding (erasure
    chunks reconstruct on read and carry their codec tag so the target
    re-encodes the full part set)."""
    blob = store.get_blob(chunk_id)
    body = {"chunk_id": chunk_id}
    erasure = store.erasure_codec_of(chunk_id)
    if erasure is not None:
        body["erasure"] = erasure
    return body, blob


class DataNodeService(Service):
    """Serves chunk blobs + journal records from one store location."""

    name = "data_node"

    def __init__(self, store, journal_dir: str):
        import os
        self.store = store
        self.journal_dir = journal_dir
        os.makedirs(journal_dir, exist_ok=True)
        self._journals: dict[str, object] = {}
        self._epochs: dict[str, tuple] = {}   # journal → (epoch, writer)
        # journal → (writer, monotonic expiry).  In-memory only: a
        # restarted journal node forgets leases, which merely makes a
        # takeover attempt possible — epoch fencing still arbitrates it.
        self._leases: dict[str, tuple[str, float]] = {}
        self._peers: dict[str, object] = {}   # replicate_chunk channels
        self._journal_lock = threading.Lock()
        self._scrub_lock = threading.Lock()

    # -- chunks ---------------------------------------------------------------

    @rpc_method()
    def put_chunk(self, body, attachments):
        chunk_id = _text(body["chunk_id"])
        erasure = body.get("erasure")
        self.store.put_blob(chunk_id, attachments[0],
                            erasure=_text(erasure) if erasure else None)
        return {}

    # Set by the daemon when P2P hot-chunk distribution is on; reads
    # then feed its heat accounting (server/p2p.py).
    p2p = None

    @rpc_method()
    def get_chunk(self, body, attachments):
        chunk_id = _text(body["chunk_id"])
        if self.p2p is not None:
            self.p2p.record_read(chunk_id)
        return {}, [self.store.get_blob(chunk_id)]

    @rpc_method()
    def has_chunk(self, body, attachments):
        return {"exists": self.store.exists(_text(body["chunk_id"]))}

    @rpc_method()
    def remove_chunk(self, body, attachments):
        self.store.remove_chunk(_text(body["chunk_id"]))
        return {}

    @rpc_method()
    def list_chunks(self, body, attachments):
        return {"chunk_ids": self.store.list_chunks()}

    @rpc_method(concurrency=1)
    def scrub_chunks(self, body, attachments):
        """Background checksum scrub (ref: the reference's disk-failure
        detection + replica failure marks feeding the replicator):
        deep-verify every local chunk's block CRCs; corrupt ones are
        QUARANTINED so list_chunks stops advertising them and the
        master's chunk replicator restores the replication factor from
        healthy holders — with no read on the user path."""
        corrupt: list = []
        checked = 0
        only = body.get("chunk_ids")
        ids = [_text(c) for c in only] if only else \
            self.store.list_chunks()
        # One scrub at a time — the RPC concurrency cap does not bind
        # the daemon's direct in-process calls, so serialize here.
        with self._scrub_lock:
            for chunk_id in ids:
                if not self.store.exists(chunk_id):
                    continue        # deleted mid-scan: not corruption
                checked += 1
                if not self.store.verify_chunk(chunk_id):
                    self.store.quarantine_chunk(chunk_id)
                    corrupt.append(chunk_id)
        return {"checked": checked, "corrupt": corrupt}

    @rpc_method()
    def replicate_chunk(self, body, attachments):
        """Push one locally-held chunk to a peer data node — the
        Replicate/Repair job of the master's chunk replicator
        (chunk_replicator.h), executed node-to-node so chunk data never
        crosses the master.  Erasure chunks: get_blob reconstructs from
        surviving parts (repairing local damage as a side effect) and
        the target re-encodes the full part set."""
        from ytsaurus_tpu.rpc import Channel, RetryingChannel
        chunk_id = _text(body["chunk_id"])
        target = _text(body["target"])
        req, blob = chunk_push_request(self.store, chunk_id)
        with self._journal_lock:
            peer = self._peers.get(target)
            if peer is None:
                peer = RetryingChannel(Channel(target, timeout=60),
                                       attempts=2, backoff=0.1)
                self._peers[target] = peer
        peer.call("data_node", "put_chunk", req, [blob])
        return {}

    # -- journals (quorum changelog storage) ----------------------------------
    #
    # Appends are POSITION-CHECKED: the writer states the index its records
    # start at; a mismatch is rejected, so this location always holds a
    # prefix of the writer's log (the invariant quorum recovery relies on).
    # Opening a journal truncates any torn tail first (LocalWal contract).

    @staticmethod
    def _check_name(name: str) -> str:
        if not name.replace("_", "").replace("-", "").isalnum():
            raise YtError(f"Bad journal name {name!r}")
        return name

    def _journal(self, name: str):
        import os

        from ytsaurus_tpu.cypress.quorum import LocalWal, record_epoch
        self._check_name(name)
        with self._journal_lock:
            entry = self._journals.get(name)
            if entry is None:
                wal = LocalWal(os.path.join(self.journal_dir,
                                            name + ".log"))
                records = wal.recover()
                entry = {"wal": wal, "count": len(records),
                         "last_epoch": record_epoch(records[-1])
                         if records else 0}
                self._journals[name] = entry
            return entry

    def _epoch_path(self, name: str) -> str:
        import os
        return os.path.join(self.journal_dir, name + ".epoch")

    def _epoch_state(self, name: str) -> "tuple[int, str]":
        """Cached (epoch, writer) — the append hot path must not read the
        sidecar file per record (it is loaded once per process)."""
        cached = self._epochs.get(name)
        if cached is None:
            from ytsaurus_tpu.utils.diskio import read_epoch_file
            cached = read_epoch_file(self._epoch_path(name))
            self._epochs[name] = cached
        return cached

    def _set_epoch_state(self, name: str, epoch: int, writer: str) -> None:
        from ytsaurus_tpu.utils.diskio import write_epoch_file
        write_epoch_file(self._epoch_path(name), epoch, writer)
        self._epochs[name] = (epoch, writer)

    def _check_writer(self, name: str, epoch, writer) -> None:
        """Fencing rule shared by append/reset/snapshot: a request from an
        older epoch — or the same epoch under a DIFFERENT writer id (two
        candidates tied on disjoint grant sets) — is rejected; a newer
        epoch is adopted (a replica that missed the acquisition learns it
        from the first write that reaches it)."""
        if epoch is None:
            return
        epoch = int(epoch)
        writer = _text(writer or "")
        stored, stored_writer = self._epoch_state(name)
        if epoch < stored or (epoch == stored and stored_writer and
                              writer != stored_writer):
            raise YtError(
                f"journal writer fenced: epoch {epoch}/{writer!r} vs "
                f"stored {stored}/{stored_writer!r}",
                code=EErrorCode.JournalEpochFenced,
                attributes={"stored_epoch": stored})
        if epoch > stored:
            self._set_epoch_state(name, epoch, writer)

    @rpc_method(concurrency=1)
    def journal_acquire(self, body, attachments):
        """Epoch acquisition (ref Hydra changelog acquisition /
        lease_tracker fencing): a writer claims a strictly higher epoch;
        stale writers' journal writes are rejected from then on.

        While an unexpired lease is held by a DIFFERENT writer the grant
        is refused — a flapping standby must not fence a healthy leader
        (disruption guard; safety never depends on it).  A granted
        acquisition also grants the lease when lease_ttl is present, so
        an elected leader is lease-covered before its first write."""
        name = self._check_name(_text(body["journal"]))
        epoch = int(body["epoch"])
        writer = _text(body.get("writer") or "")
        ttl = float(body.get("lease_ttl") or 0)
        with self._journal_lock:
            holder, expiry = self._leases.get(name, ("", 0.0))
            if holder and holder != writer and \
                    time.monotonic() < expiry:
                return {"granted": False, "epoch": self._epoch_state(name)[0],
                        "lease_holder": holder}
            stored, _ = self._epoch_state(name)
            if epoch <= stored:
                return {"granted": False, "epoch": stored}
            self._set_epoch_state(name, epoch, writer)
            if ttl > 0:
                self._leases[name] = (writer, time.monotonic() + ttl)
            return {"granted": True, "epoch": epoch}

    @rpc_method(concurrency=1)
    def journal_lease_renew(self, body, attachments):
        """Leader lease renewal: granted ONLY to the exact current epoch
        holder — a fenced writer learns it lost leadership here, and a
        writer that never won journal_acquire cannot install a lease by
        presenting a higher epoch (renewal never adopts epochs; only
        acquisition and position-checked appends do)."""
        name = self._check_name(_text(body["journal"]))
        epoch = int(body["epoch"])
        writer = _text(body.get("writer") or "")
        ttl = float(body.get("ttl") or 0)
        with self._journal_lock:
            stored, stored_writer = self._epoch_state(name)
            if epoch != stored or (stored_writer and
                                   writer != stored_writer):
                return {"granted": False, "epoch": stored}
            self._leases[name] = (writer, time.monotonic() + ttl)
            return {"granted": True}

    @rpc_method()
    def journal_lease(self, body, attachments):
        """Lease status probe for election candidates."""
        name = self._check_name(_text(body["journal"]))
        with self._journal_lock:
            holder, expiry = self._leases.get(name, ("", 0.0))
            epoch, _ = self._epoch_state(name)
            return {"writer": holder, "epoch": epoch,
                    "remaining": max(expiry - time.monotonic(), 0.0)}

    # -- journal membership (shared source of truth for multi-master) ----------
    #
    # Which node ids form the quorum set is itself metadata that every
    # master must agree on; it lives ON the journal nodes (fenced writes,
    # epoch-stamped) so a standby reads it instead of guessing from its
    # own view of node registration order.

    def _membership_path(self, name: str) -> str:
        import os
        return os.path.join(self.journal_dir, name + ".members")

    @rpc_method(concurrency=1)
    def journal_membership_put(self, body, attachments):
        import os

        from ytsaurus_tpu import yson
        name = self._check_name(_text(body["journal"]))
        _FP_STATE_WRITE.hit()
        with self._journal_lock:
            self._check_writer(name, body.get("epoch"),
                               body.get("writer"))
            payload = yson.dumps(
                {"epoch": int(body["epoch"]),
                 "member_ids": [_text(m) for m in body["member_ids"]]},
                binary=True)
            path = self._membership_path(name)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        return {}

    # analyze: allow(failpoint): read side — a missing/torn file already reads as defaults; recovery is quorum-WAL tested
    @rpc_method()
    def journal_membership_get(self, body, attachments):
        import os

        from ytsaurus_tpu import yson
        name = self._check_name(_text(body["journal"]))
        path = self._membership_path(name)
        if not os.path.exists(path):
            return {"member_ids": None, "epoch": 0}
        with open(path, "rb") as f:
            data = yson.loads(f.read())
        return {"member_ids": data.get("member_ids"),
                "epoch": int(data.get("epoch", 0))}

    @rpc_method()
    def journal_epoch(self, body, attachments):
        name = self._check_name(_text(body["journal"]))
        with self._journal_lock:
            epoch, writer = self._epoch_state(name)
            return {"epoch": epoch, "writer": writer}

    @rpc_method(concurrency=1)
    def journal_append(self, body, attachments):
        from ytsaurus_tpu.cypress.quorum import record_epoch
        name = _text(body["journal"])
        entry = self._journal(name)
        position = body.get("position")
        prev_epoch = body.get("prev_epoch")
        with self._journal_lock:
            self._check_writer(name, body.get("epoch"), body.get("writer"))
            if position is not None and int(position) != entry["count"]:
                raise YtError(
                    f"journal position mismatch: writer at {position}, "
                    f"location at {entry['count']}",
                    code=EErrorCode.JournalPositionMismatch,
                    attributes={"expected": entry["count"]})
            # Raft-style consistency check: the writer states the epoch
            # of ITS record preceding this append; a mismatch means this
            # location's tail is another (fenced) writer's fork and must
            # be reset, not extended.
            if prev_epoch is not None and \
                    int(prev_epoch) != entry["last_epoch"]:
                raise YtError(
                    f"journal tail diverged: writer expects prev epoch "
                    f"{prev_epoch}, location tail epoch is "
                    f"{entry['last_epoch']}",
                    code=EErrorCode.JournalDivergence)
            for record in body["records"]:
                entry["wal"].append(record)
                entry["count"] += 1
                entry["last_epoch"] = record_epoch(record)
        return {"count": entry["count"]}

    @rpc_method()
    def journal_read(self, body, attachments):
        import os

        from ytsaurus_tpu.cypress.master import Changelog
        name = self._check_name(_text(body["journal"]))
        path = os.path.join(self.journal_dir, name + ".log")
        # A journal this node never held must be reported as uninitialized
        # BEFORE any auto-creating open: a fresh disk may not vote an empty
        # prefix in quorum recovery.
        if not os.path.exists(path) and name not in self._journals:
            return {"records": [], "initialized": False}
        self._journal(name)        # open (truncates any torn tail)
        records, _ = Changelog.read_all(path)
        return {"records": records, "initialized": True}

    @rpc_method()
    def journal_count(self, body, attachments):
        """Count + tail-epoch — the cheap liveness/lag/divergence probe
        for catch-up (no record payloads cross the wire)."""
        import os
        name = self._check_name(_text(body["journal"]))
        path = os.path.join(self.journal_dir, name + ".log")
        if not os.path.exists(path) and name not in self._journals:
            return {"count": 0, "initialized": False}
        entry = self._journal(name)
        return {"count": entry["count"], "initialized": True,
                "last_epoch": entry["last_epoch"]}

    # analyze: allow(failpoint): unlink of a journal already truncated by fence checks; append faults inject upstream
    @rpc_method(concurrency=1)
    def journal_reset(self, body, attachments):
        """Truncate a journal to empty (after a snapshot, or a divergence
        reset in catch-up).  FENCED like appends: a stale master's
        divergence reset must not destroy the new master's records."""
        import os
        name = self._check_name(_text(body["journal"]))
        with self._journal_lock:
            self._check_writer(name, body.get("epoch"), body.get("writer"))
            entry = self._journals.pop(name, None)
            if entry is not None:
                entry["wal"].close()
            path = os.path.join(self.journal_dir, name + ".log")
            if os.path.exists(path):
                os.unlink(path)
        return {}

    # -- replicated snapshots --------------------------------------------------

    @rpc_method(concurrency=1)
    def snapshot_put(self, body, attachments):
        import os
        name = self._check_name(_text(body["name"]))
        _FP_STATE_WRITE.hit()
        with self._journal_lock:
            self._check_writer(name, body.get("epoch"),
                               body.get("writer"))
        seq = int(body["seq"])
        path = os.path.join(self.journal_dir, f"{name}.snap")
        tmp = path + ".tmp"
        from ytsaurus_tpu import yson
        with open(tmp, "wb") as f:
            f.write(yson.dumps({"seq": seq}, binary=True))
            f.write(b"\n")
            f.write(attachments[0])
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return {}

    # analyze: allow(failpoint): read side — a missing snapshot reads as seq=None; recovery is quorum-WAL tested
    @rpc_method()
    def snapshot_get(self, body, attachments):
        import os
        name = self._check_name(_text(body["name"]))
        path = os.path.join(self.journal_dir, f"{name}.snap")
        if not os.path.exists(path):
            return {"seq": None}
        from ytsaurus_tpu import yson
        with open(path, "rb") as f:
            data = f.read()
        head, _, blob = data.partition(b"\n")
        meta = yson.loads(head)
        return {"seq": int(meta["seq"])}, [blob]


class MasterService(Service):
    """Role probe for election-aware clients: leader or follower.

    Ref shape: the election service's GetStatus / cell directory role
    discovery (yt/yt/server/lib/election/)."""

    name = "master"

    def __init__(self, role_ref: dict):
        self.role_ref = role_ref       # {"value": "leader" | "follower"}

    @rpc_method()
    def get_role(self, body, attachments):
        return {"role": self.role_ref["value"]}


class NodeTracker:
    """Alive-node registry kept by the primary (heartbeat-driven).

    Nodes have STABLE ids (their store identity) and ephemeral addresses;
    journal placement binds to ids, chunk reads resolve addresses live."""

    def __init__(self, liveness_timeout: float = 15.0):
        self._nodes: dict[str, tuple[str, float]] = {}   # id → (addr, t)
        self._lock = threading.Lock()
        self.liveness_timeout = liveness_timeout

    def heartbeat(self, node_id: str, address: str) -> None:
        with self._lock:
            self._nodes[node_id] = (address, time.monotonic())

    def alive(self) -> dict[str, str]:
        now = time.monotonic()
        with self._lock:
            return {i: a for i, (a, t) in sorted(self._nodes.items())
                    if now - t < self.liveness_timeout}

    def alive_nodes(self) -> list[str]:
        return list(self.alive().values())

    def drop(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)


class NodeTrackerService(Service):
    name = "node_tracker"

    def __init__(self, tracker: NodeTracker):
        self.tracker = tracker

    @rpc_method()
    def heartbeat(self, body, attachments):
        self.tracker.heartbeat(_text(body.get("id") or body["address"]),
                               _text(body["address"]))
        return {"alive": self.tracker.alive_nodes()}

    @rpc_method()
    def list_nodes(self, body, attachments):
        return {"alive": self.tracker.alive_nodes(),
                "nodes": self.tracker.alive()}


class DriverService(Service):
    """The proxy: executes driver commands against the server-side client.

    Transactions are tx-id based across the wire (the client cannot hold a
    live TabletTransaction object); the registry maps ids to live tx state,
    like the reference's transaction leases on the proxy."""

    name = "driver"

    TX_LEASE_SECONDS = 300.0

    def __init__(self, client):
        from ytsaurus_tpu.driver import Driver
        self.client = client
        self.driver = Driver(client)
        self._transactions: dict[str, tuple[object, float]] = {}
        self._tx_lock = threading.Lock()

    def _sweep_expired_locked(self) -> None:
        """Abort transactions whose lease lapsed (crashed clients must not
        hold 2PC row locks forever — the proxy transaction-lease analog)."""
        now = time.monotonic()
        for tx_id in [i for i, (_, t) in self._transactions.items()
                      if now - t > self.TX_LEASE_SECONDS]:
            tx, _ = self._transactions.pop(tx_id)
            try:
                self.client.abort_transaction(tx)
                logger.warning("aborted expired transaction %s", tx_id)
            except Exception:      # noqa: BLE001 — sweep must not fail ops
                pass

    @rpc_method()
    def ping(self, body, attachments):
        return {"ok": True}

    @rpc_method(concurrency=8)
    def execute(self, body, attachments):
        from ytsaurus_tpu.cypress.security import authenticated_user
        command = _text(body["command"])
        parameters = body.get("parameters") or {}
        if attachments:
            # Bulk row payloads (formatted write_table bodies) ride as
            # attachments, not YSON parameters.
            parameters = dict(parameters)
            parameters["rows"] = attachments[0]
        # Per-request principal (ref: TAuthenticatedUserGuard around every
        # driver invocation).
        user = _text(body.get("user") or "root")
        with authenticated_user(user):
            result = self.driver.execute(command, parameters)
        if isinstance(result, bytes):
            return {"kind": "blob"}, [result]
        return {"kind": "value", "result": result}

    # -- transactions over the wire -------------------------------------------

    def _tx(self, tx_id: str):
        with self._tx_lock:
            self._sweep_expired_locked()
            entry = self._transactions.get(tx_id)
            if entry is not None:
                # Touch the lease on every use.
                self._transactions[tx_id] = (entry[0], time.monotonic())
        if entry is None:
            raise YtError(f"No such transaction {tx_id}",
                          code=EErrorCode.NoSuchTransaction)
        return entry[0]

    @rpc_method()
    def start_transaction(self, body, attachments):
        tx = self.client.start_transaction()
        with self._tx_lock:
            self._sweep_expired_locked()
            self._transactions[tx.id] = (tx, time.monotonic())
        return {"tx_id": tx.id, "start_timestamp": tx.start_timestamp}

    @rpc_method()
    def commit_transaction(self, body, attachments):
        tx_id = _text(body["tx_id"])
        tx = self._tx(tx_id)
        try:
            ts = self.client.commit_transaction(tx)
        finally:
            with self._tx_lock:
                self._transactions.pop(tx_id, None)
        return {"commit_timestamp": ts}

    @rpc_method()
    def abort_transaction(self, body, attachments):
        tx_id = _text(body["tx_id"])
        tx = self._tx(tx_id)
        try:
            self.client.abort_transaction(tx)
        finally:
            with self._tx_lock:
                self._transactions.pop(tx_id, None)
        return {}

    @rpc_method()
    def insert_rows_tx(self, body, attachments):
        tx = self._tx(_text(body["tx_id"]))
        self.client.insert_rows(_text(body["path"]), body["rows"], tx=tx,
                                update=bool(body.get("update", False)))
        return {}

    @rpc_method()
    def delete_rows_tx(self, body, attachments):
        tx = self._tx(_text(body["tx_id"]))
        keys = [tuple(k) for k in body["keys"]]
        self.client.delete_rows(_text(body["path"]), keys, tx=tx)
        return {}
