"""Per-tenant resource accounting (ISSUE 6 tentpole, piece 2).

Ref shape: the reference meters every request against its (user, pool)
principal — operation pool trees account CPU/memory per pool, query
agents fold per-query statistics into per-user usage counters the
scheduler's fair-share and the admin `yt top`-style views read.  Here
the serving plane already threads an admitted query's identity
(CancellationToken pool + the authenticated-user contextvar) through
`coordinator.coordinate_and_execute`, the evaluator, and the tablet
read path; this module is where finished work FOLDS into cumulative
usage:

  select_rows      ExecutionProfile counters (rows read/returned, bytes
                   scanned, compile/execute seconds, admission wait,
                   retries, cache hits) fold per (pool, user) in
                   `client.select_rows`.
  lookups          each batched flush folds its key/row counts under
                   the cohort's pool (query/serving.LookupBatcher).
  admission        rejects fold as `throttled` (AdmissionController).
  operations/jobs  each finished operation folds wall seconds + job
                   counts under its spec pool (operations/scheduler).
  views            each committed materialized-view micro-batch folds
                   rows + wall seconds under the view's pool
                   (query/views.ViewRefresher), so continuous-query
                   daemon load shows up in `yt top` like any tenant.

Cumulative per-POOL sensors mirror the fold into the profiler registry
(`accounting_usage_*{pool=}` on /metrics — bounded tag cardinality:
pools are config, users are not), so the history rings retain usage
trends; the full (pool, user) matrix serves through monitoring
`/accounting`, orchid `/accounting`, and the `yt top` CLI.  This is the
usage signal fair-share serving (ROADMAP 3) weighs pools by.
"""

from __future__ import annotations

import threading
from typing import Optional

from ytsaurus_tpu.utils.profiling import PoolSensorCache, ProfilerRegistry
from ytsaurus_tpu.utils import sanitizers

# The usage schema: one cumulative float per field per (pool, user).
USAGE_FIELDS = (
    "queries", "lookups", "rows_read", "rows_written", "bytes_read",
    "compile_seconds", "execute_seconds", "admission_wait_seconds",
    "wall_seconds", "cache_hits", "compile_count", "retries",
    "throttled", "lookup_keys", "lookup_rows_found", "lookup_batches",
    "operations", "jobs", "view_batches", "view_rows",
    "nearest_queries", "nearest_batches", "nearest_rows_scanned",
)


class UsageRecord:
    """Cumulative usage of one (pool, user) principal."""

    __slots__ = USAGE_FIELDS

    def __init__(self):
        for field in USAGE_FIELDS:
            setattr(self, field, 0.0)

    def to_dict(self) -> dict:
        return {field: getattr(self, field) for field in USAGE_FIELDS}


class ResourceAccountant:
    """Cumulative per-(pool, user) usage, with per-pool sensor mirrors.

    Folds are a handful of float adds under one small lock — the
    per-query/per-flush cost the `telemetry_overhead` bench bounds."""

    def __init__(self, registry: Optional[ProfilerRegistry] = None):
        # guards: _usage
        self._lock = sanitizers.register_lock(
            "accounting.ResourceAccountant._lock")
        self._usage: dict[tuple[str, str], UsageRecord] = {}
        self._pool_sensors = PoolSensorCache(
            "/accounting/usage", USAGE_FIELDS, registry=registry)

    def fold(self, pool: Optional[str], user: Optional[str],
             **deltas) -> None:
        pool = pool or "default"
        user = user or "root"
        with self._lock:
            record = self._usage.get((pool, user))
            if record is None:
                record = self._usage[(pool, user)] = UsageRecord()
            counters = self._pool_sensors.counters(pool)
            for field, value in deltas.items():
                if value:
                    setattr(record, field,
                            getattr(record, field) + value)
                    counters[field].increment(value)

    # -- fold sites ------------------------------------------------------------

    def observe_query(self, profile, user: Optional[str] = None) -> None:
        """One finished select's ExecutionProfile → usage."""
        stats = profile.statistics or {}
        self.fold(
            profile.pool, user or getattr(profile, "user", None),
            queries=1,
            rows_read=stats.get("rows_read", 0),
            rows_written=stats.get("rows_written", 0),
            bytes_read=stats.get("bytes_read", 0),
            compile_seconds=profile.compile_time,
            execute_seconds=profile.execute_time,
            admission_wait_seconds=profile.admission_wait,
            wall_seconds=profile.wall_time,
            cache_hits=stats.get("cache_hits", 0),
            compile_count=stats.get("compile_count", 0),
            retries=stats.get("retries", 0))

    def observe_lookup(self, pool: Optional[str], user: Optional[str],
                       keys: int, rows_found: int) -> None:
        """One member REQUEST of a batched flush: keys/rows charge the
        requesting user."""
        self.fold(pool, user, lookups=1, lookup_keys=keys,
                  lookup_rows_found=rows_found)

    def observe_lookup_batch(self, pool: Optional[str],
                             user: Optional[str]) -> None:
        """One admitted flush (1:1 with the admission slot it held —
        the per-pool reconciliation unit), charged to the cohort
        opener like the slot itself."""
        self.fold(pool, user, lookup_batches=1)

    def observe_nearest(self, pool: Optional[str], user: Optional[str],
                        rows_scanned: int = 0) -> None:
        """One member NEAREST query of a batched cohort flush: the
        exhaustive-scan row count charges the requesting user (the
        vector analog of observe_lookup)."""
        self.fold(pool, user, nearest_queries=1,
                  nearest_rows_scanned=rows_scanned)

    def observe_nearest_batch(self, pool: Optional[str],
                              user: Optional[str]) -> None:
        """One admitted NEAREST cohort flush (one batched matmul, one
        admission slot), charged to the cohort opener."""
        self.fold(pool, user, nearest_batches=1)

    def observe_throttle(self, pool: Optional[str],
                         user: Optional[str] = None) -> None:
        self.fold(pool, user, throttled=1)

    def observe_view_batch(self, pool: Optional[str],
                           rows_read: int = 0, rows_written: int = 0,
                           wall_seconds: float = 0.0,
                           user: str = "view-daemon") -> None:
        """One committed materialized-view micro-batch (ISSUE 13): the
        refresh work lands under the VIEW's pool in the same rows/wall
        fields selects use, so `yt top` ranks a pool by its continuous-
        query load alongside its interactive traffic."""
        self.fold(pool, user, view_batches=1, view_rows=rows_read,
                  rows_read=rows_read, rows_written=rows_written,
                  wall_seconds=wall_seconds)

    def observe_operation(self, pool: Optional[str],
                          user: Optional[str], wall_seconds: float,
                          jobs: int = 0) -> None:
        """A terminal operation's wall time lands in the SAME
        wall_seconds field selects use — `yt top`'s default sort must
        rank a pool that only runs operations by what it consumed."""
        self.fold(pool, user, operations=1, jobs=jobs,
                  wall_seconds=wall_seconds)

    # -- views -----------------------------------------------------------------

    def totals(self) -> dict:
        """Plane-wide totals (the conservation invariant: these equal
        the sum over every per-pool and per-user roll-up)."""
        out = {field: 0.0 for field in USAGE_FIELDS}
        with self._lock:
            for record in self._usage.values():
                for field in USAGE_FIELDS:
                    out[field] += getattr(record, field)
        return out

    def snapshot(self) -> dict:
        with self._lock:
            records = [{"pool": pool, "user": user, **rec.to_dict()}
                       for (pool, user), rec in
                       sorted(self._usage.items())]
        by_pool: dict[str, dict] = {}
        by_user: dict[str, dict] = {}
        # Totals derive from the SAME copy as the roll-ups: a fold that
        # lands after the lock released must not make one snapshot's
        # totals disagree with the sum of its own records.
        totals = {field: 0.0 for field in USAGE_FIELDS}
        for record in records:
            for field in USAGE_FIELDS:
                totals[field] += record[field]
            for roll, key in ((by_pool, record["pool"]),
                              (by_user, record["user"])):
                agg = roll.setdefault(
                    key, {field: 0.0 for field in USAGE_FIELDS})
                for field in USAGE_FIELDS:
                    agg[field] += record[field]
        return {"records": records, "by_pool": by_pool,
                "by_user": by_user, "totals": totals}


_global_accountant: Optional[ResourceAccountant] = None
# guards: _global_accountant
_lock = sanitizers.register_lock("accounting._lock", hot=False)


def get_accountant() -> ResourceAccountant:
    global _global_accountant
    if _global_accountant is None:
        with _lock:
            if _global_accountant is None:
                _global_accountant = ResourceAccountant()
    return _global_accountant
