"""Small durable-file helpers shared by WAL/journal epoch state."""

from __future__ import annotations

import os


def fsync_dir(path: str) -> None:
    """Make a rename/creation in `path` durable."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def read_epoch_file(path: str) -> tuple[int, str]:
    """(epoch, writer_id) from a fenced-epoch sidecar; (0, "") when
    missing/corrupt (corrupt = no fencing history, same as fresh)."""
    try:
        with open(path, "rb") as f:
            raw = f.read().decode().split()
        epoch = int(raw[0]) if raw else 0
        writer = raw[1] if len(raw) > 1 else ""
        return epoch, writer
    except (OSError, ValueError, IndexError):
        return 0, ""


def write_epoch_file(path: str, epoch: int, writer_id: str) -> None:
    """Atomic, fsync'd publish of (epoch, writer_id).  The parent
    directory is fsync'd after the rename: a granted/adopted fence epoch
    must survive a crash, or a node can forget a grant it already made."""
    parent = os.path.dirname(path) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(f"{epoch} {writer_id}".encode())
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(parent)
