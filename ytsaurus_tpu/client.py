"""The client API: a local in-process cluster + the IClient-shaped facade.

Ref mapping:
  NApi::IClient surface (client/api/client.h)     → YtClient methods
  yt local mode / YTInstance test clusters
    (yt/python/yt/environment/yt_env.py)          → YtCluster(root_dir)
  driver command registry (client/driver)         → method-per-command here

Cypress commands: create/get/set/list/exists/remove.
Static tables: write_table/read_table (columnar chunks in the chunk store,
chunk ids recorded as table attributes).
Dynamic tables: mount/unmount, insert/delete/lookup/select, flush/compact.
Operations: run_sort/run_merge/run_map/run_erase via the scheduler.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, Iterable, Optional, Sequence

from ytsaurus_tpu.chunks.columnar import ColumnarChunk, concat_chunks
from ytsaurus_tpu.chunks.store import ChunkCache, FsChunkStore
from ytsaurus_tpu.cypress.master import Master
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.query.coordinator import coordinate_and_execute
from ytsaurus_tpu.query.engine.evaluator import Evaluator
from ytsaurus_tpu.schema import EValueType, TableSchema
from ytsaurus_tpu.tablet.tablet import Tablet
from ytsaurus_tpu.tablet.timestamp import MAX_TIMESTAMP
from ytsaurus_tpu.tablet.transactions import TabletTransaction, TransactionManager


class YtCluster:
    """Everything one process needs to be a cluster (local mode)."""

    def __init__(self, root_dir: str, chunk_store=None, master=None):
        self.root_dir = root_dir
        os.makedirs(root_dir, exist_ok=True)
        self.master = master if master is not None else \
            Master(os.path.join(root_dir, "master"))
        self.chunk_store = chunk_store if chunk_store is not None else \
            FsChunkStore(os.path.join(root_dir, "chunks"))
        # id -> address of live data nodes (set by the primary daemon);
        # non-empty enables dispatching command jobs to exec-node slots.
        self.node_directory: "Callable[[], dict] | None" = None
        self.chunk_cache = ChunkCache(self.chunk_store)
        # Chunks written but not yet published to any table (the chunk
        # merger's write→CAS window): GC and the replicator must treat
        # them as referenced or a concurrent sweep deletes a chunk a
        # table is about to adopt.
        self.protected_chunk_ids: set = set()
        self.transactions = TransactionManager()
        self.evaluator = Evaluator()
        self.tablets: dict[str, list[Tablet]] = {}   # node id → tablets
        # Query serving plane (query/serving.py): set serving_config
        # BEFORE the first query to override the defaults; the gateway
        # is cluster-scoped so every client of this cluster shares
        # admission slots and coalesces lookups into common batches.
        self.serving_config = None
        self._gateway = None
        self._gateway_lock = threading.Lock()
        from ytsaurus_tpu.cypress.security import SecurityManager
        self.security = SecurityManager(self.master)
        self.security.ensure_defaults()

    @property
    def gateway(self):
        if self._gateway is None:
            from ytsaurus_tpu.query.serving import QueryGateway
            with self._gateway_lock:
                if self._gateway is None:
                    self._gateway = QueryGateway(self.serving_config)
        return self._gateway


def publish_table_chunks(client, chunk_store, path, chunks,
                         sorted_by=None, schema=None) -> None:
    """THE static-table chunk attribute protocol (@schema/@chunk_ids/
    @chunk_stats/@row_count/@sorted_by) — one implementation shared by the
    in-process client and the remote thin client, so tables stay
    cross-readable whichever path wrote them."""
    chunk_ids = [chunk_store.write_chunk(c) for c in chunks]
    total = sum(c.row_count for c in chunks)
    if schema is not None:
        client.set(path + "/@schema", schema.to_dict())
    client.set(path + "/@chunk_ids", chunk_ids)
    # Stats were computed ONCE at seal time (chunk meta header); reading
    # them back is a meta parse, not a host-side min/max recompute.
    client.set(path + "/@chunk_stats",
               [chunk_store.read_stats(cid) for cid in chunk_ids])
    client.set(path + "/@row_count", total)
    if sorted_by:
        client.set(path + "/@sorted_by", list(sorted_by))
    elif client.exists(path + "/@sorted_by"):
        client.remove(path + "/@sorted_by", force=True)


def _chunk_bytes(chunk) -> int:
    """Approximate resident bytes of a chunk's column planes (quota unit)."""
    import numpy as np
    total = 0
    for col in chunk.columns.values():
        total += np.asarray(col.data).nbytes
        if col.valid is not None:
            total += np.asarray(col.valid).nbytes
    return total


def _normalize_per_tablet(ids) -> "list[list[str]]":
    """tablet_chunk_ids layout: nested per-tablet lists; migrate the old
    flat layout.  THE one normalizer — GC correctness depends on every
    reader agreeing (a missed variant mis-marks chunks unreferenced)."""
    if not ids:
        return []
    if isinstance(ids[0], str):
        return [list(ids)]
    return [list(sub) for sub in ids]


def _mc():
    from ytsaurus_tpu.cypress import multicell
    return multicell


def _hedged_race(attempts: "list[Callable]", delay: float,
                 base_error: YtError):
    """rpc.channel.hedged_race with the replica-fallback error shape:
    base_error (the primary-table failure) is always the root cause."""
    from ytsaurus_tpu.rpc.channel import hedged_race

    if not attempts:
        raise base_error
    try:
        return hedged_race(attempts, delay)
    except YtError as err:
        raise YtError("all hedged replica lookups failed",
                      code=base_error.code,
                      inner_errors=[base_error, err])


class YtClient:
    def __init__(self, cluster: YtCluster):
        self.cluster = cluster
        from ytsaurus_tpu.operations.scheduler import OperationScheduler
        from ytsaurus_tpu.query.statistics import QueryStatistics
        self.scheduler = OperationScheduler(self)
        self.last_query_statistics = QueryStatistics()
        self._computed_plans: dict = {}
        self._table_replicator = None
        self._query_tracker = None
        # Stagger between hedged replica lookups (hedging_channel.h).
        self.lookup_hedging_delay = 0.05

    def exec_node_addresses(self) -> dict:
        """id -> address of data nodes hosting exec slots ({} in pure
        local mode, where jobs run in-process)."""
        if self.cluster.node_directory is None:
            return {}
        try:
            return dict(self.cluster.node_directory())
        except Exception:   # noqa: BLE001 — directory is advisory
            return {}

    @property
    def table_replicator(self):
        """Lazy shared TableReplicator (caches remote-cluster clients)."""
        if self._table_replicator is None:
            from ytsaurus_tpu.tablet.replication import TableReplicator
            self._table_replicator = TableReplicator(self)
        return self._table_replicator

    @property
    def query_tracker(self):
        """Lazy shared QueryTracker (ref server/query_tracker)."""
        if self._query_tracker is None:
            from ytsaurus_tpu.server.query_tracker import QueryTracker
            self._query_tracker = QueryTracker(self)
        return self._query_tracker

    # ------------------------------------------------------------------ cypress

    def create(self, node_type: str, path: str,
               attributes: Optional[dict] = None, recursive: bool = False,
               ignore_existing: bool = False, tx: Optional[str] = None) -> str:
        from ytsaurus_tpu.cypress import multicell
        if node_type == multicell.PORTAL_TYPE:
            multicell.reject_tx(tx)
            delegate = multicell.delegate_for(self, path, "write")
            if delegate is not None:
                # An entrance beneath another portal belongs to THAT
                # cell (chained portals).
                with multicell.as_cell_principal():
                    return delegate.create(
                        node_type, path, attributes=attributes,
                        recursive=recursive,
                        ignore_existing=ignore_existing)
            parent = path.rsplit("/", 1)[0] or "/"
            self.cluster.security.validate_permission("write", parent)
            return multicell.create_portal(self, path, attributes or {},
                                           recursive=recursive,
                                           ignore_existing=ignore_existing)
        delegate = multicell.delegate_for(self, path, "write")
        if delegate is not None:
            multicell.reject_tx(tx)
            with multicell.as_cell_principal():
                return delegate.create(node_type, path,
                                       attributes=attributes,
                                       recursive=recursive,
                                       ignore_existing=ignore_existing)
        parent = path.rsplit("/", 1)[0] or "/"
        self.cluster.security.validate_permission("write", parent)
        attributes = dict(attributes or {})
        if node_type == "table":
            schema = attributes.get("schema")
            if isinstance(schema, TableSchema):
                attributes["schema"] = schema.to_dict()
            elif isinstance(schema, (list, tuple)):
                # YT-style bare column list.
                attributes["schema"] = TableSchema.make(schema).to_dict()
            attributes.setdefault("dynamic", False)
            attributes.setdefault("chunk_ids", [])
            attributes.setdefault("row_count", 0)
        # Charge exactly the nodes this call will create: none when the
        # target pre-exists (ignore_existing), plus missing ancestors for
        # recursive creates.
        new_nodes = self._count_new_nodes(path, recursive)
        if new_nodes:
            self._charge(path, node_count=new_nodes)   # quota gate first
        try:
            return self.cluster.master.commit_mutation(
                "create", path=path, type=node_type, attributes=attributes,
                recursive=recursive, ignore_existing=ignore_existing, tx=tx)
        except YtError:
            if new_nodes:
                self._charge(path, node_count=-new_nodes)
            raise

    def _count_new_nodes(self, path: str, recursive: bool) -> int:
        tree = self.cluster.master.tree
        if tree.try_resolve(path) is not None:
            return 0
        if not recursive:
            return 1
        count = 1
        parent = path.rsplit("/", 1)[0]
        while parent and parent != "/" and \
                tree.try_resolve(parent) is None:
            count += 1
            parent = parent.rsplit("/", 1)[0]
        return count

    def get(self, path: str, tx: Optional[str] = None) -> Any:
        from ytsaurus_tpu.cypress import multicell
        # Reading the entrance path resolves to the exit (like list).
        delegate = multicell.delegate_for(self, path, "read",
                                          include_self=True)
        if delegate is not None:
            multicell.reject_tx(tx)
            with multicell.as_cell_principal():
                return delegate.get(path)
        self.cluster.security.validate_permission("read", path)
        if tx is not None:
            # Snapshot-locked reads see the pinned copy.
            pinned = self.cluster.master.tx_manager.read_snapshot(tx, path)
            if pinned is not None:
                return pinned
        return self.cluster.master.tree.get(path)

    def set(self, path: str, value: Any, tx: Optional[str] = None) -> None:
        from ytsaurus_tpu.cypress import multicell
        delegate = multicell.delegate_for(self, path, "write")
        if delegate is not None:
            multicell.reject_tx(tx)
            with multicell.as_cell_principal():
                return delegate.set(path, value)
        self.cluster.security.validate_permission("write", path)
        self.cluster.master.commit_mutation("set", path=path, value=value,
                                            tx=tx)

    def exists(self, path: str) -> bool:
        from ytsaurus_tpu.cypress import multicell
        delegate = multicell.delegate_for(self, path, None)
        if delegate is not None:
            with multicell.as_cell_principal():
                return delegate.exists(path)
        return self.cluster.master.tree.exists(path)

    def list(self, path: str) -> list[str]:
        from ytsaurus_tpu.cypress import multicell
        # Listing the entrance itself shows the EXIT's children.
        delegate = multicell.delegate_for(self, path, "read",
                                          include_self=True)
        if delegate is not None:
            with multicell.as_cell_principal():
                return delegate.list(path)
        self.cluster.security.validate_permission("read", path)
        return self.cluster.master.tree.list(path)

    # -- master transactions / locks ------------------------------------------
    # (ref: master transactions + cypress locks, transaction_server and
    # node_detail.h; commands mirror the driver's start_tx/lock surface)

    def start_tx(self, parent: Optional[str] = None) -> str:
        return self.cluster.master.commit_mutation("tx_start",
                                                   parent_id=parent)

    def commit_tx(self, tx: str) -> None:
        self.cluster.master.commit_mutation("tx_commit", tx_id=tx)

    def abort_tx(self, tx: str) -> None:
        self.cluster.master.commit_mutation("tx_abort", tx_id=tx)

    def lock(self, path: str, mode: str = "exclusive",
             tx: Optional[str] = None) -> None:
        _mc().reject_under_portal(self, path, "lock")
        if tx is None:
            raise YtError("lock requires a transaction")
        self.cluster.master.commit_mutation("lock", tx_id=tx, path=path,
                                            mode=mode)

    # -- accounts / quota metering ---------------------------------------------

    def _charge(self, path: str, **deltas) -> None:
        """Meter account usage; quota violations raise BEFORE data lands."""
        security = self.cluster.security
        account = security.account_of(path)
        if self.exists(f"//sys/accounts/{account}"):
            security.charge_account(account, **deltas)

    def copy(self, src_path: str, dst_path: str,
             recursive: bool = False) -> str:
        """Deep-copy a subtree.  Static-table chunks are shared by
        reference (never deleted while ANY table references them — the GC
        counts both copies); dynamic-table chunks are physically duplicated
        because compaction/reshard delete the source's chunk files.
        Mounted dynamic tables must unmount first."""
        _mc().reject_under_portal(self, src_path, "copy")
        _mc().reject_under_portal(self, dst_path, "copy")
        src_node = self.cluster.master.tree.try_resolve(src_path)
        if src_node is not None:
            stack = [src_node]
            while stack:
                current = stack.pop()
                if current.id in self.cluster.tablets:
                    raise YtError(
                        f"Unmount dynamic tables under {src_path!r} before "
                        "copying", code=EErrorCode.TabletNotMounted)
                stack.extend(current.children.values())
        node_id = self.cluster.master.commit_mutation(
            "copy", src=src_path, dst=dst_path, recursive=recursive)
        self._duplicate_dynamic_chunks(dst_path)
        return node_id

    def _duplicate_dynamic_chunks(self, path: str) -> None:
        """Give copied dynamic tables their own chunk files (their sources
        delete chunks on compaction/reshard)."""
        tree = self.cluster.master.tree
        node = tree.try_resolve(path)
        if node is None:
            return
        stack = [(path, node)]
        while stack:
            node_path, current = stack.pop()
            if current.type == "table" and current.attributes.get("dynamic"):
                per_tablet = _normalize_per_tablet(
                    current.attributes.get("tablet_chunk_ids", []))
                fresh = []
                for ids in per_tablet:
                    fresh.append([
                        self.cluster.chunk_store.write_chunk(
                            self.cluster.chunk_store.read_chunk(cid))
                        for cid in ids])
                if fresh:
                    self.set(node_path + "/@tablet_chunk_ids", fresh)
            for name, child in current.children.items():
                stack.append((f"{node_path}/{name}", child))

    def move(self, src_path: str, dst_path: str,
             recursive: bool = False) -> str:
        _mc().reject_under_portal(self, src_path, "move")
        _mc().reject_under_portal(self, dst_path, "move")
        node = self.cluster.master.tree.try_resolve(src_path)
        if node is not None and node.id in self.cluster.tablets:
            raise YtError(f"Unmount {src_path!r} before moving it",
                          code=EErrorCode.TabletNotMounted)
        return self.cluster.master.commit_mutation(
            "move", src=src_path, dst=dst_path, recursive=recursive)

    def link(self, target_path: str, link_path: str,
             recursive: bool = False) -> str:
        _mc().reject_under_portal(self, target_path, "link")
        _mc().reject_under_portal(self, link_path, "link")
        return self.cluster.master.commit_mutation(
            "link", target=target_path, link=link_path, recursive=recursive)

    def remove(self, path: str, recursive: bool = True,
               force: bool = False, tx: Optional[str] = None) -> None:
        from ytsaurus_tpu.cypress import multicell
        delegate = multicell.delegate_for(self, path, "remove")
        if delegate is not None:
            multicell.reject_tx(tx)
            with multicell.as_cell_principal():
                return delegate.remove(path, recursive=recursive,
                                       force=force)
        self.cluster.security.validate_permission("remove", path)
        node = self.cluster.master.tree.try_resolve(path)
        if node is not None and node.type == multicell.PORTAL_TYPE \
                and "/@" not in path:
            # Entrance removal dismantles the exit subtree on its cell
            # (exactly-once via Hive, AFTER the primary removal commits).
            return multicell.remove_portal(self, path,
                                           dict(node.attributes),
                                           recursive=recursive, tx=tx)
        nested_portals = []
        if node is not None and "/@" not in path:
            # Entrances INSIDE the removed subtree must dismantle their
            # exits too, or the secondary cell leaks the subtree (and a
            # recreated portal would resurrect stale data under it).
            # Collected now, dismantled only after the primary removal
            # COMMITS — a refused/failed remove must not destroy exit
            # data — which also means such a removal cannot ride a
            # rollback-able transaction.
            nested_portals = multicell.portals_under(path, node)
            if nested_portals:
                multicell.reject_tx(tx)
        # One subtree walk: tally metered usage + find mounted tables.
        freed_nodes, freed_disk, freed_chunks = 0, 0, 0
        mounted: list[str] = []
        if node is not None and "/@" not in path:
            stack = [node]
            while stack:
                current = stack.pop()
                freed_nodes += 1
                usage = current.attributes.get("resource_usage") or {}
                freed_disk += int(usage.get("disk_space", 0))
                freed_chunks += int(usage.get("chunk_count", 0))
                if current.id in self.cluster.tablets:
                    mounted.append(current.id)
                stack.extend(current.children.values())
        if tx is not None and mounted:
            # A transactional remove can be rolled back, but tablet
            # eviction cannot — refuse rather than strand a restored
            # dynamic table without its tablets.
            raise YtError(
                f"Unmount dynamic tables under {path!r} before a "
                "transactional remove", code=EErrorCode.TabletNotMounted)
        account = self.cluster.security.account_of(path)
        # Mutation FIRST (it can fail on a lock conflict); irreversible
        # side effects — tablet eviction, quota credit — only after it
        # lands.  Transactional removes skip the quota credit: an abort
        # restores the nodes, and usage must still cover them.
        self.cluster.master.commit_mutation(
            "remove", path=path, recursive=recursive, force=force, tx=tx)
        for entrance_path, cell_root in nested_portals:
            multicell._dismantle_exit(self, cell_root, entrance_path)
        for node_id in mounted:
            for tablet in self.cluster.tablets.pop(node_id, ()):
                tablet.set_in_memory(False)
        if tx is None and (freed_nodes or freed_disk or freed_chunks):
            if self.exists(f"//sys/accounts/{account}"):
                self.cluster.security.charge_account(
                    account, node_count=-freed_nodes,
                    disk_space=-freed_disk, chunk_count=-freed_chunks)

    def referenced_chunk_ids(self) -> set:
        """Every chunk id rooted by the metadata tree or live runtime
        tablet state (tables, per-tablet stores, ordered stores,
        operation snapshots).  Hunk chunks are NOT resolved here — their
        liveness needs a meta read per data chunk (see collect_garbage).
        Shared by GC (what to keep) and the chunk replicator (what is
        worth re-replicating).  Walks under the master's mutation lock:
        the replicator calls this from its scan thread and a mutating
        dict mid-iteration would abort the walk."""
        with self.cluster.master.mutation_lock:
            return self._referenced_chunk_ids_locked()

    def _referenced_chunk_ids_locked(self) -> set:
        referenced: set = set()
        stack = [self.cluster.master.tree.root]
        while stack:
            node = stack.pop()
            if node.type == "table":
                referenced.update(node.attributes.get("chunk_ids", []))
                for sub in _normalize_per_tablet(
                        node.attributes.get("tablet_chunk_ids", [])):
                    referenced.update(sub)
                state = node.attributes.get("ordered_state") or {}
                referenced.update(state.get("chunk_ids", []))
            # Operation snapshots root their per-stripe output chunks:
            # revival after a controller death must still find them.
            snap = node.attributes.get("snapshot")
            if isinstance(snap, dict):
                referenced.update(
                    cid for cid in (snap.get("completed") or {}).values()
                    if cid)
            stack.extend(node.children.values())
        # The master lock covers the tree, not cluster.tablets (mount/
        # unmount mutate it lock-free): snapshot the dict and each
        # tablet list in one C-level pass so a concurrent mount cannot
        # abort the replicator's walk mid-iteration.
        for tablets in list(self.cluster.tablets.values()):
            for tablet in list(tablets):
                referenced.update(tablet.chunk_ids)
        # Written-but-unpublished chunks (chunk merger's CAS window).
        referenced.update(self.cluster.protected_chunk_ids)
        return referenced

    def collect_garbage(self) -> int:
        """Remove chunk files referenced by no table (ref: the master's
        object GC sweeping unreferenced chunks, object_server).  Returns the
        number of chunks removed.  Runtime tablet state counts as a
        reference (mounted tables may hold chunks not yet persisted), and
        the sweep refuses to run while operations are in flight — a
        controller writes chunk files before publishing @chunk_ids."""
        for op in self.scheduler.list_operations():
            if op.state in ("pending", "running"):
                raise YtError(
                    f"Cannot collect garbage while operation {op.id} is "
                    f"{op.state}", code=EErrorCode.OperationFailed)
        referenced = self.referenced_chunk_ids()
        # Hunk chunks are live iff a live data chunk's meta references them
        # (ref hunk_chunk_sweeper: ref-counted hunk chunk attachment).
        # The meta pass costs a read per live chunk, so only hunk-bearing
        # stores pay it.
        from ytsaurus_tpu.chunks.hunks import is_hunk_id
        store = self.cluster.chunk_store
        all_ids = store.list_chunks()
        if any(is_hunk_id(cid) for cid in all_ids):
            for cid in all_ids:
                if cid in referenced and not is_hunk_id(cid):
                    try:
                        referenced.update(
                            store.read_meta(cid).get("hunk_chunk_ids", []))
                    except YtError:
                        pass
        removed = 0
        for cid in all_ids:
            if cid not in referenced:
                store.remove_chunk(cid)
                self.cluster.chunk_cache.invalidate(cid)
                removed += 1
        return removed

    # ------------------------------------------------------------- static tables

    def write_table(self, path: str, rows: "Sequence[dict] | bytes",
                    append: bool = False,
                    schema: "TableSchema | dict | None" = None,
                    format: Optional[str] = None) -> None:
        from ytsaurus_tpu.cypress import multicell
        delegate = multicell.delegate_for(self, path, "write")
        if delegate is not None:
            with multicell.as_cell_principal():
                return delegate.write_table(path, rows, append=append,
                                            schema=schema, format=format)
        self.cluster.security.validate_permission("write", path)
        if format == "arrow":
            from ytsaurus_tpu.arrow import (
                arrow_ipc_to_rows,
                arrow_schema_to_table_schema,
            )
            if schema is None:
                import pyarrow as _pa
                with _pa.ipc.open_stream(rows) as reader:
                    schema = arrow_schema_to_table_schema(reader.schema)
            rows = arrow_ipc_to_rows(rows)
        elif format == "skiff":
            from ytsaurus_tpu.formats import loads_skiff
            if schema is None:
                raise YtError("skiff writes require a schema",
                              code=EErrorCode.QueryUnsupported)
            if not isinstance(schema, TableSchema):
                schema = TableSchema.from_dict(schema)
            rows = loads_skiff(rows, schema)
        elif format is not None:
            from ytsaurus_tpu.formats import loads_rows
            columns = None
            if isinstance(schema, TableSchema):
                columns = schema.column_names
            rows = loads_rows(rows, format, columns=columns)
        node = self._table_node(path, create=True, schema=schema)
        if node.attributes.get("dynamic"):
            raise YtError("write_table on a dynamic table; use insert_rows",
                          code=EErrorCode.QueryUnsupported)
        table_schema = self._node_schema(node)
        if table_schema is None and rows:
            table_schema = infer_schema(rows)
            self.set(path + "/@schema", table_schema.to_dict())
        chunks: list[str] = list(node.attributes.get("chunk_ids", [])) \
            if append else []
        stats: list = list(node.attributes.get("chunk_stats", [])) \
            if append else []
        # Keep stats aligned with chunk_ids even for pre-stats tables.
        while len(stats) < len(chunks):
            stats.append({})
        row_count = int(node.attributes.get("row_count", 0)) if append else 0
        if rows:
            chunk = ColumnarChunk.from_rows(table_schema, list(rows))
            self._meter_table(path, node, chunk_delta=1,
                              disk_delta=_chunk_bytes(chunk))
            cid = self.cluster.chunk_store.write_chunk(chunk)
            chunks.append(cid)
            stats.append(self.cluster.chunk_store.read_stats(cid))
            row_count += chunk.row_count
        self.set(path + "/@chunk_ids", chunks)
        self.set(path + "/@chunk_stats", stats)
        self.set(path + "/@row_count", row_count)
        # Arbitrary rows invalidate any prior sort guarantee.
        if "sorted_by" in node.attributes:
            self.cluster.master.commit_mutation(
                "remove", path=path + "/@sorted_by", force=True)

    def _meter_table(self, path: str, node, chunk_delta: int,
                     disk_delta: int) -> None:
        """Account charge for new chunk data + per-node usage bookkeeping
        (the remove path frees from @resource_usage)."""
        self._charge(path, disk_space=disk_delta, chunk_count=chunk_delta)
        usage = dict(node.attributes.get("resource_usage") or {})
        usage["disk_space"] = int(usage.get("disk_space", 0)) + disk_delta
        usage["chunk_count"] = int(usage.get("chunk_count", 0)) + chunk_delta
        self.cluster.master.commit_mutation(
            "set", path=path + "/@resource_usage", value=usage)

    def read_table(self, path: str, format: Optional[str] = None):
        """Rows as dicts, or serialized bytes when `format` is given
        (yson/json/dsv/schemaful_dsv/skiff/arrow — ref client/formats,
        client/arrow)."""
        from ytsaurus_tpu.cypress import multicell
        delegate = multicell.delegate_for(self, path, "read")
        if delegate is not None:
            with multicell.as_cell_principal():
                return delegate.read_table(path, format=format)
        self.cluster.security.validate_permission("read", path)
        chunks = self._read_table_chunks(path)
        if format == "arrow":
            # Columnar fast path: planes → arrow arrays, no row walk.
            from ytsaurus_tpu.arrow import chunks_to_arrow_ipc
            if not chunks:
                schema = self._node_schema(self._table_node(path))
                if schema is None:
                    raise YtError(
                        "arrow reads of an empty schemaless table need "
                        "a schema", code=EErrorCode.QueryUnsupported)
                chunks = [ColumnarChunk.from_rows(schema.to_unsorted(), [])]
            return chunks_to_arrow_ipc(chunks)
        rows: list[dict] = []
        for chunk in chunks:
            rows.extend(chunk.to_rows())
        if format is None:
            return rows
        node = self._table_node(path)
        schema = self._node_schema(node)
        if format == "skiff":
            from ytsaurus_tpu.formats import dumps_skiff
            if schema is None:
                schema = infer_schema(rows)
            return dumps_skiff(rows, schema)
        from ytsaurus_tpu.formats import dumps_rows
        columns = schema.column_names if schema else None
        return dumps_rows(rows, format, columns=columns)

    # ------------------------------------------------------------ dynamic tables

    def mount_table(self, path: str) -> None:
        _mc().reject_under_portal(self, path, "mount_table")
        self.cluster.security.validate_permission("mount", path)
        node = self._table_node(path)
        schema = self._node_schema(node)
        if schema is None:
            raise YtError("mount_table requires a schema",
                          code=EErrorCode.TabletNotMounted)
        if not node.attributes.get("dynamic"):
            raise YtError(f"Table {path!r} is not dynamic; "
                          "create with attributes={'dynamic': True}",
                          code=EErrorCode.TabletNotMounted)
        if node.id in self.cluster.tablets:
            return
        if schema.is_sorted:
            # One tablet per pivot range (ref: tablet pivot keys,
            # server/master/tablet_server; partition.h range sharding).
            pivots = [tuple(p) for p in node.attributes.get("pivot_keys", [])]
            per_tablet = _normalize_per_tablet(
                node.attributes.get("tablet_chunk_ids", []))
            tablets = []
            for i in range(len(pivots) + 1):
                tablet = Tablet(schema, self.cluster.chunk_store,
                                tablet_id=f"{node.id}-{i}",
                                pivot_key=pivots[i - 1] if i else None,
                                chunk_cache=self.cluster.chunk_cache)
                tablet.chunk_ids = list(per_tablet[i]) \
                    if i < len(per_tablet) else []
                tablets.append(tablet)
            self.cluster.tablets[node.id] = tablets
        else:
            # Unsorted dynamic schema → ordered (queue) table.
            from ytsaurus_tpu.tablet.ordered import OrderedTablet
            tablet = OrderedTablet(schema, self.cluster.chunk_store,
                                   tablet_id=f"{node.id}-0",
                                   chunk_cache=self.cluster.chunk_cache)
            state = node.attributes.get("ordered_state") or {}
            tablet.chunk_ids = list(state.get("chunk_ids", []))
            tablet.chunk_ranges = [tuple(r) for r in state.get("ranges", [])]
            tablet.base_index = int(state.get("base_index", 0))
            tablet.trimmed_count = int(state.get("trimmed_count", 0))
            self.cluster.tablets[node.id] = [tablet]
        # In-memory mode: tablets own their pins so flush/compact-created
        # chunks stay resident too (ref EInMemoryMode none/uncompressed).
        if node.attributes.get("in_memory_mode", "none") != "none":
            for tablet in self.cluster.tablets[node.id]:
                tablet.set_in_memory(True)
        self.set(path + "/@tablet_state", "mounted")

    def unmount_table(self, path: str) -> None:
        _mc().reject_under_portal(self, path, "unmount_table")
        node = self._table_node(path)
        tablets = self.cluster.tablets.pop(node.id, None)
        if tablets is None:
            # Not materialized in this connection — still record the state
            # so other connections stop lazily re-mounting it.
            if node.attributes.get("tablet_state") == "mounted":
                self.set(path + "/@tablet_state", "unmounted")
            return
        from ytsaurus_tpu.tablet.ordered import OrderedTablet
        for tablet in tablets:
            tablet.flush()
            tablet.set_in_memory(False)
            tablet.mounted = False
        if isinstance(tablets[0], OrderedTablet):
            t = tablets[0]
            self.set(path + "/@ordered_state", {
                "chunk_ids": t.chunk_ids,
                "ranges": [list(r) for r in t.chunk_ranges],
                "base_index": t.base_index,
                "trimmed_count": t.trimmed_count})
        else:
            self.set(path + "/@tablet_chunk_ids",
                     [list(t.chunk_ids) for t in tablets])
        self.set(path + "/@tablet_state", "unmounted")

    def reshard_table(self, path: str, pivot_keys: Sequence[tuple]) -> None:
        _mc().reject_under_portal(self, path, "reshard_table")
        """Re-shard an (unmounted) sorted dynamic table into len(pivots)+1
        tablets; existing data redistributes to the new ranges.

        Ref: tablet_server reshard with pivot keys (tablet_manager.h);
        here redistribution rewrites the versioned chunks per range."""
        from ytsaurus_tpu.tablet.dynamic_store import _null_safe
        from ytsaurus_tpu.tablet.tablet import (
            _versioned_sort_key,
            versioned_schema,
        )
        node = self._table_node(path)
        if node.id in self.cluster.tablets:
            raise YtError(f"Table {path!r} must be unmounted to reshard",
                          code=EErrorCode.TabletNotMounted)
        schema = self._node_schema(node)
        if schema is None or not schema.is_sorted or \
                not node.attributes.get("dynamic"):
            raise YtError("reshard_table requires a sorted dynamic table",
                          code=EErrorCode.TabletNotMounted)
        from ytsaurus_tpu.tablet.tablet import _normalize_value
        key_cols = schema.key_columns
        key_width = len(key_cols)
        pivots = []
        for p in pivot_keys:
            p = tuple(p)
            if len(p) != key_width:
                raise YtError(f"Pivot {p!r} width != key width {key_width}")
            pivots.append(tuple(_normalize_value(v, c.type)
                                for v, c in zip(p, key_cols)))
        safe_pivots = [_null_safe(p) for p in pivots]
        if any(a >= b for a, b in zip(safe_pivots, safe_pivots[1:])):
            raise YtError("Pivot keys must be strictly increasing")

        # Redistribute existing versioned chunks into the new ranges.
        old = _normalize_per_tablet(
            node.attributes.get("tablet_chunk_ids", []))
        all_rows: list[dict] = []
        for ids in old:
            for cid in ids:
                all_rows.extend(self.cluster.chunk_store.read_chunk(cid)
                                .to_rows())
        key_names = schema.key_column_names
        buckets: list[list[dict]] = [[] for _ in range(len(pivots) + 1)]
        for row in all_rows:
            sk = _null_safe(tuple(row[name] for name in key_names))
            idx = 0
            for i, sp in enumerate(safe_pivots):
                if sk >= sp:
                    idx = i + 1
            buckets[idx].append(row)
        vschema = versioned_schema(schema)
        per_tablet_ids: list[list[str]] = []
        for bucket in buckets:
            if bucket:
                bucket.sort(key=_versioned_sort_key(schema))
                chunk = ColumnarChunk.from_rows(vschema, bucket)
                per_tablet_ids.append(
                    [self.cluster.chunk_store.write_chunk(chunk)])
            else:
                per_tablet_ids.append([])
        for ids in old:
            for cid in ids:
                self.cluster.chunk_store.remove_chunk(cid)
                self.cluster.chunk_cache.invalidate(cid)
        self.set(path + "/@pivot_keys", [list(p) for p in pivots])
        self.set(path + "/@tablet_chunk_ids", per_tablet_ids)
        self.set(path + "/@tablet_count", len(pivots) + 1)

    # queue (ordered table) API — ref queue_client

    def push_queue(self, path: str, rows: Sequence[dict]) -> int:
        """Append rows to an ordered table; returns first $row_index."""
        (tablet,) = self._mounted_tablets(path)
        from ytsaurus_tpu.tablet.ordered import OrderedTablet
        if not isinstance(tablet, OrderedTablet):
            raise YtError(f"{path!r} is not an ordered table",
                          code=EErrorCode.QueryUnsupported)
        ts = self.cluster.transactions.timestamps.generate()
        return tablet.append_rows(list(rows), ts)

    def pull_queue(self, path: str, offset: int = 0,
                   limit: Optional[int] = None) -> list[dict]:
        (tablet,) = self._mounted_tablets(path)
        self._require_ordered(tablet, path)
        return tablet.read_rows(offset, limit)

    def trim_rows(self, path: str, trimmed_count: int) -> None:
        (tablet,) = self._mounted_tablets(path)
        self._require_ordered(tablet, path)
        tablet.trim_rows(trimmed_count)

    # ------------------------------------------------------ queue consumers

    def register_queue_consumer(self, queue_path: str, consumer_path: str,
                                vital: bool = True) -> None:
        from ytsaurus_tpu.server.queue_agent import register_consumer
        register_consumer(self, queue_path, consumer_path, vital=vital)

    def unregister_queue_consumer(self, queue_path: str,
                                  consumer_path: str) -> None:
        from ytsaurus_tpu.server.queue_agent import unregister_consumer
        unregister_consumer(self, queue_path, consumer_path)

    def advance_consumer(self, consumer_path: str, queue_path: str,
                         new_offset: int,
                         old_offset: Optional[int] = None) -> None:
        from ytsaurus_tpu.server.queue_agent import advance_consumer
        advance_consumer(self, consumer_path, queue_path, new_offset,
                         old_offset=old_offset)

    def pull_consumer(self, consumer_path: str, queue_path: str,
                      limit: Optional[int] = None
                      ) -> "tuple[list[dict], int]":
        from ytsaurus_tpu.server.queue_agent import pull_consumer
        return pull_consumer(self, consumer_path, queue_path, limit=limit)

    # ------------------------------------------------- materialized views

    def create_materialized_view(self, name: str, query: str,
                                 source: Optional[str] = None,
                                 target: Optional[str] = None,
                                 pool: str = "views",
                                 batch_rows: Optional[int] = None) -> dict:
        """Register a continuous query (ISSUE 13): a daemon-tailed
        incremental view over an ordered table, exactly-once into a
        sorted target readable by normal selects (query/views.py)."""
        from ytsaurus_tpu.query.views import create_materialized_view
        return create_materialized_view(
            self, name, query, source=source, target=target, pool=pool,
            batch_rows=batch_rows)

    def list_views(self) -> list[str]:
        from ytsaurus_tpu.query.views import list_views
        return list_views(self)

    def get_view(self, name: str) -> dict:
        from ytsaurus_tpu.query.views import view_status
        return view_status(self, name)

    def pause_view(self, name: str) -> dict:
        from ytsaurus_tpu.query.views import set_view_state
        return set_view_state(self, name, "paused")

    def resume_view(self, name: str) -> dict:
        from ytsaurus_tpu.query.views import set_view_state
        return set_view_state(self, name, "running")

    def remove_view(self, name: str, drop_target: bool = False) -> None:
        from ytsaurus_tpu.query.views import remove_view
        remove_view(self, name, drop_target=drop_target)

    def refresh_view(self, name: str, max_batches: int = 0) -> dict:
        """Drain one view's cursor inline (no daemon): the CLI/driver
        verb behind `yt view refresh` and the test/bench loop."""
        from ytsaurus_tpu.query.views import ViewRefresher, load_view
        refresher = ViewRefresher(self, load_view(self, name))
        return refresher.refresh(max_batches=max_batches)

    @staticmethod
    def _require_ordered(tablet, path: str) -> None:
        from ytsaurus_tpu.tablet.ordered import OrderedTablet
        if not isinstance(tablet, OrderedTablet):
            raise YtError(f"{path!r} is not an ordered (queue) table",
                          code=EErrorCode.QueryUnsupported)

    def _route_rows(self, path: str, tablets, rows):
        """Group rows by owning tablet (pivot ranges); bisect over the
        tablets' own (already normalized) pivot keys."""
        import bisect

        from ytsaurus_tpu.tablet.dynamic_store import _null_safe
        safe_pivots = [
            _null_safe(tablets[0].normalize_key(tuple(t.pivot_key)))
            for t in tablets[1:]]
        out: dict[int, list] = {}
        for row in rows:
            key = tablets[0].active_store.key_of(row) \
                if isinstance(row, dict) else tuple(row)
            sk = _null_safe(tablets[0].normalize_key(key))
            idx = bisect.bisect_right(safe_pivots, sk)
            out.setdefault(idx, []).append(row)
        return out

    @staticmethod
    def _require_sorted(tablet, path: str) -> None:
        from ytsaurus_tpu.tablet.ordered import OrderedTablet
        if isinstance(tablet, OrderedTablet):
            raise YtError(f"{path!r} is an ordered table; this operation "
                          "requires a sorted dynamic table",
                          code=EErrorCode.QueryUnsupported)

    def freeze_table(self, path: str) -> None:
        for tablet in self._mounted_tablets(path):
            tablet.flush()
        self._persist_tablet_chunks(path)

    def compact_table(self, path: str,
                      retention_timestamp: Optional[int] = None) -> None:
        ts = retention_timestamp if retention_timestamp is not None else \
            self.cluster.transactions.timestamps.generate()
        for tablet in self._mounted_tablets(path):
            self._require_sorted(tablet, path)
            tablet.flush()
            tablet.compact(retention_timestamp=ts)
        self._persist_tablet_chunks(path)

    def start_transaction(self) -> TabletTransaction:
        return self.cluster.transactions.start()

    def commit_transaction(self, tx: TabletTransaction) -> int:
        self._finalize_tx(tx)
        commit_ts = self.cluster.transactions.commit(tx)
        # Sync-replica checkpoints for writes staged under this caller-owned
        # transaction (kept on the tx so an abort advances nothing).
        for path, sync_targets, era0 in getattr(
                tx, "pending_sync_advances", []):
            self._advance_sync_checkpoints(path, sync_targets, commit_ts)
            self._recheck_replication_era(path, era0, commit_ts)
        return commit_ts

    def abort_transaction(self, tx: TabletTransaction) -> None:
        self.cluster.transactions.abort(tx)

    def insert_rows(self, path: str, rows: Sequence[dict],
                    tx: Optional[TabletTransaction] = None,
                    update: bool = False) -> Optional[int]:
        """update=True: write only the provided columns; missing ones merge
        per column from older versions (ref ModifyRows update mode +
        versioned_row_merger partial writes)."""
        tablets = self._mounted_tablets(path)
        rows = self._fill_computed_columns(tablets[0].schema, list(rows))
        from ytsaurus_tpu.tablet.ordered import OrderedTablet
        if isinstance(tablets[0], OrderedTablet):
            if tx is not None:
                raise YtError("Transactional writes to ordered tables are "
                              "not supported yet",
                              code=EErrorCode.QueryUnsupported)
            self.push_queue(path, rows)
            return None
        txm = self.cluster.transactions
        own = tx is None
        tx = tx or txm.start()
        # Secondary-index rows ride the same transaction; the net mutation
        # set is computed at commit (finalize_index_mutations).
        from ytsaurus_tpu.tablet.secondary_index import record_index_intent
        record_index_intent(self, tx, path, self._table_node(path),
                            tablets[0].schema, list(rows), None, update)
        for idx, part in self._route_rows(path, tablets, list(rows)).items():
            txm.write_rows(tx, tablets[idx], part, update=update)
        # Sync replicas join the SAME 2PC commit (ref transaction.cpp:737
        # sync-replica fanout): their tablets are extra participants, so a
        # broken sync replica fails the write before anything commits.
        era0, sync_targets = self._replication_state(path)
        for rid, rc, rpath in sync_targets:
            rtablets = rc._mounted_tablets(rpath)
            for idx, part in rc._route_rows(rpath, rtablets,
                                            list(rows)).items():
                txm.write_rows(tx, rtablets[idx], part, update=update)
        if own:
            self._finalize_tx(tx)
            commit_ts = txm.commit(tx)
            self._advance_sync_checkpoints(path, sync_targets, commit_ts)
            self._recheck_replication_era(path, era0, commit_ts)
            return commit_ts
        if sync_targets or era0 is not None:
            tx.pending_sync_advances = getattr(
                tx, "pending_sync_advances", []) + \
                [(path, sync_targets, era0)]
        return None

    def delete_rows(self, path: str, keys: Sequence[tuple],
                    tx: Optional[TabletTransaction] = None) -> Optional[int]:
        tablets = self._mounted_tablets(path)
        self._require_sorted(tablets[0], path)
        keys = self._fill_computed_keys(tablets[0].schema,
                                        [tuple(k) for k in keys])
        txm = self.cluster.transactions
        own = tx is None
        tx = tx or txm.start()
        from ytsaurus_tpu.tablet.secondary_index import record_index_intent
        record_index_intent(self, tx, path, self._table_node(path),
                            tablets[0].schema, None, keys, False)
        for idx, part in self._route_rows(
                path, tablets, keys).items():
            txm.delete_rows(tx, tablets[idx], part)
        era0, sync_targets = self._replication_state(path)
        for rid, rc, rpath in sync_targets:
            rtablets = rc._mounted_tablets(rpath)
            for idx, part in rc._route_rows(rpath, rtablets, keys).items():
                txm.delete_rows(tx, rtablets[idx], part)
        if own:
            self._finalize_tx(tx)
            commit_ts = txm.commit(tx)
            self._advance_sync_checkpoints(path, sync_targets, commit_ts)
            self._recheck_replication_era(path, era0, commit_ts)
            return commit_ts
        if sync_targets or era0 is not None:
            tx.pending_sync_advances = getattr(
                tx, "pending_sync_advances", []) + \
                [(path, sync_targets, era0)]
        return None

    # --------------------------------------------------------------- replication

    def create_table_replica(self, table_path: str, replica_path: str,
                             cluster_root: Optional[str] = None,
                             mode: str = "async",
                             enabled: bool = True) -> str:
        """Register a replica of a replicated (dynamic) table.  The replica
        table must exist (same schema) on the target cluster; cluster_root
        None means this cluster.  Ref: CreateTableReplica
        (client/api/client.h), table_replica objects (tablet_server)."""
        from ytsaurus_tpu.tablet import replication as repl
        if mode not in ("sync", "async"):
            raise YtError(f"Bad replica mode {mode!r}",
                          code=EErrorCode.QueryTypeError)
        self._table_node(table_path)
        replicas = repl.replica_descriptors(self, table_path)
        rid = f"replica-{len(replicas)}"
        while rid in replicas:
            rid = rid + "-1"
        replicas[rid] = {"path": replica_path, "cluster_root": cluster_root,
                         "mode": mode, "enabled": bool(enabled),
                         "last_replicated_ts": 0, "error": None}
        repl.set_replica_descriptors(self, table_path, replicas)
        return rid

    def alter_table_replica(self, table_path: str, replica_id: str,
                            mode: Optional[str] = None,
                            enabled: Optional[bool] = None) -> None:
        from ytsaurus_tpu.tablet import replication as repl
        replicas = repl.replica_descriptors(self, table_path)
        if replica_id not in replicas:
            raise YtError(f"No such replica {replica_id!r}",
                          code=EErrorCode.ResolveError)
        if mode is not None:
            if mode not in ("sync", "async"):
                raise YtError(f"Bad replica mode {mode!r}",
                              code=EErrorCode.QueryTypeError)
            replicas[replica_id]["mode"] = mode
        if enabled is not None:
            replicas[replica_id]["enabled"] = bool(enabled)
        repl.set_replica_descriptors(self, table_path, replicas)

    def get_table_replicas(self, table_path: str) -> dict:
        from ytsaurus_tpu.tablet import replication as repl
        return repl.replica_descriptors(self, table_path)

    def _finalize_tx(self, tx) -> None:
        """Pre-commit hook: stage net secondary-index mutations recorded
        under this transaction."""
        from ytsaurus_tpu.tablet.secondary_index import (
            finalize_index_mutations,
        )
        finalize_index_mutations(self, self.cluster.transactions, tx)

    def _sync_replica_targets(self, path: str):
        """(replica_id, replica_client, replica_path) for each enabled
        sync replica of `path` (empty for non-replicated tables)."""
        return self._replication_state(path)[1]

    def _replication_state(self, path: str):
        """(era, sync_targets) in one node read.  era is None for a
        plain non-replicated table (the common case pays one attribute
        probe and nothing else); otherwise it is the replication-card
        era observed for this write, re-checked after commit so a commit
        racing a chaos sync cutover re-delivers its events to the new
        configuration (chaos_agent.h era semantics)."""
        from ytsaurus_tpu.tablet import replication as repl
        node = self._table_node(path)
        replicas = node.attributes.get(repl.REPLICAS_ATTR) or {}
        card = node.attributes.get("replication_card")
        if not replicas and not card:
            return None, []
        era = int(card["era"]) if card else 0
        out = []
        for rid, info in replicas.items():
            if info.get("enabled") and info.get("mode") == "sync":
                rc = self.table_replicator.replica_client(
                    info.get("cluster_root"))
                out.append((rid, rc, info["path"]))
        return era, out


    def _recheck_replication_era(self, path: str, era0,
                                 commit_ts: int) -> None:
        """Post-commit era check: a chaos sync cutover that raced this
        commit may have enrolled a sync replica the fanout missed;
        re-deliver the commit's events to the current configuration
        (idempotent over preserved timestamps)."""
        if era0 is None:
            return
        from ytsaurus_tpu.tablet import chaos
        if chaos.current_era(self, path) != era0:
            chaos.redeliver_commit(self, path, commit_ts)

    def _advance_sync_checkpoints(self, path: str, sync_targets,
                                  commit_ts: int) -> None:
        if not sync_targets:
            return
        from ytsaurus_tpu.tablet import replication as repl
        replicas = repl.replica_descriptors(self, path)
        for rid, _rc, _rpath in sync_targets:
            if rid in replicas:
                replicas[rid]["last_replicated_ts"] = commit_ts
        repl.set_replica_descriptors(self, path, replicas)

    def lookup_rows(self, path: str, keys: Sequence[tuple],
                    timestamp: int = MAX_TIMESTAMP,
                    column_names: Optional[Sequence[str]] = None,
                    replica_fallback: bool = False,
                    timeout: Optional[float] = None,
                    pool: Optional[str] = None
                    ) -> list[Optional[dict]]:
        """Point reads.  Routed through the cluster's QueryGateway
        (query/serving.py): concurrent lookups against one table
        coalesce into micro-batches with parallel per-tablet fan-out,
        under per-pool admission control and a deadline (`timeout`
        seconds, default ServingConfig.default_timeout).

        replica_fallback=True: when the upstream table is
        unavailable, read from the replicas — HEDGED, not sequential
        (core/rpc/hedging_channel.h): the best replica (sync first, then
        freshest) starts immediately and each further replica is armed
        after `lookup_hedging_delay`, first success wins — so one slow
        replica bounds tail latency at ~delay + healthy-replica latency
        instead of the slow replica's timeout."""
        if replica_fallback:
            try:
                return self.lookup_rows(path, keys, timestamp=timestamp,
                                        column_names=column_names,
                                        timeout=timeout, pool=pool)
            except YtError as primary_err:
                if primary_err.code in (EErrorCode.RequestThrottled,
                                        EErrorCode.DeadlineExceeded):
                    # Serving-plane verdicts are NOT unavailability: a
                    # throttle means back off (retry_after), a lapsed
                    # deadline is terminal — hedging every replica here
                    # would both bust the caller's deadline and multiply
                    # load exactly when the cluster asked for less.
                    raise
                from ytsaurus_tpu.tablet import replication as repl
                replicas = repl.replica_descriptors(self, path)
                ranked = [
                    info for info in sorted(
                        replicas.values(),
                        key=lambda i: (i.get("mode") != "sync",
                                       -int(i.get("last_replicated_ts",
                                                  0))))
                    if info.get("enabled")]

                def from_replica(info):
                    rc = self.table_replicator.replica_client(
                        info.get("cluster_root"))
                    return rc.lookup_rows(
                        info["path"], keys, timestamp=timestamp,
                        column_names=column_names)

                return _hedged_race(
                    [lambda info=info: from_replica(info)
                     for info in ranked],
                    self.lookup_hedging_delay, primary_err)
        gateway = self.cluster.gateway
        if gateway.enabled and keys:
            from ytsaurus_tpu.utils.tracing import start_query_span
            # Entry-point span: roots a (sampled) trace for this lookup,
            # or continues the ambient one (RPC handler / batched
            # caller) — the cohort's batch-flush span parents here.
            with start_query_span("query.lookup", table=path,
                                  keys=len(keys)):
                return gateway.lookup_rows(self, path, keys, timestamp,
                                           column_names=column_names,
                                           pool=pool, timeout=timeout)
        return self._lookup_rows_direct(path, keys, timestamp,
                                        column_names)

    def _lookup_rows_direct(self, path: str, keys: Sequence[tuple],
                            timestamp: int = MAX_TIMESTAMP,
                            column_names: Optional[Sequence[str]] = None
                            ) -> list[Optional[dict]]:
        """The pre-gateway path (serving disabled): sequential per-tablet
        reads, no batching, no admission.  Kept separate so the bench
        can measure batched vs. unbatched and the gateway stays
        bypassable."""
        tablets = self._mounted_tablets(path)
        self._require_sorted(tablets[0], path)
        keys = self._fill_computed_keys(tablets[0].schema,
                                        [tuple(k) for k in keys])
        routed = self._route_rows(path, tablets, keys)
        results: dict[tuple, Optional[dict]] = {}
        for idx, part in routed.items():
            for nk, row in zip(
                    [tablets[idx].normalize_key(k) for k in part],
                    tablets[idx].lookup_rows(
                        part, timestamp=timestamp,
                        column_names=column_names)):
                results[nk] = row
        # preserve request order
        return [results[tablets[0].normalize_key(k)] for k in keys]

    # --------------------------------------------------------------------- query

    def select_rows(self, query: str,
                    timestamp: int = MAX_TIMESTAMP,
                    timeout: Optional[float] = None,
                    pool: Optional[str] = None,
                    explain_analyze: bool = False,
                    params: Optional[Sequence] = None) -> "list[dict]":
        """Distributed QL over static and mounted dynamic tables, routed
        through the cluster's QueryGateway (query/serving.py): admission
        against the per-pool concurrency slots (overflow raises
        ThrottledError with a retry_after hint) and a deadline
        (`timeout` seconds, default ServingConfig.default_timeout)
        cooperatively checked between shard programs.

        Every query runs under a root trace span (sampled per
        config.TracingConfig) covering admission, per-shard staging/
        execution, evaluator compile-vs-execute, and tablet/chunk reads;
        finished queries fold into an ExecutionProfile retained by the
        flight recorder (slow-query log + sampled recent log, monitoring
        `/traces`).  `explain_analyze=True` forces sampling and returns
        the ExecutionProfile (with `.rows` carrying the result) instead
        of the bare row list — EXPLAIN ANALYZE with the compile/execute
        split reported separately.

        Per-query statistics land in `self.last_query_statistics` (ref
        TQueryStatistics) and in the structured Query log."""
        import time as _time

        from ytsaurus_tpu.query.profile import (
            ExecutionProfile,
            get_flight_recorder,
        )
        from ytsaurus_tpu.query.statistics import QueryStatistics
        from ytsaurus_tpu.utils.tracing import start_query_span
        gateway = self.cluster.gateway
        # The admission-resolved pool is the identity every plane shares
        # (admission counters, per-pool sensors, accounting): capturing
        # the raw requested name would split a query between an admitted
        # pool and an invented accounting pool.
        if gateway.enabled:
            pool = gateway.resolve_pool(pool)
        root = start_query_span("query.select", force=explain_analyze,
                                query=query[:200],
                                pool=pool or "default")
        # Statistics object threaded explicitly: `last_query_statistics`
        # is a shared attribute a concurrent select on the same client
        # (HTTP proxy / driver thread pools) would overwrite between our
        # impl finishing and the profile capture reading it.
        stats = QueryStatistics()
        t0 = _time.perf_counter()
        try:
            with root:
                if not gateway.enabled:
                    rows = self._select_rows_impl(query, timestamp, None,
                                                  stats=stats,
                                                  params=params)
                else:
                    rows = gateway.run_select(
                        lambda token: self._select_rows_impl(
                            query, timestamp, token, stats=stats,
                            params=params),
                        pool=pool, timeout=timeout)
        except YtError as err:
            # Workload recorder (ISSUE 8): failed queries are part of
            # the workload too — the record carries the classified
            # outcome (throttled/deadline/error) so a replayed mix
            # reproduces the rejection profile, not just the successes.
            from ytsaurus_tpu.query.workload import (
                get_workload_log,
                outcome_of,
            )
            get_workload_log().observe_select(
                query, stats=stats, outcome=outcome_of(err),
                wall_time=_time.perf_counter() - t0, pool=pool,
                trace_id=getattr(root, "trace_id", None))
            raise
        profile = ExecutionProfile.capture(
            root, query, stats, _time.perf_counter() - t0, pool=pool)
        if explain_analyze:
            # Attach BEFORE observe: the recorder strips rows from what
            # it retains (without_rows copy), so attaching afterwards
            # would mutate the stored object and pin the result set.
            profile.rows = rows
        get_flight_recorder().observe(profile)
        # Per-tenant resource accounting (ISSUE 6): the finished query's
        # counters fold into cumulative (pool, user) usage — the signal
        # fair-share serving weighs tenants by, served on /accounting
        # and `yt top`.
        from ytsaurus_tpu.query.accounting import get_accountant
        get_accountant().observe_query(profile)
        # Workload recorder (ISSUE 8): the finished query folds one
        # compact record (normalized text + hoisted literals + the
        # wall/compile/execute split + capacity buckets + trace id)
        # into the bounded workload log — the capture `yt replay` and
        # `bench.py --config replay` re-run.
        from ytsaurus_tpu.query.workload import get_workload_log
        get_workload_log().observe_select(query, profile=profile)
        return profile if explain_analyze else rows

    def nearest_rows(self, path: str, column: str,
                     query_vector: Sequence[float], k: int,
                     metric: str = "l2",
                     timestamp: int = MAX_TIMESTAMP,
                     timeout: Optional[float] = None,
                     pool: Optional[str] = None) -> list[dict]:
        """Top-k vector similarity over `column` (a `vector<float,N>`
        column) of `path`, served through the vector micro-batcher
        (query/vector.py): co-admitted NEAREST queries on one
        (table, column, metric) cohort execute as ONE batched
        `(batch, dim) @ (dim, rows)` distance matmul.  Returns up to
        `k` full rows ranked by `metric` ("l2", "cosine", or "dot"),
        each with a `$distance` field (similarity for "dot").

        The equivalent query-language form —
        `SELECT ... FROM [t] NEAREST(column, ?, k)` via
        `select_rows(..., params=[vec])` — runs the same distance
        kernel through the whole-plan SPMD path instead; this entry
        point is the serving-plane fast path for high-QPS workloads."""
        gateway = self.cluster.gateway
        if gateway.enabled:
            from ytsaurus_tpu.utils.tracing import start_query_span
            with start_query_span("query.nearest", table=path, k=k):
                return gateway.nearest_rows(
                    self, path, column, query_vector, k, metric=metric,
                    timestamp=timestamp, pool=pool, timeout=timeout)
        # Serving disabled: execute the same batched kernel directly
        # (a cohort of one), no admission, no coalescing window.
        from ytsaurus_tpu.chunks.columnar import concat_chunks
        from ytsaurus_tpu.query.vector import batched_nearest
        chunk = concat_chunks([t.read_snapshot(timestamp)
                               for t in self._mounted_tablets(path)])
        ranked = batched_nearest(chunk, column, [query_vector], k,
                                 metric=metric)
        rows = chunk.to_rows()
        out = []
        for row_idx, measure in ranked[0]:
            row = dict(rows[row_idx])
            row["$distance"] = measure
            out.append(row)
        return out

    def _select_rows_system(self, query: str,
                            timestamp: int = MAX_TIMESTAMP) -> list[dict]:
        """System-plane select: NO admission, NO deadline.  For internal
        metadata/bookkeeping reads (sequoia resolution, secondary-index
        maintenance, queue offsets) that must not queue behind — or
        nest inside — user admission: a write transaction must not fail
        because the read pool is saturated, and a lookup issued while
        the caller already holds an admission slot would deadlock a
        saturated pool."""
        return self._select_rows_impl(query, timestamp, None)

    def _select_rows_impl(self, query: str, timestamp: int,
                          token, stats=None, params=None) -> list[dict]:
        import logging as _logging

        from ytsaurus_tpu.query.statistics import QueryStatistics
        from ytsaurus_tpu.utils.logging import get_logger, log_event
        if stats is None:
            stats = QueryStatistics()
        self.last_query_statistics = stats   # visible even if the query fails
        plan = build_query(query, _SchemaResolver(self), params=params)
        # Every source table requires read permission (ref: query agent
        # checks table read access before executing subqueries).
        self.cluster.security.validate_permission("read", plan.source)
        for join in plan.joins:
            self.cluster.security.validate_permission(
                "read", join.foreign_table)
        from ytsaurus_tpu.query.pruning import extract_column_intervals
        intervals = extract_column_intervals(plan.where)
        if plan.joins:
            # Semi-join pushdown (ISSUE 14): a selective INNER side's
            # key [min, max] — merged off the foreign chunks' sealed
            # metadata stats, no decode — narrows the scan intervals, so
            # whole source shards whose key range cannot join anything
            # prune before staging.
            from ytsaurus_tpu.chunks.columnar import merge_column_stats
            from ytsaurus_tpu.query import planner as query_planner
            from ytsaurus_tpu.query.pruning import Interval
            foreign_meta_stats = {}
            for join in plan.joins:
                try:
                    fnode = self._table_node(join.foreign_table)
                except YtError:
                    continue
                per_chunk = fnode.attributes.get("chunk_stats") or []
                # A placeholder entry ({} — a chunk sealed before stats
                # existed) means that chunk's key range is UNKNOWN:
                # merging the OTHER chunks' bounds and pushing them
                # would prune source rows that join the legacy chunk.
                # Same per column: a column absent from any entry is
                # unbounded for this table.
                if not per_chunk or not all(isinstance(e, dict) and e
                                            for e in per_chunk):
                    continue
                merged = merge_column_stats(per_chunk)
                for cname in list(merged):
                    if cname != "$row_count" and \
                            not all(cname in e for e in per_chunk):
                        merged.pop(cname)
                foreign_meta_stats[join.foreign_table] = merged
            if foreign_meta_stats:
                pushed = query_planner.pushdown_intervals(
                    plan, foreign_meta_stats)
                for name, iv in pushed.items():
                    intervals[name] = intervals.get(
                        name, Interval()).narrow(iv)
        range_ordered_by = None
        source_chunks = self._indexed_source_chunks(plan, intervals,
                                                    timestamp)
        if source_chunks is None:
            # LIMIT scans stage shards lazily: the coordinator's
            # adaptive prefetcher fetches only what the early exit
            # reads, and pipelines staging under evaluation.
            lazy = plan.limit is not None and plan.group is None
            source_chunks = self._query_shards(plan.source, timestamp,
                                               intervals=intervals,
                                               stats=stats, lazy=lazy,
                                               token=token)
            # Tablet shards of a sorted dynamic table arrive in pivot
            # order: range-ordered by the key columns, which unlocks the
            # ORDER BY <key prefix> LIMIT early exit.
            try:
                node = self._table_node(plan.source)
                if node.attributes.get("dynamic"):
                    schema = self._node_schema(node)
                    if schema is not None and schema.key_column_names:
                        range_ordered_by = list(schema.key_column_names)
            except YtError:
                pass
        foreign = {}
        for join in plan.joins:
            if token is not None:
                token.check()
            shards = self._query_shards(join.foreign_table, timestamp)
            foreign[join.foreign_table] = (
                concat_chunks(shards) if len(shards) > 1 else shards[0])
        out = coordinate_and_execute(plan, source_chunks, foreign,
                                     evaluator=self.cluster.evaluator,
                                     merge_shards_below=4_000_000,
                                     range_ordered_by=range_ordered_by,
                                     stats=stats, token=token)
        if token is not None and token.rung:
            # Tag the degraded response (brown-out ladder): the rung and
            # the actual staleness served land in the query statistics,
            # which flow to the slow log, EXPLAIN ANALYZE, and drivers.
            stats.degraded_rung = token.rung
            stats.degraded_staleness = round(token.stale_served, 6)
        if self.cluster._gateway is not None:
            self.cluster.gateway.record_statistics(
                stats, self.cluster.evaluator.cache_size())
        log_event(get_logger("Query"), _logging.INFO, "select_rows",
                  query=query[:200], **stats.to_dict())
        return out.to_rows()

    def _indexed_source_chunks(self, plan, intervals, timestamp):
        """Serve the scan from a secondary index when one applies (WHERE
        bounds the index prefix); None → fall back to the shard scan.
        Ref: secondary-index predicate rewrite."""
        from ytsaurus_tpu.tablet.secondary_index import (
            fetch_via_index,
            pick_index,
        )
        try:
            node = self._table_node(plan.source)
        except YtError:
            return None
        if not node.attributes.get("dynamic"):
            return None
        desc = pick_index(node, intervals)
        if desc is None:
            return None
        schema = self._node_schema(node)
        try:
            rows = fetch_via_index(self, plan.source, schema, desc,
                                   intervals, timestamp)
        except YtError:
            return None
        if rows is None:
            return None
        return [ColumnarChunk.from_rows(schema.to_unsorted(), rows)]

    def backup_table(self, src_path: str, dst_path: str,
                     timestamp: Optional[int] = None) -> None:
        """Consistent backup of a dynamic table as of `timestamp` (default
        now): versions newer than the cutoff are excluded, timestamps are
        PRESERVED so a restored table serves the same MVCC reads.

        Ref: backup_manager (tablet_node/backup_manager.h) — checkpoint
        timestamp + per-tablet clipped stores; here the clip is a
        vectorized filter over the versioned snapshot planes."""
        from ytsaurus_tpu.tablet.tablet import (
            _versioned_sort_key,
            versioned_schema,
        )
        tablets = self._mounted_tablets(src_path)
        self._require_sorted(tablets[0], src_path)
        schema = tablets[0].schema
        cutoff = timestamp if timestamp is not None else \
            self.cluster.transactions.timestamps.generate()
        node = self._table_node(src_path)
        pivots = [list(p) for p in node.attributes.get("pivot_keys", [])]
        self.create("table", dst_path, recursive=True,
                    attributes={"schema": schema, "dynamic": True,
                                "pivot_keys": pivots,
                                "backup_timestamp": cutoff})
        per_tablet_chunks: list[list[str]] = []
        vschema = versioned_schema(schema)
        for tablet in tablets:
            rows = [r for r in tablet.versioned_rows_snapshot()
                    if r["$timestamp"] <= cutoff]
            rows.sort(key=_versioned_sort_key(schema))
            if rows:
                chunk = ColumnarChunk.from_rows(vschema, rows)
                per_tablet_chunks.append(
                    [self.cluster.chunk_store.write_chunk(chunk)])
            else:
                per_tablet_chunks.append([])
        self.set(dst_path + "/@tablet_chunk_ids", per_tablet_chunks)
        self.set(dst_path + "/@tablet_state", "unmounted")

    def restore_table_backup(self, backup_path: str, dst_path: str) -> None:
        """Materialize a backup as a fresh dynamic table (chunks COPY so
        the restored table's lifecycle is independent of the backup's)."""
        self.copy(backup_path, dst_path, recursive=True)

    def create_secondary_index(self, table_path: str, index_path: str,
                               columns: Sequence[str]) -> None:
        from ytsaurus_tpu.tablet.secondary_index import create_secondary_index
        create_secondary_index(self, table_path, index_path, columns)

    def drop_secondary_index(self, table_path: str, index_path: str,
                             remove_table: bool = True) -> None:
        from ytsaurus_tpu.tablet.secondary_index import drop_secondary_index
        drop_secondary_index(self, table_path, index_path,
                             remove_table=remove_table)

    # ---------------------------------------------------------------- operations

    def run_sort(self, input_path: str, output_path: str,
                 sort_by: "str | Sequence[str]", **kwargs):
        return self.scheduler.start_operation("sort", {
            "input_table_path": input_path, "output_table_path": output_path,
            "sort_by": list(sort_by) if not isinstance(sort_by, str)
            else sort_by, **kwargs})

    def run_merge(self, input_paths: Sequence[str], output_path: str,
                  mode: str = "unordered", **kwargs):
        return self.scheduler.start_operation("merge", {
            "input_table_paths": list(input_paths),
            "output_table_path": output_path, "mode": mode, **kwargs})

    def run_map(self, mapper: "Callable | str", input_path: str,
                output_path: str, **kwargs):
        """mapper: a Python callable rows→rows, or a shell COMMAND string
        run in job-proxy subprocesses (ref user_job.cpp pipes)."""
        spec = {"input_table_path": input_path,
                "output_table_path": output_path, **kwargs}
        if isinstance(mapper, str):
            spec["command"] = mapper
        else:
            spec["mapper"] = mapper
        return self.scheduler.start_operation("map", spec)

    def run_erase(self, table_path: str, **kwargs):
        return self.scheduler.start_operation(
            "erase", {"table_path": table_path, **kwargs})

    def run_reduce(self, reducer: "Callable | str",
                   input_path: "str | Sequence[str]", output_path: str,
                   reduce_by: "str | Sequence[str]", **kwargs):
        """Sorted reduce (ref CreateReduceController,
        sorted_controller.cpp:1451).  reducer: a Python callable
        (key_dict, group_rows) -> rows, or a shell COMMAND streaming
        key-contiguous sorted rows on stdin/stdout."""
        spec = {"output_table_path": output_path,
                "reduce_by": reduce_by, **kwargs}
        if isinstance(input_path, str):
            spec["input_table_path"] = input_path
        else:
            spec["input_table_paths"] = list(input_path)
        if isinstance(reducer, str):
            spec["command"] = reducer
        else:
            spec["reducer"] = reducer
        return self.scheduler.start_operation("reduce", spec)

    def run_map_reduce(self, mapper: "Callable | str | None",
                       reducer: "Callable | str", input_path: str,
                       output_path: str,
                       reduce_by: "str | Sequence[str]", **kwargs):
        """MapReduce (ref CreateMapReduceController,
        sort_controller.cpp:5029): map+partition → hash shuffle →
        per-partition sort + reduce.  mapper may be None (identity)."""
        spec = {"input_table_path": input_path,
                "output_table_path": output_path,
                "reduce_by": reduce_by, **kwargs}
        if isinstance(mapper, str):
            spec["map_command"] = mapper
        elif mapper is not None:
            spec["mapper"] = mapper
        if isinstance(reducer, str):
            spec["reduce_command"] = reducer
        else:
            spec["reducer"] = reducer
        return self.scheduler.start_operation("map_reduce", spec)

    def run_vanilla(self, tasks: dict, sync: bool = True, **kwargs):
        """Gang operation with no input (ref vanilla_controller.cpp:130):
        tasks = {name: {"job_count": N, "command": ... | "callable": ...}}.
        sync=False hosts long-lived server commands (the clique pattern);
        stop them with abort_operation."""
        return self.scheduler.start_operation(
            "vanilla", {"tasks": tasks, **kwargs}, sync=sync)

    def run_remote_copy(self, cluster_address: str, input_path: str,
                        output_path: str, **kwargs):
        """Copy a table from another cluster (ref
        remote_copy_controller.cpp)."""
        return self.scheduler.start_operation("remote_copy", {
            "cluster_address": cluster_address,
            "input_table_path": input_path,
            "output_table_path": output_path, **kwargs})

    def abort_operation(self, op_id: str):
        return self.scheduler.abort_operation(op_id)

    # ----------------------------------------------------------------- internals

    def _computed_plan(self, schema: TableSchema):
        """Cached (plan, input schema, referenced column names) for a
        schema's computed columns (ref TColumnEvaluatorCache,
        engine_api/column_evaluator.h)."""
        cached = self._computed_plans.get(schema)
        if cached is not None:
            return cached
        computed = [c for c in schema if c.expression]
        supplied = [c for c in schema if not c.expression]
        base_schema = TableSchema.make(
            [(c.name, c.type.value) for c in supplied])
        select_list = ", ".join(
            f"{c.expression} AS {c.name}" for c in computed)
        plan = build_query(f"{select_list} FROM [//$computed]",
                           {"//$computed": base_schema})
        for item, col in zip(plan.project.items, computed):
            if item.expr.type is not col.type:
                raise YtError(
                    f"Computed column {col.name!r}: expression type "
                    f"{item.expr.type.value} != column type {col.type.value}",
                    code=EErrorCode.QueryTypeError)
        # Feed only the columns the expressions actually read.
        referenced: set[str] = set()
        for item in plan.project.items:
            ir.map_expr(item.expr, lambda node: (
                referenced.add(node.name)
                if isinstance(node, ir.TReference) else None) or node)
        input_schema = TableSchema.make(
            [(c.name, c.type.value) for c in supplied
             if c.name in referenced])
        plan = build_query(f"{select_list} FROM [//$computed]",
                           {"//$computed": input_schema})
        entry = (plan, input_schema, [c.name for c in computed])
        self._computed_plans[schema] = entry
        return entry

    def _fill_computed_columns(self, schema: TableSchema,
                               rows: "list[dict]") -> "list[dict]":
        """Evaluate `expression` columns from the other columns at write time
        (ref column evaluator for computed key columns,
        library/query/engine_api/column_evaluator.h).  Runs the expressions
        through the query engine itself so semantics match SELECT exactly."""
        computed = [c for c in schema if c.expression]
        if not computed or not rows:
            return rows
        for row in rows:
            for c in computed:
                if c.name in row:
                    raise YtError(
                        f"Column {c.name!r} is computed "
                        f"({c.expression!r}) and cannot be written directly",
                        code=EErrorCode.QueryTypeError)
        plan, input_schema, _ = self._computed_plan(schema)
        chunk = ColumnarChunk.from_rows(
            input_schema, [{c.name: row.get(c.name) for c in input_schema}
                           for row in rows])
        out = self.cluster.evaluator.run_plan(plan, chunk).to_rows()
        filled = []
        for row, extra in zip(rows, out):
            merged = dict(row)
            merged.update(extra)
            filled.append(merged)
        return filled

    def _fill_computed_keys(self, schema: TableSchema,
                            keys: "list[tuple]") -> "list[tuple]":
        """Accept keys WITHOUT the computed parts (the natural key) and fill
        them, mirroring insert-time evaluation; full-width keys pass
        through.  Width is checked PER KEY so mixed batches cannot be
        misinterpreted."""
        key_cols = schema.key_columns
        if not any(c.expression for c in key_cols) or not keys:
            return keys
        natural = [c for c in key_cols if not c.expression]
        if len(natural) == len(key_cols):
            return keys
        out: "list[tuple | None]" = [None] * len(keys)
        to_fill: list[int] = []
        for i, key in enumerate(keys):
            if len(key) == len(key_cols):
                out[i] = key               # full key supplied
            elif len(key) == len(natural):
                to_fill.append(i)
            else:
                raise YtError(
                    f"Key width {len(key)} matches neither the full key "
                    f"({len(key_cols)}) nor the natural key ({len(natural)})",
                    code=EErrorCode.QueryTypeError)
        if to_fill:
            rows = [{c.name: v for c, v in zip(natural, keys[i])}
                    for i in to_fill]
            filled_rows = self._fill_computed_columns(schema, rows)
            for i, row in zip(to_fill, filled_rows):
                out[i] = tuple(row[c.name] for c in key_cols)
        return out

    def _table_node(self, path: str, create: bool = False,
                    schema: "TableSchema | dict | None" = None):
        tree = self.cluster.master.tree
        node = tree.try_resolve(path)
        if node is None:
            if not create:
                raise YtError(f"No such table {path!r}",
                              code=EErrorCode.NoSuchNode)
            attributes = {}
            if schema is not None:
                attributes["schema"] = (
                    schema.to_dict() if isinstance(schema, TableSchema)
                    else schema)
            self.create("table", path, attributes=attributes, recursive=True)
            node = tree.resolve(path)
        if node.type != "table":
            raise YtError(f"{path!r} is not a table (type {node.type})",
                          code=EErrorCode.ResolveError)
        return node

    def _node_schema(self, node) -> Optional[TableSchema]:
        schema = node.attributes.get("schema")
        if schema is None:
            return None
        return TableSchema.from_dict(schema)

    def _mounted_tablets(self, path: str) -> list[Tablet]:
        node = self._table_node(path)
        tablets = self.cluster.tablets.get(node.id)
        if tablets is None and \
                node.attributes.get("tablet_state") == "mounted":
            # Mount state is cluster metadata: a fresh connection to a
            # cluster whose master says "mounted" re-materializes the
            # tablets from the persisted chunk lists (ref: tablet cells
            # recover mounted tablets from the master after restart).
            self.mount_table(path)
            tablets = self.cluster.tablets.get(node.id)
        if tablets is None:
            raise YtError(f"Table {path!r} is not mounted",
                          code=EErrorCode.TabletNotMounted)
        return tablets

    def _persist_tablet_chunks(self, path: str) -> None:
        node = self._table_node(path)
        tablets = self.cluster.tablets.get(node.id, [])
        # Nested per-tablet layout — must match mount/unmount exactly, or a
        # restart reassigns every chunk to tablet 0.
        self.set(path + "/@tablet_chunk_ids",
                 [list(t.chunk_ids) for t in tablets])

    def _read_table_chunks(self, path: str) -> list[ColumnarChunk]:
        node = self._table_node(path)
        if node.attributes.get("dynamic"):
            return self._query_shards(path, MAX_TIMESTAMP)
        return [self.cluster.chunk_cache.get(cid)
                for cid in node.attributes.get("chunk_ids", [])]

    def _write_table_chunks(self, path: str, chunks: list[ColumnarChunk],
                            sorted_by: Optional[list[str]] = None,
                            schema: Optional[TableSchema] = None) -> None:
        self._table_node(path, create=True, schema=schema)
        publish_table_chunks(self, self.cluster.chunk_store, path, chunks,
                             sorted_by=sorted_by, schema=schema)

    def _query_shards(self, path: str, timestamp: int,
                      intervals=None, stats=None,
                      lazy: bool = False, token=None) -> list:
        """Shard chunks for a scan.  lazy=True returns zero-arg
        SUPPLIERS instead of chunks: staging (tablet snapshot / chunk
        decode) is deferred into the coordinator's adaptive prefetcher,
        so an ordered LIMIT never touches the shards its early exit
        skips (ref coordinator.h scanOrder/prefetch)."""
        node = self._table_node(path)
        if node.attributes.get("dynamic"):
            from ytsaurus_tpu.tablet.ordered import OrderedTablet
            from ytsaurus_tpu.tablet.timestamp import (
                ASYNC_LAST_COMMITTED,
            )
            tablets = self._mounted_tablets(path)
            if lazy and timestamp >= ASYNC_LAST_COMMITTED:
                # Deferred snapshots taken at read-latest would see
                # DIFFERENT cuts (shard 5 snapshots minutes after shard
                # 0 under a slow scan).  Pin one concrete timestamp
                # now — shared by BOTH table kinds — so every supplier
                # reads the same consistent cut whenever it runs; a
                # caller's concrete timestamp passes through untouched.
                timestamp = \
                    self.cluster.transactions.timestamps.generate()
            if isinstance(tablets[0], OrderedTablet):
                concrete = timestamp if timestamp < ASYNC_LAST_COMMITTED \
                    else None           # eager read-latest: no filter
                if lazy:
                    return [(lambda t=t: t.snapshot(concrete))
                            for t in tablets]
                return [t.snapshot(concrete) for t in tablets]
            # Brown-out rung 1 (ISSUE 17): an admitted-degraded token
            # carries the pool's staleness bound; sorted tablets then
            # serve their snapshot cache within the bound instead of
            # paying the MVCC merge, and the token records the max
            # staleness actually served so the response can be tagged.
            bound = getattr(token, "staleness_bound", None)
            if bound:
                def _read_bounded(t, ts):
                    chunk, stale = t.read_snapshot_bounded(ts, bound)
                    if token is not None and \
                            stale > token.stale_served:
                        token.stale_served = stale
                    return chunk
                if lazy:
                    return [(lambda t=t, ts=timestamp:
                             _read_bounded(t, ts)) for t in tablets]
                return [_read_bounded(t, timestamp) for t in tablets]
            if lazy:
                return [(lambda t=t, ts=timestamp: t.read_snapshot(ts))
                        for t in tablets]
            return [t.read_snapshot(timestamp) for t in tablets]
        chunk_ids = node.attributes.get("chunk_ids", [])
        col_stats = node.attributes.get("chunk_stats", [])
        # Range-inference analog: skip chunks whose min/max stats cannot
        # intersect the WHERE-derived intervals.  Stats pair with chunks
        # positionally, so prune ONLY when the lists are in lockstep (tables
        # persisted before stats existed must never be misaligned).
        if intervals and len(col_stats) == len(chunk_ids):
            from ytsaurus_tpu.query.pruning import chunk_may_match
            kept = [cid for cid, chunk_stats in zip(chunk_ids, col_stats)
                    if chunk_may_match(chunk_stats, intervals)]
            if stats is not None:
                stats.shards_pruned += len(chunk_ids) - len(kept)
            chunk_ids = kept
        if not chunk_ids:
            schema = self._node_schema(node)
            if schema is None:
                raise YtError(f"Empty table {path!r} has no schema",
                              code=EErrorCode.NoSuchNode)
            return [ColumnarChunk.from_rows(schema.to_unsorted(), [])]
        if lazy:
            return [(lambda cid=cid: self.cluster.chunk_cache.get(cid))
                    for cid in chunk_ids]
        return [self.cluster.chunk_cache.get(cid) for cid in chunk_ids]


class _SchemaResolver(dict):
    """Lazy table-path → schema mapping for the query builder.

    Schemas are presented unsorted: query shards are snapshot/decoded chunks
    whose schemas carry no sort annotations."""

    def __init__(self, client: YtClient):
        super().__init__()
        self.client = client

    def __contains__(self, path) -> bool:
        return self.client.exists(path)

    def __getitem__(self, path) -> TableSchema:
        node = self.client._table_node(path)
        schema = self.client._node_schema(node)
        if schema is None:
            raise YtError(f"Table {path!r} has no schema",
                          code=EErrorCode.QueryTypeError)
        if node.attributes.get("dynamic") and not schema.is_sorted:
            # Ordered tables expose $row_index/$timestamp system columns.
            from ytsaurus_tpu.tablet.ordered import ordered_chunk_schema
            return ordered_chunk_schema(schema).to_unsorted()
        return schema.to_unsorted()


def infer_schema(rows: Sequence[dict]) -> TableSchema:
    """Infer a schema from row dicts (write_table without explicit schema)."""
    if not rows:
        raise YtError("Cannot infer a schema from zero rows")
    types: dict[str, EValueType] = {}
    order: list[str] = []
    for row in rows:
        for name, value in row.items():
            if name not in types:
                order.append(name)
                types[name] = _value_type(value)
            else:
                current = types[name]
                observed = _value_type(value)
                if current is EValueType.null:
                    types[name] = observed
                elif observed is not EValueType.null and observed != current:
                    if {observed, current} <= {EValueType.int64,
                                               EValueType.double}:
                        types[name] = EValueType.double
                    else:
                        types[name] = EValueType.any
    return TableSchema.make(
        [(name, (types[name] if types[name] is not EValueType.null
                 else EValueType.int64).value) for name in order])


def _value_type(value) -> EValueType:
    if value is None:
        return EValueType.null
    if isinstance(value, bool):
        return EValueType.boolean
    if isinstance(value, int):
        return EValueType.int64 if -(2**63) <= value < 2**63 \
            else EValueType.uint64
    if isinstance(value, float):
        return EValueType.double
    if isinstance(value, (str, bytes)):
        return EValueType.string
    return EValueType.any


_cluster_registry: dict = {}
_cluster_registry_lock = threading.Lock()


def connect(root_dir: str, fresh: bool = False) -> YtClient:
    """Open (or create) a local cluster rooted at `root_dir`.

    One YtCluster instance per root per process: two clients connecting to
    the same root share cluster state, exactly like two clients of the same
    daemons (and two master instances must not double-write one WAL).
    fresh=True drops the cached instance and re-opens from disk — the
    restart/recovery path for tests exercising WAL replay."""
    key = os.path.realpath(root_dir)
    with _cluster_registry_lock:
        cluster = _cluster_registry.get(key)
        if cluster is None or fresh:
            cluster = YtCluster(root_dir)
            _cluster_registry[key] = cluster
    return YtClient(cluster)
