"""HTTP proxy (/api/v4) + `yt` CLI over a real multi-process cluster."""

import io
import json
import sys
import urllib.error
import urllib.request

import pytest

import jax

jax.config.update("jax_platforms", "cpu")

from ytsaurus_tpu.environment import LocalCluster  # noqa: E402


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    root = str(tmp_path_factory.mktemp("proxy_cluster"))
    with LocalCluster(root, n_nodes=1, replication_factor=1,
                      http_proxy=True) as lc:
        yield lc


def _url(cluster, path):
    return f"http://{cluster.http_proxy_address}{path}"


def _post(cluster, command, params, user="root"):
    req = urllib.request.Request(
        _url(cluster, f"/api/v4/{command}"),
        data=json.dumps(params).encode(),
        headers={"Content-Type": "application/json", "X-YT-User": user},
        method="POST")
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def test_ping_and_discovery(cluster):
    assert urllib.request.urlopen(_url(cluster, "/ping")).status == 200
    commands = json.loads(
        urllib.request.urlopen(_url(cluster, "/api/v4")).read())
    assert "select_rows" in commands and "write_table" in commands
    hosts = json.loads(
        urllib.request.urlopen(_url(cluster, "/hosts")).read())
    assert hosts == [cluster.http_proxy_address]


def test_rest_cypress_roundtrip(cluster):
    _post(cluster, "create", {"type": "map_node", "path": "//rest",
                              "recursive": True})
    _post(cluster, "set", {"path": "//rest/@tag", "value": 42})
    got = json.loads(urllib.request.urlopen(
        _url(cluster, "/api/v4/get?path=%22//rest/@tag%22")).read())
    assert got["value"] == 42
    got = _post(cluster, "exists", {"path": "//rest"})
    assert got["value"] is True


def test_rest_table_write_read_select(cluster):
    _post(cluster, "create", {"type": "table", "path": "//rest/t",
                              "attributes": {"schema": [
                                  {"name": "k", "type": "int64",
                                   "sort_order": "ascending"},
                                  {"name": "v", "type": "int64"}]}})
    rows = "".join(json.dumps({"k": i, "v": i * i}) + "\n"
                   for i in range(50))
    req = urllib.request.Request(
        _url(cluster, "/api/v4/write_table"),
        data=rows.encode(),
        headers={"X-YT-Parameters": json.dumps({"path": "//rest/t",
                                                "format": "json"})},
        method="PUT")
    urllib.request.urlopen(req)

    blob = urllib.request.urlopen(_url(
        cluster, '/api/v4/read_table?path="//rest/t"&format="json"')).read()
    back = [json.loads(line) for line in blob.splitlines() if line.strip()]
    assert len(back) == 50 and back[7] == {"k": 7, "v": 49}

    result = _post(cluster, "select_rows",
                   {"query": "sum(v) AS s FROM [//rest/t] GROUP BY 1"})
    assert result["value"][0]["s"] == sum(i * i for i in range(50))


def test_rest_trace_header_and_explain_analyze(cluster):
    """ISSUE 5: X-YT-Trace-Id pins (and force-samples) the query trace;
    the id is echoed on the response, and explain_analyze returns the
    ExecutionProfile dict with the compile/execute split + span tree."""
    _post(cluster, "create", {"type": "table", "path": "//rest/tr",
                              "recursive": True,
                              "attributes": {"schema": [
                                  {"name": "k", "type": "int64",
                                   "sort_order": "ascending"},
                                  {"name": "v", "type": "int64"}]}})
    rows = "".join(json.dumps({"k": i, "v": i}) + "\n" for i in range(20))
    req = urllib.request.Request(
        _url(cluster, "/api/v4/write_table"),
        data=rows.encode(),
        headers={"X-YT-Parameters": json.dumps({"path": "//rest/tr",
                                                "format": "json"})},
        method="PUT")
    urllib.request.urlopen(req)

    trace_id = "ab" * 16
    req = urllib.request.Request(
        _url(cluster, "/api/v4/select_rows"),
        data=json.dumps({"query": "sum(v) AS s FROM [//rest/tr] GROUP BY 1",
                         "explain_analyze": True}).encode(),
        headers={"Content-Type": "application/json", "X-YT-User": "root",
                 "X-YT-Trace-Id": trace_id},
        method="POST")
    with urllib.request.urlopen(req) as resp:
        assert resp.headers.get("X-YT-Trace-Id") == trace_id
        profile = json.loads(resp.read())["value"]
    assert profile["trace_id"] == trace_id
    assert profile["wall_time"] > 0
    assert "compile_time" in profile and "execute_time" in profile
    names = set()

    def walk(nodes):
        for node in nodes:
            names.add(node["name"])
            walk(node.get("children") or [])

    walk(profile["span_tree"])
    assert "query.select" in names
    assert profile["statistics"]["rows_read"] == 20


def test_rest_error_shape(cluster):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(cluster, "get", {"path": "//no/such/node"})
    body = json.loads(ei.value.read())
    assert body["code"] != 0 and "message" in body
    assert ei.value.headers.get("X-YT-Error")
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(cluster, "frobnicate", {})
    assert ei.value.status == 404


def test_rest_authenticated_user(cluster):
    _post(cluster, "create_user", {"name": "restuser"})
    _post(cluster, "create", {"type": "map_node", "path": "//restsec"})
    _post(cluster, "set", {"path": "//restsec/@acl", "value": [
        {"action": "allow", "subjects": ["restuser"],
         "permissions": ["read", "write"]}]})
    _post(cluster, "set", {"path": "//restsec/@ok", "value": 1},
          user="restuser")
    # A second user without the ACE is denied.
    _post(cluster, "create_user", {"name": "outsider"})
    with pytest.raises(urllib.error.HTTPError):
        _post(cluster, "set", {"path": "//restsec/@nope", "value": 2},
              user="outsider")


# -- CLI -----------------------------------------------------------------------

def _yt(cluster, *argv, stdin: bytes = b""):
    from ytsaurus_tpu import cli
    old_stdout, old_stdin = sys.stdout, sys.stdin
    sys.stdout = io.TextIOWrapper(io.BytesIO(), encoding="utf-8")
    sys.stdin = io.TextIOWrapper(io.BytesIO(stdin), encoding="utf-8")
    try:
        rc = cli.run(["--proxy", cluster.primary_address, *argv])
        sys.stdout.flush()
        out = sys.stdout.buffer.getvalue()
    finally:
        sys.stdout, sys.stdin = old_stdout, old_stdin
    return rc, out


def test_cli_end_to_end(cluster):
    rc, _ = _yt(cluster, "create", "map_node", "//cli", "-r")
    assert rc == 0
    rc, _ = _yt(cluster, "write-table", "//cli/t",
                stdin=b'{"k": 1, "v": 10}\n{"k": 2, "v": 20}\n')
    assert rc == 0
    rc, out = _yt(cluster, "read-table", "//cli/t", "--format", "json")
    rows = [json.loads(l) for l in out.splitlines() if l.strip()]
    assert rows == [{"k": 1, "v": 10}, {"k": 2, "v": 20}]
    rc, out = _yt(cluster, "select-rows",
                  "sum(v) AS s FROM [//cli/t] GROUP BY 1")
    assert rc == 0 and json.loads(out)[0]["s"] == 30
    rc, out = _yt(cluster, "list", "/")
    assert rc == 0 and "cli" in json.loads(out)
    rc, out = _yt(cluster, "map", "cat", "--src", "//cli/t",
                  "--dst", "//cli/out")
    assert rc == 0 and json.loads(out)["state"] == "completed"
    rc, out = _yt(cluster, "exists", "//cli/out")
    assert rc == 0 and json.loads(out) is True
    rc, out = _yt(cluster, "sort", "--src", "//cli/t",
                  "--dst", "//cli/sorted", "--sort-by", "k")
    assert rc == 0 and json.loads(out)["state"] == "completed"
    rc, out = _yt(cluster, "reduce", "cat", "--src", "//cli/sorted",
                  "--dst", "//cli/red", "--reduce-by", "k")
    assert rc == 0 and json.loads(out)["state"] == "completed"
    rc, out = _yt(cluster, "map-reduce", "cat", "--src", "//cli/t",
                  "--dst", "//cli/mr", "--reduce-by", "k")
    assert rc == 0 and json.loads(out)["state"] == "completed"
    rc, out = _yt(cluster, "read-table", "//cli/mr", "--format", "json")
    rows = [json.loads(l) for l in out.splitlines() if l.strip()]
    assert sorted(r["k"] for r in rows) == [1, 2]
    rc, out = _yt(cluster, "vanilla", "--tasks",
                  '{"t": {"job_count": 2, "command": "true"}}')
    assert rc == 0 and json.loads(out)["state"] == "completed"
    # Errors come back as rc=1 with a structured error on stderr.
    rc, _ = _yt(cluster, "get", "//definitely/missing")
    assert rc == 1
