from ytsaurus_tpu.chunks.columnar import (
    Column,
    ColumnarChunk,
    concat_chunks,
    pad_capacity,
    unify_dictionaries,
)
