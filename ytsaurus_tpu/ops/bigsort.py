"""External (spill-capable) sort: HBM-budgeted range partition + per-range
device sorts.

Ref mapping: the Sort controller's partition tree
(controller_agent/controllers/sort_controller.cpp:459 — multi-level
partitioning sized so every final partition fits one sort job's memory),
samples_fetcher.h key sampling, and partition_job.cpp row routing.

TPU-first redesign: the reference builds a tree of partition JOBS writing
partition chunks through the data plane.  Here the whole pipeline runs on
one host+device pair (the multi-chip path is parallel/shuffle.sort_table):

  pass 1  sample keys from every input block (host, cheap)
  pass 2  per block: upload → device computes each row's range id against
          the pivots (lexicographic, null-aware) → device stable-permutes
          the block so ranges are contiguous → ONE download → host slices
          append to per-range spill buffers (host RAM is the spill tier)
  pass 3  per range: upload (≤ HBM budget by construction) → device
          lexsort → yield a sorted ColumnarChunk

Skewed data re-splits: a range that outgrew the budget is recursively
re-partitioned with fresh pivots from its own keys (the reference's
multi-level partition tree, depth-bounded).

Streams of sorted range chunks concatenate into the globally sorted
output; callers keep them as separate output chunks (the chunk store is
the natural unit) rather than materializing one giant table.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterator, Sequence

import jax.numpy as jnp
import numpy as np

from ytsaurus_tpu.chunks.columnar import Column, ColumnarChunk, pad_capacity
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.ops.segments import packed_sort_indices
from ytsaurus_tpu.parallel.shuffle import (
    _encode_key_plane,
    _partition_ids,
    quantile_pivots,
)
from ytsaurus_tpu.schema import SortOrder, TableSchema

DEFAULT_HBM_BUDGET = 8 << 30        # bytes of device memory a range may use
_MAX_SPLIT_DEPTH = 4                # partition-tree depth bound
_SAMPLES_PER_BLOCK = 512


@dataclass
class SpillStats:
    """Observability + test assertions for the external sort."""

    blocks: int = 0
    ranges: int = 0
    resplits: int = 0
    peak_range_rows: int = 0
    budget_rows: int = 0
    spilled_rows: int = 0
    range_rows: list = field(default_factory=list)


def _row_bytes(schema: TableSchema) -> int:
    # Device planes are 8-byte data + 1-byte valid per column.
    return sum(9 for _ in schema)


def _check_numeric_keys(schema: TableSchema, key_names: Sequence[str]):
    for name in key_names:
        if name not in schema:
            raise YtError(f"No such sort column {name!r}",
                          code=EErrorCode.QueryTypeError)


def _host_planes(chunk: ColumnarChunk) -> dict:
    """Download a chunk's planes once: name → (data, valid) numpy arrays
    trimmed to live rows."""
    n = chunk.row_count
    out = {}
    for name, col in chunk.columns.items():
        if col.dictionary is not None or col.host_values is not None:
            raise YtError(
                f"external sort supports numeric columns only; {name!r} "
                f"is string/any (route those through the mesh shuffle "
                f"path or sort_chunks)", code=EErrorCode.QueryUnsupported)
        # analyze: allow(host-sync): external sort merges on host — one materialization per block at ingest
        out[name] = (np.asarray(col.data[:n]), np.asarray(col.valid[:n]))
    return out


def _sample_keys(planes: dict, key_names: Sequence[str],
                 k: int) -> list[tuple]:
    """Evenly-spaced (valid, value) key tuples from one block's planes."""
    n = len(planes[key_names[0]][0])
    if n == 0:
        return []
    idx = np.linspace(0, n - 1, min(k, n), dtype=np.int64)
    rows = []
    for i in idx:
        rows.append(tuple(
            # analyze: allow(host-sync): planes are host numpy here (materialized at ingest); .item() is a scalar read
            (bool(planes[name][1][i]), planes[name][0][i].item())
            for name in key_names))
    return rows


def _partition_block(planes: dict, key_names: Sequence[str],
                     pivots: list[tuple], n_ranges: int,
                     descending: bool) -> list[dict]:
    """Device pass: route one host block into per-range host buffers.

    Upload → range ids vs pivots → stable permute (device gather) →
    single download → host slicing.  Returns per-range {name: (data,
    valid)} numpy planes."""
    n = len(planes[key_names[0]][0])
    if n == 0:
        return [dict() for _ in range(n_ranges)]
    cap = pad_capacity(n)
    dev = {}
    for name, (data, valid) in planes.items():
        d = jnp.zeros(cap, dtype=jnp.asarray(data).dtype).at[:n].set(
            jnp.asarray(data))
        v = jnp.zeros(cap, dtype=bool).at[:n].set(jnp.asarray(valid))
        dev[name] = (d, v)
    live = jnp.arange(cap) < n

    pivot_planes = []
    for ki, name in enumerate(key_names):
        vals = np.array([p[ki][1] for p in pivots])
        ranks = np.array([1 if p[ki][0] else 0 for p in pivots],
                         dtype=np.int8)
        pivot_planes.append(
            (jnp.asarray(ranks),
             # analyze: allow(host-sync): dtype probe of a host numpy plane, no transfer
             jnp.asarray(vals.astype(np.asarray(planes[name][0]).dtype))))
    row_planes = [_encode_key_plane(dev[name][0], dev[name][1])
                  for name in key_names]
    pid = _partition_ids(row_planes, pivot_planes, n_ranges - 1)
    if descending:
        pid = (n_ranges - 1) - pid
    pid = jnp.where(live, pid, n_ranges)        # padding → tail
    order = jnp.argsort(pid, stable=True)
    # analyze: allow(host-sync): range boundaries are a host decision — one counts transfer per block
    counts = np.asarray(
        jnp.bincount(pid, length=n_ranges + 1))[:n_ranges]
    out: list[dict] = []
    starts = np.concatenate([[0], np.cumsum(counts)])
    # analyze: allow(host-sync): partitioned blocks spill to host files — the materialization IS the operation
    permuted = {name: (np.asarray(d[order]), np.asarray(v[order]))
                for name, (d, v) in dev.items()}
    for r in range(n_ranges):
        lo, hi = int(starts[r]), int(starts[r + 1])
        out.append({name: (d[lo:hi].copy(), v[lo:hi].copy())
                    for name, (d, v) in permuted.items()})
    return out


def _concat_range(buffers: list[dict], names: Sequence[str]) -> dict:
    out = {}
    for name in names:
        datas = [b[name][0] for b in buffers if b and len(b[name][0])]
        valids = [b[name][1] for b in buffers if b and len(b[name][0])]
        if datas:
            out[name] = (np.concatenate(datas), np.concatenate(valids))
        else:
            out[name] = (np.zeros(0, dtype=np.int64),
                         np.zeros(0, dtype=bool))
    return out


def _sort_range_planes(planes: dict, schema: TableSchema,
                       key_names: Sequence[str],
                       descending: bool) -> ColumnarChunk:
    """Per-range device lexsort of host planes → sorted ColumnarChunk."""
    n = len(planes[key_names[0]][0])
    cap = pad_capacity(max(n, 1))
    dev = {}
    for name, (data, valid) in planes.items():
        d = jnp.zeros(cap, dtype=jnp.asarray(data).dtype)
        if n:
            d = d.at[:n].set(jnp.asarray(data))
        v = jnp.zeros(cap, dtype=bool)
        if n:
            v = v.at[:n].set(jnp.asarray(valid))
        dev[name] = (d, v)
    live = jnp.arange(cap) < n
    items = [((~live), jnp.ones_like(live), False, 1)]
    for name in key_names:
        d, v = dev[name]
        items.append((d, v & live, descending, 64))
    order = packed_sort_indices(items)
    columns = {}
    for col_schema in schema:
        d, v = dev[col_schema.name]
        columns[col_schema.name] = Column(
            type=col_schema.type, data=d[order], valid=v[order])
    from ytsaurus_tpu.operations.sort_op import _with_key_order
    out_schema = _with_key_order(
        schema, list(key_names),
        SortOrder.descending if descending else SortOrder.ascending)
    return ColumnarChunk(schema=out_schema, row_count=n, columns=columns)


def external_sort(blocks: "Sequence[ColumnarChunk | Callable[[], ColumnarChunk]]",
                  key_columns: Sequence[str],
                  budget_bytes: int = DEFAULT_HBM_BUDGET,
                  descending: bool = False,
                  stats: "SpillStats | None" = None,
                  _depth: int = 0) -> Iterator[ColumnarChunk]:
    """Sort arbitrarily large input through bounded device memory.

    `blocks`: input chunks, or zero-arg callables producing them (so
    callers stream from the chunk store without holding every block).
    Yields sorted chunks whose concatenation is the globally sorted
    table; each yielded chunk's device footprint stays under
    `budget_bytes`."""
    key_names = list(key_columns)
    suppliers = [b if callable(b) else (lambda c=b: c) for b in blocks]
    if not suppliers:
        return

    # Pass 1: sample + size.  Blocks are materialized one at a time; the
    # host planes spill buffer is the only O(total) memory.
    first = suppliers[0]()
    schema = first.schema
    _check_numeric_keys(schema, key_names)
    row_bytes = _row_bytes(schema)
    budget_rows = max(budget_bytes // (row_bytes * 2), 1)   # 2x: sort scratch
    if stats is not None:
        stats.budget_rows = int(budget_rows)

    host_blocks: list[dict] = []
    samples: list[tuple] = []
    total_rows = 0
    current: "ColumnarChunk | None" = first
    for i, supplier in enumerate(suppliers):
        chunk = current if i == 0 else supplier()
        current = None
        planes = _host_planes(chunk)
        host_blocks.append(planes)
        samples.extend(_sample_keys(planes, key_names, _SAMPLES_PER_BLOCK))
        total_rows += chunk.row_count
        if stats is not None:
            stats.blocks += 1
            stats.spilled_rows += chunk.row_count

    if total_rows <= budget_rows:
        # HBM-resident: one device sort, no partition pass.
        merged = _concat_range(host_blocks, [c.name for c in schema])
        if stats is not None:
            stats.ranges += 1
            stats.range_rows.append(total_rows)
            stats.peak_range_rows = max(stats.peak_range_rows, total_rows)
        yield _sort_range_planes(merged, schema, key_names, descending)
        return

    n_ranges = int(min(max(-(-total_rows // budget_rows) * 2, 2), 512))
    pivots = quantile_pivots(samples, n_ranges, len(key_names))

    # Pass 2: device-route every block into per-range spill buffers,
    # releasing each unrouted block as it's consumed (host RAM stays at
    # ~1x the data plus one in-flight block).
    range_buffers: list[list[dict]] = [[] for _ in range(n_ranges)]
    for i in range(len(host_blocks)):
        routed = _partition_block(host_blocks[i], key_names, pivots,
                                  n_ranges, descending)
        host_blocks[i] = None
        for r, part in enumerate(routed):
            if part and len(next(iter(part.values()))[0]):
                range_buffers[r].append(part)
    del host_blocks

    # Pass 3: per-range device sort, in range order.
    names = [c.name for c in schema]
    for r in range(n_ranges):
        merged = _concat_range(range_buffers[r], names)
        range_buffers[r] = []            # release spill as we go
        n = len(merged[key_names[0]][0])
        if n == 0:
            continue
        if n > budget_rows and _depth < _MAX_SPLIT_DEPTH:
            # Skew: this range outgrew the budget — re-split it with
            # pivots from its OWN keys (multi-level partition tree).
            if stats is not None:
                stats.resplits += 1
            sub = ColumnarChunk(
                schema=schema, row_count=n,
                columns={name: Column(type=schema.get(name).type,
                                      data=jnp.asarray(merged[name][0]),
                                      valid=jnp.asarray(merged[name][1]))
                         for name in names})
            yield from external_sort(
                [sub], key_names, budget_bytes=budget_bytes,
                descending=descending, stats=stats, _depth=_depth + 1)
            continue
        if stats is not None:
            stats.ranges += 1
            stats.range_rows.append(n)
            stats.peak_range_rows = max(stats.peak_range_rows, n)
        yield _sort_range_planes(merged, schema, key_names, descending)
