"""Leader election + automatic master failover.

Unit level: lease grant/renew/fence rules on the journal plane and the
elector's takeover/step-down decisions (fake channels).  Process level:
a 2-master cluster survives a leader kill mid-write-load with no
acknowledged-write loss (ref: Hydra elections + lease_tracker,
yt/yt/server/lib/election/).
"""

import threading
import time

import pytest

from tests.test_quorum_wal import FakeJournalChannel
from ytsaurus_tpu.cypress.election import LeaderElector
from ytsaurus_tpu.cypress.quorum import QuorumWal
from ytsaurus_tpu.errors import YtError


class FakeLeaseChannel(FakeJournalChannel):
    """Adds the lease surface of DataNodeService."""

    def __init__(self):
        super().__init__()
        self.lease = ("", 0.0)

    def call(self, service, method, body=None, attachments=(), **kw):
        if self.down:
            raise YtError("down", code=2)
        if method == "journal_lease":
            holder, expiry = self.lease
            return {"writer": holder, "epoch": self.epoch,
                    "remaining": max(expiry - time.monotonic(), 0.0)}, []
        if method == "journal_lease_renew":
            if body["epoch"] < self.epoch or (
                    body["epoch"] == self.epoch and self.writer and
                    body["writer"] != self.writer):
                return {"granted": False, "epoch": self.epoch}, []
            self.lease = (body["writer"],
                          time.monotonic() + body["ttl"])
            return {"granted": True}, []
        if method == "journal_acquire":
            holder, expiry = self.lease
            if holder and holder != body.get("writer") and \
                    time.monotonic() < expiry:
                return {"granted": False, "epoch": self.epoch,
                        "lease_holder": holder}, []
            out = super().call(service, method, body, attachments, **kw)
            if out[0].get("granted") and body.get("lease_ttl"):
                self.lease = (body.get("writer"),
                              time.monotonic() + body["lease_ttl"])
            return out
        return super().call(service, method, body, attachments, **kw)


def test_acquire_grants_lease_and_blocks_disruption(tmp_path):
    remotes = [FakeLeaseChannel(), FakeLeaseChannel(), FakeLeaseChannel()]
    leader = QuorumWal(str(tmp_path / "a.log"), "j", remotes, quorum=2,
                       count_local_ack=False, bootstrap_from_local=True,
                       lease_ttl=5.0)
    leader.recover()
    # Lease landed with the acquisition on every remote.
    assert all(r.lease[0] == leader.writer_id for r in remotes)
    # A flapping standby cannot fence the healthy leader: acquisition is
    # refused while the lease stands.
    standby = QuorumWal(str(tmp_path / "b.log"), "j", remotes, quorum=2,
                        count_local_ack=False, lease_ttl=5.0)
    with pytest.raises(YtError):
        standby.recover()
    leader.append({"op": "set", "args": {"n": 1}})   # still the writer


def test_elector_waits_for_foreign_lease_expiry(tmp_path):
    remotes = [FakeLeaseChannel(), FakeLeaseChannel(), FakeLeaseChannel()]
    for r in remotes:
        r.lease = ("other-writer", time.monotonic() + 0.8)
    elector = LeaderElector("j", remotes, "me", lease_ttl=1.0,
                            poll_interval=0.1)
    t0 = time.monotonic()
    assert elector.wait_until_electable(timeout=10.0)
    assert time.monotonic() - t0 >= 0.7      # waited out the lease
    elector.stop()


def test_elector_step_down_when_fenced():
    remotes = [FakeLeaseChannel(), FakeLeaseChannel(), FakeLeaseChannel()]
    for r in remotes:
        r.epoch, r.writer = 1, "me"
        r.lease = ("me", time.monotonic() + 5.0)
    lost = threading.Event()
    elector = LeaderElector("j", remotes, "me", lease_ttl=0.9)
    elector.start_renewing(1, lost.set)
    time.sleep(0.4)
    assert not lost.is_set()                 # healthy renewal
    # A new writer fences the epoch on every location.
    for r in remotes:
        r.epoch, r.writer = 2, "usurper"
    assert lost.wait(timeout=5.0)            # step-down fires
    elector.stop()


@pytest.mark.slow
def test_leader_failover_no_acked_write_loss(tmp_path):
    """VERDICT r2 #3 done-criterion: kill the leader mid-write-load;
    the standby takes over and every ACKNOWLEDGED write survives.

    slow: ~50s of sequential fsync'd writes through a real 2-master
    failover — the single largest tier-1 wall-clock item; the quick pass
    keeps election coverage via the other tests here + the clock-quorum
    failover tests, and the full (slow-inclusive) pass still runs it."""
    from ytsaurus_tpu.environment import LocalCluster
    from ytsaurus_tpu.remote_client import connect_remote

    with LocalCluster(str(tmp_path / "c"), n_nodes=3, n_masters=2,
                      lease_ttl=3.0) as cluster:
        client = connect_remote(cluster.master_addresses)
        client.create("map_node", "//home/f", recursive=True)
        acked: list[int] = []
        failed: list[int] = []
        done = threading.Event()

        def writer():
            for i in range(400):
                try:
                    client.create("document", f"//home/f/d{i}")
                    acked.append(i)
                except YtError:
                    failed.append(i)   # in-flight during failover: fine
                if done.is_set():
                    return

        thread = threading.Thread(target=writer)
        thread.start()
        try:
            # Let some writes land, then kill the leader.
            deadline = time.monotonic() + 30
            while len(acked) < 20 and time.monotonic() < deadline:
                time.sleep(0.1)
            assert len(acked) >= 20
            killed = cluster.kill_leader()
            # Writes continue through the failover window.
            thread.join(timeout=180)
            assert not thread.is_alive()
        finally:
            done.set()
            thread.join(timeout=30)
        new_leader = cluster.leader_index(timeout=60)
        assert new_leader != killed
        # Failover actually made progress: writes landed after the kill.
        assert len(acked) >= 50
        names = set(client.list("//home/f"))
        missing = [i for i in acked if f"d{i}" not in names]
        assert not missing, f"acked writes lost: {missing[:10]}"
