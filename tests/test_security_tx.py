"""Master transactions/locks + security (users, ACLs, accounts, quotas)."""

import os

import pytest

from ytsaurus_tpu.client import YtClient, YtCluster
from ytsaurus_tpu.cypress.master import Master
from ytsaurus_tpu.cypress.security import authenticated_user
from ytsaurus_tpu.errors import EErrorCode, YtError


@pytest.fixture
def client(tmp_path):
    return YtClient(YtCluster(str(tmp_path / "cluster")))


# -- master transactions -------------------------------------------------------

def test_tx_commit_keeps_changes(client):
    tx = client.start_tx()
    client.create("map_node", "//home", tx=tx)
    client.set("//home/@flag", 1, tx=tx)
    client.commit_tx(tx)
    assert client.get("//home/@flag") == 1


def test_tx_abort_rolls_back_create_and_set(client):
    client.create("map_node", "//home")
    client.set("//home/@color", "blue")
    tx = client.start_tx()
    client.create("map_node", "//home/sub", tx=tx)
    client.set("//home/@color", "red", tx=tx)
    client.abort_tx(tx)
    assert not client.exists("//home/sub")
    assert client.get("//home/@color") == "blue"


def test_tx_abort_restores_removed_subtree(client):
    client.create("map_node", "//a/b", recursive=True)
    client.set("//a/b/@x", 42)
    tx = client.start_tx()
    client.remove("//a", tx=tx)
    assert not client.exists("//a")
    client.abort_tx(tx)
    assert client.get("//a/b/@x") == 42


def test_exclusive_lock_blocks_other_writers(client):
    client.create("map_node", "//locked")
    tx = client.start_tx()
    client.lock("//locked", mode="exclusive", tx=tx)
    with pytest.raises(YtError) as ei:
        client.set("//locked/@x", 1)            # non-tx writer
    assert ei.value.code == EErrorCode.ConcurrentTransactionLockConflict
    other = client.start_tx()
    with pytest.raises(YtError):
        client.set("//locked/@x", 1, tx=other)   # other tx
    # Subtree containment: creating UNDER the locked node also conflicts.
    with pytest.raises(YtError):
        client.create("map_node", "//locked/child", tx=other)
    client.commit_tx(tx)
    client.set("//locked/@x", 1)                 # free after commit


def test_shared_locks_coexist_but_block_exclusive(client):
    client.create("map_node", "//shared")
    tx1, tx2 = client.start_tx(), client.start_tx()
    client.lock("//shared", mode="shared", tx=tx1)
    client.lock("//shared", mode="shared", tx=tx2)   # ok
    tx3 = client.start_tx()
    with pytest.raises(YtError):
        client.lock("//shared", mode="exclusive", tx=tx3)


def test_snapshot_lock_pins_reads(client):
    client.create("map_node", "//snap")
    client.set("//snap/@v", 1)
    tx = client.start_tx()
    client.lock("//snap", mode="snapshot", tx=tx)
    client.set("//snap/@v", 2)                   # outside writer proceeds
    assert client.get("//snap/@v") == 2
    assert client.get("//snap/@v", tx=tx) == 1   # pinned view


def test_nested_tx_commit_into_parent_then_abort(client):
    client.create("map_node", "//n")
    parent = client.start_tx()
    child = client.start_tx(parent=parent)
    client.set("//n/@x", 10, tx=child)
    client.commit_tx(child)
    assert client.get("//n/@x") == 10
    client.abort_tx(parent)                      # parent abort undoes child
    assert not client.exists("//n/@x")


def test_implicit_locks_conflict_between_txs(client):
    client.create("map_node", "//w")
    tx1 = client.start_tx()
    client.set("//w/@a", 1, tx=tx1)              # implicit exclusive lock
    tx2 = client.start_tx()
    with pytest.raises(YtError):
        client.set("//w/@b", 2, tx=tx2)


def test_tx_state_survives_restart(tmp_path):
    root = str(tmp_path / "cluster")
    client = YtClient(YtCluster(root))
    client.create("map_node", "//persist")
    client.set("//persist/@v", "old")
    tx = client.start_tx()
    client.set("//persist/@v", "dirty", tx=tx)
    client.cluster.master.build_snapshot()       # undo must be IN snapshot

    reopened = YtClient(YtCluster(root))
    assert reopened.get("//persist/@v") == "dirty"
    reopened.abort_tx(tx)                        # rollback after restart
    assert reopened.get("//persist/@v") == "old"


def test_tx_survives_restart_via_wal_replay_alone(tmp_path):
    """No snapshot: recovery must REPLAY tx_start with its original id, or
    every later tx-scoped record orphans (regression: ids were generated at
    apply time, so replay minted fresh ones)."""
    root = str(tmp_path / "cluster")
    client = YtClient(YtCluster(root))
    client.create("map_node", "//r")
    client.set("//r/@v", "old")
    tx = client.start_tx()
    client.set("//r/@v", "dirty", tx=tx)

    reopened = YtClient(YtCluster(root))
    assert reopened.get("//r/@v") == "dirty"
    assert tx in reopened.cluster.master.tx_manager.transactions
    reopened.abort_tx(tx)
    assert reopened.get("//r/@v") == "old"


# -- security ------------------------------------------------------------------

def test_users_groups_membership(client):
    sec = client.cluster.security
    sec.create_user("alice")
    sec.create_group("devs", members=["alice"])
    assert "devs" in sec.groups_of("alice")
    sec.remove_member("devs", "alice")
    assert "devs" not in sec.groups_of("alice")


def test_acl_allow_and_deny(client):
    sec = client.cluster.security
    sec.create_user("alice")
    sec.create_user("bob")
    client.create("map_node", "//prod")
    client.set("//prod/@acl", [
        {"action": "allow", "subjects": ["alice"],
         "permissions": ["read", "write"]},
    ])
    with authenticated_user("alice"):
        client.set("//prod/@tag", 1)             # allowed
        assert client.get("//prod/@tag") == 1
    with authenticated_user("bob"):
        with pytest.raises(YtError) as ei:
            client.set("//prod/@tag", 2)
        assert ei.value.code == EErrorCode.AuthorizationError


def test_acl_inheritance_and_deny_wins(client):
    sec = client.cluster.security
    sec.create_user("alice")
    client.create("map_node", "//top/mid/leaf", recursive=True)
    client.set("//top/@acl", [
        {"action": "allow", "subjects": ["alice"],
         "permissions": ["write"]}])
    with authenticated_user("alice"):
        client.set("//top/mid/leaf/@x", 1)       # inherited allow
    client.set("//top/mid/@acl", [
        {"action": "deny", "subjects": ["alice"],
         "permissions": ["write"]}])
    with authenticated_user("alice"):
        with pytest.raises(YtError):
            client.set("//top/mid/leaf/@x", 2)   # deny beats allow


def test_group_based_acl(client):
    sec = client.cluster.security
    sec.create_user("carol")
    sec.create_group("admins", members=["carol"])
    client.create("map_node", "//adm")
    client.set("//adm/@acl", [
        {"action": "allow", "subjects": ["admins"],
         "permissions": ["write"]}])
    with authenticated_user("carol"):
        client.set("//adm/@ok", True)


def test_reads_default_open_writes_closed(client):
    sec = client.cluster.security
    sec.create_user("eve")
    client.create("map_node", "//data")
    with authenticated_user("eve"):
        assert client.get("//data") == {}        # default read ok
        with pytest.raises(YtError):
            client.set("//data/@x", 1)           # no write grant


def test_unknown_user_rejected(client):
    with authenticated_user("ghost"):
        with pytest.raises(YtError) as ei:
            client.get("//sys")
    assert ei.value.code == EErrorCode.AuthenticationError


def test_account_quota_node_count(client):
    sec = client.cluster.security
    sec.create_account("small", resource_limits={"node_count": 2})
    client.create("map_node", "//qq")
    client.set("//qq/@account", "small")
    client.create("map_node", "//qq/a")
    client.create("map_node", "//qq/b")
    with pytest.raises(YtError) as ei:
        client.create("map_node", "//qq/c")
    assert ei.value.code == EErrorCode.AccountLimitExceeded
    # Removal frees quota.
    client.remove("//qq/a")
    client.create("map_node", "//qq/c")


def test_account_disk_quota_on_write(client):
    sec = client.cluster.security
    sec.create_account("tiny", resource_limits={"disk_space": 64})
    client.create("map_node", "//t")
    client.set("//t/@account", "tiny")
    with pytest.raises(YtError) as ei:
        client.write_table("//t/big", [{"k": i} for i in range(1000)])
    assert ei.value.code == EErrorCode.AccountLimitExceeded


def test_remote_security_and_tx(tmp_path):
    """Thin-client surface over a real daemon cluster."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from ytsaurus_tpu.environment import LocalCluster
    from ytsaurus_tpu.remote_client import connect_remote

    with LocalCluster(str(tmp_path / "lc"), n_nodes=1) as cluster:
        cl = connect_remote(cluster.primary_address)
        cl.create_user("alice")
        cl.create("map_node", "//secured")
        cl.set("//secured/@acl", [
            {"action": "allow", "subjects": ["alice"],
             "permissions": ["read", "write"]}])
        assert cl.check_permission("alice", "write",
                                   "//secured")["action"] == "allow"

        alice = cl.as_user("alice")
        alice.set("//secured/@note", "hi")
        assert alice.get("//secured/@note") == "hi"

        cl.create_user("bob")
        bob = cl.as_user("bob")
        with pytest.raises(YtError) as ei:
            bob.set("//secured/@note", "nope")
        assert ei.value.code == EErrorCode.AuthorizationError

        # Master tx over the wire.
        tx = cl.start_tx()
        cl.set("//secured/@note", "dirty", tx=tx)
        cl.abort_tx(tx)
        assert cl.get("//secured/@note") == "hi"
        alice.close()
        bob.close()
        cl.close()
