// Demo/CI binary: CRUD + static table + dynamic insert/lookup/select
// round-trip against a live proxy.  Exits non-zero on any mismatch;
// tests/test_go_sdk.py builds and runs it against a LocalCluster.
package main

import (
	"fmt"
	"os"
	"reflect"

	"ytsaurus-tpu/sdk/go/yt"
)

func must(err error) {
	if err != nil {
		fmt.Fprintln(os.Stderr, "FAIL:", err)
		os.Exit(1)
	}
}

func check(cond bool, what string) {
	if !cond {
		fmt.Fprintln(os.Stderr, "FAIL:", what)
		os.Exit(1)
	}
}

func main() {
	if len(os.Args) < 2 {
		fmt.Fprintln(os.Stderr, "usage: demo <proxy host:port>")
		os.Exit(2)
	}
	c := yt.NewClient(os.Args[1])
	must(c.Ping())

	// Cypress CRUD.
	must(c.Create("map_node", "//go/home", &yt.CreateOptions{Recursive: true}))
	must(c.Set("//go/home/@owner", "gopher"))
	var owner string
	must(c.Get("//go/home/@owner", &owner))
	check(owner == "gopher", "attribute round-trip")
	ok, err := c.Exists("//go/home")
	must(err)
	check(ok, "exists after create")
	names, err := c.List("//go")
	must(err)
	check(len(names) == 1 && names[0] == "home", "list children")

	// Static table write/read.
	rows := []map[string]any{
		{"name": "a", "score": 1.5},
		{"name": "b", "score": 2.5},
	}
	must(c.WriteTable("//go/static", rows))
	got, err := c.ReadTable("//go/static")
	must(err)
	check(len(got) == 2 && got[0]["name"] == "a" &&
		got[1]["score"] == 2.5, "static table round-trip")

	// Dynamic table insert/lookup/select.
	schema := []map[string]any{
		{"name": "k", "type": "int64", "sort_order": "ascending"},
		{"name": "v", "type": "string"},
	}
	must(c.Create("table", "//go/dyn", &yt.CreateOptions{
		Recursive:  true,
		Attributes: map[string]any{"schema": schema, "dynamic": true},
	}))
	must(c.MountTable("//go/dyn"))
	must(c.InsertRows("//go/dyn", []map[string]any{
		{"k": 1, "v": "one"}, {"k": 2, "v": "two"}, {"k": 3, "v": "three"},
	}))
	looked, err := c.LookupRows("//go/dyn", [][]any{{2}, {99}})
	must(err)
	check(len(looked) == 2 && looked[0]["v"] == "two" && looked[1] == nil,
		"lookup hit+miss")
	selected, err := c.SelectRows(
		"k, v FROM [//go/dyn] WHERE k >= 2 ORDER BY k LIMIT 10")
	must(err)
	check(reflect.DeepEqual(
		[]any{selected[0]["k"], selected[1]["k"]}, []any{2.0, 3.0}),
		"select ordered rows")
	must(c.DeleteRows("//go/dyn", [][]any{{1}}))
	looked, err = c.LookupRows("//go/dyn", [][]any{{1}})
	must(err)
	check(looked[0] == nil, "delete visible")

	must(c.Remove("//go/static", false))
	ok, err = c.Exists("//go/static")
	must(err)
	check(!ok, "removed")

	fmt.Println("GO-SDK-DEMO PASS")
}
