"""Config system: YsonStruct validation/merge + dynamic config manager."""

import pytest

from ytsaurus_tpu.config import (
    DaemonConfig,
    DynamicConfigManager,
    RpcConfig,
    YsonStruct,
    param,
)
from ytsaurus_tpu.errors import EErrorCode, YtError


class CacheConfig(YsonStruct):
    capacity = param(100, type=int, ge=0)
    codec = param("lz4", type=str, choices={"none", "lz4", "zstd"})


class RootConfig(YsonStruct):
    name = param("x", type=str)
    ratio = param(0.5, type=float, ge=0.0, le=1.0)
    cache = param(type=CacheConfig)

    def postprocess(self):
        if self.name == "forbidden":
            raise YtError("bad name", code=EErrorCode.InvalidConfig)


def test_defaults():
    cfg = RootConfig()
    assert cfg.name == "x" and cfg.ratio == 0.5
    assert cfg.cache.capacity == 100 and cfg.cache.codec == "lz4"


def test_from_dict_nested_and_bytes_keys():
    cfg = RootConfig.from_dict(
        {b"name": b"prod", "cache": {b"capacity": 7, "codec": b"zstd"}})
    assert cfg.name == "prod"
    assert cfg.cache.capacity == 7 and cfg.cache.codec == "zstd"


def test_int_promotes_to_float():
    assert RootConfig.from_dict({"ratio": 1}).ratio == 1.0


@pytest.mark.parametrize("data", [
    {"ratio": 2.0},                       # > le
    {"cache": {"capacity": -1}},          # < ge
    {"cache": {"codec": "gzip"}},         # not in choices
    {"ratio": "half"},                    # wrong type
    {"nope": 1},                          # unrecognized
    {"name": "forbidden"},                # postprocess
])
def test_validation_failures(data):
    with pytest.raises(YtError) as ei:
        RootConfig.from_dict(data)
    assert ei.value.code == EErrorCode.InvalidConfig


def test_error_names_the_path():
    with pytest.raises(YtError, match="cache/capacity"):
        RootConfig.from_dict({"cache": {"capacity": -5}})


def test_explicit_null_resets_to_default():
    cfg = RootConfig.from_dict({"cache": {"capacity": None}})
    assert cfg.cache.capacity == 100
    merged = RootConfig().merge({"ratio": None})
    assert merged.ratio == 0.5


def test_merge_is_recursive_and_nondestructive():
    base = RootConfig()
    merged = base.merge({"cache": {"capacity": 9}})
    assert merged.cache.capacity == 9
    assert merged.cache.codec == "lz4"      # untouched sibling survives
    assert base.cache.capacity == 100        # original untouched


def test_round_trip():
    cfg = RootConfig.from_dict({"name": "a", "cache": {"capacity": 3}})
    assert RootConfig.from_dict(cfg.to_dict()) == cfg


def test_keep_unrecognized():
    class Loose(YsonStruct):
        keep_unrecognized = True
        a = param(1, type=int)

    cfg = Loose.from_dict({"a": 2, "extra": "kept"})
    assert cfg.a == 2 and cfg.unrecognized == {"extra": "kept"}
    assert cfg.to_dict()["extra"] == "kept"


def test_daemon_config_shape():
    cfg = DaemonConfig.from_dict({
        "role": "primary",
        "rpc": {"port": 9013},
        "master": {"journal_nodes": 3},
    })
    assert cfg.rpc.port == 9013
    assert cfg.rpc.max_workers == RpcConfig().max_workers
    assert cfg.master.journal_nodes == 3


def test_dynamic_config_applies_and_keeps_last_good():
    patches = [None]

    manager = DynamicConfigManager(lambda: patches[0], RootConfig(),
                                   period=1000)
    seen = []
    manager.subscribe(lambda cfg: seen.append(cfg.cache.capacity))

    assert not manager.poll_once()           # no patch, no change
    patches[0] = {"cache": {"capacity": 5}}
    assert manager.poll_once()
    assert manager.config.cache.capacity == 5
    assert seen == [5]

    # Same patch again: no re-fire.
    assert not manager.poll_once()

    # Bad patch: rejected, last good config stays, error exported.
    patches[0] = {"cache": {"capacity": -3}}
    assert not manager.poll_once()
    assert manager.config.cache.capacity == 5
    assert manager.last_error is not None \
        and manager.last_error.code == EErrorCode.InvalidConfig

    # Recovery after a bad patch.
    patches[0] = {"cache": {"capacity": 8}}
    assert manager.poll_once()
    assert manager.config.cache.capacity == 8 and manager.last_error is None
