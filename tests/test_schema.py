"""Schema / logical-type tests (ref model: client/table_client/schema.h)."""

import pytest

from ytsaurus_tpu import ColumnSchema, EValueType, SortOrder, TableSchema, YtError


def test_make_and_lookup():
    schema = TableSchema.make([
        ("k", "int64", "ascending"),
        ("v", "double"),
        ("s", "string"),
    ])
    assert schema.column_names == ["k", "v", "s"]
    assert schema.get("k").sort_order is SortOrder.ascending
    assert schema.get("v").type is EValueType.double
    assert schema.is_sorted
    assert schema.key_column_names == ["k"]
    assert "v" in schema and "missing" not in schema


def test_key_prefix_enforced():
    with pytest.raises(YtError):
        TableSchema.make([("a", "int64"), ("k", "int64", "ascending")])


def test_duplicate_column_rejected():
    with pytest.raises(YtError):
        TableSchema.make([("a", "int64"), ("a", "double")])


def test_roundtrip_dict():
    schema = TableSchema.make(
        [("k", "uint64", "descending"), ("v", "boolean")], unique_keys=True)
    d = schema.to_dict()
    back = TableSchema.from_dict(d)
    assert back == schema
    assert back.unique_keys


def test_to_unsorted_and_select():
    schema = TableSchema.make([("k", "int64", "ascending"), ("v", "double")])
    unsorted = schema.to_unsorted()
    assert not unsorted.is_sorted
    sub = schema.select(["v"])
    assert sub.column_names == ["v"]


def test_select_reorder_clears_sort_order():
    schema = TableSchema.make([("k", "int64", "ascending"), ("v", "double")])
    sub = schema.select(["v", "k"])
    assert sub.column_names == ["v", "k"]
    assert not sub.is_sorted
    # prefix-preserving projection keeps the key
    sub2 = schema.select(["k", "v"])
    assert sub2.key_column_names == ["k"]
