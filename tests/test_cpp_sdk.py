"""C++ SDK: build with g++ and drive a live HTTP proxy.

Ref model: yt/cpp/mapreduce — the native C++ client over the proxy
protocol.  The test compiles sdk/cpp and runs the demo binary against a
LocalCluster proxy end to end.
"""

import os
import shutil
import subprocess

import pytest

import jax

jax.config.update("jax_platforms", "cpu")

from ytsaurus_tpu.environment import LocalCluster  # noqa: E402

SDK_DIR = os.path.join(os.path.dirname(__file__), "..", "sdk", "cpp")


@pytest.fixture(scope="module")
def demo_binary(tmp_path_factory):
    if shutil.which("g++") is None:
        pytest.skip("g++ not available")
    build = tmp_path_factory.mktemp("cpp_sdk")
    out = str(build / "demo")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-Wall", "-o", out,
         os.path.join(SDK_DIR, "demo.cpp"),
         os.path.join(SDK_DIR, "yt_client.cpp")],
        check=True, capture_output=True)
    return out


def test_cpp_sdk_end_to_end(demo_binary, tmp_path):
    with LocalCluster(str(tmp_path), n_nodes=1, replication_factor=1,
                      http_proxy=True) as cluster:
        host, port = cluster.http_proxy_address.rsplit(":", 1)
        proc = subprocess.run([demo_binary, host, port],
                              capture_output=True, timeout=120)
        assert proc.returncode == 0, proc.stderr.decode()
        assert proc.stdout.startswith(b"SDK OK")
        # The C++-written data is visible through the Python client too.
        from ytsaurus_tpu.remote_client import connect_remote
        cl = connect_remote(cluster.primary_address)
        assert cl.select_rows(
            "k, v FROM [//from_cpp/t] WHERE k = 1") == [{"k": 1, "v": 10}]
