"""SLO tracking: burn-rate alerting over the metrics-history rings
(ISSUE 6 tentpole, piece 3).

Ref shape: SRE multi-window multi-burn-rate alerting (the policy the
reference's monitoring system implements over Solomon series): an SLO
declares an objective (fraction of good events) and the alert condition
is on the BURN RATE — how fast the error budget is being consumed —
measured over two windows at once.  The fast window (default 5min)
catches a regression within minutes; the slow window (default 1h) keeps
a single blip from paging; the alert fires only when BOTH exceed the
threshold and resolves once the fast window recovers.

SLIs come from the history rings, not from per-request logs: counter
deltas for availability/ratio objectives, histogram bucket deltas for
latency objectives ("99% of selects under 50ms" needs only the bucket
rings).  Declaration lives in `config.TelemetryConfig.slos`; evaluation
runs after every telemetry sample (utils/profiling.TelemetrySampler)
and on demand from the monitoring `/slo` endpoint.
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import deque
from typing import Optional

from ytsaurus_tpu.utils.profiling import MetricsHistory, get_history
from ytsaurus_tpu.utils import sanitizers


class SloTracker:
    """Evaluates every declared SLO over the history rings and keeps the
    active/resolved alert state (bounded).  Thread-safe; one global
    instance per process plus private ones in tests."""

    RESOLVED_CAPACITY = 64

    def __init__(self, config=None,
                 history: Optional[MetricsHistory] = None):
        self._config = config
        self._history = history
        # guards: _active, _resolved, _last_eval
        self._lock = sanitizers.register_lock("slo.SloTracker._lock")
        self._active: dict[str, dict] = {}
        self._resolved: deque = deque(maxlen=self.RESOLVED_CAPACITY)
        self._last_eval: dict[str, dict] = {}

    @property
    def config(self):
        if self._config is not None:
            return self._config
        from ytsaurus_tpu.config import telemetry_config
        return telemetry_config()

    @property
    def history(self) -> MetricsHistory:
        return self._history if self._history is not None \
            else get_history()

    # -- SLI math --------------------------------------------------------------

    def _error_rate(self, slo, window: float,
                    now: Optional[float]) -> tuple[float, float]:
        """(error_rate, total_events) over the trailing window."""
        if slo.kind == "latency":
            delta = self.history.window_delta(slo.sensor, slo.tags,
                                              window, now)
            if delta is None or not isinstance(delta, tuple) \
                    or len(delta) < 4:
                return 0.0, 0.0
            d_count, _d_sum, d_buckets, bounds = delta
            if d_count <= 0 or bounds is None:
                return 0.0, 0.0
            # Good events: buckets whose UPPER bound fits the latency
            # bound (bisect_right: a bound exactly equal is still good).
            bound_s = slo.bound_ms / 1e3
            good_buckets = bisect.bisect_right(list(bounds), bound_s)
            good = sum(d_buckets[:good_buckets])
            return max(d_count - good, 0) / d_count, float(d_count)
        good = self.history.window_delta(slo.good_sensor, slo.tags,
                                         window, now) or 0.0
        bad = self.history.window_delta(slo.bad_sensor, slo.tags,
                                        window, now) or 0.0
        total = good + bad
        if total <= 0:
            return 0.0, 0.0
        return bad / total, float(total)

    def _burn(self, slo, window: float,
              now: Optional[float]) -> tuple[float, float, float]:
        rate, total = self._error_rate(slo, window, now)
        budget = max(1.0 - slo.objective, 1e-9)
        return rate / budget, rate, total

    # -- evaluation ------------------------------------------------------------

    def evaluate(self, now: Optional[float] = None) -> dict:
        """One evaluation pass over every declared SLO; updates alert
        state and returns the full snapshot."""
        wall = time.time() if now is None else now
        slos = dict(self.config.slos or {})
        results: dict[str, dict] = {}
        for name, slo in slos.items():
            burn_fast, rate_fast, n_fast = self._burn(
                slo, slo.fast_window, now)
            burn_slow, rate_slow, n_slow = self._burn(
                slo, slo.slow_window, now)
            firing = burn_fast > slo.burn_threshold and \
                burn_slow > slo.burn_threshold
            results[name] = {
                "slo": name, "kind": slo.kind,
                "objective": slo.objective,
                "burn_threshold": slo.burn_threshold,
                "burn_fast": round(burn_fast, 4),
                "burn_slow": round(burn_slow, 4),
                "error_rate_fast": round(rate_fast, 6),
                "error_rate_slow": round(rate_slow, 6),
                "events_fast": n_fast, "events_slow": n_slow,
                "firing": firing,
            }
        with self._lock:
            self._last_eval = results
            for name, result in results.items():
                active = self._active.get(name)
                if result["firing"]:
                    if active is None:
                        self._active[name] = {**result, "state": "firing",
                                              "since": wall}
                    else:
                        active.update(result)
                elif active is not None and \
                        results[name]["burn_fast"] <= \
                        slos[name].burn_threshold:
                    # Resolve on FAST-window recovery: the slow window
                    # lags by design and must not pin a healed alert.
                    self._active.pop(name)
                    self._resolved.append({**active, **result,
                                           "state": "resolved",
                                           "resolved_at": wall})
            # Drop alerts whose SLO was undeclared (dynamic config).
            for stale in [n for n in self._active if n not in slos]:
                self._active.pop(stale)
        return self.snapshot()

    # -- views -----------------------------------------------------------------

    def active_alerts(self) -> list[dict]:
        with self._lock:
            return [dict(a) for _n, a in sorted(self._active.items())]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "slos": {name: dict(r)
                         for name, r in sorted(self._last_eval.items())},
                "active_alerts": [dict(a) for _n, a in
                                  sorted(self._active.items())],
                "resolved_alerts": [dict(a) for a in self._resolved],
            }


_global_tracker: Optional[SloTracker] = None
# guards: _global_tracker
_tracker_lock = sanitizers.register_lock("slo._tracker_lock",
                                         hot=False)


def get_slo_tracker() -> SloTracker:
    global _global_tracker
    if _global_tracker is None:
        with _tracker_lock:
            if _global_tracker is None:
                _global_tracker = SloTracker()
    return _global_tracker


def configure(cfg) -> None:
    """Rebind the global tracker to a new telemetry config (called by
    config.set_telemetry_config; None restores lazy defaults)."""
    global _global_tracker
    with _tracker_lock:
        _global_tracker = None if cfg is None else SloTracker(cfg)
