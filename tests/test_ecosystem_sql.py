"""CHYT-analog SQL dialect: translation + execution via query tracker.

Ref model: yt/chyt (ClickHouse SQL over YT tables) served through the
query tracker's engine registry (server/query_tracker/chyt_engine.cpp).
"""

import pytest

from ytsaurus_tpu import YtError
from ytsaurus_tpu.client import connect
from ytsaurus_tpu.ecosystem.sql import translate_sql
from ytsaurus_tpu.server.query_tracker import QueryTracker


def test_translate_basics():
    assert translate_sql('SELECT a, b FROM "//t" WHERE a <> 2') == \
        "a, b FROM [//t] WHERE a != 2"
    assert translate_sql("SELECT * FROM `//dir/t` LIMIT 5") == \
        "* FROM [//dir/t] LIMIT 5"
    assert translate_sql("SELECT x FROM t ORDER BY x DESC "
                         "LIMIT 10 OFFSET 20") == \
        "x FROM [//t] ORDER BY x DESC OFFSET 20 LIMIT 10"
    assert translate_sql(
        'SELECT uniq(u) AS c FROM "//t" GROUP BY g;') == \
        "cardinality (u) AS c FROM [//t] GROUP BY g"
    # ANSI double-quoted identifiers outside FROM become bare names.
    assert translate_sql('SELECT "weird name" FROM [//t]') == \
        "weird name FROM [//t]"


def test_sql_execution(tmp_path):
    client = connect(str(tmp_path))
    client.write_table("//sales", [
        {"region": "eu", "amount": 10},
        {"region": "us", "amount": 20},
        {"region": "eu", "amount": 30}])
    qt = QueryTracker(client)
    qid = qt.start_query(
        'SELECT region, sum(amount) AS total FROM "//sales" '
        "GROUP BY region ORDER BY region ASC LIMIT 10",
        engine="chyt", sync=True)
    assert qt.read_query_result(qid) == [
        {"region": b"eu", "total": 40}, {"region": b"us", "total": 20}]
    # Alias engine name.
    qid2 = qt.start_query(
        "SELECT region, count(*) AS n FROM `//sales` GROUP BY region "
        "ORDER BY region ASC LIMIT 5", engine="sql", sync=True)
    assert qt.read_query_result(qid2) == [
        {"region": b"eu", "n": 2}, {"region": b"us", "n": 1}]


def test_sql_join(tmp_path):
    client = connect(str(tmp_path))
    client.write_table("//facts", [{"k": 1, "g": 0}, {"k": 2, "g": 1}])
    client.write_table("//dims", [{"g": 0, "name": "even"},
                                  {"g": 1, "name": "odd"}])
    qt = QueryTracker(client)
    qid = qt.start_query(
        'SELECT k, name FROM "//facts" JOIN "//dims" USING g '
        "ORDER BY k ASC LIMIT 10", engine="chyt", sync=True)
    assert qt.read_query_result(qid) == [
        {"k": 1, "name": b"even"}, {"k": 2, "name": b"odd"}]


def test_translate_dialect_extensions():
    # CH LIMIT offset, count shorthand.
    assert translate_sql("SELECT x FROM t ORDER BY x LIMIT 20, 10") == \
        "x FROM [//t] ORDER BY x OFFSET 20 LIMIT 10"
    # == equality and casts.
    assert translate_sql("SELECT toInt64(x) AS i FROM t WHERE y == 3") \
        == "int64 (x) AS i FROM [//t] WHERE y = 3"
    assert translate_sql("SELECT toFloat64(x) AS d FROM t") == \
        "double (x) AS d FROM [//t]"
    # DISTINCT → GROUP BY.
    assert translate_sql("SELECT DISTINCT a, b FROM t") == \
        "a, b FROM [//t] GROUP BY a, b"
    # -If combinators become null-skipping CASE aggregates with CH's
    # zero default on empty match sets.
    assert translate_sql("SELECT countIf(x > 2) AS c FROM t") == \
        "if_null (sum (CASE WHEN x > 2 THEN 1 END), 0) AS c FROM [//t]"
    assert translate_sql(
        "SELECT sumIf(v, g = 1) AS s FROM t GROUP BY g") == \
        "if_null (sum (CASE WHEN g = 1 THEN v END), 0) AS s " \
        "FROM [//t] GROUP BY g"
    with pytest.raises(YtError):
        translate_sql("SELECT toString(x) FROM t")
    with pytest.raises(YtError):
        translate_sql("SELECT DISTINCT a + 1 FROM t")


def test_sql_conditional_aggregates_and_distinct(tmp_path):
    from ytsaurus_tpu.ecosystem.sql import execute_sql
    client = connect(str(tmp_path))
    client.write_table("//ev", [
        {"g": 0, "v": 1}, {"g": 0, "v": 5}, {"g": 1, "v": 7},
        {"g": 1, "v": 2}, {"g": 0, "v": 9}])
    rows = execute_sql(client,
                       "SELECT countIf(v > 4) AS big, sumIf(v, v > 4) "
                       "AS s FROM `//ev` GROUP BY 1 AS one")
    assert rows == [{"big": 3, "s": 21}]
    rows = execute_sql(client, "SELECT DISTINCT g FROM `//ev`")
    assert sorted(r["g"] for r in rows) == [0, 1]


def test_distinct_with_order_by_and_empty_if_combinators(tmp_path):
    from ytsaurus_tpu.ecosystem.sql import execute_sql
    # GROUP BY lands BEFORE ORDER BY in the rewritten clause order.
    assert translate_sql("SELECT DISTINCT a FROM t ORDER BY a LIMIT 3") \
        == "a FROM [//t] GROUP BY a ORDER BY a LIMIT 3"
    with pytest.raises(YtError):
        translate_sql("SELECT DISTINCT a FROM t GROUP BY a")
    client = connect(str(tmp_path))
    client.write_table("//e", [{"g": 1, "v": 4}, {"g": 2, "v": 6}])
    rows = execute_sql(client,
                       "SELECT DISTINCT g FROM `//e` ORDER BY g DESC "
                       "LIMIT 10")
    assert [r["g"] for r in rows] == [2, 1]
    # CH default-value semantics: no matching rows → 0, not NULL.
    rows = execute_sql(client,
                       "SELECT countIf(v > 100) AS c, sumIf(v, v > 100) "
                       "AS s FROM `//e` GROUP BY 1 AS one")
    assert rows == [{"c": 0, "s": 0}]


def test_empty_subquery_yields_empty_not_error(tmp_path):
    from ytsaurus_tpu.ecosystem.sql import execute_sql
    client = connect(str(tmp_path))
    client.write_table("//s", [{"v": 1}, {"v": 2}])
    # Plain projection over an empty subquery → empty rowset.
    rows = execute_sql(client,
                       "SELECT v FROM (SELECT v FROM `//s` "
                       "WHERE v > 100)")
    assert rows == []
    # Aggregation over it → the group simply does not exist (QL GROUP
    # BY over zero rows yields zero groups).
    rows = execute_sql(client,
                       "SELECT count(*) AS n FROM (SELECT v FROM `//s` "
                       "WHERE v > 100) GROUP BY 1 AS one")
    assert rows == []


def test_subquery_split_ignores_string_literals(tmp_path):
    from ytsaurus_tpu.ecosystem.sql import execute_sql
    client = connect(str(tmp_path))
    client.write_table("//notes", [{"note": "from (x)", "v": 1},
                                   {"note": "plain", "v": 2}])
    rows = execute_sql(
        client, "SELECT v FROM `//notes` WHERE note = 'from (x)'")
    assert [r["v"] for r in rows] == [1]


def test_sql_subquery(tmp_path):
    from ytsaurus_tpu.ecosystem.sql import execute_sql
    client = connect(str(tmp_path))
    client.write_table("//orders", [
        {"cust": "a", "amount": 10}, {"cust": "a", "amount": 20},
        {"cust": "b", "amount": 5}, {"cust": "c", "amount": 50}])
    # Outer aggregate over an inner per-customer aggregate.
    rows = execute_sql(client, """
        SELECT count(*) AS n, max(total) AS top FROM (
            SELECT cust, sum(amount) AS total FROM `//orders`
            GROUP BY cust
        ) AS per_cust WHERE total > 6 GROUP BY 1 AS one""")
    (row,) = rows
    assert row["n"] == 2 and row["top"] == 50
    # Plain projection over a filtered subquery, with ORDER.
    rows = execute_sql(client, """
        SELECT cust, total FROM (
            SELECT cust, sum(amount) AS total FROM `//orders`
            GROUP BY cust
        ) ORDER BY total DESC LIMIT 2""")
    assert [r["total"] for r in rows] == [50, 30]
    assert rows[0]["cust"] in (b"c", "c")


def test_sql_errors_surface(tmp_path):
    client = connect(str(tmp_path))
    qt = QueryTracker(client)
    qid = qt.start_query("SELECT ~~~ nonsense", engine="chyt", sync=True)
    record = qt.get_query(qid)
    assert record["state"] == "failed"
    with pytest.raises(YtError):
        qt.read_query_result(qid)


# -- JOIN forms (ref CHYT join translation) ------------------------------------

def _join_fixture(tmp_path):
    client = connect(str(tmp_path))
    client.write_table("//facts", [{"g": i % 3, "v": i} for i in range(9)])
    client.write_table("//dims", [{"g": 0, "name": "zero"},
                                  {"g": 1, "name": "one"}])
    return client


def test_join_modifiers_normalize(tmp_path):
    from ytsaurus_tpu.ecosystem.sql import execute_sql
    client = _join_fixture(tmp_path)
    base = execute_sql(
        client, 'SELECT name, sum(v) AS t FROM "//facts" '
                'JOIN "//dims" USING g GROUP BY name')
    want = {tuple(sorted(r.items())) for r in base}
    for form in ("INNER JOIN", "ALL INNER JOIN", "ANY JOIN"):
        rows = execute_sql(
            client, f'SELECT name, sum(v) AS t FROM "//facts" '
                    f'{form} "//dims" USING g GROUP BY name')
        assert {tuple(sorted(r.items())) for r in rows} == want, form


def test_join_table_aliases_and_qualified_columns(tmp_path):
    from ytsaurus_tpu.ecosystem.sql import execute_sql
    client = _join_fixture(tmp_path)
    rows = execute_sql(
        client, 'SELECT f.v, d.name FROM "//facts" AS f '
                'JOIN "//dims" AS d ON f.g = d.g '
                'ORDER BY f.v ASC LIMIT 3')
    assert [r["v"] for r in rows] == [0, 1, 3]
    assert rows[0]["name"] == b"zero"
    # Bare (AS-less) aliases work too.
    rows = execute_sql(
        client, 'SELECT d.name, sum(f.v) AS t FROM "//facts" f '
                'JOIN "//dims" d USING g GROUP BY d.name')
    assert {r["name"]: r["t"] for r in rows} == \
        {b"zero": 9, b"one": 12}


def test_left_join_keeps_unmatched(tmp_path):
    from ytsaurus_tpu.ecosystem.sql import execute_sql
    client = _join_fixture(tmp_path)
    rows = execute_sql(
        client, 'SELECT v, name FROM "//facts" '
                'LEFT JOIN "//dims" USING g WHERE v = 8')
    assert rows == [{"v": 8, "name": None}]


def test_unsupported_join_kinds_fail_loudly(tmp_path):
    from ytsaurus_tpu.ecosystem.sql import execute_sql
    client = _join_fixture(tmp_path)
    for kind in ("CROSS", "RIGHT", "FULL"):
        with pytest.raises(YtError):
            execute_sql(client, f'SELECT 1 AS x FROM "//facts" '
                                f'{kind} JOIN "//dims" USING g')


def test_on_clause_with_distinct_names_preserved(tmp_path):
    from ytsaurus_tpu.ecosystem.sql import translate_sql
    # Same-name equalities become USING; distinct names stay ON.
    ql = translate_sql('SELECT x FROM "//a" t1 JOIN "//b" t2 '
                       'ON t1.k = t2.j')
    assert "ON" in ql and "USING" not in ql
    ql = translate_sql('SELECT x FROM "//a" t1 JOIN "//b" t2 '
                       'ON t1.k = t2.k AND t1.h = t2.h')
    assert "USING k , h" in ql or "USING k, h" in ql
