"""Chunk pools, fair share, job manager (user commands, speculation,
preemption), sliced map operations."""

import time

import numpy as np
import pytest

from ytsaurus_tpu.chunks import ColumnarChunk
from ytsaurus_tpu.client import YtClient, YtCluster
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.operations.chunk_pools import Stripe, build_stripes
from ytsaurus_tpu.operations.fair_share import (
    PoolState,
    compute_fair_shares,
    find_preemptable,
    pick_pool,
)
from ytsaurus_tpu.operations.jobs import Job, JobManager, run_command_job
from ytsaurus_tpu.schema import TableSchema


@pytest.fixture
def client(tmp_path):
    return YtClient(YtCluster(str(tmp_path / "cluster")))


def _chunk(n, start=0):
    schema = TableSchema.make([("k", "int64"), ("v", "int64")])
    return ColumnarChunk.from_arrays(schema, {
        "k": np.arange(start, start + n), "v": np.arange(n) * 2})


# -- chunk pools ---------------------------------------------------------------

def test_stripes_split_oversized_chunk():
    stripes = build_stripes([_chunk(10_000)], rows_per_job=3000)
    assert len(stripes) == 4
    assert sum(s.row_count for s in stripes) == 10_000
    assert all(s.row_count <= 3000 for s in stripes)
    # Materialized stripes cover every row exactly once.
    seen = []
    for s in stripes:
        seen.extend(r["k"] for r in s.materialize().to_rows())
    assert sorted(seen) == list(range(10_000))


def test_stripes_pack_small_chunks():
    chunks = [_chunk(100, start=i * 100) for i in range(20)]
    stripes = build_stripes(chunks, rows_per_job=1000)
    assert len(stripes) == 2
    assert all(s.row_count == 1000 for s in stripes)


def test_stripes_ordered_keeps_order():
    chunks = [_chunk(500, start=i * 500) for i in range(4)]
    stripes = build_stripes(chunks, rows_per_job=600, ordered=True)
    flat = []
    for s in stripes:
        flat.extend(r["k"] for r in s.materialize().to_rows())
    assert flat == list(range(2000))


def test_stripes_max_job_count():
    stripes = build_stripes([_chunk(10_000)], rows_per_job=100,
                            max_job_count=3)
    assert len(stripes) <= 3


def test_stripes_max_job_count_multi_chunk_hard_cap():
    # Greedy packing overshoots on awkward multi-chunk splits; the cap is
    # a contract (regression: 5x600 rows with cap 2 produced 3 stripes).
    chunks = [_chunk(600, start=i * 600) for i in range(5)]
    stripes = build_stripes(chunks, rows_per_job=100, max_job_count=2)
    assert len(stripes) <= 2
    assert sum(s.row_count for s in stripes) == 3000
    ordered = build_stripes(chunks, rows_per_job=100, max_job_count=2,
                            ordered=True)
    assert len(ordered) <= 2
    flat = []
    for s in ordered:
        flat.extend(r["k"] for r in s.materialize().to_rows())
    assert flat == list(range(3000))


# -- fair share ----------------------------------------------------------------

def test_fair_share_weights():
    a = PoolState("a", weight=3.0, pending=100)
    b = PoolState("b", weight=1.0, pending=100)
    compute_fair_shares([a, b], total_slots=8)
    assert abs(a.fair_share - 0.75) < 1e-9
    assert abs(b.fair_share - 0.25) < 1e-9


def test_fair_share_respects_demand_and_redistributes():
    a = PoolState("a", weight=1.0, pending=1)      # tiny demand
    b = PoolState("b", weight=1.0, pending=100)
    compute_fair_shares([a, b], total_slots=8)
    assert abs(a.fair_share - 1 / 8) < 1e-9        # capped by demand
    assert abs(b.fair_share - 7 / 8) < 1e-9        # takes the slack


def test_min_share_guarantee():
    a = PoolState("a", weight=0.001, min_share_ratio=0.5, pending=100)
    b = PoolState("b", weight=10.0, pending=100)
    compute_fair_shares([a, b], total_slots=10)
    assert a.fair_share >= 0.5 - 1e-9


def test_pick_pool_serves_most_starved():
    a = PoolState("a", pending=5, running=3)
    b = PoolState("b", pending=5, running=0)
    compute_fair_shares([a, b], total_slots=4)
    assert pick_pool([a, b]).name == "b"


def test_find_preemptable():
    a = PoolState("a", running=4, pending=0)
    b = PoolState("b", running=0, pending=3)
    compute_fair_shares([a, b], total_slots=4)
    assert find_preemptable([a, b]).name == "a"
    # No starvation → no preemption.
    c = PoolState("c", running=2, pending=0)
    compute_fair_shares([c], total_slots=4)
    assert find_preemptable([c]) is None


# -- job manager ---------------------------------------------------------------

def test_jobs_run_and_collect_results():
    manager = JobManager(slots=3)
    jobs = [Job(op_id="op1", index=i, run=lambda j, i=i: i * i)
            for i in range(10)]
    results = manager.run_all(jobs)
    assert results == [i * i for i in range(10)]
    assert all(j.state == "completed" for j in jobs)


def test_job_failure_propagates():
    manager = JobManager(slots=2)

    def boom(job):
        raise YtError("nope", code=EErrorCode.OperationFailed)

    jobs = [Job(op_id="op1", index=0, run=boom)]
    with pytest.raises(YtError, match="nope"):
        manager.run_all(jobs)


def test_command_job_pipes_and_stderr():
    manager = JobManager(slots=1)

    def run(job):
        return run_command_job(job, "tr a-z A-Z", b"hello\n")

    [result] = manager.run_all([Job(op_id="o", index=0, run=run,
                                    preemptible=True)])
    assert result == b"HELLO\n"

    def bad(job):
        return run_command_job(job, "echo oops >&2; exit 3", b"")

    job = Job(op_id="o", index=1, run=bad, preemptible=True)
    with pytest.raises(YtError) as ei:
        manager.run_all([job])
    assert ei.value.attributes.get("exit_code") == 3
    assert b"oops" in job.stderr_tail


def test_speculative_twin_rescues_straggler():
    manager = JobManager(slots=4, speculation_factor=1.5,
                         min_speculation_seconds=0.3)
    state = {"first": True}

    def sometimes_slow(job):
        # First attempt hangs (a straggler); the twin returns fast.
        if state["first"]:
            state["first"] = False
            return run_command_job(job, "sleep 30; echo late", b"")
        return run_command_job(job, "echo fast", b"")

    quick = [Job(op_id="op", index=i,
                 run=lambda j: run_command_job(j, "echo q", b""),
                 preemptible=True) for i in range(3)]
    straggler = Job(op_id="op", index=99, run=sometimes_slow,
                    preemptible=True)
    t0 = time.monotonic()
    manager.run_all(quick + [straggler], timeout=20)
    assert time.monotonic() - t0 < 15          # did not wait out the sleep
    assert straggler.result in (b"fast\n", b"late\n")


def test_preemption_requeues_over_share_job():
    manager = JobManager(slots=2)
    # Fill both slots with long-running pool-a commands.
    hogs = [Job(op_id="a", index=i, pool="a", preemptible=True,
                run=lambda j: run_command_job(j, "sleep 60; echo hog", b""))
            for i in range(2)]
    manager.submit(hogs)
    time.sleep(0.5)
    # A starving pool-b job arrives.
    quick = Job(op_id="b", index=0, pool="b",
                run=lambda j: run_command_job(j, "echo fast", b""),
                preemptible=True)
    manager.submit([quick])
    assert manager.maybe_preempt() is True
    manager.wait([quick], timeout=20)
    assert quick.result == b"fast\n"
    # The victim re-queued rather than failed.
    assert any(j.attempt > 0 or j.state in ("pending", "running")
               for j in hogs)
    manager.abort_operation("a")


# -- sliced map operations -----------------------------------------------------

def test_map_python_callable_sliced(client):
    client.write_table("//in", [{"k": i, "v": i * 2} for i in range(5000)])
    op = client.run_map(lambda rows: [{"k": r["k"], "v": r["v"] + 1}
                                      for r in rows],
                        "//in", "//out", rows_per_job=1000)
    assert op.state == "completed"
    assert op.result["jobs"] == 5
    out = client.read_table("//out")
    assert len(out) == 5000
    assert {r["v"] - 2 * r["k"] for r in out} == {1}


def test_map_shell_command(client):
    client.write_table("//in", [{"k": i} for i in range(100)])
    op = client.run_map("cat", "//in", "//out", job_count=4)
    assert op.state == "completed"
    assert op.result["jobs"] >= 2
    assert sorted(r["k"] for r in client.read_table("//out")) == \
        list(range(100))


def test_map_command_failure_reports_stderr(client):
    client.write_table("//in", [{"k": 1}])
    with pytest.raises(YtError) as ei:
        client.run_map("echo broken >&2; exit 7", "//in", "//out")
    err = ei.value
    # The stderr tail + exit code surface through the operation error.
    flat = str(err.to_dict())
    assert "broken" in flat and "7" in flat


def test_map_command_jq_style_transform(client):
    client.write_table("//in", [{"name": "a"}, {"name": "b"}])
    op = client.run_map("sed s/name/user/", "//in", "//out")
    assert op.state == "completed"
    out = client.read_table("//out")
    assert sorted(r["user"] for r in out) == [b"a", b"b"]
