#!/usr/bin/env python
"""Sensor-catalog lint (ISSUE 6 satellite).

Every sensor the tree creates must appear in the checked-in catalog
(`tools/sensor_catalog.json`) with its kind and tag set — dashboards,
SLO configs (`config.TelemetryConfig.slos` reference sensors BY NAME),
and the `/metrics/history` consumers all key on sensor names, so a
rename that skips the catalog silently breaks them.  The lint fails in
tests instead.

How it finds sensors (static AST walk over ytsaurus_tpu/**/*.py — no
imports, so a module with heavy deps can't break the lint):

- sensor sites are calls `<recv>.counter("name") / .gauge / .histogram
  / .summary / .timer` (timer wraps a summary);
- the receiver's PREFIX is resolved through simple assignment chains:
  `Profiler("/p")`, `<recv>.with_tags(...)`, `<recv>.with_prefix("/q")`,
  names and `self.attr` bound in the enclosing function scope first,
  then module scope (module bindings that conflict are dropped as
  ambiguous rather than guessed);
- literal-name sites with a resolved prefix must match the catalog
  EXACTLY (name + kind); unresolved-prefix sites must match some
  same-kind entry by leaf name;
- dynamic-name sites (e.g. per-field usage counters) must sit under a
  prefix declared in the catalog's `dynamic_prefixes` with the same
  kind.

The reverse direction holds too: catalog entries no site creates are
stale and fail the lint, so deletions can't leave dead dashboard rows.

Usage: python tools/check_sensor_catalog.py [--root DIR]
Exit 0 clean; exit 1 with one line per violation.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys

SENSOR_METHODS = {"counter": "counter", "gauge": "gauge",
                  "histogram": "histogram", "summary": "summary",
                  "timer": "summary"}

CATALOG_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "sensor_catalog.json")

# Files whose Profiler class DEFINES the sensor methods (their internal
# `self.summary(name)` plumbing is not a sensor site).
SKIP_FILES = {os.path.join("utils", "profiling.py")}


class _Prefix:
    """Resolution result: a literal prefix string, or None (unknown)."""

    __slots__ = ("value", "tags")

    def __init__(self, value, tags=()):
        self.value = value
        self.tags = tuple(tags)


def _literal_str(node):
    return node.value if isinstance(node, ast.Constant) and \
        isinstance(node.value, str) else None


def _resolve(node, scope: dict, module: dict, depth: int = 0):
    """Resolve an expression to a _Prefix, or None when unresolvable."""
    if depth > 16 or node is None:
        return None
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id == "Profiler":
            prefix = _literal_str(node.args[0]) if node.args else ""
            return _Prefix(prefix) if prefix is not None else None
        if isinstance(fn, ast.Attribute):
            if fn.attr == "with_tags":
                base = _resolve(fn.value, scope, module, depth + 1)
                if base is None:
                    return None
                tags = [kw.arg for kw in node.keywords if kw.arg]
                return _Prefix(base.value, base.tags + tuple(tags))
            if fn.attr == "with_prefix":
                base = _resolve(fn.value, scope, module, depth + 1)
                extra = _literal_str(node.args[0]) if node.args else None
                if base is None or extra is None:
                    return None
                return _Prefix(base.value + extra, base.tags)
        return None
    if isinstance(node, ast.IfExp):
        # `prof.with_tags(pool=p) if p else prof`: both arms must agree
        # on the prefix; the tag set is the union.
        left = _resolve(node.body, scope, module, depth + 1)
        right = _resolve(node.orelse, scope, module, depth + 1)
        if left is not None and right is not None and \
                left.value == right.value:
            return _Prefix(left.value,
                           dict.fromkeys(left.tags + right.tags))
        return None
    if isinstance(node, ast.Name):
        target = scope.get(node.id, module.get(node.id))
        if target is None or target is node:
            return None
        return _resolve(target, scope, module, depth + 1)
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        key = f"self.{node.attr}"
        target = scope.get(key, module.get(key))
        return _resolve(target, scope, module, depth + 1) \
            if target is not None else None
    return None


def _bindings(body_nodes, deep: bool = False) -> dict:
    """name -> value-expr for simple assignments in a statement list.
    `deep` recurses into nested functions/classes (the module-wide flat
    map); shallow stops at them (one function's own scope).  Conflicting
    re-binds drop to AMBIGUOUS so resolution never guesses between
    prefixes."""
    out: dict = {}
    ambiguous = object()

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if not deep and isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Assign) and \
                    len(child.targets) == 1:
                target = child.targets[0]
                key = None
                if isinstance(target, ast.Name):
                    key = target.id
                elif isinstance(target, ast.Attribute) and \
                        isinstance(target.value, ast.Name) and \
                        target.value.id == "self":
                    key = f"self.{target.attr}"
                if key is not None:
                    prior = out.get(key)
                    if prior is None:
                        out[key] = child.value
                    elif prior is not ambiguous and \
                            ast.dump(prior) != ast.dump(child.value):
                        out[key] = ambiguous
            visit(child)

    for stmt in body_nodes:
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.targets[0], ast.Name):
            prior = out.get(stmt.targets[0].id)
            if prior is None:
                out[stmt.targets[0].id] = stmt.value
            elif prior is not ambiguous and \
                    ast.dump(prior) != ast.dump(stmt.value):
                out[stmt.targets[0].id] = ambiguous
        visit(stmt)
    return {k: v for k, v in out.items() if v is not ambiguous}


def scan_file(path: str) -> list[dict]:
    """Every sensor-creation site in one file:
    {kind, name (leaf or None), prefix (str or None), line}."""
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    module_scope = _bindings(tree.body, deep=True)
    sites = []

    def pool_cache_sites(call):
        """`PoolSensorCache("prefix", ("a", "b"))` declares one counter
        per name, pool-tagged; a non-literal name set (a runtime field
        list) is one dynamic site under the prefix."""
        prefix = _literal_str(call.args[0]) if call.args else None
        names = None
        if len(call.args) > 1 and isinstance(call.args[1],
                                             (ast.Tuple, ast.List)):
            names = [_literal_str(e) for e in call.args[1].elts]
            if any(n is None for n in names):
                names = None
        if names:
            return [{"kind": "counter", "name": n, "prefix": prefix,
                     "tags": ["pool"], "line": call.lineno}
                    for n in names]
        return [{"kind": "counter", "name": None, "prefix": prefix,
                 "tags": ["pool"], "line": call.lineno}]

    # PoolSensorCache constructors carry literal prefixes, so they need
    # no scope resolution — one whole-tree pass, outside the line-keyed
    # dedup below (one constructor line declares SEVERAL sensors).
    cache_sites = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Name) and \
                node.func.id == "PoolSensorCache":
            cache_sites.extend(pool_cache_sites(node))

    def walk(node, scope):
        for child in ast.walk(node):
            if not (isinstance(child, ast.Call) and
                    isinstance(child.func, ast.Attribute) and
                    child.func.attr in SENSOR_METHODS):
                continue
            kind = SENSOR_METHODS[child.func.attr]
            leaf = _literal_str(child.args[0]) if child.args else None
            prefix = _resolve(child.func.value, scope, module_scope)
            sites.append({
                "kind": kind, "name": leaf,
                "prefix": prefix.value if prefix else None,
                "tags": list(prefix.tags) if prefix else [],
                "line": child.lineno,
            })

    # Walk each function with its own scope bindings layered over the
    # module's; module-level sites use the module scope alone.
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    seen_lines = set()
    for fn in funcs:
        scope = _bindings(fn.body)
        before = len(sites)
        walk(fn, scope)
        for site in sites[before:]:
            seen_lines.add(site["line"])
    # De-dup: nested functions are walked twice (outer pass includes
    # inner bodies); keep the innermost (later, more-local) resolution.
    best: dict[int, dict] = {}
    for site in sites:
        prior = best.get(site["line"])
        if prior is None or (prior["prefix"] is None and
                             site["prefix"] is not None):
            best[site["line"]] = site
    module_sites = []
    walk_target = [n for n in tree.body
                   if not isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
    for stmt in walk_target:
        before = len(module_sites)
        for child in ast.walk(stmt):
            if (isinstance(child, ast.Call) and
                    isinstance(child.func, ast.Attribute) and
                    child.func.attr in SENSOR_METHODS and
                    child.lineno not in best):
                prefix = _resolve(child.func.value, {}, module_scope)
                module_sites.append({
                    "kind": SENSOR_METHODS[child.func.attr],
                    "name": _literal_str(child.args[0])
                    if child.args else None,
                    "prefix": prefix.value if prefix else None,
                    "tags": list(prefix.tags) if prefix else [],
                    "line": child.lineno,
                })
    return sorted([*best.values(), *module_sites, *cache_sites],
                  key=lambda s: s["line"])


def _full_name(prefix: str, leaf: str) -> str:
    return f"{prefix}/{leaf}" if prefix else leaf


def check(root: str, catalog_path: str = CATALOG_PATH) -> list[str]:
    with open(catalog_path, "r", encoding="utf-8") as f:
        catalog = json.load(f)
    entries: dict = catalog.get("sensors", {})
    dynamic: dict = catalog.get("dynamic_prefixes", {})
    errors: list[str] = []
    used_entries: set = set()
    used_dynamic: set = set()
    by_leaf: dict = {}
    for name, spec in entries.items():
        by_leaf.setdefault((name.rsplit("/", 1)[-1], spec["kind"]),
                           []).append(name)

    pkg_root = os.path.join(root, "ytsaurus_tpu")
    for dirpath, _dirnames, filenames in os.walk(pkg_root):
        for filename in sorted(filenames):
            if not filename.endswith(".py"):
                continue
            path = os.path.join(dirpath, filename)
            rel = os.path.relpath(path, pkg_root)
            if rel in SKIP_FILES:
                continue
            try:
                sites = scan_file(path)
            except SyntaxError as exc:
                errors.append(f"{rel}: unparseable: {exc}")
                continue
            for site in sites:
                where = f"{rel}:{site['line']}"
                kind, leaf = site["kind"], site["name"]
                prefix = site["prefix"]
                if leaf is None:
                    # Dynamic sensor name: its prefix must be declared.
                    if prefix is None:
                        errors.append(
                            f"{where}: dynamic sensor name with "
                            f"unresolvable prefix — declare it under "
                            f"dynamic_prefixes in the catalog")
                    elif prefix not in dynamic:
                        errors.append(
                            f"{where}: dynamic {kind} under {prefix!r} "
                            f"not in catalog dynamic_prefixes")
                    elif dynamic[prefix]["kind"] != kind:
                        errors.append(
                            f"{where}: dynamic {kind} under {prefix!r} "
                            f"but catalog declares "
                            f"{dynamic[prefix]['kind']!r}")
                    else:
                        used_dynamic.add(prefix)
                    continue
                if prefix is not None:
                    name = _full_name(prefix, leaf)
                    spec = entries.get(name)
                    if spec is not None and spec["kind"] == kind:
                        used_entries.add(name)
                        continue
                    if spec is not None:
                        errors.append(
                            f"{where}: {name} is a {kind} but the "
                            f"catalog says {spec['kind']!r}")
                        continue
                    errors.append(
                        f"{where}: {kind} {name!r} missing from "
                        f"tools/sensor_catalog.json")
                    continue
                # Unresolved prefix: leaf+kind must match something.
                matches = by_leaf.get((leaf, kind), [])
                if matches:
                    used_entries.update(matches)
                else:
                    errors.append(
                        f"{where}: {kind} leaf {leaf!r} matches no "
                        f"catalog entry (prefix unresolved)")

    for name in sorted(set(entries) - used_entries):
        errors.append(f"catalog: stale entry {name!r} — no code site "
                      f"creates it")
    for prefix in sorted(set(dynamic) - used_dynamic):
        errors.append(f"catalog: stale dynamic_prefix {prefix!r}")
    return errors


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    parser.add_argument("--catalog", default=CATALOG_PATH)
    args = parser.parse_args(argv)
    errors = check(args.root, args.catalog)
    for error in errors:
        print(error, file=sys.stderr)
    if errors:
        print(f"{len(errors)} sensor-catalog violation(s)",
              file=sys.stderr)
        return 1
    print("sensor catalog clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
