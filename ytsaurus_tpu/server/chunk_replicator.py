"""ChunkReplicator: master-side background re-replication and repair.

Ref: yt/yt/server/master/chunk_server/chunk_replicator.h — the master
continuously compares each chunk's replica set to its target replication
factor and schedules Replicate/Repair jobs on data nodes (job types:
yt/yt/client/job_tracker_client/public.h:31-59).  Before this module a
dead node's chunks stayed under-replicated until the next read happened
to walk past the hole (repair-on-read only).

TPU-native redesign: replica placement is rendezvous-hashed over the
alive-node list (server/remote_store.py::placement_rank), so the
replicator derives each chunk's DESIRED holders deterministically and
only has to learn the ACTUAL holders — one id-only list_chunks poll per
node per scan, no chunk directory.  Data never flows through the master:
a repair "job" is one replicate_chunk RPC to a surviving holder, which
pushes the blob straight to the missing target node (erasure chunks are
reconstructed by the holder's read path if its own parts are damaged and
re-encoded on the target).
"""

from __future__ import annotations

import threading
from typing import Callable

from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.rpc import Channel, RetryingChannel
from ytsaurus_tpu.server.remote_store import placement_rank
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("chunk_replicator")

# Consecutive list_chunks failures after which a heartbeat-alive node is
# treated as storage-dead (scans proceed without it instead of waiting
# for membership to settle).
LISTING_FAILURE_THRESHOLD = 3
# Hard bound on consecutive skipped scans: a node that FLAPS (fails,
# then answers, resetting its failure count) must not defer repair of
# chunks lost elsewhere indefinitely — after this many skips the scan
# proceeds with whatever answered.
MAX_CONSECUTIVE_SKIPS = 5


class ChunkReplicator:
    """Periodic scan → replicate under-replicated chunks toward their
    rendezvous targets."""

    def __init__(self, nodes_provider: Callable[[], list[str]],
                 replication_factor: int = 2, interval: float = 3.0,
                 timeout: float = 60.0,
                 liveness_provider: "Callable[[], set] | None" = None):
        self._nodes_provider = nodes_provider
        # Rooted-chunk-id provider (YtClient.referenced_chunk_ids): a
        # DELETED chunk whose removal missed a down node must not be
        # resurrected to full RF when that node rejoins — only live
        # chunks are worth replicating.  Hunk chunks are exempt (their
        # liveness needs per-chunk meta reads; a stale hunk copy is a
        # bounded leak until the next GC sweep, which lists and removes
        # it from every then-alive node).
        self._liveness_provider = liveness_provider
        self.replication_factor = replication_factor
        self.interval = interval
        self.timeout = timeout
        self._channels: dict[str, RetryingChannel] = {}
        self._listing_failures: dict[str, int] = {}
        self._consecutive_skips = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.stats = {"scans": 0, "scans_skipped": 0,
                      "replications_requested": 0,
                      "replications_failed": 0, "chunks_seen": 0,
                      "under_replicated": 0}

    def _channel(self, address: str) -> RetryingChannel:
        ch = self._channels.get(address)
        if ch is None:
            ch = RetryingChannel(Channel(address, timeout=self.timeout),
                                 attempts=2, backoff=0.1)
            self._channels[address] = ch
        return ch

    def scan_once(self) -> int:
        """One full pass; returns the number of replication requests
        issued.  Exposed for tests and for an on-demand Orchid poke."""
        self.stats["scans"] += 1
        alive = sorted(self._nodes_provider())
        # Failure history is only meaningful for CURRENT members: a node
        # that left and rejoined must not inherit stale counts (one
        # hiccup would then read as 3 "consecutive" failures).
        for address in list(self._listing_failures):
            if address not in alive:
                del self._listing_failures[address]
        if len(alive) < 2:
            return 0
        holders: dict[str, set[str]] = {}
        reachable: list[str] = []
        for address in alive:
            try:
                body, _ = self._channel(address).call(
                    "data_node", "list_chunks", {})
                reachable.append(address)
                self._listing_failures.pop(address, None)
                for cid in body.get("chunk_ids", []):
                    cid = cid.decode() if isinstance(cid, bytes) else cid
                    holders.setdefault(cid, set()).add(address)
            except YtError:
                self._listing_failures[address] = \
                    self._listing_failures.get(address, 0) + 1
        # A heartbeat-ALIVE node that failed a listing is either having a
        # TRANSIENT hiccup (GC pause, overload) — re-computing rendezvous
        # targets without it would mass-copy chunks off-rank, so skip the
        # scan and let membership settle — or it is PERSISTENTLY broken
        # (dead disk behind a live heartbeat), in which case after
        # LISTING_FAILURE_THRESHOLD consecutive failures its chunks ARE
        # effectively lost and re-replicating around it is the point.
        settling = [a for a in alive if a not in reachable and
                    self._listing_failures.get(a, 0) <
                    LISTING_FAILURE_THRESHOLD]
        if settling and self._consecutive_skips < MAX_CONSECUTIVE_SKIPS:
            self._consecutive_skips += 1
            self.stats["scans_skipped"] += 1
            return 0
        self._consecutive_skips = 0
        self.stats["chunks_seen"] = len(holders)
        live: "set | None" = None
        if self._liveness_provider is not None:
            try:
                live = set(self._liveness_provider())
            except Exception:   # noqa: BLE001 — advisory; skip filtering
                live = None
        issued = 0
        under = 0
        from ytsaurus_tpu.chunks.hunks import is_hunk_id
        for chunk_id, holding in holders.items():
            if live is not None and chunk_id not in live and \
                    not is_hunk_id(chunk_id):
                continue            # unrooted: GC's business, not ours
            # Desired holders under the CURRENT alive list; a chunk whose
            # rendezvous targets all hold it is healthy even if an old
            # (now off-rank) replica also survives.
            targets = placement_rank(chunk_id, reachable)[
                : self.replication_factor]
            missing = [t for t in targets if t not in holding]
            if not missing:
                continue
            under += 1
            # The job runs ON a surviving holder (rank order for
            # determinism): master-free data path.
            source = next((a for a in placement_rank(chunk_id, sorted(
                holding)) if a in holding), None)
            if source is None:
                continue
            for target in missing:
                try:
                    self._channel(source).call(
                        "data_node", "replicate_chunk",
                        {"chunk_id": chunk_id, "target": target})
                    issued += 1
                except YtError as err:
                    self.stats["replications_failed"] += 1
                    logger.warning("replicate %s %s->%s failed: %s",
                                   chunk_id, source, target, err)
        self.stats["under_replicated"] = under
        self.stats["replications_requested"] += issued
        if issued:
            logger.info("chunk replicator: %d replications issued "
                        "(%d under-replicated of %d chunks)",
                        issued, under, len(holders))
        return issued

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.scan_once()
            except Exception as exc:    # noqa: BLE001 — keep scanning
                logger.warning("chunk replicator scan failed: %s", exc)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="chunk-replicator")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
