"""Chunk store: content-addressed chunk files on a filesystem + block cache.

Ref mapping: data node chunk storage (server/node/data_node/blob_chunk.h,
chunk_store.h) collapses to a host-side store whose unit is the whole
columnar chunk (the reference's block granularity matters for its TCP data
plane; here chunks decode straight into device planes, so the cache holds
decoded chunks — the analog of the tablet node's in-memory mode
(tablet_node/in_memory_manager.h) at `uncompressed` level).
"""

from __future__ import annotations

import os
import threading
import uuid
from collections import OrderedDict
from typing import Optional

from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.chunks.encoding import (
    DEFAULT_CODEC,
    deserialize_chunk,
    read_chunk_meta,
    serialize_chunk,
)
from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils import failpoints

# Fault sites on every disk boundary (ISSUE 2): disk-shaped failures are
# OSErrors so the replica/read ladders above this layer treat injected
# faults exactly like a dying location.
_FP_READ = failpoints.register_site(
    "chunks.store.read",
    error=lambda s: OSError(f"injected read failure at {s}"))
_FP_WRITE = failpoints.register_site(
    "chunks.store.write",
    error=lambda s: OSError(f"injected write failure at {s}"))
_FP_DECODE = failpoints.register_site(
    "chunks.store.decode",
    error=lambda s: YtError(f"injected decode failure at {s}",
                            code=EErrorCode.ChunkFormatError))
_FP_PART_READ = failpoints.register_site(
    "chunks.erasure.part_read",
    error=lambda s: OSError(f"injected part loss at {s}"))
_FP_REMOVE = failpoints.register_site(
    "chunks.store.remove",
    error=lambda s: OSError(f"injected remove failure at {s}"))


def _stats_missing_sketch(stats: dict) -> bool:
    """True when a sealed column_stats payload predates the NDV sketch
    (read_stats then decode-backfills it like the pre-stats path)."""
    return any(isinstance(entry, dict) and "ndv_sketch" not in entry
               for name, entry in stats.items() if name != "$row_count")


def new_chunk_id() -> str:
    return uuid.uuid4().hex


class FsChunkStore:
    """Chunks as files under root/<id[:2]>/<id>.chunk."""

    # Bounded FIFO memo of per-chunk column stats: chunks are immutable,
    # so an entry never goes stale; removal just leaves a dead key that
    # ages out.
    _STATS_MEMO_LIMIT = 4096

    def __init__(self, root: str, codec: str = DEFAULT_CODEC):
        self.root = root
        self.codec = codec
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        self._stats_memo: "OrderedDict[str, dict]" = OrderedDict()

    def _path(self, chunk_id: str) -> str:
        return os.path.join(self.root, chunk_id[:2], f"{chunk_id}.chunk")

    def _part_path(self, chunk_id: str, index: int) -> str:
        return os.path.join(self.root, chunk_id[:2],
                            f"{chunk_id}.part{index}")

    def _erasure_meta_path(self, chunk_id: str) -> str:
        return os.path.join(self.root, chunk_id[:2], f"{chunk_id}.erasure")

    def write_chunk(self, chunk: ColumnarChunk,
                    chunk_id: Optional[str] = None,
                    codec: Optional[str] = None,
                    erasure: Optional[str] = None) -> str:
        chunk_id = chunk_id or new_chunk_id()
        blob = serialize_chunk(chunk, codec or self.codec, hunk_store=self)
        return self.put_blob(chunk_id, blob, erasure=erasure)

    def _atomic_write(self, path: str, blob: bytes) -> None:
        # torn-write injection truncates the payload AND fails the write
        # after the torn bytes hit the tmp file: the rename below never
        # runs, so readers can only ever see the previous complete state
        # — the atomicity this staging protocol exists to provide.
        blob, torn = _FP_WRITE.write_hit(blob)
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        if torn:
            raise OSError(f"injected torn write: {path} "
                          "(torn tmp left unpublished)")
        os.replace(tmp, path)      # atomic publish

    def _write_erasure(self, chunk_id: str, blob: bytes,
                       erasure: str) -> str:
        """Erasure-coded layout: k+m part files + a small meta file (ref:
        striped erasure writer, ytlib/chunk_client/striped_erasure_writer.h)."""
        from ytsaurus_tpu import yson
        from ytsaurus_tpu.chunks.erasure import get_erasure_codec

        codec = get_erasure_codec(erasure)
        parts = codec.encode(blob)
        os.makedirs(os.path.dirname(self._path(chunk_id)), exist_ok=True)
        for i, part in enumerate(parts):
            self._atomic_write(self._part_path(chunk_id, i), part)
        self._atomic_write(self._erasure_meta_path(chunk_id), yson.dumps(
            {"codec": erasure, "size": len(blob)}, binary=True))
        return chunk_id

    def put_blob(self, chunk_id: str, blob: bytes,
                 erasure: Optional[str] = None) -> str:
        """Store an already-serialized chunk blob (the data-node RPC path:
        placement decisions happen remotely, bytes land here)."""
        if erasure is not None:
            return self._write_erasure(chunk_id, blob, erasure)
        path = self._path(chunk_id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        self._atomic_write(path, blob)
        return chunk_id

    def get_blob(self, chunk_id: str) -> bytes:
        return self._read_blob(chunk_id)

    def read_chunk(self, chunk_id: str) -> ColumnarChunk:
        from ytsaurus_tpu.utils.tracing import child_span
        with child_span("chunk.read", chunk_id=chunk_id,
                        location=self.root):
            _FP_DECODE.hit()
            return deserialize_chunk(self._read_blob(chunk_id),
                                     hunk_store=self)

    def read_meta(self, chunk_id: str) -> dict:
        return read_chunk_meta(self._read_blob(chunk_id))

    def read_stats(self, chunk_id: str,
                   backfill_sketch: bool = False) -> dict:
        """Per-column min/max/has_null (+ NDV sketch) pruning stats.

        Written-at-seal chunks carry them in the meta header (one blob
        read, no block decompress).  BACKFILL: chunks persisted before
        stats existed decode once, compute host-side, and memoize — the
        pre-stats cost paid once per chunk instead of per scan.  Chunks
        sealed WITH stats but before the NDV sketch joined them
        decode-backfill the same way only when `backfill_sketch` asks
        for it (the planner's stats fold) — metadata-only consumers
        ($timestamp reads, bounds pruning) must never pay a full chunk
        decode for a sketch they do not read."""
        with self._lock:
            stats = self._stats_memo.get(chunk_id)
            if stats is not None and not (backfill_sketch
                                          and _stats_missing_sketch(stats)):
                return stats
        stats = self.read_meta(chunk_id).get("column_stats")
        if stats is None or (backfill_sketch
                             and _stats_missing_sketch(stats)):
            from ytsaurus_tpu.chunks.columnar import chunk_column_stats
            stats = chunk_column_stats(self.read_chunk(chunk_id))
        with self._lock:
            self._stats_memo[chunk_id] = stats
            while len(self._stats_memo) > self._STATS_MEMO_LIMIT:
                self._stats_memo.popitem(last=False)
        return stats

    def _read_blob(self, chunk_id: str) -> bytes:
        _FP_READ.hit()
        path = self._path(chunk_id)
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            pass
        blob = self._read_erasure_blob(chunk_id)
        if blob is None:
            raise YtError(f"No such chunk {chunk_id}",
                          code=EErrorCode.NoSuchChunk)
        return blob

    def _read_erasure_blob(self, chunk_id: str) -> Optional[bytes]:
        from ytsaurus_tpu import yson
        from ytsaurus_tpu.chunks.erasure import get_erasure_codec

        meta_path = self._erasure_meta_path(chunk_id)
        try:
            with open(meta_path, "rb") as f:
                meta = yson.loads(f.read())
        except FileNotFoundError:
            return None
        codec = get_erasure_codec(meta["codec"])

        def read_part(i):
            try:
                _FP_PART_READ.hit()
                with open(self._part_path(chunk_id, i), "rb") as f:
                    return f.read()
            except OSError:
                return None            # erased / lost part → repair below
        # Fast path: data parts only; parity reads happen only on damage.
        parts = [read_part(i) for i in range(codec.data_parts)]
        if any(p is None for p in parts):
            from ytsaurus_tpu.utils.tracing import child_span
            parts += [read_part(i) for i in range(codec.data_parts,
                                                  codec.total_parts)]
            lost = [i for i, part in enumerate(parts) if part is None]
            with child_span("chunk.erasure_repair", chunk_id=chunk_id,
                            lost_parts=len(lost)):
                blob = codec.decode(parts, meta["size"])
                # Repair-on-read (ref chunk_replicator.h Repair jobs
                # invoked from the read ladder): the decode just proved
                # the chunk reconstructs, so rebuild the lost parts NOW
                # instead of paying parity reads on every future access.
                if lost:
                    try:
                        fresh = codec.encode(blob)
                        for i in lost:
                            self._atomic_write(
                                self._part_path(chunk_id, i), fresh[i])
                    except OSError:
                        pass   # repair is best-effort; the read
                        # succeeded
            return blob
        parts += [None] * codec.parity_parts
        return codec.decode(parts, meta["size"])

    def exists(self, chunk_id: str) -> bool:
        return os.path.exists(self._path(chunk_id)) or \
            os.path.exists(self._erasure_meta_path(chunk_id))

    def verify_chunk(self, chunk_id: str) -> bool:
        """Deep-verify one chunk: decode the blob, which re-checks every
        block's CRC-64 (and, for erasure chunks, reconstructs through
        any damaged parts).  False = the stored bytes cannot produce a
        valid chunk — scrub material."""
        try:
            deserialize_chunk(self._read_blob(chunk_id), hunk_store=self)
            return True
        except Exception:   # noqa: BLE001 — corruption surfaces as
            # anything (CRC YtError, varint ValueError, meta KeyError):
            # every decode failure means the stored bytes are bad.
            return False

    # analyze: allow(failpoint): enumeration helper — read faults inject at chunks.store.read / chunks.erasure.part_read
    def _chunk_paths(self, chunk_id: str) -> "list[str]":
        """Every file that can belong to this chunk (blob, erasure meta
        + parts) — THE enumeration shared by remove and quarantine, so a
        layout change cannot desync them."""
        paths = [self._path(chunk_id)]
        meta_path = self._erasure_meta_path(chunk_id)
        if os.path.exists(meta_path):
            from ytsaurus_tpu import yson
            from ytsaurus_tpu.chunks.erasure import get_erasure_codec
            try:
                with open(meta_path, "rb") as f:
                    name = yson.loads(f.read())["codec"]
                    name = name.decode() if isinstance(name, bytes) \
                        else name
                    total = get_erasure_codec(name).total_parts
            except Exception:   # noqa: BLE001 — damaged meta: sweep wide
                total = 32
            paths.append(meta_path)
            paths.extend(self._part_path(chunk_id, i)
                         for i in range(total))
        return paths

    # analyze: allow(failpoint): per-file os.replace already tolerates races; the scrub is DRIVEN by the decode failpoints
    def quarantine_chunk(self, chunk_id: str) -> None:
        """Move a corrupt chunk's files aside (`.quarantine` suffix) so
        the store stops advertising it while the bytes stay on disk for
        post-mortem — the scrubber's analog of the reference marking a
        replica as failed before the replicator re-replicates."""
        for path in self._chunk_paths(chunk_id):
            try:
                os.replace(path, path + ".quarantine")
            except FileNotFoundError:
                continue            # raced with remove/another scrub

    # analyze: allow(failpoint): metadata peek on the replicate path; part faults inject at chunks.erasure.part_read
    def erasure_codec_of(self, chunk_id: str) -> Optional[str]:
        """Codec name when the chunk is stored erasure-coded, else None
        (lets the replicator preserve the encoding on the target)."""
        from ytsaurus_tpu import yson
        try:
            with open(self._erasure_meta_path(chunk_id), "rb") as f:
                meta = yson.loads(f.read())
        except FileNotFoundError:
            return None
        codec = meta.get("codec")
        return codec.decode() if isinstance(codec, bytes) else codec

    def remove_chunk(self, chunk_id: str) -> None:
        """Dispose a chunk's files.  Removal is ADVISORY GC: flush,
        compaction, resharding, and intermediate-cleanup all call this
        on their success path, so a disk error here must never fail the
        operation that already committed — a failed unlink leaves a
        garbage file for the next sweep (the `chunks.store.remove`
        failpoint injects exactly that, fired by the chaos soak)."""
        try:
            _FP_REMOVE.hit()
        except OSError:
            return
        for path in self._chunk_paths(chunk_id):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
            except OSError:
                continue        # garbage file stays; next GC retries

    def list_chunks(self) -> list[str]:
        out = set()
        for sub in os.listdir(self.root):
            subdir = os.path.join(self.root, sub)
            if not os.path.isdir(subdir):
                continue
            for name in os.listdir(subdir):
                if name.endswith(".chunk"):
                    out.add(name[:-len(".chunk")])
                elif name.endswith(".erasure"):
                    out.add(name[:-len(".erasure")])
        return sorted(out)


class ChunkCache:
    """LRU cache of DECODED chunks (device-resident planes), byte-budgeted.

    The HBM staging manager: holding a decoded chunk pins its planes on
    device, so the budget bounds device memory spent on cached table data.
    """

    def __init__(self, store: FsChunkStore, capacity_bytes: int = 2 << 30):
        self.store = store
        self.capacity_bytes = capacity_bytes
        self._entries: OrderedDict[str, tuple[ColumnarChunk, int]] = OrderedDict()
        self._pinned: set[str] = set()
        self._used = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _chunk_bytes(chunk: ColumnarChunk) -> int:
        total = 0
        for col in chunk.columns.values():
            total += col.data.size * col.data.dtype.itemsize
            total += col.valid.size
        return total

    def get(self, chunk_id: str) -> ColumnarChunk:
        with self._lock:
            entry = self._entries.get(chunk_id)
            if entry is not None:
                self._entries.move_to_end(chunk_id)
                self.hits += 1
                return entry[0]
        chunk = self.store.read_chunk(chunk_id)
        size = self._chunk_bytes(chunk)
        with self._lock:
            self.misses += 1
            if chunk_id not in self._entries:
                self._entries[chunk_id] = (chunk, size)
                self._used += size
                self._evict_locked()
        return chunk

    def _evict_locked(self) -> None:
        # Pinned entries (in-memory mode tables) never evict.  The newest
        # entry (just inserted, still being returned to a caller) survives,
        # so the cache may overshoot by exactly one chunk's working set.
        evictable = [cid for cid in self._entries if cid not in self._pinned]
        i = 0
        while self._used > self.capacity_bytes and i < len(evictable) - 1:
            victim = evictable[i]
            i += 1
            _, size = self._entries.pop(victim)
            self._used -= size

    def pin(self, chunk_id: str) -> None:
        """Keep this chunk's decoded planes resident (ref in_memory_manager
        preload, tablet_node/in_memory_manager.h:62).  Entry insertion and
        pin-marking happen under ONE lock acquisition, or a concurrent
        eviction could drop the chunk between them."""
        with self._lock:
            if chunk_id in self._entries:
                self._pinned.add(chunk_id)
                self._entries.move_to_end(chunk_id)
                return
        chunk = self.store.read_chunk(chunk_id)
        size = self._chunk_bytes(chunk)
        with self._lock:
            if chunk_id not in self._entries:
                self._entries[chunk_id] = (chunk, size)
                self._used += size
            self._pinned.add(chunk_id)
            self._evict_locked()

    def unpin(self, chunk_id: str) -> None:
        with self._lock:
            self._pinned.discard(chunk_id)

    def invalidate(self, chunk_id: str) -> None:
        with self._lock:
            self._pinned.discard(chunk_id)
            entry = self._entries.pop(chunk_id, None)
            if entry is not None:
                self._used -= entry[1]
