"""Row formats: serialize/parse rowsets as yson / json / dsv / schemaful_dsv.

Ref: yt/yt/client/formats + library/formats — format objects convert between
wire bytes and rows for table IO and job IO.  The same four format names are
accepted by `YtClient.read_table(..., format=)` / `write_table(..., format=)`.
"""

from __future__ import annotations

import json
from typing import Optional, Sequence

from ytsaurus_tpu import yson
from ytsaurus_tpu.errors import EErrorCode, YtError


def _to_jsonable(value):
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    if isinstance(value, dict):
        return {k: _to_jsonable(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_to_jsonable(v) for v in value]
    return value


def _dsv_escape(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\t", "\\t") \
        .replace("\n", "\\n").replace("=", "\\=")


def _dsv_unescape(text: str) -> str:
    out = []
    i = 0
    while i < len(text):
        c = text[i]
        if c == "\\" and i + 1 < len(text):
            nxt = text[i + 1]
            out.append({"t": "\t", "n": "\n", "\\": "\\", "=": "="}.get(nxt, nxt))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _dsv_split(text: str, sep: str) -> list[str]:
    """Split on unescaped separators (backslash escapes survive)."""
    parts = []
    buf = []
    i = 0
    while i < len(text):
        c = text[i]
        if c == "\\" and i + 1 < len(text):
            buf.append(text[i:i + 2])
            i += 2
        elif c == sep:
            parts.append("".join(buf))
            buf = []
            i += 1
        else:
            buf.append(c)
            i += 1
    parts.append("".join(buf))
    return parts


def _dsv_split_kv(field: str) -> tuple[str, str]:
    """Split key=value on the first UNESCAPED '='."""
    i = 0
    while i < len(field):
        if field[i] == "\\":
            i += 2
        elif field[i] == "=":
            return field[:i], field[i + 1:]
        else:
            i += 1
    return field, ""


def _value_to_text(value) -> str:
    if value is None:
        return ""
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    if isinstance(value, bool):
        return "true" if value else "false"
    return str(value)


def dumps_rows(rows: Sequence[dict], format: str = "yson",
               columns: Optional[Sequence[str]] = None) -> bytes:
    """Serialize rows in the named format (list fragment semantics)."""
    if format == "yson":
        return b";".join(yson.dumps(row) for row in rows) + \
            (b";" if rows else b"")
    if format == "json":
        return b"\n".join(
            json.dumps(_to_jsonable(row), sort_keys=True).encode()
            for row in rows) + (b"\n" if rows else b"")
    if format == "dsv":
        lines = []
        for row in rows:
            fields = [f"{_dsv_escape(k)}={_dsv_escape(_value_to_text(v))}"
                      for k, v in row.items() if v is not None]
            lines.append("\t".join(fields))
        return ("\n".join(lines) + ("\n" if rows else "")).encode()
    if format == "schemaful_dsv":
        if not columns:
            raise YtError("schemaful_dsv requires a column list",
                          code=EErrorCode.QueryUnsupported)
        lines = []
        for row in rows:
            lines.append("\t".join(
                _dsv_escape(_value_to_text(row.get(c))) for c in columns))
        return ("\n".join(lines) + ("\n" if rows else "")).encode()
    raise YtError(f"Unknown format {format!r}",
                  code=EErrorCode.QueryUnsupported)


def loads_rows(data: bytes, format: str = "yson",
               columns: Optional[Sequence[str]] = None) -> list[dict]:
    """Parse rows from the named format."""
    if format == "yson":
        values = yson.loads(data, yson_type="list_fragment")
        for v in values:
            if not isinstance(v, dict):
                raise YtError(f"Expected map rows, got {type(v).__name__}")
        return values
    if format == "json":
        rows = []
        for line in data.splitlines():
            if line.strip():
                rows.append(json.loads(line))
        return rows
    if format == "dsv":
        rows = []
        for line in data.decode().splitlines():
            row = {}
            if line:
                for field in _dsv_split(line, "\t"):
                    if not field:
                        continue
                    key, value = _dsv_split_kv(field)
                    row[_dsv_unescape(key)] = _dsv_unescape(value)
            rows.append(row)
        return rows
    if format == "schemaful_dsv":
        if not columns:
            raise YtError("schemaful_dsv requires a column list",
                          code=EErrorCode.QueryUnsupported)
        rows = []
        for line in data.decode().splitlines():
            parts = line.split("\t")
            if len(parts) != len(columns):
                raise YtError(f"schemaful_dsv row width {len(parts)} != "
                              f"{len(columns)}")
            rows.append({c: _dsv_unescape(p)
                         for c, p in zip(columns, parts)})
        return rows
    raise YtError(f"Unknown format {format!r}",
                  code=EErrorCode.QueryUnsupported)
