"""The `yt` command-line interface.

Ref shape: yt/python/yt/wrapper/cli_impl.py — one binary, subcommand per
driver command, `--proxy` (or YT_PROXY env) selects the cluster, table
data flows through stdin/stdout in wire formats.

Usage (python -m ytsaurus_tpu.cli, or the `yt()` console entry):

  yt --proxy 127.0.0.1:9013 list /
  yt create map_node //home/me -r
  yt write-table //t --format json   < rows.json
  yt read-table //t --format dsv
  yt select-rows 'k, sum(v) AS s FROM [//t] GROUP BY k'
  yt map 'grep foo' --src //in --dst //out
  yt sort --src //in --dst //out --sort-by k
  yt start-tx / commit-tx / lock ...

The proxy address is the PRIMARY RPC endpoint (the thin-client plane);
`--user` stamps the authenticated principal.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional

from ytsaurus_tpu.errors import YtError


def _json_default(value):
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    return str(value)


def _print(value) -> None:
    if value is None:
        return
    if isinstance(value, bytes):
        sys.stdout.buffer.write(value)
        if not value.endswith(b"\n"):
            sys.stdout.buffer.write(b"\n")
        return
    print(json.dumps(value, default=_json_default, indent=2))


def _rows_arg(rows: Optional[str]):
    blob = rows.encode() if rows else sys.stdin.buffer.read()
    return blob


def _decode_deep(value):
    """Bytes → str recursively (orchid values round-tripped through the
    YSON wire carry byte strings)."""
    if isinstance(value, bytes):
        return value.decode("utf-8", "replace")
    if isinstance(value, dict):
        return {_decode_deep(k): _decode_deep(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_decode_deep(v) for v in value]
    return value


def _fetch_trace(cl, trace_id: str):
    """Span tree of one trace: the remote orchid (`/tracing/traces/<id>`
    — what the monitoring /traces endpoint also renders) when the client
    has one, else this process's own collector."""
    tree = None
    if hasattr(cl, "get_orchid"):
        try:
            tree = cl.get_orchid(f"/tracing/traces/{trace_id}")
        except YtError:
            tree = None
    if not tree:
        from ytsaurus_tpu.utils.tracing import span_tree
        tree = span_tree(trace_id)
    return _decode_deep(tree) if tree else None


def _fetch_accounting(cl) -> dict:
    """The /accounting snapshot: the remote orchid when the client has
    one (daemon-side usage), else this process's own accountant.  A
    FAILING remote read propagates — silently falling back to this
    short-lived process's empty accountant would print an all-zero
    table and read as "cluster idle" when the daemon is broken."""
    if hasattr(cl, "get_orchid"):
        return _decode_deep(cl.get_orchid("/accounting") or {})
    from ytsaurus_tpu.query.accounting import get_accountant
    return get_accountant().snapshot()


# The `yt top` table columns (a readable subset of USAGE_FIELDS).
_TOP_COLUMNS = ("queries", "lookups", "rows_read", "bytes_read",
                "compile_seconds", "execute_seconds", "wall_seconds",
                "throttled", "jobs")

# Fair-share columns appended when --by pool (ISSUE 17): the admission
# controller's live allocation next to the historical usage — share is
# the pool's fair allocation in slots, use its running queries, demand
# running + queued.  demand >> share is the "who is being squeezed"
# signal the brown-out ladder and the SLO bench act on.
_FAIR_COLUMNS = ("share", "use", "demand")


def _serving_pool_rollup(gateways: list) -> dict:
    """Aggregate per-pool fair-share state across the live gateways."""
    rollup: dict = {}
    for gw in gateways or []:
        admission = (gw or {}).get("admission") or {}
        for name, pool in (admission.get("pools") or {}).items():
            agg = rollup.setdefault(
                name, {"share": 0.0, "use": 0, "demand": 0})
            agg["share"] += float(pool.get("fair_slots", 0.0))
            agg["use"] += int(pool.get("in_flight", 0))
            agg["demand"] += int(pool.get("demand",
                                          pool.get("in_flight", 0) +
                                          pool.get("waiting", 0)))
    return rollup


def _format_top(snapshot: dict, by: str, sort_key: str,
                limit: int, serving: Optional[dict] = None) -> str:
    """`yt top --by pool`: per-tenant resource usage, heaviest first —
    the serving-plane answer to "who is eating the cluster"."""
    rollup = dict(snapshot.get(f"by_{by}") or {})
    fair = _serving_pool_rollup((serving or {}).get("gateways")) \
        if by == "pool" else {}
    # A pool can be queued (demand) before any query of it finishes
    # (usage) — fair-share-only pools still get a row.
    for name in fair:
        rollup.setdefault(name, {})
    rows = sorted(rollup.items(),
                  key=lambda kv: -float(kv[1].get(sort_key, 0.0)))
    if limit > 0:
        rows = rows[:limit]
    totals = snapshot.get("totals") or {}

    def fmt(record, field):
        value = float(record.get(field, 0.0))
        if field.endswith("_seconds"):
            return f"{value:.3f}"
        if field == "bytes_read":
            return f"{value / 1e6:.1f}MB" if value >= 1e6 \
                else f"{value:.0f}"
        return f"{value:.0f}"

    def fair_cells(name):
        if not fair:
            return []
        pool = fair.get(name)
        if pool is None:
            return ["-"] * len(_FAIR_COLUMNS)
        return [f"{pool['share']:.2f}", f"{pool['use']:.0f}",
                f"{pool['demand']:.0f}"]

    fair_header = list(_FAIR_COLUMNS) if fair else []
    header = [by, *_TOP_COLUMNS, *fair_header]
    table = [[name, *[fmt(record, f) for f in _TOP_COLUMNS],
              *fair_cells(name)]
             for name, record in rows]
    fair_totals = []
    if fair:
        fair_totals = [
            f"{sum(p['share'] for p in fair.values()):.2f}",
            f"{sum(p['use'] for p in fair.values()):.0f}",
            f"{sum(p['demand'] for p in fair.values()):.0f}"]
    table.append(["TOTAL", *[fmt(totals, f) for f in _TOP_COLUMNS],
                  *fair_totals])
    widths = [max(len(str(row[i])) for row in [header, *table])
              for i in range(len(header))]
    lines = ["  ".join(str(cell).rjust(width)
                       for cell, width in zip(row, widths))
             for row in [header, *table]]
    return "\n".join(lines)


def _fetch_serving(cl) -> dict:
    """The /serving snapshot (fair-share admission state) for the
    `yt top --by pool` share/use/demand columns.  Best-effort: a
    cluster without a serving plane just drops the columns — usage
    history still renders."""
    try:
        if hasattr(cl, "get_orchid"):
            return _decode_deep(cl.get_orchid("/serving") or {})
        from ytsaurus_tpu.query.serving import serving_snapshot
        return {"gateways": serving_snapshot()}
    except Exception:   # noqa: BLE001 — the fair-share columns are an
        # overlay on the usage table, not the table itself.
        return {}


def _fetch_workload(cl) -> dict:
    """The /workload snapshot: remote orchid when the client has one,
    else this process's own workload log (same propagate-don't-mask
    policy as `yt top`)."""
    if hasattr(cl, "get_orchid"):
        return _decode_deep(cl.get_orchid("/workload") or {})
    from ytsaurus_tpu.query.workload import get_workload_log
    return get_workload_log().snapshot()


def _fetch_compile(cl) -> dict:
    """The /compile snapshot (compilation observatory)."""
    if hasattr(cl, "get_orchid"):
        return _decode_deep(cl.get_orchid("/compile") or {})
    from ytsaurus_tpu.query.engine.evaluator import (
        get_compile_observatory,
    )
    return get_compile_observatory().snapshot()


def _fetch_mesh(cl) -> dict:
    """The /mesh snapshot (mesh execution observatory)."""
    if hasattr(cl, "get_orchid"):
        return _decode_deep(cl.get_orchid("/mesh") or {})
    from ytsaurus_tpu.parallel.mesh_observatory import (
        get_mesh_observatory,
    )
    return get_mesh_observatory().snapshot()


_COMPILE_TOP_COLUMNS = ("compiles", "hits", "disk_hits",
                        "compile_seconds", "shape_count", "evictions",
                        "last_miss_cause")


def _format_table(header: list, rows: list) -> str:
    table = [header, *rows]
    widths = [max(len(str(row[i])) for row in table)
              for i in range(len(header))]
    return "\n".join("  ".join(str(cell).rjust(width)
                               for cell, width in zip(row, widths))
                     for row in table)


def _format_compile_top(snapshot: dict, sort_key: str,
                        limit: int) -> str:
    """`yt compile-cache top`: fingerprints ranked by compile burn —
    the observability answer to "what is this fleet recompiling"."""
    rows = list(snapshot.get("fingerprints") or [])
    rows.sort(key=lambda r: -float(r.get(sort_key) or 0.0))
    if limit > 0:
        rows = rows[:limit]
    totals = snapshot.get("totals") or {}

    def fmt(record, field):
        value = record.get(field)
        if field == "compile_seconds":
            return f"{float(value or 0.0):.3f}"
        if field == "last_miss_cause":
            return str(value or "-")
        return f"{int(value or 0)}"

    body = [[r.get("fingerprint", "?"),
             *[fmt(r, f) for f in _COMPILE_TOP_COLUMNS]] for r in rows]
    lines = [_format_table(["fingerprint", *_COMPILE_TOP_COLUMNS],
                           body)]
    lines.append(f"totals: {int(totals.get('hits', 0))} hits / "
                 f"{int(totals.get('misses', 0))} misses / "
                 f"{int(totals.get('evictions', 0))} evictions over "
                 f"{int(totals.get('fingerprints', 0))} fingerprints")
    disk = snapshot.get("disk")
    if disk:
        lines.append(
            f"disk tier: {int(disk.get('hits', 0))} hits / "
            f"{int(disk.get('misses', 0))} misses / "
            f"{int(disk.get('errors', 0))} errors; "
            f"{int(disk.get('files', 0))} artifacts, "
            f"{int(disk.get('bytes', 0))} bytes "
            f"(cap {int(disk.get('capacity_bytes', 0))}) "
            f"at {disk.get('dir')}")
    # Captured XLA artifacts (behind WorkloadConfig.capture_artifacts):
    # local AND SPMD executables with their cost_analysis FLOPs/bytes
    # (ISSUE 20 — fused/stitched programs stopped showing up blank).
    artifacts = snapshot.get("artifacts") or []
    if artifacts:
        lines.append("artifacts:")

        def num(value):
            return "-" if value is None else f"{int(float(value))}"

        lines.append(_format_table(
            ["fingerprint", "flops", "bytes_accessed",
             "compile_seconds"],
            [[art.get("fingerprint", "?"), num(art.get("flops")),
              num(art.get("bytes_accessed")),
              f"{float(art.get('compile_seconds') or 0.0):.3f}"]
             for art in artifacts]))
    return "\n".join(lines)


_MESH_TOP_SORT = {"skew": "skew_max", "bytes": "exchange_bytes",
                  "memory": "memory_watermark_bytes"}

_MESH_TOP_COLUMNS = ("path", "shards", "executions", "skew_max",
                     "exchange_bytes", "quota_headroom",
                     "memory_watermark_bytes", "drift_max", "skewed")


def _format_mesh_top(snapshot: dict, sort_key: str, limit: int) -> str:
    """`yt mesh top`: SPMD program fingerprints ranked by shard skew /
    exchange bytes / memory watermark — the observability answer to
    "which program is hot and where"."""
    field = _MESH_TOP_SORT.get(sort_key, sort_key)
    rows = list(snapshot.get("programs") or [])
    rows.sort(key=lambda r: -float(r.get(field) or 0.0))
    if limit > 0:
        rows = rows[:limit]

    def fmt(record, col):
        value = record.get(col)
        if col == "path":
            return str(value or "-")
        if col in ("skew_max", "quota_headroom", "drift_max"):
            return f"{float(value or 0.0):.3f}"
        return f"{int(value or 0)}"

    body = [[r.get("fingerprint", "?"),
             *[fmt(r, col) for col in _MESH_TOP_COLUMNS]] for r in rows]
    totals = snapshot.get("totals") or {}
    lines = [_format_table(["fingerprint", *_MESH_TOP_COLUMNS], body)]
    lines.append(
        f"totals: {int(totals.get('executions', 0))} executions "
        f"({int(totals.get('balanced', 0))} balanced / "
        f"{int(totals.get('skewed', 0))} skewed) over "
        f"{int(totals.get('programs', 0))} programs, "
        f"{int(totals.get('compiled', 0))} compile captures")
    return "\n".join(lines)


def _format_prewarm_report(report: dict) -> str:
    lines = [
        f"prewarmed {report.get('capture', '<capture>')}: "
        f"{report.get('compiled', 0)} compiled, "
        f"{report.get('aot_hits', 0)} AOT hits, "
        f"{report.get('already_cached', 0)} already cached "
        f"({report.get('seconds', 0.0):.3f}s compile+load)",
        f"records: {report.get('records', 0)} selects, "
        f"{report.get('skipped', 0)} skipped",
    ]
    reasons = report.get("skip_reasons") or {}
    if reasons:
        lines.append("skips: " + ", ".join(
            f"{why} {n}" for why, n in sorted(reasons.items())))
    return "\n".join(lines)


def _format_replay_report(report: dict) -> str:
    lat = report.get("latency") or {}
    cache = report.get("compile_cache") or {}

    def rate(value):
        return "n/a" if value is None else f"{value * 100:.2f}%"

    lines = [
        f"replayed {report.get('queries', 0)} queries in "
        f"{report.get('elapsed_seconds', 0.0):.3f}s "
        f"(offered {report.get('offered_rate') or 'max'}/s, achieved "
        f"{report.get('achieved_rate')}/s)",
        f"outcomes: {report.get('ok', 0)} ok, "
        f"{report.get('throttled', 0)} throttled, "
        f"{report.get('deadline', 0)} deadline, "
        f"{report.get('error', 0)} error",
        f"latency: p50 {lat.get('p50_ms', 0)}ms  p99 "
        f"{lat.get('p99_ms', 0)}ms  p999 {lat.get('p999_ms', 0)}ms  "
        f"max {lat.get('max_ms', 0)}ms",
        f"compile cache: {cache.get('hits', 0)} hits / "
        f"{cache.get('misses', 0)} misses "
        f"({cache.get('disk_hits', 0)} disk hits, "
        f"{cache.get('fresh_compiles', 0)} fresh compiles; "
        f"hit rate {rate(cache.get('hit_rate'))}, steady-state "
        f"{rate(cache.get('steady_hit_rate'))})",
    ]
    slowest = report.get("slowest") or []
    if slowest:
        lines.append("slowest (trace ids -> /traces or `yt trace`):")
        for entry in slowest:
            lines.append(
                f"  {entry.get('wall_ms', 0)}ms  "
                f"trace={entry.get('trace_id') or '<unsampled>'}  "
                f"[{entry.get('outcome')}] {entry.get('query')}")
    return "\n".join(lines)


def _format_profile(profile) -> str:
    """ExecutionProfile object (in-process client) OR its dict form
    (remote client / HTTP proxy) → the pretty EXPLAIN ANALYZE text, via
    the one shared renderer in query/profile.py."""
    if hasattr(profile, "format"):
        return profile.format()
    from ytsaurus_tpu.query.profile import format_profile_dict
    return format_profile_dict(_decode_deep(dict(profile)))


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog="yt")
    parser.add_argument("--proxy", default=os.environ.get("YT_PROXY"),
                        help="primary address host:port (env YT_PROXY)")
    parser.add_argument("--user", default=os.environ.get("YT_USER", "root"))
    sub = parser.add_subparsers(dest="subcommand", required=True)

    def cmd(name, *args_defs, **kw):
        p = sub.add_parser(name, **kw)
        for flags, opts in args_defs:
            p.add_argument(*flags, **opts)
        return p

    cmd("list", (("path",), {"nargs": "?", "default": "/"}))
    cmd("get", (("path",), {}))
    cmd("set", (("path",), {}), (("value",), {}))
    cmd("exists", (("path",), {}))
    cmd("create", (("type",), {}), (("path",), {}),
        (("-r", "--recursive"), {"action": "store_true"}),
        (("-i", "--ignore-existing"), {"action": "store_true"}),
        (("--attributes",), {"default": None}))
    cmd("remove", (("path",), {}),
        (("-f", "--force"), {"action": "store_true"}))
    cmd("copy", (("src",), {}), (("dst",), {}),
        (("-r", "--recursive"), {"action": "store_true"}))
    cmd("move", (("src",), {}), (("dst",), {}),
        (("-r", "--recursive"), {"action": "store_true"}))
    cmd("link", (("target",), {}), (("link",), {}))
    cmd("write-table", (("path",), {}),
        (("--format",), {"default": "json"}),
        (("--append",), {"action": "store_true"}),
        (("--rows",), {"default": None, "help": "inline rows (else stdin)"}))
    cmd("read-table", (("path",), {}), (("--format",), {"default": "json"}))
    cmd("select-rows", (("query",), {}),
        (("--explain-analyze",), {"action": "store_true",
                                  "help": "print the per-query "
                                          "ExecutionProfile (wall/"
                                          "compile/execute split + span "
                                          "tree) instead of rows"}),
        (("--param",), {"action": "append", "default": None,
                        "dest": "params",
                        "help": "bind the next `?` placeholder (JSON "
                                "value; a JSON list binds a query "
                                "vector); repeat per placeholder"}))
    cmd("nearest-rows", (("path",), {}), (("column",), {}),
        (("query_vector",), {"help": "JSON list of floats"}),
        (("k",), {"type": int}),
        (("--metric",), {"default": "l2",
                         "choices": ["l2", "cosine", "dot"]}))
    cmd("trace", (("trace_id",), {}),
        (("--json",), {"action": "store_true",
                       "help": "raw span tree instead of the pretty "
                               "rendering"}))
    cmd("top", (("--by",), {"default": "pool",
                            "choices": ["pool", "user"],
                            "help": "roll resource usage up by pool "
                                    "(default) or user"}),
        (("--sort",), {"default": "wall_seconds",
                       "help": "usage column to sort by (descending); "
                               "e.g. rows_read, bytes_read, queries"}),
        (("--limit",), {"type": int, "default": 20}),
        (("--json",), {"action": "store_true",
                       "help": "raw accounting snapshot instead of the "
                               "table"}))
    cmd("workload", (("action",), {"choices": ["capture", "export",
                                               "import", "show"],
                                   "help": "capture: pull the cluster's "
                                           "workload log into --out; "
                                           "export: this process's log; "
                                           "import: load a capture into "
                                           "the local log; show: "
                                           "fingerprint roll-up"}),
        (("--out",), {"default": None,
                      "help": "capture file to write (capture/export)"}),
        (("--file",), {"default": None,
                       "help": "capture file to read (import)"}),
        (("--limit",), {"type": int, "default": 0,
                        "help": "cap records written/shown (0 = all "
                                "retained)"}),
        (("--json",), {"action": "store_true"}))
    cmd("replay", (("--capture",), {"required": True,
                                    "help": "versioned workload capture "
                                            "(yt workload capture/"
                                            "export)"}),
        (("--speed",), {"type": float, "default": 1.0,
                        "help": "time-compression of the recorded "
                                "inter-arrival spacing"}),
        (("--rate",), {"type": float, "default": None,
                       "help": "fixed open-loop offered rate (qps); "
                               "overrides recorded spacing"}),
        (("--limit",), {"type": int, "default": 0,
                        "help": "replay only the first N records"}),
        (("--workers",), {"type": int, "default": 16}),
        (("--pool",), {"default": None}),
        (("--timeout",), {"type": float, "default": None}),
        (("--json",), {"action": "store_true",
                       "help": "raw report instead of the pretty "
                               "rendering"}))
    cmd("prewarm", (("--capture",), {"required": True,
                                     "help": "versioned workload capture "
                                             "to replay COMPILE-ONLY "
                                             "(ISSUE 18): every distinct "
                                             "program the capture "
                                             "implies compiles into the "
                                             "memory/disk/cluster AOT "
                                             "tiers without executing a "
                                             "query"}),
        (("--limit",), {"type": int, "default": 0,
                        "help": "prewarm only the first N select "
                                "records (0 = all)"}),
        (("--json",), {"action": "store_true"}))
    cmd("analyze",
        # No `choices` here: the pass registry lives in tools/analyze
        # (PASSES); the driver validates, so a new pass needs no CLI
        # lockstep edit.
        (("--pass",), {"dest": "passes", "action": "append",
                       "default": None,
                       "help": "run only this pass (repeatable; "
                               "default: all — locks, guards, jax, "
                               "coverage, errors, sensors; guards = "
                               "annotation-free lock-guard inference + "
                               "atomicity lint + annotation drift, "
                               "rules guard-inference/guard-read/"
                               "atomicity/guard-drift)"}),
        (("--json",), {"action": "store_true",
                       "help": "machine-readable findings (pass, rule, "
                               "path, line, message, severity) + "
                               "ratchet verdict + lock-order graph + "
                               "the guards reconciliation graph "
                               "(inferred locks, superset edges, "
                               "sanitizer site map)"}),
        (("--update-baseline",), {"action": "store_true",
                                  "help": "rewrite tools/analyze/"
                                          "baseline.json to the current "
                                          "counts (tighten the ratchet "
                                          "AFTER fixing findings)"}),
        (("--no-baseline",), {"action": "store_true",
                              "help": "report raw findings instead of "
                                      "the ratchet verdict"}),
        (("--analyze-root",), {"default": None,
                               "help": "repo root to analyze (default: "
                                       "the installed tree)"}))
    cmd("view", (("action",), {"choices": ["create", "list", "show",
                                           "pause", "resume", "remove",
                                           "refresh"],
                               "help": "continuous queries (ISSUE 13): "
                                       "create registers an incremental "
                                       "materialized view over an "
                                       "ordered table; list/show read "
                                       "the registry + lag/freshness; "
                                       "pause/resume gate the daemon; "
                                       "refresh drains the cursor "
                                       "inline"}),
        (("name",), {"nargs": "?", "default": None}),
        (("--query",), {"default": None,
                        "help": "view QL (create), e.g. 'g, sum(v) AS "
                                "s FROM [//q] GROUP BY g'"}),
        (("--source",), {"default": None,
                         "help": "ordered source table (defaults to "
                                 "the query's FROM table)"}),
        (("--target",), {"default": None,
                         "help": "sorted target table (default: "
                                 "//sys/views/<name>/target)"}),
        (("--pool",), {"default": "views",
                       "help": "resource pool the refresh work is "
                               "accounted under"}),
        (("--batch-rows",), {"type": int, "default": None}),
        (("--max-batches",), {"type": int, "default": 0,
                              "help": "refresh: cap drained batches "
                                      "(0 = to the head)"}),
        (("--drop-target",), {"action": "store_true",
                              "help": "remove: also drop the target "
                                      "table"}),
        (("--json",), {"action": "store_true"}))
    cmd("compile-cache", (("action",), {"choices": ["top"]}),
        (("--limit",), {"type": int, "default": 20}),
        (("--sort",), {"default": "compile_seconds",
                       "help": "observatory column to rank by "
                               "(descending); e.g. compiles, "
                               "shape_count, evictions"}),
        (("--json",), {"action": "store_true"}))
    cmd("mesh", (("action",), {"choices": ["top"]}),
        (("--limit",), {"type": int, "default": 20}),
        (("--sort",), {"default": "skew",
                       "help": "rank programs by skew | bytes | memory "
                               "(or any roll-up column, e.g. "
                               "executions, drift_max)"}),
        (("--json",), {"action": "store_true"}))
    cmd("insert-rows", (("path",), {}),
        (("--rows",), {"default": None}))
    cmd("lookup-rows", (("path",), {}), (("--keys",), {"required": True}))
    cmd("mount-table", (("path",), {}))
    cmd("unmount-table", (("path",), {}))
    cmd("map", (("mapper_command",), {}),
        (("--src",), {"required": True}), (("--dst",), {"required": True}),
        (("--format",), {"default": "json"}),
        (("--pool",), {"default": "default"}),
        (("--job-count",), {"type": int, "default": None}))
    cmd("sort", (("--src",), {"required": True}),
        (("--dst",), {"required": True}),
        (("--sort-by",), {"required": True,
                          "help": "comma-separated key columns"}))
    cmd("reduce", (("reducer_command",), {}),
        (("--src",), {"required": True}), (("--dst",), {"required": True}),
        (("--reduce-by",), {"required": True,
                            "help": "comma-separated key columns"}),
        (("--sort-by",), {"default": None}),
        (("--format",), {"default": "json"}),
        (("--job-count",), {"type": int, "default": None}))
    cmd("map-reduce", (("reducer_command",), {}),
        (("--mapper-command",), {"default": None}),
        (("--src",), {"required": True}), (("--dst",), {"required": True}),
        (("--reduce-by",), {"required": True}),
        (("--sort-by",), {"default": None}),
        (("--partition-count",), {"type": int, "default": None}),
        (("--format",), {"default": "json"}))
    cmd("merge", (("--src",), {"required": True,
                               "help": "comma-separated input tables"}),
        (("--dst",), {"required": True}),
        (("--mode",), {"default": "unordered"}))
    cmd("erase", (("path",), {}))
    cmd("vanilla", (("--tasks",), {"required": True,
                                   "help": "JSON: {name: {job_count, "
                                           "command}}"}),
        (("--max-gang-restarts",), {"type": int, "default": 2}))
    cmd("remote-copy", (("--cluster",), {"required": True,
                                         "help": "source cluster "
                                                 "host:port"}),
        (("--src",), {"required": True}), (("--dst",), {"required": True}))
    cmd("abort-op", (("op_id",), {}))
    cmd("start-tx")
    cmd("commit-tx", (("tx",), {}))
    cmd("abort-tx", (("tx",), {}))
    cmd("lock", (("path",), {}), (("--tx",), {"required": True}),
        (("--mode",), {"default": "exclusive"}))
    cmd("create-user", (("name",), {}))
    cmd("create-account", (("name",), {}))
    cmd("check-permission", (("user",), {}), (("permission",), {}),
        (("path",), {}))
    cmd("get-operation", (("op_id",), {}))
    cmd("orchid", (("path",), {"nargs": "?", "default": "/"}))
    return parser


def _run_analyze(a) -> int:
    """`yt analyze`: the static-analysis suite (tools/analyze), run
    OFFLINE — no proxy, no cluster, no jax import.  The analyzer is
    loaded from the repo checkout next to this package."""
    import importlib.util
    repo = a.analyze_root or os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    driver = os.path.join(repo, "tools", "analyze", "__main__.py")
    if not os.path.exists(driver):
        print(f"error: analyzer not found at {driver} (run from a "
              f"repo checkout, or pass --analyze-root)", file=sys.stderr)
        return 2
    if repo not in sys.path:
        sys.path.insert(0, repo)
    spec = importlib.util.spec_from_file_location("yt_analyze_main",
                                                  driver)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    argv = ["--root", repo]
    for name in a.passes or []:
        argv += ["--pass", name]
    if a.json:
        argv.append("--json")
    if a.update_baseline:
        argv.append("--update-baseline")
    if a.no_baseline:
        argv.append("--no-baseline")
    return mod.main(argv)


# Subcommands that run locally, without a cluster connection.
_OFFLINE_COMMANDS = {"analyze"}


def run(argv: "list[str] | None" = None,
        client=None) -> int:
    args = build_parser().parse_args(argv)
    if args.subcommand in _OFFLINE_COMMANDS:
        return _run_analyze(args)
    caller_owns_client = client is not None
    if client is None:
        if not args.proxy:
            print("error: --proxy (or YT_PROXY) is required",
                  file=sys.stderr)
            return 2
        # The thin client never needs the accelerator; pin the platform
        # BEFORE any lazy jax import (env alone is insufficient when an
        # accelerator plugin is pre-registered — a dead tunnel would hang
        # the CLI).  YT_CLI_PLATFORM overrides for on-device operations.
        import jax
        jax.config.update("jax_platforms",
                          os.environ.get("YT_CLI_PLATFORM", "cpu"))
        from ytsaurus_tpu.remote_client import RemoteYtClient
        client = RemoteYtClient(args.proxy, user=args.user)
    try:
        _print(_dispatch(client, args))
        return 0
    except YtError as err:
        print(json.dumps(err.to_dict(), default=_json_default),
              file=sys.stderr)
        return 1
    finally:
        if not caller_owns_client and hasattr(client, "close"):
            client.close()


def _dispatch(cl, a):
    c = a.subcommand
    if c == "list":
        return cl.list(a.path)
    if c == "get":
        return cl.get(a.path)
    if c == "set":
        return cl.set(a.path, json.loads(a.value))
    if c == "exists":
        return cl.exists(a.path)
    if c == "create":
        attributes = json.loads(a.attributes) if a.attributes else None
        return cl.create(a.type, a.path, attributes=attributes,
                         recursive=a.recursive,
                         ignore_existing=a.ignore_existing)
    if c == "remove":
        return cl.remove(a.path, force=a.force)
    if c == "copy":
        return cl.copy(a.src, a.dst, recursive=a.recursive)
    if c == "move":
        return cl.move(a.src, a.dst, recursive=a.recursive)
    if c == "link":
        return cl.link(a.target, a.link)
    if c == "write-table":
        return cl.write_table(a.path, _rows_arg(a.rows), format=a.format,
                              append=a.append)
    if c == "read-table":
        return cl.read_table(a.path, format=a.format)
    if c == "select-rows":
        params = [json.loads(p) for p in a.params] if a.params else None
        if a.explain_analyze:
            profile = cl.select_rows(a.query, explain_analyze=True,
                                     params=params)
            print(_format_profile(profile))
            return None
        return cl.select_rows(a.query, params=params)
    if c == "nearest-rows":
        return cl.nearest_rows(a.path, a.column,
                               json.loads(a.query_vector), a.k,
                               metric=a.metric)
    if c == "trace":
        tree = _fetch_trace(cl, a.trace_id)
        if not tree:
            raise YtError(f"no such trace {a.trace_id!r} "
                          "(unsampled, evicted, or wrong cluster)")
        if a.json:
            return tree
        from ytsaurus_tpu.query.profile import format_span_tree
        print(f"trace {a.trace_id}")
        print("\n".join(format_span_tree(tree)))
        return None
    if c == "top":
        snapshot = _fetch_accounting(cl)
        serving = _fetch_serving(cl) if a.by == "pool" else None
        if a.json:
            if serving:
                snapshot = dict(snapshot)
                snapshot["serving"] = serving
            return snapshot
        print(_format_top(snapshot, a.by, a.sort, a.limit, serving))
        return None
    if c == "workload":
        from ytsaurus_tpu.query import workload as wl
        if a.action in ("capture", "export"):
            if not a.out:
                raise YtError("workload capture/export requires --out")
            if a.action == "capture":
                snapshot = _fetch_workload(cl)
                records = [wl.WorkloadRecord.from_dict(r)
                           for r in snapshot.get("records") or []]
            else:
                records = wl.get_workload_log().records()
            written = wl.write_capture(a.out, records,
                                       limit=a.limit or None)
            return {"written": written, "path": a.out}
        if a.action == "import":
            if not a.file:
                raise YtError("workload import requires --file")
            return {"imported":
                    wl.get_workload_log().import_capture(a.file)}
        snapshot = _fetch_workload(cl)            # show
        if a.json:
            return snapshot
        rows = snapshot.get("fingerprints") or []
        if a.limit:
            rows = rows[:a.limit]
        print(_format_table(
            ["fingerprint", "kind", "count", "ok", "throttled",
             "deadline", "errors", "wall_s", "compile_s", "query"],
            [[r.get("fingerprint"), r.get("kind"), r.get("count"),
              r.get("ok"), r.get("throttled"), r.get("deadline"),
              r.get("errors"),
              f"{float(r.get('wall_seconds') or 0):.3f}",
              f"{float(r.get('compile_seconds') or 0):.3f}",
              str(r.get("query"))[:60]] for r in rows]))
        return None
    if c == "replay":
        from ytsaurus_tpu.query import workload as wl
        records = wl.load_capture(a.capture)   # fails loudly on version
        report = wl.replay(cl, records, speed=a.speed, rate=a.rate,
                           max_workers=a.workers, pool=a.pool,
                           timeout=a.timeout, limit=a.limit or None)
        if a.json:
            return report
        print(_format_replay_report(report))
        return None
    if c == "prewarm":
        # Compile-only capture replay (ISSUE 18): the caches being
        # warmed live in the SERVING process, so this needs an
        # in-process client (tests, embedded use, `yt ... --proxy`
        # pointing at a thin client cannot reach them).  Daemons warm
        # themselves at startup via YT_TPU_PREWARM_CAPTURE.
        if getattr(cl, "cluster", None) is None:
            raise YtError(
                "prewarm requires an in-process client: the compile "
                "caches live in the serving process.  Start the daemon "
                "with YT_TPU_PREWARM_CAPTURE=<capture> (or set "
                "tiering.prewarm_capture) to warm a replica at startup")
        from ytsaurus_tpu.query.engine.prewarm import prewarm_capture_file
        report = prewarm_capture_file(
            a.capture, client=cl,
            evaluator=cl.cluster.evaluator,
            limit=a.limit or None)
        if a.json:
            return report
        print(_format_prewarm_report(report))
        return None
    if c == "view":
        return _dispatch_view(cl, a)
    if c == "compile-cache":
        snapshot = _fetch_compile(cl)
        if a.json:
            return snapshot
        print(_format_compile_top(snapshot, a.sort, a.limit))
        return None
    if c == "mesh":
        snapshot = _fetch_mesh(cl)
        if a.json:
            return snapshot
        print(_format_mesh_top(snapshot, a.sort, a.limit))
        return None
    if c == "insert-rows":
        rows = json.loads(_rows_arg(a.rows))
        return cl.insert_rows(a.path, rows)
    if c == "lookup-rows":
        keys = [tuple(k) for k in json.loads(a.keys)]
        return cl.lookup_rows(a.path, keys)
    if c == "mount-table":
        return cl.mount_table(a.path)
    if c == "unmount-table":
        return cl.unmount_table(a.path)
    if c == "map":
        kw = {"format": a.format, "pool": a.pool}
        if a.job_count:
            kw["job_count"] = a.job_count
        op = cl.run_map(a.mapper_command, a.src, a.dst, **kw)
        return {"operation_id": op.id, "state": op.state}
    if c == "sort":
        op = cl.run_sort(a.src, a.dst, a.sort_by.split(","))
        return {"operation_id": op.id, "state": op.state}
    if c == "reduce":
        kw = {"format": a.format}
        if a.sort_by:
            kw["sort_by"] = a.sort_by.split(",")
        if a.job_count:
            kw["job_count"] = a.job_count
        op = cl.run_reduce(a.reducer_command, a.src, a.dst,
                           reduce_by=a.reduce_by.split(","), **kw)
        return {"operation_id": op.id, "state": op.state}
    if c == "map-reduce":
        kw = {"format": a.format}
        if a.sort_by:
            kw["sort_by"] = a.sort_by.split(",")
        if a.partition_count:
            kw["partition_count"] = a.partition_count
        op = cl.run_map_reduce(a.mapper_command, a.reducer_command,
                               a.src, a.dst,
                               reduce_by=a.reduce_by.split(","), **kw)
        return {"operation_id": op.id, "state": op.state}
    if c == "merge":
        op = cl.run_merge(a.src.split(","), a.dst, mode=a.mode)
        return {"operation_id": op.id, "state": op.state}
    if c == "erase":
        op = cl.run_erase(a.path)
        return {"operation_id": op.id, "state": op.state}
    if c == "vanilla":
        op = cl.run_vanilla(json.loads(a.tasks),
                            max_gang_restarts=a.max_gang_restarts)
        return {"operation_id": op.id, "state": op.state,
                "result": op.result}
    if c == "remote-copy":
        op = cl.run_remote_copy(a.cluster, a.src, a.dst)
        return {"operation_id": op.id, "state": op.state,
                "result": op.result}
    if c == "abort-op":
        op = cl.abort_operation(a.op_id)
        return {"operation_id": op.id, "state": op.state}
    if c == "start-tx":
        return cl.start_tx()
    if c == "commit-tx":
        return cl.commit_tx(a.tx)
    if c == "abort-tx":
        return cl.abort_tx(a.tx)
    if c == "lock":
        return cl.lock(a.path, mode=a.mode, tx=a.tx)
    if c == "create-user":
        return cl.create_user(a.name)
    if c == "create-account":
        return cl.create_account(a.name)
    if c == "check-permission":
        return cl.check_permission(a.user, a.permission, a.path)
    if c == "get-operation":
        return cl._execute("get_operation", {"operation_id": a.op_id})
    if c == "orchid":
        return cl.get_orchid(a.path)
    raise AssertionError(c)


def _dispatch_view(cl, a):
    """`yt view <action>` — the continuous-query verbs."""
    def require_name():
        if not a.name:
            raise YtError(f"view {a.action} requires a view name")
        return a.name

    if a.action == "create":
        if not a.query:
            raise YtError("view create requires --query")
        return cl.create_materialized_view(
            require_name(), a.query, source=a.source, target=a.target,
            pool=a.pool, batch_rows=a.batch_rows)
    if a.action == "list":
        statuses = []
        for name in cl.list_views():
            try:
                statuses.append(cl.get_view(name))
            except YtError as err:
                # One broken view (dropped source, unmounted tablet)
                # must not hide the registry — least of all the entry
                # the operator wants to remove.  JSON keeps the error
                # in its own field; placeholders are render-only.
                statuses.append({"name": name, "error": str(err)})
        if a.json:
            return statuses
        print(_format_table(
            ["view", "state", "source", "target", "offset", "lag",
             "pool"],
            [[s["name"], s.get("state", "error"),
              s.get("source", s.get("error", "")[:60]),
              s.get("target", "-"), s.get("offset", "-"),
              s.get("lag_rows", "-"), s.get("pool", "-")]
             for s in statuses]))
        return None
    if a.action == "show":
        return cl.get_view(require_name())
    if a.action == "pause":
        return cl.pause_view(require_name())
    if a.action == "resume":
        return cl.resume_view(require_name())
    if a.action == "remove":
        cl.remove_view(require_name(), drop_target=a.drop_target)
        return {"removed": a.name}
    if a.action == "refresh":
        return cl.refresh_view(require_name(),
                               max_batches=a.max_batches)
    raise AssertionError(a.action)


def main() -> None:
    raise SystemExit(run())


if __name__ == "__main__":
    main()
