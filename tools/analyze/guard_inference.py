"""Inferred guard-discipline + atomicity pass (`yt analyze --pass guards`).

PR 8's `locks` pass checks only what someone remembered to annotate —
11 modules carry `# guards:` comments, the other ~180 files are
invisible to it.  This pass is the annotation-FREE complement
(RacerD-shaped, after the Facebook Infer analysis): for every class in
the tree it discovers the lock fields, propagates held-lock sets
through the intra-class call graph, classifies every `self._field`
access site as locked/unlocked, and infers the guard relation from the
evidence — a field written under a lock at one site and mutated without
it at another is a finding, no annotation required.

Inference model
---------------
  locks      `self._x = threading.Lock()/RLock()/Condition()` (plus the
             sanitizer registration helper `register_lock(...)` and
             module-level `_LOCK = threading.Lock()`).
  held sets  syntactic `with <lock>:` scopes, UNIONED with the method's
             inferred ENTRY context: a private method (leading `_`, only
             ever invoked as `self.m(...)` inside its class) inherits the
             INTERSECTION of the lock sets held at its call sites,
             fixpoint-iterated; a method that escapes — public name, or
             referenced as a value (`Thread(target=self._run)`, executor
             `submit(self._work)`, any callback registration: the
             thread-entry roots) — can assume nothing and enters with
             the empty set.
  evidence   the guard set of a field is the union of locks effectively
             held at its write sites.  Non-empty evidence makes every
             effectively-unlocked WRITE a `guard-inference` finding.
  escapes    `__init__` writes BEFORE the object escapes the
             constructor (self passed to a call, a bound method
             captured, a thread started) race with nobody and are
             exempt — and contribute no evidence.  Methods named
             `*_locked` document "caller holds the lock" (the PR 8
             convention): they enter with the full class lock set.

Rules
-----
  guard-inference  a write to an inferred-guarded field at a site whose
                   effective held-lock set misses every evidence lock.
  guard-read       an unlocked read of an inferred-guarded field from a
                   method that elsewhere USES a lock — the torn-read /
                   stale-read shape.  (Lock-free reads are sometimes
                   intentional: waive with a reason.)
  atomicity        check-then-act: a guarded read's result feeds a
                   guarded write in a DIFFERENT `with` region of the
                   same lock in the same function — the lock was
                   released between the check and the act, so the acted-
                   on value may be stale (the TOCTOU shape PR 6/8 kept
                   finding by hand).  Re-reading the field inside the
                   second region (double-checked locking) is exempt.
  guard-drift      a declared `# guards:` annotation the inference
                   contradicts: the annotated field's guarded accesses
                   all hold a DIFFERENT lock, or the field has no
                   post-construction access at all (stale annotation).

The runtime complement lives in `ytsaurus_tpu/utils/sanitizers.py`: the
instrumented-lock layer observes the DYNAMIC acquisition-order graph,
and tier-1 asserts it is a subgraph of `reconciliation_graph()` below —
any edge the AST propagation missed fails the build with stacks.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.analyze.core import (
    Finding,
    SourceFile,
    dotted_name,
    walk_functions,
)
from tools.analyze.lock_discipline import (
    MUTATORS,
    LockInfo,
    build_order_graph,
    collect_locks,
)

PASS_NAME = "guards"

# Constructor shapes that MAKE a lock: `threading.Lock()`,
# `threading.RLock()`, `Condition(...)`, plus the sanitizer registration
# helper (`sanitizers.register_lock("site", ...)` returns the lock it
# registers — plain or instrumented).
_LOCK_FACTORY_SUFFIXES = ("Lock", "RLock", "Condition", "Semaphore",
                          "BoundedSemaphore")
_REGISTER_HELPERS = {"register_lock", "register_rlock",
                     "register_condition"}

# Dunder methods are externally callable by definition; they get the
# empty entry context like any public method.


def _is_lock_ctor(value: ast.AST) -> "tuple[bool, Optional[str]]":
    """(is_lock, registered_site_name) for an assignment RHS."""
    if not isinstance(value, ast.Call):
        return False, None
    name = dotted_name(value.func).rsplit(".", 1)[-1]
    if name in _REGISTER_HELPERS:
        site = None
        if value.args and isinstance(value.args[0], ast.Constant) and \
                isinstance(value.args[0].value, str):
            site = value.args[0].value
        return True, site
    if name in _LOCK_FACTORY_SUFFIXES:
        return True, None
    return False, None


class InferredLock:
    """One discovered lock field (no annotation needed)."""

    __slots__ = ("path", "cls", "attr", "line", "site_name")

    def __init__(self, path: str, cls: Optional[str], attr: str,
                 line: int, site_name: Optional[str] = None):
        self.path = path
        self.cls = cls
        self.attr = attr
        self.line = line
        self.site_name = site_name      # sanitizers.register_lock name

    @property
    def node_id(self) -> str:
        scope = f"{self.cls}." if self.cls else ""
        return f"{self.path}::{scope}{self.attr}"


def collect_inferred_locks(f: SourceFile) -> "list[InferredLock]":
    """Every lock-typed field/global in a module, by constructor shape."""
    out: list[InferredLock] = []
    seen: set = set()

    def note(cls, attr, line, site):
        key = (cls, attr)
        if key not in seen:
            seen.add(key)
            out.append(InferredLock(f.path, cls, attr, line, site))

    for node in f.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            is_lock, site = _is_lock_ctor(node.value)
            if is_lock:
                note(None, node.targets[0].id, node.lineno, site)
        elif isinstance(node, ast.ClassDef):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assign) and \
                        len(sub.targets) == 1 and \
                        isinstance(sub.targets[0], ast.Attribute) and \
                        isinstance(sub.targets[0].value, ast.Name) and \
                        sub.targets[0].value.id == "self":
                    is_lock, site = _is_lock_ctor(sub.value)
                    if is_lock:
                        note(node.name, sub.targets[0].attr, sub.lineno,
                             site)
    return out


# -- per-function access walking -----------------------------------------------


class _Access:
    __slots__ = ("field", "kind", "line", "held", "method", "verb")

    def __init__(self, field, kind, line, held, method, verb=""):
        self.field = field
        self.kind = kind            # 'read' | 'write'
        self.line = line
        self.held = held            # frozenset of SYNTACTIC locks held
        self.method = method
        self.verb = verb


class _Region:
    """One `with <lock>:` region inside a function (atomicity lint)."""

    __slots__ = ("lock", "node", "start", "end", "reads", "writes",
                 "tainted", "cond_names")

    def __init__(self, lock, node, cond_names):
        self.lock = lock
        self.node = node
        self.start = node.lineno
        self.end = node.end_lineno or node.lineno
        self.reads: set[str] = set()        # guarded fields read
        self.writes: list = []              # (field, line, stmt_names)
        self.tainted: dict[str, set] = {}   # name -> source fields
        # Names appearing in enclosing if/while tests (with linenos) —
        # control dependence for the check-then-act detection.
        self.cond_names = cond_names        # list[(lineno, set[str])]


def _mutation_targets(node: ast.AST):
    """(field, is_self, verb, attr_node) mutations attributable to THIS
    node alone — assignment/augassign/del targets (subscripts peeled)
    and mutator-method receivers; mirrors lock_discipline's walker but
    keeps the node identity so reads can exclude write bases."""
    targets = []
    if isinstance(node, ast.Assign):
        targets = [(t, "assigned") for t in node.targets]
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [(node.target, "assigned")]
    elif isinstance(node, ast.Delete):
        targets = [(t, "deleted") for t in node.targets]
    elif isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in MUTATORS:
            targets = [(fn.value, f"mutated via .{fn.attr}()")]
    for target, verb in targets:
        while isinstance(target, ast.Subscript):
            target = target.value
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            yield target.attr, True, verb, target
        elif isinstance(target, ast.Name):
            yield target.id, False, verb, target


class _FunctionScan:
    """One walk of a function body: accesses with syntactic held sets,
    self-call sites, value-references to methods, and the `with` regions
    for the atomicity lint."""

    def __init__(self, f: SourceFile, cls: Optional[str], fn: ast.AST,
                 lock_attrs: "set[str]", mod_locks: "set[str]",
                 class_fields: "set[str]", mod_fields: "set[str]",
                 method_names: "set[str]"):
        self.f = f
        self.cls = cls
        self.fn = fn
        self.lock_attrs = lock_attrs
        self.mod_locks = mod_locks
        self.class_fields = class_fields
        self.mod_fields = mod_fields
        self.method_names = method_names
        self.accesses: list[_Access] = []
        self.call_sites: list[tuple[str, frozenset]] = []
        self.value_refs: set[str] = set()       # methods that escape here
        self.regions: list[_Region] = []
        # Plain assignments (names, line) anywhere in the function —
        # the atomicity lint's taint-kill set (a name REASSIGNED between
        # the check region and the act region no longer carries the
        # stale read).
        self.assignments: list[tuple[set, int]] = []
        self.mod_globals: set[str] = {
            n for node in ast.walk(fn) if isinstance(node, ast.Global)
            for n in node.names}
        self._held: list[str] = []
        self._write_nodes: set[int] = set()
        self._cond_stack: list[tuple[int, set]] = []
        self._region_stack: list[_Region] = []
        # Every call's OWN func node (any nesting depth): a `self.m`
        # that is some call's callee is a direct invocation, not a
        # bound-method capture.
        self._callee_nodes: set[int] = {
            id(c.func) for c in ast.walk(fn)
            if isinstance(c, ast.Call)}

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        name = dotted_name(expr)
        if name.startswith("self.") and name[5:] in self.lock_attrs:
            return name[5:]
        if name in self.mod_locks:
            return name
        return None

    def run(self) -> "_FunctionScan":
        for stmt in self.fn.body:
            self._visit(stmt)
        return self

    def _note_mutations(self, node: ast.AST) -> None:
        held = frozenset(self._held)
        for field, is_self, verb, target in _mutation_targets(node):
            self._write_nodes.add(id(target))
            if is_self and field in self.class_fields:
                acc = _Access(field, "write", node.lineno, held,
                              self.fn.name, verb)
            elif not is_self and field in self.mod_fields and \
                    (field in self.mod_globals or
                     verb.startswith("mutated")):
                acc = _Access(field, "write", node.lineno, held,
                              self.fn.name, verb)
            else:
                continue
            self.accesses.append(acc)
            if self._region_stack:
                region = self._region_stack[-1]
                names = {n.id for n in ast.walk(node)
                         if isinstance(n, ast.Name)}
                region.writes.append((field, node.lineno, names))

    def _note_reads(self, node: ast.AST) -> None:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and \
                isinstance(node.ctx, ast.Load) and \
                node.attr in self.class_fields and \
                id(node) not in self._write_nodes:
            self.accesses.append(_Access(
                node.attr, "read", node.lineno, frozenset(self._held),
                self.fn.name))
            if self._region_stack:
                self._region_stack[-1].reads.add(node.attr)

    def _note_region_taint(self, node: ast.AST) -> None:
        """Inside a region, `x = <expr reading guarded field>` taints x."""
        if isinstance(node, ast.Assign):
            names = {t.id for t in node.targets
                     if isinstance(t, ast.Name)}
            for t in node.targets:
                if isinstance(t, ast.Tuple):
                    names |= {e.id for e in t.elts
                              if isinstance(e, ast.Name)}
            if names:
                self.assignments.append((names, node.lineno))
        if not self._region_stack or not isinstance(node, ast.Assign):
            return
        fields = {n.attr for n in ast.walk(node.value)
                  if isinstance(n, ast.Attribute) and
                  isinstance(n.value, ast.Name) and n.value.id == "self"
                  and n.attr in self.class_fields}
        fields |= {n.id for n in ast.walk(node.value)
                   if isinstance(n, ast.Name) and n.id in self.mod_fields}
        if not fields:
            return
        region = self._region_stack[-1]
        for target in node.targets:
            if isinstance(target, ast.Name):
                region.tainted.setdefault(target.id, set()).update(fields)
            elif isinstance(target, ast.Tuple):
                for elt in target.elts:
                    if isinstance(elt, ast.Name):
                        region.tainted.setdefault(elt.id,
                                                  set()).update(fields)

    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not self.fn:
            # Nested defs are separate dynamic scopes — but a reference
            # to self.m inside one still escapes m (callback capture).
            for sub in ast.walk(node):
                self._note_value_ref(sub)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: list[str] = []
            opened: list[_Region] = []
            for item in node.items:
                for sub in ast.walk(item.context_expr):
                    self._note_mutations(sub)
                    self._note_reads(sub)
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    acquired.append(lock)
                    region = _Region(lock, node,
                                     list(self._cond_stack))
                    self.regions.append(region)
                    opened.append(region)
            self._held.extend(acquired)
            self._region_stack.extend(opened)
            for stmt in node.body:
                self._visit(stmt)
            del self._held[len(self._held) - len(acquired):]
            del self._region_stack[len(self._region_stack) - len(opened):]
            return
        if isinstance(node, (ast.If, ast.While)):
            names = {n.id for n in ast.walk(node.test)
                     if isinstance(n, ast.Name)}
            for sub in ast.walk(node.test):
                self._note_mutations(sub)
                self._note_reads(sub)
                self._note_call(sub)
                self._note_value_ref(sub)
            self._cond_stack.append((node.lineno, names))
            for stmt in [*node.body, *node.orelse]:
                self._visit(stmt)
            self._cond_stack.pop()
            return
        self._note_mutations(node)
        self._note_reads(node)
        self._note_call(node)
        self._note_value_ref(node)
        self._note_region_taint(node)
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    def _note_call(self, node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name.startswith("self.") and "." not in name[5:] and \
                    name[5:] in self.method_names:
                self.call_sites.append((name[5:], frozenset(self._held)))

    def _note_value_ref(self, node: ast.AST) -> None:
        """`self.m` used as a VALUE (not the callee of a direct call):
        thread targets, executor submits, stored callbacks — including
        plain assignment capture (`self._cb = self._run`) — m escapes
        and can assume no caller-held locks."""
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and \
                node.attr in self.method_names and \
                id(node) not in self._callee_nodes:
            self.value_refs.add(node.attr)


# -- entry-context fixpoint ----------------------------------------------------


def _init_escape_line(fn: ast.AST, method_names: "set[str]") -> int:
    """First line of `__init__` where self ESCAPES the constructor:
    self passed raw to a call, a bound method captured (thread target),
    or a thread/executor started on a self attribute.  Writes before
    this line are pre-publication and race with nobody."""
    escape = (fn.end_lineno or fn.lineno) + 1
    for node in ast.walk(fn):
        line = getattr(node, "lineno", None)
        if line is None or line >= escape:
            continue
        if isinstance(node, ast.Call):
            for arg in [*node.args, *[k.value for k in node.keywords]]:
                # `self.x` as an argument reads a field, it does not
                # leak the object — only a RAW `self` (not the .value of
                # an attribute access) or a BOUND METHOD escapes.
                attr_values = {id(sub.value) for sub in ast.walk(arg)
                               if isinstance(sub, ast.Attribute)}
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id == "self" \
                            and id(sub) not in attr_values:
                        escape = line
                    elif isinstance(sub, ast.Attribute) and \
                            isinstance(sub.value, ast.Name) and \
                            sub.value.id == "self" and \
                            sub.attr in method_names:
                        escape = line
            name = dotted_name(node.func)
            if name.endswith(".start") and name.startswith("self."):
                escape = min(escape, line)
    return escape


class _ClassModel:
    """Everything inferred about one class (or the module scope when
    cls is None): locks, fields, per-method scans, entry contexts."""

    def __init__(self, f: SourceFile, cls: Optional[str],
                 lock_attrs: "set[str]", mod_locks: "set[str]",
                 fns: "list[ast.AST]"):
        self.f = f
        self.cls = cls
        self.lock_attrs = lock_attrs
        self.mod_locks = mod_locks
        self.fns = {fn.name: fn for fn in fns}
        method_names = set(self.fns)
        if cls is not None:
            class_fields = self._self_fields(fns) - lock_attrs
            mod_fields = set()
        else:
            class_fields = set()
            mod_fields = self._module_fields(f)
        self.class_fields = class_fields
        self.mod_fields = mod_fields
        self.scans = {
            fn.name: _FunctionScan(f, cls, fn, lock_attrs, mod_locks,
                                   class_fields, mod_fields,
                                   method_names).run()
            for fn in fns}
        self.entry = self._entry_contexts()

    @staticmethod
    def _self_fields(fns) -> "set[str]":
        out: set[str] = set()
        for fn in fns:
            for node in ast.walk(fn):
                if isinstance(node, ast.Attribute) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id == "self":
                    out.add(node.attr)
        return out

    @staticmethod
    def _module_fields(f: SourceFile) -> "set[str]":
        out: set[str] = set()
        for node in f.tree.body:
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
            elif isinstance(node, ast.AnnAssign) and \
                    isinstance(node.target, ast.Name):
                out.add(node.target.id)
        return out

    def _entry_contexts(self) -> "dict[str, frozenset]":
        locks = frozenset(self.lock_attrs | self.mod_locks)
        escaped: set[str] = set()
        callers: dict[str, list] = {}
        for name, scan in self.scans.items():
            escaped |= scan.value_refs
            for callee, held in scan.call_sites:
                callers.setdefault(callee, []).append((name, held))
        entry: dict[str, frozenset] = {}
        private: set[str] = set()
        for name in self.scans:
            if name.endswith("_locked"):
                # Convention: "caller holds the lock".
                entry[name] = locks
            elif not name.startswith("_") or name.startswith("__") or \
                    name in escaped or name not in callers:
                entry[name] = frozenset()
            else:
                private.add(name)
                entry[name] = locks         # ⊤: narrowed by fixpoint
        for _ in range(8):
            changed = False
            for name in private:
                new = None
                for caller, held in callers[name]:
                    ctx = held | entry.get(caller, frozenset())
                    new = ctx if new is None else (new & ctx)
                new = frozenset(new or ())
                if new != entry[name]:
                    entry[name] = new
                    changed = True
            if not changed:
                break
        # The EVIDENCE context is the dual: the UNION of locks held at
        # some call site.  A private helper locked at one call site and
        # bare at another has entry ∅ (it can assume nothing — flag its
        # accesses) but evidence {lock} (somebody DOES think the field
        # needs it — the inconsistency is the finding).
        entry_any = {name: entry[name] for name in self.scans}
        for _ in range(8):
            changed = False
            for name in private:
                new = frozenset()
                for caller, held in callers[name]:
                    new |= held | entry_any.get(caller, frozenset())
                if new != entry_any[name]:
                    entry_any[name] = new
                    changed = True
            if not changed:
                break
        self.entry_any = entry_any
        return entry

    def effective_accesses(self):
        """Every access with its EFFECTIVE held set (syntactic ∪ entry
        context), `__init__` pre-publication accesses dropped."""
        method_names = set(self.fns)
        for name, scan in self.scans.items():
            ctx = self.entry.get(name, frozenset())
            if name == "__init__":
                cut = _init_escape_line(scan.fn, method_names)
                for acc in scan.accesses:
                    if acc.line >= cut:
                        yield _Access(acc.field, acc.kind, acc.line,
                                      acc.held | ctx, name, acc.verb)
                continue
            for acc in scan.accesses:
                yield _Access(acc.field, acc.kind, acc.line,
                              acc.held | ctx, name, acc.verb)


def _class_models(f: SourceFile) -> "list[_ClassModel]":
    inferred = collect_inferred_locks(f)
    mod_locks = {l.attr for l in inferred if l.cls is None}
    # Annotated module locks count as locks too even when their ctor
    # shape is unusual (they carry explicit `# guards:` intent).
    annotated, _ = collect_locks(f)
    mod_locks |= {l.attr for l in annotated if l.cls is None}
    models: list[_ClassModel] = []
    by_class: dict[Optional[str], list] = {}
    for cls, fn in walk_functions(f.tree):
        by_class.setdefault(cls, []).append(fn)
    for cls, fns in by_class.items():
        if cls is None:
            lock_attrs: set[str] = set()
        else:
            lock_attrs = {l.attr for l in inferred if l.cls == cls}
            lock_attrs |= {l.attr for l in annotated if l.cls == cls}
        models.append(_ClassModel(f, cls, lock_attrs, mod_locks, fns))
    return models


# -- the pass ------------------------------------------------------------------


def _guard_evidence(model: _ClassModel
                    ) -> "dict[tuple, dict[str, int]]":
    """(field, scope_is_class) -> {lock: locked-write count}.  Evidence
    uses the UNION entry context (entry_any): a write in a helper that
    SOME caller locks counts as intent, even when another call path is
    bare — that inconsistency is exactly what the pass reports."""
    lock_universe = model.lock_attrs | model.mod_locks
    method_names = set(model.fns)
    evidence: dict[tuple, dict[str, int]] = {}
    for name, scan in model.scans.items():
        ctx_any = model.entry_any.get(name, frozenset())
        cut = _init_escape_line(scan.fn, method_names) \
            if name == "__init__" else 0
        for acc in scan.accesses:
            if acc.kind != "write" or acc.line < cut:
                continue
            scope_is_class = acc.field in model.class_fields
            key = (acc.field, scope_is_class)
            locks = (acc.held | ctx_any) & lock_universe
            if locks:
                slot = evidence.setdefault(key, {})
                for lock in locks:
                    slot[lock] = slot.get(lock, 0) + 1
    return evidence


def _check_model(model: _ClassModel,
                 findings: "list[Finding]") -> None:
    f = model.f
    evidence = _guard_evidence(model)
    if not evidence:
        _check_atomicity(model, {}, findings)
        return
    guards = {key: set(locks) for key, locks in evidence.items()}
    # Methods that use locks at all — guard-read only fires there (a
    # class used single-threaded through a lock-free facade would
    # otherwise drown the report).
    for acc in model.effective_accesses():
        scope_is_class = acc.field in model.class_fields
        key = (acc.field, scope_is_class)
        inferred = guards.get(key)
        if not inferred or acc.held & inferred:
            continue
        if acc.method.endswith("_locked") or acc.method == "__del__":
            continue
        fn = model.fns.get(acc.method)
        scope = f"{model.cls}." if model.cls else ""
        lock_names = " or ".join(
            f"`{'self.' if l in model.lock_attrs else ''}{l}`"
            for l in sorted(inferred))
        if acc.kind == "write":
            if f.waived("guard-inference", acc.line) or \
                    (fn is not None and
                     f.function_waived("guard-inference", fn)):
                continue
            owner = "self." if scope_is_class else ""
            findings.append(Finding(
                PASS_NAME, "guard-inference", f.path, acc.line,
                f"{owner}{acc.field} is {acc.verb} in "
                f"{scope}{acc.method} without {lock_names}, but "
                f"{_evidence_note(evidence[key])} — either lock this "
                f"site or waive with `# analyze: "
                f"allow(guard-inference): reason`"))
        else:
            scan = model.scans.get(acc.method)
            if scan is None or not _method_uses_locks(scan, model):
                continue
            if _double_checked(scan, model, acc, inferred):
                continue    # lock-free fast path + locked re-check
            if f.waived("guard-read", acc.line) or \
                    (fn is not None and
                     f.function_waived("guard-read", fn)):
                continue
            findings.append(Finding(
                PASS_NAME, "guard-read", f.path, acc.line,
                f"self.{acc.field} is read in {scope}{acc.method} "
                f"without {lock_names} while the method takes locks "
                f"elsewhere — a torn/stale read; lock it or waive with "
                f"`# analyze: allow(guard-read): reason`",
                severity="warning"))
    _check_atomicity(model, guards, findings)


def _method_uses_locks(scan: _FunctionScan, model: _ClassModel) -> bool:
    return bool(scan.regions) or \
        bool(model.entry.get(scan.fn.name))


def _double_checked(scan: _FunctionScan, model: _ClassModel,
                    acc: _Access, inferred: "set[str]") -> bool:
    """The double-checked lazy-init idiom: a lock-free read of a field
    that the SAME method also RE-READS under one of its guard locks, or
    conditionally INSTALLS under the lock (plain/setdefault assignment),
    is the sanctioned fast path.  A locked destructive mutation
    (.clear()/.pop()) does NOT sanction an unlocked read — that's the
    stale-read shape, not lazy init."""
    ctx = model.entry.get(acc.method, frozenset())
    return any(other.field == acc.field and other.line != acc.line and
               ((other.held | ctx) & inferred) and
               (other.kind == "read" or other.verb == "assigned" or
                "setdefault" in other.verb)
               for other in scan.accesses)


def _evidence_note(locked_writes: "dict[str, int]") -> str:
    parts = [f"{count} write{'s' if count > 1 else ''} hold "
             f"`{lock}`" for lock, count in sorted(locked_writes.items())]
    return "elsewhere " + " and ".join(parts)


def _check_atomicity(model: _ClassModel, guards: dict,
                     findings: "list[Finding]") -> None:
    """Check-then-act across lock regions of one function: a name bound
    from a guarded read in region A, feeding (or gating) a guarded write
    in a LATER region B of the same lock — the lock was dropped between
    check and act.  Re-reading the field inside B (double-checked
    locking) exempts."""
    f = model.f
    for scan in model.scans.values():
        regions = sorted(scan.regions, key=lambda r: r.start)
        for i, ra in enumerate(regions):
            if not ra.tainted:
                continue
            guarded_sources = {
                field for fields in ra.tainted.values()
                for field in fields}
            for rb in regions[i + 1:]:
                if rb.lock != ra.lock or rb.start <= ra.end:
                    continue
                if rb.reads & guarded_sources:
                    continue        # double-checked: B re-validates

                def alive(names, boundary):
                    """Tainted names NOT reassigned between the check
                    region's close and `boundary` — a reassignment
                    replaces the stale read with a fresh value."""
                    return {n for n in names
                            if not any(ra.end < line < boundary and
                                       n in assigned
                                       for assigned, line
                                       in scan.assignments)}

                tainted_names = set(ra.tainted)
                # Control dependence: B is inside an if/while (opened
                # after A closed) testing a tainted name.
                control_alive = alive(tainted_names, rb.start)
                control = any(
                    line > ra.end and names & control_alive
                    for line, names in rb.cond_names)
                for field, line, stmt_names in rb.writes:
                    key = (field, field in model.class_fields)
                    if guards and key not in guards:
                        continue
                    if not (control or
                            stmt_names & alive(tainted_names, line)):
                        continue
                    if f.waived("atomicity", line):
                        continue
                    sources = ", ".join(sorted(guarded_sources))
                    findings.append(Finding(
                        PASS_NAME, "atomicity", f.path, line,
                        f"check-then-act: `{field}` is written here "
                        f"under `{rb.lock}` based on a value read from "
                        f"{sources} in the earlier `with {ra.lock}` "
                        f"region at line {ra.start} — the lock was "
                        f"released in between, so the decision may be "
                        f"stale; merge the regions or re-validate "
                        f"inside this one (waive with `# analyze: "
                        f"allow(atomicity): reason`)"))
                    break


def _check_drift(f: SourceFile, models: "list[_ClassModel]",
                 findings: "list[Finding]") -> None:
    """Annotation cross-check: declared `# guards:` entries the
    inference contradicts or finds dead."""
    annotated, _ = collect_locks(f)
    by_scope = {m.cls: m for m in models}
    for info in annotated:
        model = by_scope.get(info.cls)
        if model is None:
            continue
        accesses = [a for a in model.effective_accesses()]
        for field in sorted(info.guards):
            if info.cls is not None and \
                    field not in model.class_fields and \
                    field not in model.mod_fields:
                continue        # lock-annotation typo rule owns this
            field_accs = [a for a in accesses if a.field == field]
            writes = [a for a in field_accs if a.kind == "write"]
            if f.waived("guard-drift", info.line):
                continue
            if not field_accs and info.cls is not None:
                findings.append(Finding(
                    PASS_NAME, "guard-drift", f.path, info.line,
                    f"`# guards:` on {info.attr!r} names {field!r} but "
                    f"the {'class' if info.cls else 'module'} has no "
                    f"post-construction access to it — stale "
                    f"annotation; delete or correct it"))
                continue
            locked = [a for a in writes if info.attr in a.held]
            other = sorted({lock for a in writes
                            for lock in a.held
                            if lock != info.attr and
                            lock in (model.lock_attrs |
                                     model.mod_locks)})
            if writes and not locked and other:
                findings.append(Finding(
                    PASS_NAME, "guard-drift", f.path, info.line,
                    f"`# guards:` says {info.attr!r} guards {field!r} "
                    f"but every guarded write of {field!r} holds "
                    f"{', '.join(repr(o) for o in other)} instead — "
                    f"annotation drift; correct the annotation"))


def run(files: "list[SourceFile]") -> "list[Finding]":
    findings: list[Finding] = []
    for f in files:
        models = _class_models(f)
        for model in models:
            _check_model(model, findings)
        _check_drift(f, models, findings)
    return findings


# -- reconciliation graph (dynamic ⊆ static gate) ------------------------------

# Aggressive call resolution for the SUPERSET graph the runtime
# sanitizer reconciles against: beyond lock_discipline's self-methods /
# same-file functions / singleton accessors, resolve METHOD calls by
# unique name across every lock-bearing class tree-wide (ambiguous
# names add edges to ALL candidates — over-approximation is sound for a
# superset graph, which is never used for cycle detection).


def all_lock_infos(files: "list[SourceFile]"
                   ) -> "dict[str, list[LockInfo]]":
    """Annotated + inferred locks per file, as LockInfos (inferred ones
    carry empty guard sets) — the node universe of the reconciliation
    graph."""
    out: dict[str, list[LockInfo]] = {}
    for f in files:
        annotated, _ = collect_locks(f)
        seen = {(l.cls, l.attr) for l in annotated}
        locks = list(annotated)
        for il in collect_inferred_locks(f):
            if (il.cls, il.attr) not in seen:
                locks.append(LockInfo(f.path, il.cls, il.attr, set(),
                                      il.line))
        if locks:
            out[f.path] = locks
    return out


def registered_site_map(files: "list[SourceFile]") -> "dict[str, str]":
    """sanitizers.register_lock site name -> static lock node id, read
    straight off the registration call sites (the AST is the single
    source of truth for the mapping the reconciliation test uses)."""
    out: dict[str, str] = {}
    for f in files:
        for il in collect_inferred_locks(f):
            if il.site_name:
                out[il.site_name] = il.node_id
    return out


def reconciliation_graph(files: "list[SourceFile]") -> dict:
    """The superset acquisition-order graph: every annotated + inferred
    lock, edges from syntactic nesting plus a deep interprocedural
    closure (self-methods, same-file functions, accessors from
    lock_discipline, and tree-wide unique/ambiguous method-name
    resolution into lock-bearing classes)."""
    locks_by_file = all_lock_infos(files)
    # Tree-wide method-name index over every class in a LOCK-BEARING
    # file: name -> [(path, cls)].  Patches cross-file attribute calls
    # like `self.hits_n.increment()` into profiling.Counter.increment —
    # and non-lock classes of those files matter too (a Profiler has no
    # lock itself, but Profiler.counter reaches the registry's).
    method_index: dict[str, list] = {}
    fn_index: dict[str, list] = {}
    ctor_index: dict[str, list] = {}
    for f in files:
        if f.path not in locks_by_file:
            continue
        for node in f.tree.body:
            if isinstance(node, ast.ClassDef):
                ctor_index.setdefault(node.name, []).append(
                    (f.path, node.name))
        for cls, fn in walk_functions(f.tree):
            if cls is not None:
                method_index.setdefault(fn.name, []).append(
                    (f.path, cls))
            else:
                fn_index.setdefault(fn.name, []).append((f.path, None))
    edges = build_order_graph(files, locks_by_file,
                              method_index=method_index,
                              fn_index=fn_index,
                              ctor_index=ctor_index)
    return {
        "locks": sorted(l.node_id for ls in locks_by_file.values()
                        for l in ls),
        "edges": sorted([a, b, f"{p}:{line}"]
                        for (a, b), (p, line) in edges.items()),
        "site_map": registered_site_map(files),
    }
