"""Daemon entry: `python -m ytsaurus_tpu.server.daemon --role primary|node`.

The multiplexed-binary pattern (ref server/all/main.cpp): one entry point,
role picked by flag.

  primary  — metadata master + tablet host + transaction coordinator +
             scheduler + driver proxy, with chunk data placed on remote
             data nodes (RpcChunkStore) once any register; falls back to a
             local store location until then.
  node     — blob chunk store + journal location, heartbeating to the
             primary.

The bound port is written to <root>/<role>.port for launcher discovery.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time


# analyze: allow(failpoint): bootstrap plumbing — a failed port write kills the spawn, surfaced by the cluster-start timeout
def _write_port_file(root: str, role: str, port: int) -> None:
    path = os.path.join(root, f"{role}.port")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(port))
    os.replace(tmp, path)


# analyze: allow(failpoint): daemon entry point — its I/O is bootstrap plumbing; fault sites live in the planes it hosts
def run_primary(root: str, port: int, replication_factor: int = 2,
                journal_nodes: int = 3,
                bootstrap_timeout: float = 60.0,
                election: bool = False, master_index: int = 0,
                lease_ttl: float = 6.0, kafka: bool = False,
                clocks: "str | None" = None) -> None:
    from ytsaurus_tpu import yson
    from ytsaurus_tpu.client import YtClient, YtCluster
    from ytsaurus_tpu.cypress.election import LeaderElector
    from ytsaurus_tpu.cypress.master import Master
    from ytsaurus_tpu.cypress.quorum import QuorumWal
    from ytsaurus_tpu.errors import YtError
    from ytsaurus_tpu.rpc import Channel, RetryingChannel, RpcServer
    from ytsaurus_tpu.server.remote_store import RpcChunkStore
    from ytsaurus_tpu.server.services import (
        DriverService,
        MasterService,
        NodeTracker,
        NodeTrackerService,
    )

    from ytsaurus_tpu.server.monitoring import MonitoringServer
    from ytsaurus_tpu.server.orchid import OrchidService, default_orchid

    os.makedirs(root, exist_ok=True)
    tracker = NodeTracker()
    # Bootstrap service set first: nodes must be able to register before
    # the master recovers (quorum WAL recovery reads their journals).
    role = {"value": "follower" if election else "leader"}
    server = RpcServer([NodeTrackerService(tracker),
                        MasterService(role)], port=port)
    server.start()
    _write_port_file(root, "primary", server.port)
    orchid = default_orchid()
    orchid.register("/node_tracker/alive", tracker.alive)
    orchid.register("/master/role", lambda: role["value"])
    server.add_service(OrchidService(orchid))
    monitoring = MonitoringServer(orchid)
    monitoring.start()
    _write_port_file(root, "primary.monitoring", monitoring.port)
    print(f"primary bootstrap on {server.address}", flush=True)

    # Journal membership is STICKY: chosen once, persisted, reused across
    # restarts so recovery always consults the same journal owners.
    journal_cfg_path = os.path.join(root, "journal_config.yson")
    wanted: list[str] | None = None
    if os.path.exists(journal_cfg_path):
        with open(journal_cfg_path, "rb") as f:
            wanted = [j.decode() if isinstance(j, bytes) else j
                      for j in yson.loads(f.read())["journal_node_ids"]]
    def _fetch_published_membership(
            ) -> "tuple[list[str] | None, bool]":
        """(highest-epoch membership record found on any alive node,
        every-alive-node-answered).  Under multi-master election the
        journal nodes are the shared source of truth for WHICH nodes
        form the quorum set — each master guessing from its own
        registration-order view could yield non-intersecting quorum
        sets (acked-write loss).  The completeness bit gates choosing a
        FRESH membership: "no record found" only counts when every node
        actually answered."""
        best: "tuple[int, list[str]] | None" = None
        complete = True
        for _, addr in sorted(tracker.alive().items()):
            channel = Channel(addr, timeout=5)
            try:
                body, _ = channel.call("data_node",
                                       "journal_membership_get",
                                       {"journal": "master_wal"})
                members = body.get("member_ids")
                if members is not None:
                    members = [m.decode() if isinstance(m, bytes) else m
                               for m in members]
                    epoch = int(body.get("epoch", 0))
                    if best is None or epoch > best[0]:
                        best = (epoch, members)
            except YtError:
                complete = False
                continue
            finally:
                channel.close()
        return (best[1] if best is not None else None), complete

    deadline = time.monotonic() + bootstrap_timeout
    chosen: dict[str, str] = {}
    had_prior_config = wanted is not None
    fresh_bootstrap = False
    clean_sweeps = 0
    if election:
        # Under election the sticky LOCAL config is advisory only: the
        # record published on the journal nodes (highest epoch) always
        # wins, since membership may have been upgraded by another
        # master while this one was down.
        wanted = None
    while time.monotonic() < deadline:
        alive = tracker.alive()
        if election:
            # Prefer membership already published to the journal nodes
            # (a previous leader's choice) over choosing our own.
            published, complete = _fetch_published_membership()
            if published is not None:
                if published != wanted:
                    wanted = published
                    continue
            elif wanted is None:
                # A fresh membership may be chosen ONLY by master 0, on
                # a root with no prior config (a restart implies a
                # published record exists somewhere — wait for it), and
                # only after two consecutive COMPLETE sweeps of enough
                # nodes found nothing (a transiently unreachable node
                # may be the one holding the record).
                clean_sweeps = clean_sweeps + 1 \
                    if complete and len(alive) >= journal_nodes else 0
                if master_index != 0 or had_prior_config or \
                        clean_sweeps < 2:
                    time.sleep(0.3)
                    continue
                chosen = dict(sorted(alive.items())[:journal_nodes])
                fresh_bootstrap = True
                break
        if wanted is not None:
            if all(i in alive for i in wanted):
                chosen = {i: alive[i] for i in wanted}
                break
        elif len(alive) >= journal_nodes:
            chosen = dict(sorted(alive.items())[:journal_nodes])
            break
        time.sleep(0.2)
    else:
        if wanted is not None:
            raise YtError(f"journal nodes {wanted} did not register within "
                          f"{bootstrap_timeout}s")
        if election:
            # No degraded bootstrap under election: divergent degraded
            # sets across masters can fail to intersect.
            raise YtError(
                f"election bootstrap needs {journal_nodes} journal nodes "
                f"(or a published membership) within {bootstrap_timeout}s")
        # Fewer nodes than asked for: take what registered rather than
        # collapsing to a local-only WAL.  Epoch acquisition needs a
        # strict majority of remotes, so an ODD remote count (default 3)
        # keeps takeover live under one dead journal node; an even count
        # still appends fine but requires all remotes up at takeover.
        alive = tracker.alive()
        if alive and journal_nodes > 0:
            chosen = dict(sorted(alive.items())[:journal_nodes])
            print(f"# only {len(chosen)}/{journal_nodes} journal nodes "
                  f"registered within {bootstrap_timeout}s; using "
                  f"{sorted(chosen)} (membership upgrades after recovery "
                  "as more nodes register)", flush=True)
        else:
            print(f"# no data nodes within {bootstrap_timeout}s; "
                  "falling back to local-only WAL", flush=True)

    def _persist_journal_config(ids: list[str]) -> None:
        tmp = journal_cfg_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(yson.dumps({"journal_node_ids": sorted(ids)},
                               binary=True))
        os.replace(tmp, journal_cfg_path)

    if chosen and wanted is None:
        _persist_journal_config(sorted(chosen))

    master_dir = os.path.join(root, "master")
    os.makedirs(master_dir, exist_ok=True)
    wal = None
    elector = None

    def _build_channels(members: dict) -> list:
        return [RetryingChannel(Channel(addr, timeout=30),
                                attempts=2, backoff=0.1)
                for _, addr in sorted(members.items())]

    if chosen:
        channels = _build_channels(chosen)

        def make_wal():
            # First adoption of this quorum config (we just wrote the
            # journal membership): any existing local log predates the
            # quorum and is authoritative — it seeds the replicas
            # instead of being outvoted by their empty journals.  Under
            # election only a verified FRESH bootstrap (master 0, no
            # prior config, complete no-record sweeps) may treat local
            # history as authoritative: anything else would reset the
            # journals from a stale or empty local log.
            # Election mode uses a REMOTE-ONLY quorum: a failover
            # successor recovers with a fresh local location, so read
            # and write quorums must intersect over the shared journal
            # nodes alone (see QuorumWal.count_local_ack).
            locations = 1 + len(channels)
            return QuorumWal(
                os.path.join(master_dir, Master.CHANGELOG),
                journal_name="master_wal",
                remote_channels=channels,
                quorum=(len(channels) // 2 + 1) if election
                else locations // 2 + 1,
                count_local_ack=not election,
                bootstrap_from_local=(
                    fresh_bootstrap if election else wanted is None),
                lease_ttl=lease_ttl if election else 0.0)

        wal = make_wal()
        print(f"quorum WAL over local + {sorted(chosen)} "
              f"(quorum {wal.quorum})", flush=True)
    if election and wal is None:
        raise YtError("--election requires journal nodes (the journal "
                      "plane carries votes and leases)")
    def _publish_membership() -> None:
        """Write the (epoch-stamped) membership to every journal node so
        any master resolves the same quorum set."""
        for replica in wal.replicas:
            try:
                replica.channel.call(
                    "data_node", "journal_membership_put",
                    {"journal": wal.journal_name, "epoch": wal.epoch,
                     "writer": wal.writer_id,
                     "member_ids": sorted(chosen)}, idempotent=False)
            except YtError as err:
                print(f"# membership publish failed on one node: {err}",
                      flush=True)

    if election:
        # Candidate loop: wait for the lease plane to be takeover-free,
        # then try to win the epoch (which also claims the lease on each
        # granting location).  A lost race returns to standby.
        while True:
            elector = LeaderElector(
                "master_wal",
                lambda: [r.channel for r in wal.replicas],
                wal.writer_id, lease_ttl=lease_ttl,
                hold_down=master_index * (lease_ttl / 4.0))
            print(f"standby (master {master_index}): awaiting "
                  "leadership", flush=True)
            elector.wait_until_electable()
            # Re-resolve membership RIGHT BEFORE takeover: the previous
            # leader may have upgraded it while this standby slept, and
            # recovering over a stale subset could drop records acked on
            # the newer set (then re-publish the stale set at a higher
            # epoch, poisoning future bootstraps).
            latest, _ = _fetch_published_membership()
            if latest is not None and sorted(latest) != sorted(chosen):
                alive_now = tracker.alive()
                if all(i in alive_now for i in latest):
                    print(f"membership changed to {sorted(latest)}; "
                          "rebuilding WAL", flush=True)
                    elector.stop()
                    wal.close()
                    chosen.clear()
                    chosen.update({i: alive_now[i] for i in latest})
                    _persist_journal_config(sorted(chosen))
                    channels = _build_channels(chosen)
                    wal = make_wal()
                    continue
            try:
                master = Master(master_dir, wal=wal)
                break
            except YtError as err:
                print(f"takeover failed: {err}; back to standby",
                      flush=True)
                elector.stop()
                wal.close()          # no fd leak across retries
                time.sleep(1.0)
                wal = make_wal()     # fresh writer identity for next try
        _publish_membership()
    else:
        master = Master(master_dir, wal=wal)
    # A membership persisted while under-strength (slow node startup on a
    # previous boot) upgrades here, AFTER recovery: new locations are
    # seeded with the full committed log before the larger quorum is
    # adopted, so the sticky config never pins the cluster to a degraded
    # journal set forever.
    if wal is not None and len(chosen) < journal_nodes:
        extra = {i: a for i, a in sorted(tracker.alive().items())
                 if i not in chosen}
        extra = dict(list(extra.items())[:journal_nodes - len(chosen)])
        adopted = {}
        for node_id, addr in sorted(extra.items()):
            channel = RetryingChannel(Channel(addr, timeout=30),
                                      attempts=2, backoff=0.1)
            # One node at a time: only nodes the WAL actually KEPT are
            # persisted — a failed catch-up must not become a phantom
            # quorum member that outvotes acknowledged records next boot.
            if wal.extend([channel]) == 1:
                adopted[node_id] = addr
        if adopted:
            chosen.update(adopted)
            _persist_journal_config(sorted(chosen))
            if election:
                _publish_membership()
            print(f"quorum WAL membership upgraded to "
                  f"{sorted(chosen)} (quorum {wal.quorum})",
                  flush=True)
    if election and elector is not None:
        def on_lease_lost():
            # The automaton may be ahead of what a new leader recovered;
            # serving (even reads) risks confusion — fail-stop for a
            # supervised restart as a follower (Hydra restart semantics).
            master._poisoned = True
            role["value"] = "follower"
            print("leadership lost (lease not renewable); exiting for "
                  "supervised restart", flush=True)
            os._exit(17)

        # Epoch via callable: _maybe_reacquire bumps it after orphaned
        # fences, and renewals must follow or a healthy leader's
        # renewals are denied everywhere.
        elector.start_renewing(lambda: wal.epoch, on_lease_lost)
    # The primary holds NO chunk location of its own: all chunk data lives
    # on data-node processes.
    store = RpcChunkStore(tracker.alive_nodes,
                          replication_factor=replication_factor)
    cluster = YtCluster(root, chunk_store=store, master=master)
    cluster.node_directory = tracker.alive    # enables exec-node dispatch
    if clocks:
        # Tablet commits take timestamps from the CLOCK QUORUM, not an
        # in-process provider: timestamps stay monotone across master
        # failover because the oracle outlives any master (ref
        # clock_server/cluster_clock).
        from ytsaurus_tpu.tablet.clock import QuorumTimestampProvider
        provider = QuorumTimestampProvider(
            [a.strip() for a in clocks.split(",") if a.strip()])
        cluster.transactions.timestamps = provider
        print(f"tablet timestamps from clock quorum: {clocks}",
              flush=True)
    client = YtClient(cluster)
    server.add_service(DriverService(client))
    # Cluster compile-artifact tier (ISSUE 17): AOT executables publish
    # to the chunk store on compile and fetch on miss, so a replica
    # added mid-storm joins HOT — zero inline compiles for shapes its
    # peers already built.  Content-addressed ids make this safe to
    # share across every primary of the cluster.
    from ytsaurus_tpu.query.engine.aot_cache import (
        ClusterArtifactStore,
        set_cluster_store,
    )
    artifact_store = ClusterArtifactStore(store)
    set_cluster_store(artifact_store)
    orchid.register("/query/compile_cache/cluster",
                    artifact_store.snapshot)
    # Adaptive tiering plane (ISSUE 18): the tier ladder's live state —
    # kill switch, promotion queue, per-fingerprint interpreted-run
    # roll-up — next to the compile cache it feeds.
    orchid.register("/query/tiers", cluster.evaluator.tier_snapshot)
    monitoring.tier_evaluator = cluster.evaluator
    # Capture-driven prewarm (ISSUE 18 tentpole, piece c): replay an
    # exported workload capture COMPILE-ONLY before serving traffic, so
    # a restarted replica's first queries hit warm programs instead of
    # paying inline compiles.  Gated on the env var (daemon idiom) or
    # TieringConfig.prewarm_capture; a missing/broken capture logs and
    # serves cold — prewarm is an optimization, never a boot gate.
    from ytsaurus_tpu.config import tiering_config
    prewarm_capture = os.environ.get("YT_TPU_PREWARM_CAPTURE") or \
        tiering_config().prewarm_capture
    if prewarm_capture:
        from ytsaurus_tpu.query.engine.prewarm import prewarm_capture_file
        try:
            report = prewarm_capture_file(prewarm_capture, client=client,
                                          evaluator=cluster.evaluator)
            print(f"prewarm {prewarm_capture}: "
                  f"{report['compiled']} compiled, "
                  f"{report['aot_hits']} AOT hits, "
                  f"{report['skipped']} skipped "
                  f"({report['seconds']:.3f}s)", flush=True)
        except Exception as err:   # noqa: BLE001 — serve cold instead
            print(f"prewarm failed ({prewarm_capture}): {err}",
                  flush=True)
    # Background re-replication: a dead node's chunks regain their
    # replication factor within ~interval, read or no read (ref
    # chunk_replicator.h).  A follower's empty node tracker makes its
    # scans no-ops, so starting unconditionally is safe under election.
    # Liveness from the metadata tree keeps deleted chunks from being
    # resurrected off a node that missed their removal.
    from ytsaurus_tpu.server.chunk_replicator import ChunkReplicator
    replicator = ChunkReplicator(
        tracker.alive_nodes, replication_factor=replication_factor,
        liveness_provider=client.referenced_chunk_ids)
    replicator.start()
    orchid.register("/chunk_replicator", lambda: dict(replicator.stats))
    # Small-chunk background compaction (ref chunk_merger.h:136).
    from ytsaurus_tpu.server.chunk_merger import ChunkMerger
    merger = ChunkMerger(client).start()
    orchid.register("/chunk_merger", lambda: dict(merger.stats))
    # Continuous CPU profiler + span export (ref ytprof cpu_profiler.h,
    # jaeger/tracer.h): always-on statistical sampling served via
    # Orchid; finished spans batch-flush to <root>/traces.jsonl.
    try:
        profiler_interval = float(
            os.environ.get("YT_TPU_PROFILER_INTERVAL", 0.05))
    except ValueError:
        # 'off'/'50ms'/'': the operator meant SOMETHING non-default —
        # disable rather than refuse to boot the primary.
        print("# YT_TPU_PROFILER_INTERVAL unparseable; profiler off",
              flush=True)
        profiler_interval = 0.0
    if profiler_interval > 0:
        from ytsaurus_tpu.utils.profiler import (
            SamplingProfiler,
            TraceExporter,
            jsonl_sink,
        )
        cpu_profiler = SamplingProfiler(
            interval=profiler_interval).start()
        orchid.register("/profiler", lambda: {
            **cpu_profiler.state(),
            "hotspots": cpu_profiler.hotspots()})
        orchid.register("/profiler/collapsed",
                        lambda: cpu_profiler.collapsed())
        exporter = TraceExporter(
            jsonl_sink(os.path.join(root, "traces.jsonl"))).start()
        orchid.register("/tracing/export", lambda: dict(exporter.stats))
        # The exporter DRAINS the collector: recent_spans now serves
        # from the exporter's tail or it would always read empty.
        orchid.register("/tracing/recent_spans",
                        lambda: list(exporter.recent))
    # Generalized service discovery (ref server/discovery_server): any
    # process can publish into named groups; NodeTracker stays the
    # data-node special case.
    from ytsaurus_tpu.server.discovery import (
        DAEMONS_GROUP,
        DiscoveryService,
        DiscoveryTracker,
        announce_daemon,
    )
    discovery = DiscoveryTracker()
    server.add_service(DiscoveryService(discovery))
    orchid.register("/discovery", discovery.list_groups)
    # Cluster telemetry plane (ISSUE 6): start the sampler that fills
    # the metrics-history rings + evaluates SLO burn rates, register
    # this primary's monitoring endpoint in /daemons, and wire the
    # /cluster roll-up to scrape every registered member.
    from ytsaurus_tpu.utils.profiling import start_telemetry
    start_telemetry()
    announce_daemon(discovery, "primary", monitoring.address,
                    role="primary")
    monitoring.cluster_members = \
        lambda: discovery.list_members(DAEMONS_GROUP)
    if kafka:
        # Kafka wire protocol over queues (ref server/kafka_proxy):
        # in-process with the primary, like the query tracker / queue
        # agent, so consumer registrations ride the same client.
        from ytsaurus_tpu.server.kafka_proxy import KafkaProxy
        kafka_proxy = KafkaProxy(client).start()
        _write_port_file(root, "kafka", kafka_proxy.port)
        print(f"kafka proxy serving on {kafka_proxy.address}", flush=True)
    if os.environ.get("YT_TPU_SEQUOIA", "") not in ("", "0"):
        # Sequoia resolve ground table (cypress/sequoia.py): path
        # resolution served from a dynamic table, kept consistent off
        # the mutation stream.
        from ytsaurus_tpu.cypress.sequoia import SequoiaResolver
        sequoia = SequoiaResolver(client).enable()
        # verify() is a full tree walk + three ground-table scans under
        # the mutation lock — far too heavy to run on EVERY /sequoia
        # Orchid read (each read would stall the whole mutation stream).
        # Reads serve cached counters; verification runs on a background
        # cadence, and /sequoia/verify is the explicit on-demand action.
        verify_state = {"divergent": [], "verify_runs": 0,
                        "verified_at": None}

        def _sequoia_verify():
            # The tree walk compares live tree vs table snapshots: hold
            # the mutation lock so a concurrent mutation can't produce a
            # torn (spuriously divergent) read.
            with client.cluster.master.mutation_lock:
                divergent = sequoia.verify()
            verify_state["divergent"] = divergent
            verify_state["verify_runs"] += 1
            verify_state["verified_at"] = time.time()
            return {"divergent": divergent,
                    "verify_runs": verify_state["verify_runs"]}

        def _sequoia_state():
            return {"enabled": True,
                    "records": len(sequoia._paths),
                    "divergent": list(verify_state["divergent"]),
                    "verify_runs": verify_state["verify_runs"],
                    "verified_at": verify_state["verified_at"]}

        _sequoia_verify()                  # one startup pass seeds the cache
        verify_interval = float(
            os.environ.get("YT_TPU_SEQUOIA_VERIFY_INTERVAL", 60))

        def _sequoia_verify_loop() -> None:
            while True:
                time.sleep(verify_interval)
                try:
                    _sequoia_verify()
                except Exception as exc:  # noqa: BLE001 — keep the cadence
                    print(f"# sequoia verify failed: {exc}", flush=True)

        if verify_interval > 0:
            threading.Thread(target=_sequoia_verify_loop, daemon=True,
                             name="sequoia-verify").start()
        orchid.register("/sequoia", _sequoia_state)
        orchid.register("/sequoia/verify", _sequoia_verify)
        print("sequoia ground tables enabled", flush=True)
    role["value"] = "leader"
    print(f"primary serving on {server.address}"
          + (f" (leader, master {master_index})" if election else ""),
          flush=True)
    threading.Event().wait()       # serve until killed


def run_node(root: str, port: int, primary_address: str,
             node_id: str | None = None) -> None:
    from ytsaurus_tpu.chunks.store import FsChunkStore
    from ytsaurus_tpu.rpc import Channel, RetryingChannel, RpcServer
    from ytsaurus_tpu.server.services import DataNodeService

    from ytsaurus_tpu.server.monitoring import MonitoringServer
    from ytsaurus_tpu.server.orchid import OrchidService, default_orchid

    from ytsaurus_tpu.server.exec_service import ExecNodeService

    os.makedirs(root, exist_ok=True)
    node_id = node_id or os.path.basename(os.path.normpath(root))
    store = FsChunkStore(os.path.join(root, "chunks"))
    service = DataNodeService(store, os.path.join(root, "journals"))
    exec_service = ExecNodeService(store)
    orchid = default_orchid()
    orchid.register("/data_node", lambda: {
        "id": node_id, "chunk_count": len(store.list_chunks())})
    orchid.register("/exec_node", lambda: exec_service.exec_stats({}, ()))
    # Periodic checksum scrub: corrupt chunks quarantine themselves and
    # the master's replicator restores RF from healthy holders.
    scrub_interval = float(os.environ.get("YT_TPU_SCRUB_INTERVAL", 300))
    scrub_state = {"checked": 0, "corrupt": 0}

    def scrub_loop() -> None:
        while True:
            time.sleep(scrub_interval)
            try:
                out = service.scrub_chunks({}, ())
                scrub_state["checked"] += out["checked"]
                scrub_state["corrupt"] += len(out["corrupt"])
            except Exception as exc:  # noqa: BLE001 — keep scrubbing
                print(f"# scrub failed: {exc}", file=sys.stderr,
                      flush=True)

    if scrub_interval > 0:
        threading.Thread(target=scrub_loop, daemon=True,
                         name="chunk-scrubber").start()
    orchid.register("/data_node/scrub", lambda: dict(scrub_state))
    server = RpcServer([service, exec_service,
                        OrchidService(orchid)], port=port)
    server.start()
    _write_port_file(root, "node", server.port)
    # P2P hot-chunk distribution (ref data_node/p2p.h TP2PDistributor):
    # reads past the heat threshold seed copies onto peers, discovered
    # through the primary's node tracker.
    from ytsaurus_tpu.server.p2p import P2PDistributor
    self_address = f"127.0.0.1:{server.port}"

    def p2p_peers() -> list:
        from ytsaurus_tpu.errors import YtError as _YtError
        from ytsaurus_tpu.rpc import Channel
        # Every primary answers (the node already heartbeats them all);
        # falling over keeps discovery alive when one master is down.
        for addr in primary_address.split(","):
            if not addr.strip():
                continue
            channel = Channel(addr.strip(), timeout=10)
            try:
                body, _ = channel.call("node_tracker", "list_nodes", {})
                return [a.decode() if isinstance(a, bytes) else a
                        for a in body.get("alive") or []]
            except _YtError:
                continue
            finally:
                channel.close()
        return []

    p2p = P2PDistributor(
        store, lambda: self_address, p2p_peers,
        hot_threshold=int(os.environ.get("YT_TPU_P2P_THRESHOLD", 50)),
        window=float(os.environ.get("YT_TPU_P2P_WINDOW", 5.0)),
        cooldown=float(os.environ.get("YT_TPU_P2P_COOLDOWN", 120.0)),
    ).start()
    service.p2p = p2p
    orchid.register("/data_node/p2p", lambda: dict(p2p.stats))
    monitoring = MonitoringServer(orchid)
    monitoring.start()
    _write_port_file(root, "node.monitoring", monitoring.port)
    # Telemetry plane (ISSUE 6): every daemon samples its own sensors
    # into bounded history rings; the primary's /cluster scrapes them.
    from ytsaurus_tpu.server.discovery import DAEMONS_GROUP
    from ytsaurus_tpu.utils.profiling import start_telemetry
    start_telemetry()
    print(f"data node {node_id} serving on {server.address}", flush=True)

    # Multi-master: heartbeat EVERY primary (comma-separated), each on
    # its OWN thread — a hung (not dead) master must not stall the
    # heartbeats that keep this node alive on the healthy leader.
    address = server.address

    def beat(primary: str) -> None:
        channel = RetryingChannel(Channel(primary, timeout=10),
                                  attempts=2, backoff=0.1)
        while True:
            try:
                channel.call("node_tracker", "heartbeat",
                             {"id": node_id, "address": address})
                # Telemetry membership rides the same cadence: the
                # primary's /cluster roll-up scrapes every /daemons
                # member's monitoring endpoint.  Own try: the discovery
                # service only comes up after WAL recovery, and its
                # absence during bootstrap must not spam the log (the
                # node_tracker beat above already succeeded).
                try:
                    channel.call("discovery", "heartbeat",
                                 {"group": DAEMONS_GROUP,
                                  "member_id": node_id,
                                  "address": monitoring.address,
                                  "attributes": {"role": "node"}})
                except Exception:   # noqa: BLE001
                    pass
            except Exception as exc:  # noqa: BLE001 — keep heartbeating
                print(f"# heartbeat to {primary} failed: {exc}",
                      file=sys.stderr, flush=True)
            time.sleep(2.0)

    primaries = [a.strip() for a in primary_address.split(",")
                 if a.strip()]
    for primary in primaries[1:]:
        threading.Thread(target=beat, args=(primary,),
                         daemon=True, name=f"heartbeat-{primary}").start()
    beat(primaries[0])


# analyze: allow(failpoint): daemon entry point — bootstrap plumbing; clock-quorum faults are injected via journal sites
def run_clock(root: str, port: int, journals: "str | None", index: int,
              lease_ttl: float,
              journals_file: "str | None" = None) -> None:
    """Clock-quorum peer (ref server/clock_server/cluster_clock +
    server/timestamp_provider): serves HLC timestamps under a
    quorum-persisted ceiling, independent of the masters — tablet
    commits keep taking timestamps with the primary down.

    The RPC port binds FIRST (answering NotClockLeader until the core
    exists), so launchers can learn the address before the journal
    plane is even up; --journals-file is polled for the journal
    addresses, breaking the clock↔node startup ordering cycle without
    pre-allocating ports."""
    import time as _time

    from ytsaurus_tpu.rpc import Channel, RpcServer
    from ytsaurus_tpu.tablet.clock import (
        ClockServer,
        ClockService,
        NotClockLeader,
    )

    os.makedirs(root, exist_ok=True)
    holder: dict = {"clock": None}

    class _LateBound:
        def generate_batch(self, count=1):
            clock = holder["clock"]
            if clock is None:
                raise NotClockLeader()
            return clock.generate_batch(count)

        @property
        def is_leader(self):
            clock = holder["clock"]
            return bool(clock is not None and clock.is_leader)

    server = RpcServer([ClockService(_LateBound())], port=port)
    server.start()
    _write_port_file(root, "clock", server.port)
    print(f"clock peer {index} serving on {server.address}", flush=True)
    if journals is None:
        while True:
            try:
                with open(journals_file) as f:
                    journals = f.read().strip()
                if journals:
                    break
            except FileNotFoundError:
                pass
            _time.sleep(0.2)
    channels = [Channel(a.strip(), timeout=10)
                for a in journals.split(",") if a.strip()]
    holder["clock"] = ClockServer(root, channels, index=index,
                                  lease_ttl=lease_ttl).start()
    threading.Event().wait()


def run_proxy(root: str, port: int, primary_address: str) -> None:
    """HTTP proxy daemon: REST /api/v4 bridged to the primary's RPC plane
    (ref: the standalone http_proxy process, server/http_proxy)."""
    from ytsaurus_tpu.remote_client import RemoteYtClient
    from ytsaurus_tpu.server.http_proxy import HttpProxy

    os.makedirs(root, exist_ok=True)
    proxy = HttpProxy(
        lambda user: RemoteYtClient(primary_address, user=user),
        port=port)
    _write_port_file(root, "proxy", proxy.port)
    print(f"http proxy serving on {proxy.address} -> {primary_address}",
          flush=True)
    proxy.serve_forever()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--role",
                        choices=("primary", "node", "proxy",
                                 "master_cache", "tcp_proxy", "clock",
                                 "scheduler"),
                        required=True)
    parser.add_argument("--journals", default=None,
                        help="journal-node addresses (clock role)")
    parser.add_argument("--journals-file", default=None,
                        help="file to poll for journal addresses "
                             "(clock role; alternative to --journals)")
    parser.add_argument("--clocks", default=None,
                        help="clock-peer addresses (primary role): take "
                             "tablet timestamps from the clock quorum")
    parser.add_argument("--root", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--primary", default=None,
                        help="primary address (node role)")
    parser.add_argument("--replication-factor", type=int, default=2)
    parser.add_argument("--journal-nodes", type=int, default=3,
                        help="remote WAL locations (0 = local-only WAL); "
                             "odd counts keep takeover live under one "
                             "dead journal node")
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--bootstrap-timeout", type=float, default=60.0)
    parser.add_argument("--election", action="store_true",
                        help="multi-master mode: lease-based leader "
                             "election over the journal plane")
    parser.add_argument("--master-index", type=int, default=0,
                        help="this master's index (staggers takeover "
                             "attempts; index 0 bootstraps fresh "
                             "clusters)")
    parser.add_argument("--lease-ttl", type=float, default=6.0)
    parser.add_argument("--kafka", action="store_true",
                        help="serve the Kafka wire protocol over queues "
                             "(primary role; port in <root>/kafka.port)")
    args = parser.parse_args()

    # Daemons never touch accelerators; pin CPU before any jax import so a
    # dead tunnel cannot hang a server process.
    import jax
    jax.config.update("jax_platforms", "cpu")

    if args.role == "primary":
        run_primary(args.root, args.port, args.replication_factor,
                    journal_nodes=args.journal_nodes,
                    bootstrap_timeout=args.bootstrap_timeout,
                    election=args.election,
                    master_index=args.master_index,
                    lease_ttl=args.lease_ttl, kafka=args.kafka,
                    clocks=args.clocks)
    elif args.role == "proxy":
        if not args.primary:
            parser.error("--primary is required for --role proxy")
        run_proxy(args.root, args.port, args.primary)
    elif args.role == "master_cache":
        if not args.primary:
            parser.error("--primary is required for --role master_cache")
        from ytsaurus_tpu.server.master_cache import run_master_cache
        run_master_cache(args.root, args.port, args.primary)
    elif args.role == "scheduler":
        if not args.primary:
            parser.error("--primary is required for --role scheduler")
        from ytsaurus_tpu.server.scheduler_daemon import run_scheduler
        run_scheduler(args.root, args.port, args.primary)
    elif args.role == "clock":
        if not args.journals and not args.journals_file:
            parser.error("--journals or --journals-file is required "
                         "for --role clock")
        run_clock(args.root, args.port, args.journals,
                  args.master_index, args.lease_ttl,
                  journals_file=args.journals_file)
    elif args.role == "tcp_proxy":
        if not args.primary:
            parser.error("--primary is required for --role tcp_proxy")
        from ytsaurus_tpu.server.tcp_proxy import TcpProxy
        os.makedirs(args.root, exist_ok=True)
        proxy = TcpProxy([a.strip() for a in args.primary.split(",")
                          if a.strip()], port=args.port).start()
        _write_port_file(args.root, "tcp_proxy", proxy.port)
        print(f"tcp proxy serving on {proxy.address} -> {args.primary}",
              flush=True)
        threading.Event().wait()
    else:
        if not args.primary:
            parser.error("--primary is required for --role node")
        run_node(args.root, args.port, args.primary, node_id=args.node_id)


if __name__ == "__main__":
    main()
