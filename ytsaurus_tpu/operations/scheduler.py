"""Operations: scheduler + controllers for map / merge / sort / erase.

Ref mapping:
  TScheduler + StartOperation RPC      → OperationScheduler.start_operation
    (server/scheduler/scheduler.cpp)
  TOperationControllerBase lifecycle   → _Controller.prepare/execute/commit
    (controller_agent/operation_controller_detail.cpp: SafePrepare /
     SafeMaterialize / commit)
  operation records in Cypress         → //sys/operations/<id> attributes
Jobs here are whole-chunk device programs rather than per-slice user
processes; the controller state machine, operation records, and failure
propagation match the reference's shape.  Scheduling fan-out across many
hosts arrives with the multi-host control plane (future round); operations
run synchronously or on a worker thread.
"""

from __future__ import annotations

import threading
import traceback
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from ytsaurus_tpu.errors import EErrorCode, YtError


@dataclass
class Operation:
    id: str
    type: str                      # map | merge | sort | erase
    spec: dict
    state: str = "pending"         # pending|running|completed|failed|aborted
    error: Optional[dict] = None
    result: dict = field(default_factory=dict)


class OperationScheduler:
    def __init__(self, client):
        self.client = client
        self._operations: dict[str, Operation] = {}
        self._lock = threading.Lock()

    # -- public API ------------------------------------------------------------

    def start_operation(self, op_type: str, spec: dict,
                        sync: bool = True) -> Operation:
        op = Operation(id=uuid.uuid4().hex, type=op_type, spec=dict(spec))
        with self._lock:
            self._operations[op.id] = op
        self._record(op)
        if sync:
            self._run(op)
        else:
            thread = threading.Thread(target=self._run, args=(op,),
                                      daemon=True)
            thread.start()
        return op

    def get_operation(self, op_id: str) -> Operation:
        op = self._operations.get(op_id)
        if op is None:
            raise YtError(f"No such operation {op_id}",
                          code=EErrorCode.NoSuchOperation)
        return op

    def list_operations(self) -> list[Operation]:
        return list(self._operations.values())

    # -- lifecycle -------------------------------------------------------------

    def _run(self, op: Operation) -> None:
        op.state = "running"
        self._record(op)
        try:
            controller = _CONTROLLERS.get(op.type)
            if controller is None:
                raise YtError(f"Unknown operation type {op.type!r}",
                              code=EErrorCode.OperationFailed)
            result = controller(self.client, op.spec)
            op.result = result or {}
            op.state = "completed"
        except YtError as e:
            op.state = "failed"
            op.error = e.to_dict()
        except Exception as e:                      # noqa: BLE001
            op.state = "failed"
            op.error = YtError(
                f"Operation crashed: {e}",
                code=EErrorCode.OperationFailed,
                attributes={"traceback": traceback.format_exc()}).to_dict()
        self._record(op)
        if op.state == "failed" and op.spec.get("raise_on_failure", True):
            raise YtError.from_dict(op.error)

    def _record(self, op: Operation) -> None:
        # Each client.set is one fsync'd WAL mutation; write immutable fields
        # once at registration and only the state transition afterwards.
        path = f"//sys/operations/{op.id}"
        client = self.client
        if not client.exists(path):
            client.create("document", path, recursive=True,
                          ignore_existing=True)
            client.set(path + "/@operation_type", op.type)
            client.set(path + "/@spec", _clean_spec(op.spec))
        client.set(path + "/@state", op.state)
        if op.error is not None:
            client.set(path + "/@error", op.error)


def _clean_spec(spec: dict) -> dict:
    return {k: v for k, v in spec.items() if not callable(v)}


# -- controllers ---------------------------------------------------------------


def _sort_controller(client, spec: dict) -> dict:
    """Ref: sort_controller.cpp — here: read input chunks, device sort (or
    mesh shuffle when a mesh is attached), write output."""
    from ytsaurus_tpu.operations.sort_op import sort_chunks

    input_path = _one(spec, "input_table_path")
    output_path = _one(spec, "output_table_path")
    sort_by = spec["sort_by"]
    if isinstance(sort_by, str):
        sort_by = [sort_by]
    chunks = client._read_table_chunks(input_path)
    if not chunks:
        client._write_table_chunks(output_path, [], sorted_by=sort_by)
        return {"rows": 0}
    out = sort_chunks(chunks, sort_by,
                      descending=spec.get("descending", False))
    client._write_table_chunks(output_path, [out], sorted_by=sort_by,
                               schema=out.schema)
    return {"rows": out.row_count}


def _merge_controller(client, spec: dict) -> dict:
    """Ref: ordered/sorted merge (ordered_controller.cpp,
    sorted_controller.cpp)."""
    from ytsaurus_tpu.chunks.columnar import concat_chunks
    from ytsaurus_tpu.operations.sort_op import sort_chunks

    input_paths = spec["input_table_paths"]
    output_path = _one(spec, "output_table_path")
    mode = spec.get("mode", "unordered")
    chunks = []
    for path in input_paths:
        chunks.extend(client._read_table_chunks(path))
    if not chunks:
        client._write_table_chunks(output_path, [])
        return {"rows": 0}
    chunks = _align_schemas(chunks)
    if mode == "sorted":
        key_names = spec.get("merge_by") or \
            chunks[0].schema.key_column_names
        if not key_names:
            raise YtError("sorted merge requires merge_by or sorted input")
        out = sort_chunks(chunks, key_names)
        client._write_table_chunks(output_path, [out], sorted_by=key_names,
                                   schema=out.schema)
    else:
        out = concat_chunks(chunks) if len(chunks) > 1 else chunks[0]
        client._write_table_chunks(output_path, [out], schema=out.schema)
    return {"rows": out.row_count}


def _map_controller(client, spec: dict) -> dict:
    """Ref: unordered_controller.cpp + the user-process map job
    (job_proxy/user_job.cpp).  The mapper is a Python callable
    rows→rows (row-dict iterables); query-shaped mappers should use
    select_rows instead."""
    mapper: Callable = spec["mapper"]
    input_path = _one(spec, "input_table_path")
    output_path = _one(spec, "output_table_path")
    chunks = client._read_table_chunks(input_path)
    out_rows: list[dict] = []
    for chunk in chunks:
        result = mapper(chunk.to_rows())
        out_rows.extend(result)
    schema = spec.get("output_schema")
    client.write_table(output_path, out_rows, schema=schema)
    return {"rows": len(out_rows)}


def _erase_controller(client, spec: dict) -> dict:
    path = _one(spec, "table_path")
    client._write_table_chunks(path, [])
    return {"rows": 0}


def _align_schemas(chunks):
    """Inputs from different tables may agree on columns but differ in order
    or sort annotations; align them onto one unsorted schema for merging."""
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    from ytsaurus_tpu.schema import TableSchema

    base = {c.name: c.type for c in chunks[0].schema}
    for chunk in chunks[1:]:
        other = {c.name: c.type for c in chunk.schema}
        if other != base:
            raise YtError(
                f"Merge inputs have incompatible schemas: {sorted(base)} vs "
                f"{sorted(other)}", code=EErrorCode.QueryTypeError)
    target = TableSchema.make(
        [(c.name, c.type.value) for c in chunks[0].schema])
    return [
        ColumnarChunk(schema=target, row_count=chunk.row_count,
                      columns={name: chunk.columns[name]
                               for name in target.column_names})
        for chunk in chunks
    ]


def _one(spec: dict, key: str) -> str:
    value = spec.get(key)
    if not value or not isinstance(value, str):
        raise YtError(f"Operation spec requires {key!r}")
    return value


_CONTROLLERS = {
    "sort": _sort_controller,
    "merge": _merge_controller,
    "map": _map_controller,
    "erase": _erase_controller,
}
