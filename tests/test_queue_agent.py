"""Queue agent: consumers, offsets, lags, auto-trim.

Ref model: client/queue_client consumer tables + server/queue_agent
controller passes (status, vital-consumer-gated trimming).
"""

import pytest

from ytsaurus_tpu import YtError
from ytsaurus_tpu.client import connect
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.server.queue_agent import QueueAgent

QUEUE_SCHEMA = TableSchema.make([("msg", "string"), ("n", "int64")])


@pytest.fixture
def client(tmp_path):
    return connect(str(tmp_path))


def make_queue(client, path, n_rows=10):
    client.create("table", path, recursive=True,
                  attributes={"schema": QUEUE_SCHEMA, "dynamic": True})
    client.mount_table(path)
    client.push_queue(path, [{"msg": f"m{i}", "n": i} for i in range(n_rows)])


def test_consumer_pull_advance_cycle(client):
    make_queue(client, "//q")
    client.register_queue_consumer("//q", "//c")
    rows, next_off = client.pull_consumer("//c", "//q", limit=4)
    assert [r["n"] for r in rows] == [0, 1, 2, 3]
    assert next_off == 4
    client.advance_consumer("//c", "//q", next_off)
    rows, next_off = client.pull_consumer("//c", "//q", limit=4)
    assert [r["n"] for r in rows] == [4, 5, 6, 7]
    assert next_off == 8
    # Optimistic concurrency: stale old_offset is rejected.
    with pytest.raises(YtError):
        client.advance_consumer("//c", "//q", 9, old_offset=2)
    client.advance_consumer("//c", "//q", 8, old_offset=4)
    # Offsets never move backwards.
    with pytest.raises(YtError):
        client.advance_consumer("//c", "//q", 3)


def test_queue_status_and_lag(client):
    make_queue(client, "//q", n_rows=6)
    client.register_queue_consumer("//q", "//c1")
    client.register_queue_consumer("//q", "//c2", vital=False)
    client.advance_consumer("//c1", "//q", 4)
    agent = QueueAgent(client)
    status = agent.queue_status("//q")
    assert status["partitions"][0]["upper_row_index"] == 6
    assert status["consumers"]["//c1"] == {
        "offset": 4, "lag": 2, "vital": True}
    assert status["consumers"]["//c2"]["lag"] == 6
    assert status["consumers"]["//c2"]["vital"] is False


def test_auto_trim_gated_by_vital_consumers(client):
    make_queue(client, "//q", n_rows=10)
    client.set("//q/@auto_trim_config", {"enable": True})
    client.register_queue_consumer("//q", "//vital1")
    client.register_queue_consumer("//q", "//vital2")
    client.register_queue_consumer("//q", "//lazy", vital=False)
    client.advance_consumer("//vital1", "//q", 7)
    client.advance_consumer("//vital2", "//q", 5)
    agent = QueueAgent(client)
    out = agent.step()
    # Trim to min(vital offsets)=5; the non-vital consumer at 0 is ignored.
    assert out["//q"]["partitions"][0]["trimmed_row_count"] == 5
    assert out["//q"]["partitions"][0]["available_row_count"] == 5
    # @queue_status exported for observability.
    assert client.get("//q/@queue_status")["partitions"][0][
        "trimmed_row_count"] == 5
    # A consumer behind the trim horizon resumes at the horizon.
    rows, next_off = client.pull_consumer("//lazy", "//q", limit=2)
    assert [r["n"] for r in rows] == [5, 6]
    assert next_off == 7


def test_unregister_consumer(client):
    make_queue(client, "//q")
    client.register_queue_consumer("//q", "//c")
    client.unregister_queue_consumer("//q", "//c")
    agent = QueueAgent(client)
    assert agent.queue_status("//q")["consumers"] == {}
    assert agent._registered_queues() == []


def test_register_validates(client):
    make_queue(client, "//q")
    # Non-queue target rejected.
    client.write_table("//static", [{"a": 1}])
    with pytest.raises(YtError):
        client.register_queue_consumer("//static", "//c")
    # Existing non-consumer table rejected as a consumer.
    with pytest.raises(YtError):
        client.register_queue_consumer("//q", "//static")
