"""Backend selection helper for driver entry points.

A dead TPU tunnel HANGS backend initialization (it does not raise), so the
health probe runs `jax.devices()` in a subprocess with a timeout before this
process touches backends; on failure the process falls back to CPU with a
stderr notice so results are never silently mislabeled.
"""

from __future__ import annotations

import os
import subprocess
import sys

_PROBED = False


def ensure_backend(timeout: float = 120.0):
    """Returns the jax module with a usable backend selected."""
    global _PROBED
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # Even an explicit-CPU env can hang if an accelerator plugin was
        # pre-registered at interpreter start; pinning via jax.config takes
        # effect immediately in this process.
        jax.config.update("jax_platforms", "cpu")
        jax.devices()
        return jax
    if not _PROBED:
        _PROBED = True
        try:
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout, check=True, capture_output=True,
                env=dict(os.environ))
        except subprocess.TimeoutExpired:
            print(f"# accelerator backend probe HUNG (> {timeout:.0f}s; "
                  "dead tunnel?); falling back to CPU", file=sys.stderr)
            jax.config.update("jax_platforms", "cpu")
        except subprocess.CalledProcessError as exc:
            tail = (exc.stderr or b"")[-800:].decode("utf-8", "replace")
            print("# accelerator backend probe FAILED; falling back to CPU. "
                  f"probe stderr tail:\n{tail}", file=sys.stderr)
            jax.config.update("jax_platforms", "cpu")
        except Exception as exc:  # pragma: no cover - defensive
            print(f"# accelerator backend probe errored ({exc!r}); "
                  "falling back to CPU", file=sys.stderr)
            jax.config.update("jax_platforms", "cpu")
    jax.devices()
    return jax
