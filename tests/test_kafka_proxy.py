"""Kafka proxy: wire-protocol (v0) round-trips against a live TCP
listener backed by ordered tables.

Ref model: yt/yt/server/kafka_proxy — stock Kafka clients against YT
queues.  No Kafka client library ships in this image, so the test
speaks the public v0 wire format directly over TCP (framing, request
headers, message sets built per spec — exercising the server exactly
as a real client would).
"""

import socket
import struct

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.server.kafka_proxy import (
    API_FETCH,
    API_LIST_OFFSETS,
    API_METADATA,
    API_OFFSET_COMMIT,
    API_OFFSET_FETCH,
    API_PRODUCE,
    API_VERSIONS,
    KafkaProxy,
    Reader,
    array,
    bytes_,
    encode_message,
    i16,
    i32,
    i64,
    string,
)


@pytest.fixture
def proxy(tmp_path):
    client = connect(str(tmp_path / "c"))
    p = KafkaProxy(client, topic_root="//kafka").start()
    yield p
    p.stop()


def call(proxy, api_key, body, version=0, client_id="pytest"):
    """One framed request/response round-trip over a fresh socket."""
    payload = i16(api_key) + i16(version) + i32(77) + string(client_id) \
        + body
    with socket.create_connection((proxy.host, proxy.port),
                                  timeout=30) as sock:
        sock.sendall(struct.pack(">i", len(payload)) + payload)
        header = sock.recv(4)
        (length,) = struct.unpack(">i", header)
        data = b""
        while len(data) < length:
            chunk = sock.recv(length - len(data))
            assert chunk, "connection closed mid-response"
            data += chunk
    r = Reader(data)
    assert r.i32() == 77            # correlation id echoes
    return r


def test_kafka_proxy_in_cluster_daemon(tmp_path):
    """The proxy runs inside the primary daemon (real process): produce
    over TCP, then observe the rows through the Python thin client."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    from ytsaurus_tpu.environment import LocalCluster
    from ytsaurus_tpu.remote_client import connect_remote

    with LocalCluster(str(tmp_path / "kc"), n_nodes=1,
                      replication_factor=1, kafka_proxy=True) as cluster:
        host, port = cluster.kafka_address.rsplit(":", 1)

        class P:                                   # call() shim
            pass
        p = P()
        p.host, p.port = host, int(port)
        _produce(p, "wire", [(b"a", b"1"), (None, b"2")])
        high, msgs = _fetch(p, "wire", 0)
        assert high == 2
        assert msgs == [(0, b"a", b"1"), (1, None, b"2")]
        cl = connect_remote(cluster.primary_address)
        rows = cl.pull_queue("//kafka/wire", offset=0)
        assert [r["value"] for r in rows] == [b"1", b"2"]


def test_api_versions(proxy):
    r = call(proxy, API_VERSIONS, b"")
    assert r.i16() == 0
    n = r.i32()
    keys = []
    for _ in range(n):
        keys.append(r.i16())
        r.i16()
        r.i16()
    assert {API_PRODUCE, API_FETCH, API_METADATA,
            API_VERSIONS} <= set(keys)


def test_metadata_auto_creates_topic(proxy):
    r = call(proxy, API_METADATA, array([string("events")]))
    n_brokers = r.i32()
    assert n_brokers == 1
    assert r.i32() == 0             # broker node id
    assert r.string() == proxy.host
    assert r.i32() == proxy.port
    n_topics = r.i32()
    assert n_topics == 1
    assert r.i16() == 0             # topic error
    assert r.string() == "events"
    n_parts = r.i32()
    assert n_parts == 1
    assert r.i16() == 0 and r.i32() == 0
    # The backing ordered table exists.
    assert proxy.client.exists("//kafka/events")


def _produce(proxy, topic, records):
    message_set = b"".join(
        encode_message(k, v, 0) for k, v in records)
    body = i16(1) + i32(30000) + array([
        string(topic) + array([i32(0) + bytes_(message_set)])])
    r = call(proxy, API_PRODUCE, body)
    assert r.i32() == 1
    assert r.string() == topic
    assert r.i32() == 1
    assert r.i32() == 0             # partition
    assert r.i16() == 0             # error
    return r.i64()                  # base offset


def _fetch(proxy, topic, offset, max_bytes=1 << 20):
    body = i32(-1) + i32(100) + i32(1) + array([
        string(topic) + array([i32(0) + i64(offset) + i32(max_bytes)])])
    r = call(proxy, API_FETCH, body)
    assert r.i32() == 1
    assert r.string() == topic
    assert r.i32() == 1
    assert r.i32() == 0
    assert r.i16() == 0
    high = r.i64()
    blob = r.bytes_() or b""
    out = []
    rr = Reader(blob)
    while rr.pos + 12 <= len(rr.data):
        off = rr.i64()
        size = rr.i32()
        msg = Reader(rr._take(size))
        msg.i32()
        msg.i8()
        msg.i8()
        out.append((off, msg.bytes_(), msg.bytes_()))
    return high, out


def test_produce_fetch_roundtrip(proxy):
    base = _produce(proxy, "logs", [(b"k1", b"hello"), (None, b"world")])
    assert base == 0
    high, msgs = _fetch(proxy, "logs", 0)
    assert high == 2
    assert msgs == [(0, b"k1", b"hello"), (1, None, b"world")]
    # Append more; fetch from a mid offset.
    assert _produce(proxy, "logs", [(b"k3", b"!")]) == 2
    high, msgs = _fetch(proxy, "logs", 2)
    assert high == 3
    assert msgs == [(2, b"k3", b"!")]
    # Fetch at the head: empty message set, watermark reported.
    high, msgs = _fetch(proxy, "logs", 3)
    assert high == 3 and msgs == []


def test_unsupported_version_answered_in_v0_shape(proxy):
    r = call(proxy, API_VERSIONS, b"", version=3)
    assert r.i16() == 35            # UNSUPPORTED_VERSION, v0 body
    assert r.i32() > 0              # supported api array still present


def test_acks_zero_produce_sends_no_response(proxy):
    message_set = encode_message(None, b"fire-and-forget", 0)
    body = i16(0) + i32(30000) + array([
        string("noack") + array([i32(0) + bytes_(message_set)])])
    payload = i16(API_PRODUCE) + i16(0) + i32(5) + string("t") + body
    with socket.create_connection((proxy.host, proxy.port),
                                  timeout=10) as sock:
        sock.sendall(struct.pack(">i", len(payload)) + payload)
        # No response frame: the next (normal) request's response must be
        # the FIRST bytes read — correlation id framing stays in sync.
        payload2 = i16(API_VERSIONS) + i16(0) + i32(42) + string("t")
        sock.sendall(struct.pack(">i", len(payload2)) + payload2)
        header = sock.recv(4)
        (length,) = struct.unpack(">i", header)
        data = b""
        while len(data) < length:
            data += sock.recv(length - len(data))
        assert Reader(data).i32() == 42
    # The acks=0 write still landed.
    _, msgs = _fetch(proxy, "noack", 0)
    assert [v for _, _, v in msgs] == [b"fire-and-forget"]


def test_compressed_message_set_rejected(proxy):
    _produce(proxy, "gz", [(None, b"plain")])      # topic exists
    # attributes=1 (gzip wrapper): refused with CORRUPT_MESSAGE.
    body_msg = struct.pack(">b", 0) + struct.pack(">b", 1) + \
        i32(-1) + bytes_(b"\x1f\x8b-not-really-gzip")
    import zlib as _z
    crc = struct.unpack(">i", struct.pack(
        ">I", _z.crc32(body_msg) & 0xFFFFFFFF))[0]
    message_set = i64(0) + i32(len(body_msg) + 4) + i32(crc) + body_msg
    body = i16(1) + i32(30000) + array([
        string("gz") + array([i32(0) + bytes_(message_set)])])
    r = call(proxy, API_PRODUCE, body)
    r.i32()
    assert r.string() == "gz"
    r.i32()
    assert r.i32() == 0
    assert r.i16() == 2             # CORRUPT_MESSAGE
    # Nothing was appended.
    high, _ = _fetch(proxy, "gz", 0)
    assert high == 1


def test_fetch_respects_max_bytes(proxy):
    _produce(proxy, "big", [(None, bytes(200)) for _ in range(10)])
    _, msgs = _fetch(proxy, "big", 0, max_bytes=500)
    assert 1 <= len(msgs) < 10


def test_fetch_long_poll_blocks_until_data(proxy):
    """max_wait/min_bytes: a fetch at the head blocks until a producer
    appends (or the wait elapses) instead of busy-returning empty."""
    import threading
    import time as _time

    _produce(proxy, "lp", [(None, b"seed")])

    def delayed_produce():
        _time.sleep(0.4)
        _produce(proxy, "lp", [(None, b"fresh")])

    t = threading.Thread(target=delayed_produce)
    t.start()
    t0 = _time.monotonic()
    body = i32(-1) + i32(5000) + i32(1) + array([
        string("lp") + array([i32(0) + i64(1) + i32(1 << 20)])])
    r = call(proxy, API_FETCH, body)
    elapsed = _time.monotonic() - t0
    t.join()
    r.i32()
    r.string()
    r.i32()
    r.i32()
    assert r.i16() == 0
    assert r.i64() == 2                     # watermark after the append
    blob = r.bytes_() or b""
    assert b"fresh" in blob
    assert 0.3 < elapsed < 4.0              # blocked, then woke on data
    # An empty poll with a short wait returns promptly and empty.
    t0 = _time.monotonic()
    body = i32(-1) + i32(200) + i32(1) + array([
        string("lp") + array([i32(0) + i64(2) + i32(1 << 20)])])
    r = call(proxy, API_FETCH, body)
    assert _time.monotonic() - t0 < 2.0
    r.i32(); r.string(); r.i32(); r.i32(); r.i16(); r.i64()
    assert (r.bytes_() or b"") == b""


def test_list_offsets(proxy):
    _produce(proxy, "off", [(None, b"a"), (None, b"b")])
    body = i32(-1) + array([
        string("off") + array([i32(0) + i64(-1) + i32(1)])])
    r = call(proxy, API_LIST_OFFSETS, body)
    r.i32()
    assert r.string() == "off"
    r.i32()
    assert r.i32() == 0 and r.i16() == 0
    n = r.i32()
    assert n == 1 and r.i64() == 2          # latest == high watermark


def test_offset_commit_and_fetch(proxy):
    _produce(proxy, "grp", [(None, b"x"), (None, b"y"), (None, b"z")])
    body = string("team-a") + array([
        string("grp") + array([i32(0) + i64(2) + string("")])])
    r = call(proxy, API_OFFSET_COMMIT, body)
    r.i32()
    assert r.string() == "grp"
    r.i32()
    assert r.i32() == 0 and r.i16() == 0
    # Offset fetch round-trips the committed position.
    body = string("team-a") + array([
        string("grp") + array([i32(0)])])
    r = call(proxy, API_OFFSET_FETCH, body)
    r.i32()
    assert r.string() == "grp"
    r.i32()
    assert r.i32() == 0
    assert r.i64() == 2
    # Unknown group: -1 (no committed offset).
    body = string("team-b") + array([
        string("grp") + array([i32(0)])])
    r = call(proxy, API_OFFSET_FETCH, body)
    r.i32()
    r.string()
    r.i32()
    r.i32()
    assert r.i64() == -1


# -- consumer groups (ref group_coordinator.h) ---------------------------------

from ytsaurus_tpu.server.kafka_proxy import (  # noqa: E402
    API_FIND_COORDINATOR,
    API_HEARTBEAT,
    API_JOIN_GROUP,
    API_LEAVE_GROUP,
    API_SYNC_GROUP,
)


def _join(proxy, group, member_id="", session_ms=30000,
          protocols=(("range", b"subscribed"),)):
    body = string(group) + i32(session_ms) + string(member_id) + \
        string("consumer") + array([string(n) + bytes_(m)
                                    for n, m in protocols])
    r = call(proxy, API_JOIN_GROUP, body)
    err = r.i16()
    generation = r.i32()
    protocol = r.string()
    leader = r.string()
    mid = r.string()
    n = r.i32()
    members = [(r.string(), r.bytes_()) for _ in range(max(n, 0))]
    return {"error": err, "generation": generation, "protocol": protocol,
            "leader": leader, "member_id": mid, "members": members}


def _sync(proxy, group, generation, member_id, assignments=()):
    body = string(group) + i32(generation) + string(member_id) + \
        array([string(m) + bytes_(b) for m, b in assignments])
    r = call(proxy, API_SYNC_GROUP, body)
    return r.i16(), r.bytes_()


def _heartbeat(proxy, group, generation, member_id):
    body = string(group) + i32(generation) + string(member_id)
    return call(proxy, API_HEARTBEAT, body).i16()


def test_find_coordinator_points_here(proxy):
    r = call(proxy, API_FIND_COORDINATOR, string("team"))
    assert r.i16() == 0
    assert r.i32() == 0
    assert r.string() == proxy.host and r.i32() == proxy.port


def test_single_member_group_lifecycle(proxy):
    j = _join(proxy, "g1")
    assert j["error"] == 0
    assert j["leader"] == j["member_id"]
    assert j["protocol"] == "range"
    assert j["members"] == [(j["member_id"], b"subscribed")]
    err, assignment = _sync(proxy, "g1", j["generation"], j["member_id"],
                            [(j["member_id"], b"p0")])
    assert err == 0 and assignment == b"p0"
    assert _heartbeat(proxy, "g1", j["generation"], j["member_id"]) == 0
    # Wrong generation / unknown member are rejected.
    assert _heartbeat(proxy, "g1", j["generation"] + 5,
                      j["member_id"]) == 22
    assert _heartbeat(proxy, "g1", j["generation"], "ghost") == 25


def test_two_consumers_rebalance_on_member_death(proxy):
    """The VERDICT done-criterion: two concurrent consumers over TCP;
    killing one (stopping its heartbeats) rebalances the survivor."""
    import threading
    import time as _time

    # A joins alone and stabilizes (short session: its death must be
    # noticed quickly).
    a = _join(proxy, "g2", session_ms=1500)
    assert a["error"] == 0
    _sync(proxy, "g2", a["generation"], a["member_id"],
          [(a["member_id"], b"all")])

    # B joins -> group enters rebalance; A must rejoin for the round to
    # close, prompted by its heartbeat.
    b_result = {}

    def join_b():
        b_result.update(_join(proxy, "g2", session_ms=30000))

    thread = threading.Thread(target=join_b)
    thread.start()
    deadline = _time.monotonic() + 10
    while _time.monotonic() < deadline:
        if _heartbeat(proxy, "g2", a["generation"],
                      a["member_id"]) == 27:      # REBALANCE_IN_PROGRESS
            break
        _time.sleep(0.1)
    a2 = _join(proxy, "g2", member_id=a["member_id"], session_ms=1500)
    thread.join(timeout=30)
    assert a2["error"] == 0 and b_result.get("error") == 0
    assert a2["generation"] == b_result["generation"] > a["generation"]
    assert a2["leader"] == b_result["leader"]
    leader, follower = (a2, b_result) \
        if a2["leader"] == a2["member_id"] else (b_result, a2)
    assert len(leader["members"]) == 2
    assignments = [(mid, f"part-{i}".encode())
                   for i, (mid, _meta) in enumerate(leader["members"])]
    err, leader_assign = _sync(proxy, "g2", leader["generation"],
                               leader["member_id"], assignments)
    assert err == 0 and leader_assign
    err, follower_assign = _sync(proxy, "g2", follower["generation"],
                                 follower["member_id"])
    assert err == 0 and follower_assign
    assert {leader_assign, follower_assign} == \
        {b"part-0", b"part-1"}

    # A dies (no more heartbeats).  The sweeper expires it; B is pulled
    # into a new round and ends up sole leader of the next generation.
    b_id = b_result["member_id"]
    deadline = _time.monotonic() + 15
    code = 0
    while _time.monotonic() < deadline:
        code = _heartbeat(proxy, "g2", b_result["generation"], b_id)
        if code == 27:
            break
        _time.sleep(0.3)
    assert code == 27, "survivor never saw the rebalance"
    b2 = _join(proxy, "g2", member_id=b_id)
    assert b2["error"] == 0
    assert b2["generation"] > b_result["generation"]
    assert b2["leader"] == b_id
    assert len(b2["members"]) == 1
    err, assignment = _sync(proxy, "g2", b2["generation"], b_id,
                            [(b_id, b"everything")])
    assert err == 0 and assignment == b"everything"


def test_leave_group_triggers_rebalance(proxy):
    a = _join(proxy, "g3")
    assert a["error"] == 0
    _sync(proxy, "g3", a["generation"], a["member_id"],
          [(a["member_id"], b"x")])
    body = string("g3") + string(a["member_id"])
    assert call(proxy, API_LEAVE_GROUP, body).i16() == 0
    # Gone: its heartbeats are now rejected.
    assert _heartbeat(proxy, "g3", a["generation"], a["member_id"]) == 25


def test_api_versions_advertises_v1_and_v1_bodies_parse(proxy):
    from ytsaurus_tpu.server.kafka_proxy import (
        API_VERSIONS,
        SUPPORTED_VERSIONS,
    )
    r = call(proxy, API_VERSIONS, b"")
    assert r.i16() == 0
    n = r.i32()
    advertised = {}
    for _ in range(n):
        key = r.i16()
        r.i16()                         # min
        advertised[key] = r.i16()       # max
    assert advertised[API_PRODUCE] == 1
    assert advertised[API_FETCH] == 1
    assert advertised == SUPPORTED_VERSIONS
    # Produce v1: response carries the throttle_time tail.
    msg = encode_message(None, b"v1-payload", 0)
    body = i16(1) + i32(1000) + array([
        string("vt") + array([i32(0) + bytes_(msg)])])
    r = call(proxy, API_PRODUCE, body, version=1)
    n = r.i32()
    assert n == 1
    assert r.string() == "vt"
    r.i32()
    assert r.i32() == 0 and r.i16() == 0
    r.i64()                             # base offset
    assert r.i32() == 0                 # throttle_time_ms
    # Fetch v1: throttle_time comes FIRST.
    body = i32(-1) + i32(0) + i32(0) + array([
        string("vt") + array([i32(0) + i64(0) + i32(1 << 20)])])
    r = call(proxy, API_FETCH, body, version=1)
    assert r.i32() == 0                 # throttle_time_ms
    assert r.i32() == 1                 # topic count
    assert r.string() == "vt"
    # Versions past the advertised max still close the connection.
    import socket as _socket
    import struct as _struct
    payload = i16(API_FETCH) + i16(9) + i32(5) + string("x") + b""
    with _socket.create_connection((proxy.host, proxy.port),
                                   timeout=10) as sock:
        sock.sendall(_struct.pack(">i", len(payload)) + payload)
        assert sock.recv(4) == b""      # closed
