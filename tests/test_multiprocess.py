"""Multi-process cluster integration: 1 primary + 2 data-node daemons.

The YTInstance-style launcher spins REAL processes; the thin client talks
driver RPC to the primary while chunk data moves client↔data-node
directly.  Mirrors tests/test_client.py's coverage surface over the wire.
"""

import numpy as np
import pytest

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.remote_client import connect_remote
from ytsaurus_tpu.schema import TableSchema


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    from ytsaurus_tpu.environment import LocalCluster
    with LocalCluster(str(tmp_path_factory.mktemp("mpcluster")),
                      n_nodes=2) as c:
        yield c


@pytest.fixture()
def client(cluster):
    cl = connect_remote(cluster.primary_address)
    yield cl
    cl.close()


def test_cypress_crud_over_rpc(client):
    client.create("map_node", "//mp/crud/user", recursive=True)
    client.set("//mp/crud/user/@owner", "tester")
    assert client.get("//mp/crud/user/@owner") == "tester"
    assert client.exists("//mp/crud/user")
    assert client.list("//mp/crud") == ["user"]
    client.create("document", "//mp/crud/user/doc")
    client.set("//mp/crud/user/doc", {"a": [1, 2]})
    assert client.get("//mp/crud/user/doc") == {"a": [1, 2]}
    client.remove("//mp/crud/user")
    assert not client.exists("//mp/crud/user")


def test_write_read_table_roundtrip(client):
    rows = [{"name": "a", "score": 1.5}, {"name": "b", "score": None}]
    client.write_table("//mp/static/t", rows)
    assert client.read_table("//mp/static/t") == \
        [{"name": b"a", "score": 1.5}, {"name": b"b", "score": None}]
    assert client.get("//mp/static/t/@row_count") == 2


def test_chunks_replicated_across_node_processes(cluster, client):
    client.write_table("//mp/repl/t", [{"x": i} for i in range(100)])
    chunk_ids = client.get("//mp/repl/t/@chunk_ids")
    assert chunk_ids
    # Both replicas exist: ask each node directly.
    from ytsaurus_tpu.rpc import Channel
    for cid in chunk_ids:
        found = 0
        for address in cluster.node_addresses:
            ch = Channel(address, timeout=10)
            body, _ = ch.call("data_node", "has_chunk", {"chunk_id": cid})
            found += bool(body.get("exists"))
            ch.close()
        assert found == 2, f"chunk {cid} has {found} replicas"


def test_select_rows_server_side(client):
    client.write_table("//mp/q/t", [{"k": i, "v": i * 10}
                                    for i in range(50)])
    rows = client.select_rows(
        "k, v FROM [//mp/q/t] WHERE k >= 40 ORDER BY k ASC LIMIT 3")
    assert rows == [{"k": 40, "v": 400}, {"k": 41, "v": 410},
                    {"k": 42, "v": 420}]


def test_dynamic_table_over_rpc(client):
    schema = TableSchema.make([("k", "int64", "ascending"), ("v", "string")])
    client.create("table", "//mp/dyn/t", recursive=True,
                  attributes={"schema": schema, "dynamic": True})
    client.mount_table("//mp/dyn/t")
    client.insert_rows("//mp/dyn/t", [{"k": 1, "v": "one"},
                                      {"k": 2, "v": "two"}])
    out = client.lookup_rows("//mp/dyn/t", [(1,), (3,)])
    assert out[0]["v"] == b"one"
    assert out[1] is None
    client.delete_rows("//mp/dyn/t", [(2,)])
    rows = client.select_rows("k, v FROM [//mp/dyn/t]")
    assert [r["k"] for r in rows] == [1]
    client.unmount_table("//mp/dyn/t")
    client.mount_table("//mp/dyn/t")       # remount restores from chunks
    assert client.select_rows("k FROM [//mp/dyn/t]") == [{"k": 1}]


def test_transaction_conflict_over_rpc(client):
    schema = TableSchema.make([("k", "int64", "ascending"), ("v", "int64")])
    client.create("table", "//mp/tx/t", recursive=True,
                  attributes={"schema": schema, "dynamic": True})
    client.mount_table("//mp/tx/t")
    tx1 = client.start_transaction()
    tx2 = client.start_transaction()
    client.insert_rows("//mp/tx/t", [{"k": 1, "v": 10}], tx=tx1)
    client.insert_rows("//mp/tx/t", [{"k": 1, "v": 20}], tx=tx2)
    client.commit_transaction(tx1)
    with pytest.raises(YtError) as ei:
        client.commit_transaction(tx2)
    assert ei.value.contains(EErrorCode.TransactionLockConflict)
    assert client.lookup_rows("//mp/tx/t", [(1,)])[0]["v"] == 10


def test_queue_over_rpc(client):
    schema = TableSchema.make([("msg", "string"), ("n", "int64")])
    client.create("table", "//mp/queue/q", recursive=True,
                  attributes={"schema": schema, "dynamic": True,
                              "ordered": True})
    client.mount_table("//mp/queue/q")
    first = client.push_queue("//mp/queue/q", [{"msg": "a", "n": 1},
                                               {"msg": "b", "n": 2}])
    assert first == 0
    rows = client.pull_queue("//mp/queue/q", 1)
    assert rows[0]["msg"] == b"b"
    client.trim_rows("//mp/queue/q", 1)
    assert [r["n"] for r in client.pull_queue("//mp/queue/q", 0)] == [2]


def test_operations_over_rpc(client):
    client.write_table("//mp/ops/in",
                       [{"k": 3, "v": 1}, {"k": 1, "v": 2}, {"k": 2, "v": 3}])
    op = client.run_sort("//mp/ops/in", "//mp/ops/sorted", ["k"])
    assert op.state == "completed"
    assert [r["k"] for r in client.read_table("//mp/ops/sorted")] == \
        [1, 2, 3]
    op = client.run_map(lambda rows: [{"k2": r["k"] * 2} for r in rows],
                        "//mp/ops/sorted", "//mp/ops/mapped")
    assert op.state == "completed"
    assert [r["k2"] for r in client.read_table("//mp/ops/mapped")] == \
        [2, 4, 6]


def test_reduce_and_map_reduce_over_rpc(client):
    rows = [{"k": i % 4, "v": i} for i in range(40)]
    client.write_table("//mp/red/in", rows)
    client.run_sort("//mp/red/in", "//mp/red/sorted", ["k"])

    def reducer(key, group):
        return [{"k": key["k"], "n": len(group)}]

    op = client.run_reduce(reducer, "//mp/red/sorted", "//mp/red/out",
                           reduce_by="k")
    assert op.state == "completed"
    assert {r["k"]: r["n"]
            for r in client.read_table("//mp/red/out")} == \
        {k: 10 for k in range(4)}
    op = client.run_map_reduce(
        None, reducer, "//mp/red/in", "//mp/red/mr", reduce_by="k",
        partition_count=2)
    assert op.state == "completed"
    assert {r["k"]: r["n"]
            for r in client.read_table("//mp/red/mr")} == \
        {k: 10 for k in range(4)}


def test_error_codes_cross_the_wire(client):
    with pytest.raises(YtError) as ei:
        client.read_table("//mp/none/such")
    assert ei.value.code == EErrorCode.NoSuchNode


def test_node_failure_read_fallback(tmp_path):
    from ytsaurus_tpu.environment import LocalCluster
    with LocalCluster(str(tmp_path / "failover"), n_nodes=2) as cluster:
        client = connect_remote(cluster.primary_address)
        client.write_table("//mp/ha/t", [{"x": i} for i in range(500)])
        cluster.kill_node(0)
        # Replica on the surviving node serves the read.
        rows = client.read_table("//mp/ha/t")
        assert len(rows) == 500
        client.close()


@pytest.mark.slow   # ~28s; tier-1 keeps restart/revival coverage via
# test_scheduler_daemon::test_kill9_mid_operation_revives_and_completes and
# the quorum-WAL recovery suite (test_quorum_wal).
def test_primary_restart_recovers_metadata(tmp_path):
    from ytsaurus_tpu.environment import LocalCluster
    root = str(tmp_path / "restartable")
    with LocalCluster(root, n_nodes=2) as cluster:
        client = connect_remote(cluster.primary_address)
        client.create("map_node", "//mp/meta", recursive=True)
        client.set("//mp/meta/@answer", 42)
        client.write_table("//mp/meta/t", [{"x": 7}])
        client.close()
    # Entire cluster restarts from on-disk state.
    with LocalCluster(root, n_nodes=2) as cluster:
        client = connect_remote(cluster.primary_address)
        assert client.get("//mp/meta/@answer") == 42
        assert client.read_table("//mp/meta/t") == [{"x": 7}]
        client.close()


@pytest.mark.slow   # ~22s; tier-1 keeps WAL recovery coverage via
# test_primary_restart_recovers_metadata + the test_quorum_wal suite
def test_quorum_wal_survives_primary_disk_loss(tmp_path):
    """The master's metadata must recover from node journal replicas after
    the primary's local changelog is destroyed (quorum-of-3 WAL)."""
    import os
    import shutil
    from ytsaurus_tpu.environment import LocalCluster
    root = str(tmp_path / "quorum")
    with LocalCluster(root, n_nodes=2) as cluster:
        client = connect_remote(cluster.primary_address)
        client.create("map_node", "//mp/wal", recursive=True)
        client.set("//mp/wal/@k", "precious")
        client.close()
    # Destroy the primary's local WAL (keep journal config + snapshot-less
    # master dir shape).
    changelog = os.path.join(root, "primary", "master", "changelog.log")
    assert os.path.exists(changelog)
    os.unlink(changelog)
    with LocalCluster(root, n_nodes=2) as cluster:
        client = connect_remote(cluster.primary_address)
        assert client.get("//mp/wal/@k") == "precious"
        client.close()
