"""Tablet balancer: keep tablet sizes bounded by automatic resharding.

Ref mapping:
  server/tablet_balancer (+ master-side     → TabletBalancer.step scans
  tablet_manager reshard actions)             mounted sorted dynamic
                                              tables and reshards the
                                              unbalanced ones
  partition sample keys                      → pivot selection samples row
  (tablet_node/partition.h:39-49)             keys from tablet snapshots
                                              and cuts at row-count
                                              quantiles
  @enable_tablet_balancer / desired sizes    → same attributes here
  (bundle/tablet config)

Design delta: resharding is the existing pivot-rewrite path (unmount →
reshard → remount), so balancing is a policy loop over row-count stats,
not a separate data mover.
"""

from __future__ import annotations

from typing import Optional

from ytsaurus_tpu.errors import YtError

DEFAULT_DESIRED_ROWS = 1_000_000


class TabletBalancer:
    def __init__(self, client,
                 desired_tablet_rows: int = DEFAULT_DESIRED_ROWS):
        self.client = client
        self.desired_tablet_rows = desired_tablet_rows

    def _table_desired(self, node) -> int:
        return int(node.attributes.get("desired_tablet_row_count")
                   or self.desired_tablet_rows)

    def tablet_row_counts(self, path: str) -> list[int]:
        return [t.read_snapshot().row_count
                for t in self.client._mounted_tablets(path)]

    def needs_balancing(self, path: str) -> bool:
        """Split-worthy: a tablet over 2x desired; merge-worthy: two
        adjacent tablets together under half the desired size."""
        node = self.client._table_node(path)
        desired = self._table_desired(node)
        counts = self.tablet_row_counts(path)
        if any(c > 2 * desired for c in counts):
            return True
        return any(counts[i] + counts[i + 1] < desired // 2
                   for i in range(len(counts) - 1))

    def compute_pivots(self, path: str, desired: int) -> list[tuple]:
        """Quantile pivots over the live keys (sample-key analog)."""
        tablets = self.client._mounted_tablets(path)
        key_names = tablets[0].schema.key_column_names
        keys: list[tuple] = []
        for tablet in tablets:
            chunk = tablet.read_snapshot()
            rows = chunk.to_rows()
            keys.extend(tuple(r[n] for n in key_names) for r in rows)
        keys.sort()
        total = len(keys)
        if total == 0:
            return []
        n_tablets = max(-(-total // desired), 1)
        pivots = []
        for i in range(1, n_tablets):
            pivot = keys[i * total // n_tablets]
            if not pivots or pivot > pivots[-1]:
                pivots.append(pivot)
        return pivots

    def balance_table(self, path: str) -> bool:
        """Reshard one table if unbalanced.  Returns True when resharded."""
        node = self.client._table_node(path)
        if not self.needs_balancing(path):
            return False
        desired = self._table_desired(node)
        pivots = self.compute_pivots(path, desired)
        self.client.unmount_table(path)
        try:
            self.client.reshard_table(path, pivots)
        finally:
            self.client.mount_table(path)
        return True

    def step(self) -> dict:
        """One balancer pass over every mounted sorted dynamic table with
        balancing enabled (@enable_tablet_balancer, default True)."""
        out = {}
        stack = [("/", self.client.cluster.master.tree.root)]
        while stack:
            path, node = stack.pop()
            for name, child in node.children.items():
                stack.append((f"/{path.rstrip('/')}/{name}", child))
            if node.type != "table" or \
                    not node.attributes.get("dynamic") or \
                    node.attributes.get("tablet_state") != "mounted":
                continue
            if node.attributes.get("enable_tablet_balancer") is False:
                continue
            try:
                tablets = self.client.cluster.tablets.get(node.id)
                if not tablets or not tablets[0].schema.is_sorted:
                    continue
                out[path] = self.balance_table(path)
            except YtError as err:
                out[path] = str(err)
        return out
