// Native fast paths for the chunk codec layer.
//
// TPU-native equivalent of the reference's C++ codec/checksum internals
// (ytlib/table_chunk_format/*_column_writer.cpp, library/cpp/yt/coding
// varint + zigzag, core/misc checksums): varint streams for integer column
// segments, bit-packed validity bitmaps, CRC-64/XZ block checksums, and
// delta coding for sorted key columns.  Compiled once with g++ at first use
// and loaded through ctypes (no pybind11 in the image); Python fallbacks in
// native/__init__.py keep behavior identical when no compiler is available.
//
// ABI: plain C, int64/uint64/uint8 buffers, lengths as int64.

#include <cstdint>
#include <cstring>

extern "C" {

// --- zigzag varint ----------------------------------------------------------

// Encodes n int64s; returns number of bytes written (caller provides a
// buffer of at least 10*n bytes).
int64_t yt_varint_encode_zigzag(const int64_t* values, int64_t n,
                                uint8_t* out) {
    uint8_t* p = out;
    for (int64_t i = 0; i < n; ++i) {
        uint64_t v = (static_cast<uint64_t>(values[i]) << 1) ^
                     static_cast<uint64_t>(values[i] >> 63);
        while (v >= 0x80) {
            *p++ = static_cast<uint8_t>(v) | 0x80;
            v >>= 7;
        }
        *p++ = static_cast<uint8_t>(v);
    }
    return p - out;
}

// Decodes n int64s from the stream; returns bytes consumed, or -1 on
// truncation.
int64_t yt_varint_decode_zigzag(const uint8_t* data, int64_t size, int64_t n,
                                int64_t* out) {
    const uint8_t* p = data;
    const uint8_t* end = data + size;
    for (int64_t i = 0; i < n; ++i) {
        uint64_t v = 0;
        int shift = 0;
        while (true) {
            if (p >= end) return -1;
            uint8_t byte = *p++;
            v |= static_cast<uint64_t>(byte & 0x7F) << shift;
            if (!(byte & 0x80)) break;
            shift += 7;
        }
        out[i] = static_cast<int64_t>((v >> 1) ^ (~(v & 1) + 1));
    }
    return p - data;
}

// --- validity bitmaps -------------------------------------------------------

void yt_bitmap_pack(const uint8_t* bools, int64_t n, uint8_t* out) {
    std::memset(out, 0, (n + 7) / 8);
    for (int64_t i = 0; i < n; ++i) {
        if (bools[i]) out[i >> 3] |= static_cast<uint8_t>(1u << (i & 7));
    }
}

// Returns 0 on success, -1 if the bit buffer is too small for n bits.
int64_t yt_bitmap_unpack(const uint8_t* bits, int64_t bits_size, int64_t n,
                         uint8_t* out) {
    if (bits_size * 8 < n) return -1;
    for (int64_t i = 0; i < n; ++i) {
        out[i] = (bits[i >> 3] >> (i & 7)) & 1;
    }
    return 0;
}

// --- delta coding for sorted/clustered int columns --------------------------

void yt_delta_encode(const int64_t* values, int64_t n, int64_t* out) {
    int64_t prev = 0;
    for (int64_t i = 0; i < n; ++i) {
        out[i] = values[i] - prev;
        prev = values[i];
    }
}

void yt_delta_decode(const int64_t* deltas, int64_t n, int64_t* out) {
    int64_t acc = 0;
    for (int64_t i = 0; i < n; ++i) {
        acc += deltas[i];
        out[i] = acc;
    }
}

// --- CRC-64/XZ (poly 0x42F0E1EBA9EA3693, reflected) -------------------------

static uint64_t g_crc_table[256];
static bool g_crc_init = false;

static void crc64_init() {
    const uint64_t poly = 0xC96C5795D7870F42ULL;  // reflected polynomial
    for (int i = 0; i < 256; ++i) {
        uint64_t crc = static_cast<uint64_t>(i);
        for (int j = 0; j < 8; ++j) {
            crc = (crc & 1) ? (crc >> 1) ^ poly : crc >> 1;
        }
        g_crc_table[i] = crc;
    }
    g_crc_init = true;
}

uint64_t yt_crc64(const uint8_t* data, int64_t size, uint64_t seed) {
    if (!g_crc_init) crc64_init();
    uint64_t crc = ~seed;
    for (int64_t i = 0; i < size; ++i) {
        crc = g_crc_table[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
    }
    return ~crc;
}

// --- dictionary code remap (hot path of cross-chunk string unification) -----

void yt_remap_i32(const int32_t* codes, int64_t n, const int32_t* table,
                  int64_t table_size, int32_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        int32_t c = codes[i];
        out[i] = (c >= 0 && c < table_size) ? table[c] : 0;
    }
}

}  // extern "C"
