// SDK demo/integration binary: exercised by tests/test_cpp_sdk.py against
// a live LocalCluster HTTP proxy.  Exit 0 = every check passed.
#include "yt_client.hpp"

#include <cstdlib>
#include <iostream>

int main(int argc, char** argv) {
    if (argc != 3) {
        std::cerr << "usage: demo <host> <port>\n";
        return 2;
    }
    try {
        yt_tpu::Client client(argv[1], std::atoi(argv[2]));

        std::string commands = client.ListCommands();
        if (commands.find("select_rows") == std::string::npos) {
            std::cerr << "command registry missing select_rows\n";
            return 1;
        }

        client.Create("map_node", "//from_cpp");
        if (!client.Exists("//from_cpp")) {
            std::cerr << "created node does not exist\n";
            return 1;
        }
        client.Set("//from_cpp/@origin", "\"cpp-sdk\"");

        client.WriteTable("//from_cpp/t",
                          "[{\"k\": 1, \"v\": 10},"
                          " {\"k\": 2, \"v\": 20},"
                          " {\"k\": 3, \"v\": 30}]");
        std::string rows =
            client.SelectRows("k, v FROM [//from_cpp/t] WHERE k >= 2");
        if (rows.find("\"k\": 2") == std::string::npos &&
            rows.find("\"k\":2") == std::string::npos) {
            std::cerr << "select result missing k=2: " << rows << "\n";
            return 1;
        }
        std::string all = client.ReadTable("//from_cpp/t");
        std::cout << "SDK OK " << all << "\n";
        return 0;
    } catch (const std::exception& err) {
        std::cerr << "SDK FAILED: " << err.what() << "\n";
        return 1;
    }
}
