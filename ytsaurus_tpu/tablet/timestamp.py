"""Hybrid logical clock timestamps.

Ref: yt/yt/server/timestamp_provider + client/transaction_client — cluster
timestamps are (unix_time << 30) | counter, totally ordered, monotone.
A single in-process provider stands in for the clock quorum; the interface
matches what a distributed quorum implementation would expose.
"""

from __future__ import annotations

import threading
import time

COUNTER_BITS = 30
MIN_TIMESTAMP = 0
MAX_TIMESTAMP = (1 << 62) - 1
# Sync-read sentinel (ref NTransactionClient::SyncLastCommittedTimestamp).
SYNC_LAST_COMMITTED = MAX_TIMESTAMP - 1
ASYNC_LAST_COMMITTED = MAX_TIMESTAMP - 2


class TimestampProvider:
    """Monotone hybrid timestamps; thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._last = 0

    def generate(self) -> int:
        with self._lock:
            wall = int(time.time()) << COUNTER_BITS
            candidate = max(wall, self._last + 1)
            self._last = candidate
            return candidate

    def last(self) -> int:
        with self._lock:
            return self._last

    def observe(self, ts: int) -> None:
        """Fold an externally observed timestamp into the clock (hybrid
        logical clock advance: replicated commits keep local timestamps
        monotone across clusters/processes)."""
        with self._lock:
            if ts > self._last:
                self._last = ts


_global_provider = TimestampProvider()


def generate_timestamp() -> int:
    return _global_provider.generate()
