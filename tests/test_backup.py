"""Dynamic-table backups at a checkpoint timestamp.

Ref model: tablet_node/backup_manager.h — consistent cut at a timestamp,
preserved MVCC timestamps, restore as an independent table.
"""

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.schema import TableSchema

SCHEMA = TableSchema.make([
    ("k", "int64", "ascending"), ("v", "string")], unique_keys=True)


@pytest.fixture
def client(tmp_path):
    c = connect(str(tmp_path))
    c.create("table", "//t", recursive=True,
             attributes={"schema": SCHEMA, "dynamic": True})
    c.mount_table("//t")
    return c


def test_backup_excludes_later_writes(client):
    client.insert_rows("//t", [{"k": 1, "v": "before"}])
    cutoff = client.cluster.transactions.timestamps.generate()
    client.insert_rows("//t", [{"k": 1, "v": "after"},
                               {"k": 2, "v": "late"}])
    client.backup_table("//t", "//backups/t1", timestamp=cutoff)
    client.mount_table("//backups/t1")
    assert client.lookup_rows("//backups/t1", [(1,), (2,)]) == [
        {"k": 1, "v": b"before"}, None]
    # Source unaffected.
    assert client.lookup_rows("//t", [(1,)]) == [{"k": 1, "v": b"after"}]


def test_backup_preserves_timestamps_and_tombstones(client):
    client.insert_rows("//t", [{"k": 1, "v": "x"}])
    ts_after_insert = client.cluster.transactions.timestamps.generate()
    client.delete_rows("//t", [(1,)])
    client.backup_table("//t", "//b")
    client.mount_table("//b")
    # Deleted as of now; alive at the pre-delete timestamp (MVCC kept).
    assert client.lookup_rows("//b", [(1,)]) == [None]
    assert client.lookup_rows("//b", [(1,)],
                              timestamp=ts_after_insert) == [
        {"k": 1, "v": b"x"}]


def test_backup_restore_independent(client):
    client.insert_rows("//t", [{"k": 5, "v": "keep"}])
    client.backup_table("//t", "//b")
    client.restore_table_backup("//b", "//restored")
    client.mount_table("//restored")
    client.insert_rows("//restored", [{"k": 6, "v": "new"}])
    # Backup untouched by writes to the restored table.
    client.mount_table("//b")
    assert client.lookup_rows("//b", [(6,)]) == [None]
    assert client.lookup_rows("//restored", [(5,), (6,)]) == [
        {"k": 5, "v": b"keep"}, {"k": 6, "v": b"new"}]


def test_backup_keeps_pivots(client):
    client.unmount_table("//t")
    client.reshard_table("//t", [(10,)])
    client.mount_table("//t")
    client.insert_rows("//t", [{"k": 1, "v": "a"}, {"k": 20, "v": "b"}])
    client.backup_table("//t", "//b")
    assert client.get("//b/@pivot_keys") == [[10]]
    client.mount_table("//b")
    assert client.lookup_rows("//b", [(1,), (20,)]) == [
        {"k": 1, "v": b"a"}, {"k": 20, "v": b"b"}]
