"""Chunk merger: background compaction of small static-table chunks.

Ref model: server/master/chunk_server/chunk_merger.h — append-heavy
tables accumulate small chunks; the master merges adjacent runs into
fewer chunks without changing what readers see.
"""

import pytest

from ytsaurus_tpu.client import connect
from ytsaurus_tpu.server.chunk_merger import ChunkMerger


@pytest.fixture
def client(tmp_path):
    return connect(str(tmp_path))


def _append_many(client, path, groups=6, rows_per=10):
    for g in range(groups):
        client.write_table(path, [{"k": g * rows_per + i, "v": g}
                                  for i in range(rows_per)], append=True)


def test_merges_small_adjacent_chunks_preserving_order(client):
    _append_many(client, "//t")
    before = client.get("//t/@chunk_ids")
    assert len(before) == 6
    expected = client.read_table("//t")
    merger = ChunkMerger(client, min_chunk_rows=1000)
    assert merger.scan_once() == 1
    after = client.get("//t/@chunk_ids")
    assert len(after) < len(before)
    assert client.read_table("//t") == expected        # order intact
    assert client.get("//t/@row_count") == 60
    assert merger.stats["chunks_merged_away"] >= 4


def test_large_chunks_left_alone(client):
    _append_many(client, "//big", groups=3, rows_per=50)
    merger = ChunkMerger(client, min_chunk_rows=10)     # 50 >= 10: large
    assert merger.scan_once() == 0
    assert len(client.get("//big/@chunk_ids")) == 3


def test_sorted_table_stays_sorted(client):
    client.write_table("//in", [{"k": i} for i in range(40)])
    client.run_sort("//in", "//s", sort_by=["k"])
    # Append more sorted data as separate small chunks via direct writes.
    for lo in (40, 50, 60):
        chunks = client._read_table_chunks("//s")
        from ytsaurus_tpu.chunks.columnar import ColumnarChunk
        extra = ColumnarChunk.from_rows(
            chunks[0].schema, [{"k": lo + i} for i in range(10)])
        client._write_table_chunks("//s", chunks + [extra],
                                   sorted_by=["k"])
    merger = ChunkMerger(client, min_chunk_rows=1000)
    merger.scan_once()
    assert client.get("//s/@sorted_by") == ["k"]
    ks = [r["k"] for r in client.read_table("//s")]
    assert ks == sorted(ks) and len(ks) == 70
    # Query pruning stats stay aligned with the new chunk list.
    ids = client.get("//s/@chunk_ids")
    stats = client.get("//s/@chunk_stats")
    assert len(stats) == len(ids)


def test_cas_race_lost_leaves_table_untouched(client):
    _append_many(client, "//race")
    merger = ChunkMerger(client, min_chunk_rows=1000)
    real_plan = merger._merge_plan

    def racing_plan(ids, counts):
        # A writer lands between the snapshot and the swap.
        client.write_table("//race", [{"k": 999, "v": 9}], append=True)
        return real_plan(ids, counts)
    merger._merge_plan = racing_plan
    assert merger.scan_once() == 0
    assert merger.stats["cas_races_lost"] == 1
    rows = client.read_table("//race")
    assert len(rows) == 61                       # nothing lost
    # Next scan (no race) succeeds.
    merger._merge_plan = real_plan
    assert merger.scan_once() == 1
    assert len(client.read_table("//race")) == 61


def test_copied_table_sharing_chunks_unaffected(client):
    _append_many(client, "//orig")
    client.copy("//orig", "//copy")
    expected = client.read_table("//copy")
    merger = ChunkMerger(client, min_chunk_rows=1000)
    merged = merger.scan_once()
    assert merged >= 1
    # Old chunks stay readable through the copy (GC owns reclamation).
    assert client.read_table("//copy") == expected
    assert client.read_table("//orig") == expected
    # After GC, both tables must STILL read (only unreferenced go).
    client.collect_garbage()
    assert client.read_table("//copy") == expected
    assert client.read_table("//orig") == expected


def test_dynamic_tables_skipped(client):
    from ytsaurus_tpu.schema import TableSchema
    schema = TableSchema.make([("k", "int64", "ascending"),
                               ("v", "int64")])
    client.create("table", "//dyn", recursive=True,
                  attributes={"schema": schema, "dynamic": True})
    client.mount_table("//dyn")
    client.insert_rows("//dyn", [{"k": 1, "v": 1}])
    merger = ChunkMerger(client, min_chunk_rows=1000)
    assert merger.scan_once() == 0
