"""Tablet transactions: snapshot-isolated writes with 2PC across tablets.

Ref mapping:
  transaction start/commit/abort       → tablet_node/transaction_manager.h
  client-side row buffering per tablet → ytlib/api/native/transaction.cpp
                                         (ModifyRows batching)
  2PC prepare/commit                   → server/lib/transaction_supervisor
Conflict model (ref sorted_dynamic_store row locks): at prepare, a write to
key K conflicts if (a) another transaction holds a prepared lock on K, or
(b) a commit newer than our start timestamp already touched K.  Prepare
locks all keys on all participant tablets, then commit applies everywhere at
one commit timestamp — the single-process stand-in for coordinator+
participants exchanging Hive messages.
"""

from __future__ import annotations

import threading
import uuid
from dataclasses import dataclass, field
from typing import Optional

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.tablet.tablet import Tablet
from ytsaurus_tpu.tablet.timestamp import TimestampProvider


@dataclass
class _Modification:
    kind: str                 # "write" | "delete"
    row: dict | tuple
    update: bool = False      # partial write (per-column merge)


@dataclass
class TabletTransaction:
    id: str
    start_timestamp: int
    modifications: dict[int, list[_Modification]] = field(default_factory=dict)
    state: str = "active"     # active | committed | aborted

    def _record(self, tablet_key: int, mod: _Modification):
        if self.state != "active":
            raise YtError(f"Transaction {self.id} is {self.state}",
                          code=EErrorCode.NoSuchTransaction)
        self.modifications.setdefault(tablet_key, []).append(mod)


class TransactionManager:
    """Coordinates transactions over a set of tablets (one per process —
    the analog of a tablet cell's transaction manager + supervisor)."""

    def __init__(self, timestamp_provider: Optional[TimestampProvider] = None):
        self.timestamps = timestamp_provider or TimestampProvider()
        self._tablets: dict[int, Tablet] = {}
        self._prepared_locks: dict[tuple[int, tuple], str] = {}
        self._lock = threading.Lock()
        self._transactions: dict[str, TabletTransaction] = {}

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> TabletTransaction:
        tx = TabletTransaction(id=uuid.uuid4().hex,
                               start_timestamp=self.timestamps.generate())
        self._transactions[tx.id] = tx
        return tx

    def write_rows(self, tx: TabletTransaction, tablet: Tablet,
                   rows: list[dict], update: bool = False) -> None:
        key = id(tablet)
        self._tablets[key] = tablet
        # Validate the WHOLE batch before recording anything: a mid-batch
        # failure must not leave earlier rows recorded in a live tx (and a
        # commit-phase failure would half-apply the transaction).
        for row in rows:
            tablet.validate_required(tablet.normalize_row(row),
                                     partial=update)
        for row in rows:
            tx._record(key, _Modification("write", dict(row), update))

    def delete_rows(self, tx: TabletTransaction, tablet: Tablet,
                    keys: list[tuple]) -> None:
        key = id(tablet)
        self._tablets[key] = tablet
        for k in keys:
            tx._record(key, _Modification("delete", tuple(k)))

    def abort(self, tx: TabletTransaction) -> None:
        with self._lock:
            if tx.state in ("committing", "committed"):
                # Aborting a committed tx must not mask its durable writes.
                raise YtError(f"Transaction {tx.id} is {tx.state}",
                              code=EErrorCode.InvalidTransactionState)
            self._release_locks(tx)
            tx.state = "aborted"

    # -- 2PC -------------------------------------------------------------------

    def commit(self, tx: TabletTransaction) -> int:
        """Prepare (lock + conflict check on every participant), then commit
        at a fresh timestamp.  Raises TransactionLockConflict and aborts on
        any conflict."""
        # Build the touched-key list BEFORE the state transition: key
        # normalization can raise on malformed client input, and that must
        # leave the tx abortable (still 'active'), not stuck 'committing'.
        if tx.state != "active":
            raise YtError(f"Transaction {tx.id} is {tx.state}",
                          code=EErrorCode.NoSuchTransaction)
        touched: list[tuple[int, tuple]] = []
        for tablet_key, mods in tx.modifications.items():
            tablet = self._tablets[tablet_key]
            for mod in mods:
                row_key = (tablet.active_store.key_of(mod.row)
                           if mod.kind == "write" else tuple(mod.row))
                touched.append((tablet_key, tablet.normalize_key(row_key)))
        with self._lock:
            # Exclusive 'committing' transition under the lock: a concurrent
            # commit/abort of the same tx must fail fast, not apply twice.
            if tx.state != "active":
                raise YtError(f"Transaction {tx.id} is {tx.state}",
                              code=EErrorCode.NoSuchTransaction)
            tx.state = "committing"
            # Phase 1: prepare — participants mounted, locks, conflicts.
            for tablet_key in tx.modifications:
                tablet = self._tablets[tablet_key]
                if not tablet.mounted:
                    tx.state = "aborted"
                    raise YtError(
                        f"Tablet {tablet.tablet_id} is not mounted",
                        code=EErrorCode.TabletNotMounted)
            acquired: list[tuple[int, tuple]] = []
            try:
                for tablet_key, row_key in touched:
                    holder = self._prepared_locks.get((tablet_key, row_key))
                    if holder is not None and holder != tx.id:
                        raise YtError(
                            f"Row lock conflict on key {row_key}",
                            code=EErrorCode.TransactionLockConflict,
                            attributes={"winner": holder})
                    tablet = self._tablets[tablet_key]
                    last = tablet.last_committed_timestamp(row_key)
                    if last is not None and last > tx.start_timestamp:
                        raise YtError(
                            f"Write conflict on key {row_key}: committed at "
                            f"{last} > start {tx.start_timestamp}",
                            code=EErrorCode.TransactionLockConflict)
                    self._prepared_locks[(tablet_key, row_key)] = tx.id
                    acquired.append((tablet_key, row_key))
            except YtError:
                for lk in acquired:
                    self._prepared_locks.pop(lk, None)
                tx.state = "aborted"
                raise
            # Phase 2: commit at one timestamp on every participant.
            # Apply errors must still release locks or later transactions
            # deadlock on stale lock entries; record/prepare-time validation
            # (required columns, mounted participants) keeps this phase from
            # half-applying in the cases we can check upfront.
            commit_ts = self.timestamps.generate()
            try:
                for tablet_key, mods in tx.modifications.items():
                    tablet = self._tablets[tablet_key]
                    for mod in mods:
                        if mod.kind == "write":
                            tablet.write_row(mod.row, commit_ts,
                                             update=mod.update)
                        else:
                            tablet.delete_row(mod.row, commit_ts)
            except Exception:
                tx.state = "aborted"
                raise
            finally:
                self._release_locks(tx)
            tx.state = "committed"
            return commit_ts

    def _release_locks(self, tx: TabletTransaction) -> None:
        for lk in [k for k, holder in self._prepared_locks.items()
                   if holder == tx.id]:
            self._prepared_locks.pop(lk, None)
