"""Builtin function registry: typing rules for scalar and aggregate functions.

Analog of the reference's builtin function registry
(library/query/base/builtin_function_registry.cpp).  Implementations live in
the engine (ytsaurus_tpu/query/engine/expr.py); this module owns signatures.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.schema import EValueType, VectorType

_NUMERIC_RANK = {EValueType.int64: 1, EValueType.uint64: 2, EValueType.double: 3}


def is_numeric(ty: EValueType) -> bool:
    return ty in _NUMERIC_RANK


def promote_numeric(a: EValueType, b: EValueType, context: str) -> EValueType:
    if a is EValueType.null:
        return b
    if b is EValueType.null:
        return a
    if not is_numeric(a) or not is_numeric(b):
        raise YtError(f"Type mismatch in {context}: {a.value} vs {b.value}",
                      code=EErrorCode.QueryTypeError)
    return a if _NUMERIC_RANK[a] >= _NUMERIC_RANK[b] else b


def unify(a: EValueType, b: EValueType, context: str) -> EValueType:
    """Common type for comparisons / IF branches."""
    if a is b:
        return a
    if a is EValueType.null:
        return b
    if b is EValueType.null:
        return a
    if is_numeric(a) and is_numeric(b):
        return promote_numeric(a, b, context)
    raise YtError(f"Type mismatch in {context}: {a.value} vs {b.value}",
                  code=EErrorCode.QueryTypeError)


def _type_error(name, arg_types):
    return YtError(
        f"Function {name!r} does not accept arguments "
        f"({', '.join(t.value for t in arg_types)})",
        code=EErrorCode.QueryTypeError)


@dataclass(frozen=True)
class ScalarFunction:
    name: str
    infer: Callable[[tuple[EValueType, ...]], EValueType]
    min_args: int = 1
    max_args: Optional[int] = None


def _infer_if(ts):
    if len(ts) != 3 or unify(ts[0], EValueType.boolean, "if") is not EValueType.boolean:
        raise _type_error("if", ts)
    return unify(ts[1], ts[2], "if branches")


def _infer_is_null(ts):
    return EValueType.boolean


def _infer_if_null(ts):
    return unify(ts[0], ts[1], "if_null")


def _cast(to):
    def infer(ts):
        src = ts[0]
        if src is EValueType.null or is_numeric(src) or \
                (src is EValueType.boolean and to is not EValueType.double):
            return to
        raise _type_error(to.value, ts)
    return infer


def _infer_same_numeric(name):
    def infer(ts):
        if not is_numeric(ts[0]) and ts[0] is not EValueType.null:
            raise _type_error(name, ts)
        return ts[0]
    return infer


def _infer_string_to_string(ts):
    if ts[0] not in (EValueType.string, EValueType.null):
        raise _type_error("string fn", ts)
    return EValueType.string


def _infer_string_to_int(ts):
    if ts[0] not in (EValueType.string, EValueType.null):
        raise _type_error("length", ts)
    return EValueType.int64


def _infer_string_pred(ts):
    if any(t not in (EValueType.string, EValueType.null) for t in ts):
        raise _type_error("string predicate", ts)
    return EValueType.boolean


def _infer_double_math(ts):
    if not is_numeric(ts[0]) and ts[0] is not EValueType.null:
        raise _type_error("math fn", ts)
    return EValueType.double


def _infer_int_math(ts):
    if not is_numeric(ts[0]) and ts[0] is not EValueType.null:
        raise _type_error("math fn", ts)
    return EValueType.int64


def _infer_hash(ts):
    return EValueType.uint64


SCALAR_FUNCTIONS: dict[str, ScalarFunction] = {}


def _register(name, infer, min_args=1, max_args=None):
    SCALAR_FUNCTIONS[name] = ScalarFunction(
        name=name, infer=infer, min_args=min_args,
        max_args=max_args if max_args is not None else min_args)


_register("if", _infer_if, 3)
_register("is_null", _infer_is_null, 1)
_register("if_null", _infer_if_null, 2)
_register("int64", _cast(EValueType.int64), 1)
_register("uint64", _cast(EValueType.uint64), 1)
_register("double", _cast(EValueType.double), 1)
_register("boolean", _cast(EValueType.boolean), 1)
_register("abs", _infer_same_numeric("abs"), 1)
_register("floor", _infer_double_math, 1)
_register("ceil", _infer_double_math, 1)
_register("sqrt", _infer_double_math, 1)
_register("lower", _infer_string_to_string, 1)
_register("upper", _infer_string_to_string, 1)


def _infer_concat(ts):
    if any(t not in (EValueType.string, EValueType.null) for t in ts):
        raise _type_error("concat", ts)
    return EValueType.string


def _infer_float_pred(ts):
    if ts[0] not in (EValueType.double, EValueType.null):
        raise _type_error("float predicate", ts)
    return EValueType.boolean


_register("concat", _infer_concat, 2)
_register("is_finite", _infer_float_pred, 1)
_register("is_nan", _infer_float_pred, 1)


def _infer_timestamp(name):
    def infer(ts):
        if ts[0] not in (EValueType.int64, EValueType.uint64, EValueType.null):
            raise _type_error(name, ts)
        return EValueType.int64
    return infer


for _name in ("timestamp_floor_hour", "timestamp_floor_day",
              "timestamp_floor_week", "timestamp_floor_month",
              "timestamp_floor_year"):
    _register(_name, _infer_timestamp(_name), 1)
_register("length", _infer_string_to_int, 1)
_register("is_prefix", _infer_string_pred, 2)
_register("is_substr", _infer_string_pred, 2)
_register("farm_hash", _infer_hash, 1, 16)


def _infer_string_hash(ts):
    # bigb_hash hashes uid STRINGS (ref bigb_hash registration) — the
    # lowering builds a per-vocabulary table, so non-string input is a
    # type error, not silent zeros.
    if ts[0] not in (EValueType.string, EValueType.null):
        raise _type_error("bigb_hash", ts)
    return EValueType.uint64


_register("bigb_hash", _infer_string_hash, 1, 1)
_register("min_of", lambda ts: _min_of(ts), 2, 16)
_register("max_of", lambda ts: _min_of(ts), 2, 16)


# Regex family (ref base/builtin_function_registry.cpp regex_* — RE2
# there, Python re here; the QL surface is identical for the shared
# syntax subset).  Pattern (and rewrite) arguments must be literals:
# they compile at plan time against the column vocabulary.
def _infer_regex_match(ts):
    if any(t not in (EValueType.string, EValueType.null) for t in ts):
        raise _type_error("regex match", ts)
    return EValueType.boolean


def _infer_regex_replace(ts):
    if any(t not in (EValueType.string, EValueType.null) for t in ts):
        raise _type_error("regex replace", ts)
    return EValueType.string


_register("regex_full_match", _infer_regex_match, 2)
_register("regex_partial_match", _infer_regex_match, 2)
_register("regex_replace_first", _infer_regex_replace, 3)
_register("regex_replace_all", _infer_regex_replace, 3)
_register("regex_escape", _infer_string_to_string, 1)
_register("sha256", _infer_string_to_string, 1)
_register("parse_int64", _infer_string_to_int, 1)


def _infer_substr(ts):
    if ts[0] not in (EValueType.string, EValueType.null):
        raise _type_error("substr", ts)
    for t in ts[1:]:
        if t not in (EValueType.int64, EValueType.uint64):
            raise _type_error("substr", ts)
    return EValueType.string


_register("substr", _infer_substr, 2, 3)


def _infer_distance(name):
    """(vector<float,N>, vector<float,N>) -> double: the NEAREST distance
    family.  Both args must be vectors of the SAME dim (the interned
    VectorType makes that an identity check)."""
    def infer(ts):
        if len(ts) != 2 or not all(isinstance(t, VectorType) for t in ts):
            raise YtError(
                f"Function {name!r} expects two vector arguments, got "
                f"({', '.join(t.value for t in ts)})",
                code=EErrorCode.QueryTypeError)
        if ts[0] is not ts[1]:
            raise YtError(
                f"Function {name!r} dim mismatch: "
                f"{ts[0].value} vs {ts[1].value}",
                code=EErrorCode.QueryTypeError)
        return EValueType.double
    return infer


_register("l2_distance", _infer_distance("l2_distance"), 2)
_register("distance", _infer_distance("distance"), 2)
_register("cosine_distance", _infer_distance("cosine_distance"), 2)
_register("dot_product", _infer_distance("dot_product"), 2)


def _min_of(ts):
    ty = ts[0]
    for t in ts[1:]:
        ty = unify(ty, t, "min_of/max_of")
    return ty


@dataclass(frozen=True)
class AggregateFunction:
    name: str
    infer_result: Callable[[EValueType], EValueType]
    infer_state: Callable[[EValueType], EValueType]


def _agg_same(ty: EValueType) -> EValueType:
    return ty


def _agg_numeric(ty: EValueType) -> EValueType:
    if not is_numeric(ty) and ty is not EValueType.null:
        raise YtError(f"Aggregate requires a numeric argument, got {ty.value}",
                      code=EErrorCode.QueryTypeError)
    return ty


# argmin/argmax take (value_expr, by_expr); result type = value type.
TWO_ARG_AGGREGATES = {"argmin", "argmax"}

AGGREGATE_FUNCTIONS: dict[str, AggregateFunction] = {
    "argmin": AggregateFunction("argmin", _agg_same, _agg_same),
    "argmax": AggregateFunction("argmax", _agg_same, _agg_same),
    "sum": AggregateFunction("sum", _agg_numeric, _agg_numeric),
    "min": AggregateFunction("min", _agg_same, _agg_same),
    "max": AggregateFunction("max", _agg_same, _agg_same),
    "avg": AggregateFunction("avg", lambda ty: (_agg_numeric(ty), EValueType.double)[1],
                             lambda ty: EValueType.double),
    "count": AggregateFunction("count", lambda ty: EValueType.int64,
                               lambda ty: EValueType.int64),
    "first": AggregateFunction("first", _agg_same, _agg_same),
    "cardinality": AggregateFunction("cardinality", lambda ty: EValueType.uint64,
                                     lambda ty: EValueType.uint64),
}


def is_aggregate(name: str) -> bool:
    return name in AGGREGATE_FUNCTIONS


# --- window functions ---------------------------------------------------------
#
# Signature registry for `fn(...) OVER (...)` (the reference has no window
# functions — layer-6 gap in VERDICT.md; the CH dialect spelling is shared).
# Lowerings live in query/engine/window.py as segmented prefix scans.


@dataclass(frozen=True)
class WindowFunction:
    name: str
    min_args: int
    max_args: int
    infer_result: Callable[[Optional[EValueType]], EValueType]
    needs_order: bool = False        # ranking/offset require ORDER BY
    is_aggregate: bool = False       # framed aggregates accept ROWS frames


def _win_int64(ty):
    return EValueType.int64


def _win_same(ty):
    return ty


def _win_numeric(ty):
    if not is_numeric(ty) and ty is not EValueType.null:
        raise YtError(
            f"Window aggregate requires a numeric argument, got {ty.value}",
            code=EErrorCode.QueryTypeError)
    return ty


def _win_avg(ty):
    _win_numeric(ty)
    return EValueType.double


WINDOW_FUNCTIONS: dict[str, WindowFunction] = {
    "row_number": WindowFunction("row_number", 0, 0, _win_int64,
                                 needs_order=False),
    "rank": WindowFunction("rank", 0, 0, _win_int64, needs_order=True),
    "dense_rank": WindowFunction("dense_rank", 0, 0, _win_int64,
                                 needs_order=True),
    "lag": WindowFunction("lag", 1, 3, _win_same, needs_order=True),
    "lead": WindowFunction("lead", 1, 3, _win_same, needs_order=True),
    # first/last_value honor the frame (standard semantics: with ORDER
    # BY and the default RANGE-peers frame, last_value is the end of the
    # current row's PEER group — the current row when keys are unique).
    "first_value": WindowFunction("first_value", 1, 1, _win_same,
                                  is_aggregate=True),
    "last_value": WindowFunction("last_value", 1, 1, _win_same,
                                 is_aggregate=True),
    "sum": WindowFunction("sum", 1, 1, _win_numeric, is_aggregate=True),
    "min": WindowFunction("min", 1, 1, _win_same, is_aggregate=True),
    "max": WindowFunction("max", 1, 1, _win_same, is_aggregate=True),
    "avg": WindowFunction("avg", 1, 1, _win_avg, is_aggregate=True),
    "count": WindowFunction("count", 1, 1, lambda ty: EValueType.int64,
                            is_aggregate=True),
}


def is_window_function(name: str) -> bool:
    return name in WINDOW_FUNCTIONS
