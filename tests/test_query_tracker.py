"""Query tracker: persistent queries, states, results, engines.

Ref model: server/query_tracker (start/get/list/abort/read_query_result,
engine field, result row caps).
"""

import pytest

from ytsaurus_tpu import YtError
from ytsaurus_tpu.client import connect
from ytsaurus_tpu.driver import Driver
from ytsaurus_tpu.server.query_tracker import QueryTracker, register_engine


@pytest.fixture
def client(tmp_path):
    c = connect(str(tmp_path))
    c.write_table("//data/t", [{"k": i, "v": i * 10} for i in range(5)])
    return c


def test_query_lifecycle(client):
    qt = client.query_tracker
    qid = qt.start_query("k, v FROM [//data/t] WHERE k >= 3")
    record = qt.wait(qid)
    assert record["state"] == "completed"
    assert record["engine"] == "ql"
    assert record["finish_time"] >= record["start_time"]
    assert qt.read_query_result(qid) == [
        {"k": 3, "v": 30}, {"k": 4, "v": 40}]


def test_failed_query_records_error(client):
    qt = client.query_tracker
    qid = qt.start_query("k FROM [//no/such/table]")
    record = qt.wait(qid)
    assert record["state"] == "failed"
    assert "no/such/table" in record["error"]
    with pytest.raises(YtError):
        qt.read_query_result(qid)


def test_list_and_abort(client):
    qt = QueryTracker(client)
    done = qt.start_query("k FROM [//data/t]", sync=True)
    # sync=True: already completed; abort must refuse.
    with pytest.raises(YtError):
        qt.abort_query(done)
    listed = qt.list_queries(state="completed")
    assert [q["id"] for q in listed] == [done]
    assert qt.list_queries(state="failed") == []


def test_result_truncation(client):
    qt = QueryTracker(client, result_row_limit=2)
    qid = qt.start_query("k FROM [//data/t]", sync=True)
    record = qt.get_query(qid)
    assert record["truncated"] is True
    assert len(qt.read_query_result(qid)) == 2


def test_custom_engine_plug_point(client):
    register_engine("rot13", lambda cl, q: [{"echo": q[::-1]}])
    qt = QueryTracker(client)
    qid = qt.start_query("abc", engine="rot13", sync=True)
    assert qt.read_query_result(qid) == [{"echo": "cba"}]
    with pytest.raises(YtError):
        qt.start_query("x", engine="nope")


def test_query_records_scoped_per_user(client):
    from ytsaurus_tpu.cypress.security import authenticated_user
    sec = client.cluster.security
    sec.create_user("alice")
    sec.create_user("bob")
    client.set("//data/t/@acl", [
        {"action": "allow", "subjects": ["alice", "bob"],
         "permissions": ["read"]}])
    qt = QueryTracker(client)
    with authenticated_user("alice"):
        qid = qt.start_query("k FROM [//data/t] WHERE k = 0", sync=True)
        assert qt.read_query_result(qid) == [{"k": 0}]
    # Another user can neither see nor read alice's query.
    with authenticated_user("bob"):
        assert qt.list_queries() == []
        with pytest.raises(YtError):
            qt.read_query_result(qid)
        with pytest.raises(YtError):
            qt.get_query(qid)
    # Root (superuser) sees everything.
    assert [q["id"] for q in qt.list_queries()] == [qid]


def test_async_query_runs_as_caller(client):
    """The worker thread must NOT escalate to root (ref: query tracker
    executes under the query's user)."""
    from ytsaurus_tpu.cypress.security import authenticated_user
    sec = client.cluster.security
    sec.create_user("carol")
    client.write_table("//secret", [{"s": 1}])
    client.set("//secret/@acl", [
        {"action": "deny", "subjects": ["carol"], "permissions": ["read"]}])
    qt = QueryTracker(client)
    with authenticated_user("carol"):
        qid = qt.start_query("s FROM [//secret]")
        record = qt.wait(qid)
    assert record["state"] == "failed"
    assert "carol" in record["error"] or "denied" in record["error"].lower()


def test_driver_commands(client):
    drv = Driver(client)
    qid = drv.execute("start_query",
                      {"query": "k FROM [//data/t] WHERE k = 1"})
    client.query_tracker.wait(qid)
    assert drv.execute("get_query",
                          {"query_id": qid})["state"] == "completed"
    assert drv.execute("read_query_result",
                          {"query_id": qid}) == [{"k": 1}]
    assert len(drv.execute("list_queries", {})) == 1
