"""Vectorized MVCC read pipeline (ISSUE 4): property tests proving the
columnar merge/flush/compaction are row-exact against the retained
Python reference implementations, plus the snapshot cache, the
host-plane LRU, chunk-meta pruning stats, and the coordinator's
single-sync shard fan-out.
"""

import random
import tempfile

import pytest

from ytsaurus_tpu.chunks.columnar import ColumnarChunk
from ytsaurus_tpu.chunks.store import FsChunkStore
from ytsaurus_tpu.config import TabletConfig, set_tablet_config
from ytsaurus_tpu.schema import TableSchema
from ytsaurus_tpu.tablet.tablet import (
    Tablet,
    _drop_superseded,
    _versioned_sort_key,
    _written,
)
from ytsaurus_tpu.tablet.timestamp import MAX_TIMESTAMP


@pytest.fixture(autouse=True)
def _force_vectorized():
    """Route every MVCC merge through the columnar pipeline (the
    dispatch threshold would keep tiny test tablets on the Python
    path), restoring defaults afterwards."""
    set_tablet_config(TabletConfig(vectorized_scan_min_rows=0))
    yield
    set_tablet_config(None)


SCHEMAS = {
    "int_key": TableSchema.make([
        ("k", "int64", "ascending"), ("a", "int64"), ("b", "string"),
        ("c", "double")]),
    "multi_key": TableSchema.make([
        ("k1", "int64", "ascending"), ("k2", "string", "ascending"),
        ("x", "int64"), ("y", "boolean")]),
}


def _tablet(schema) -> Tablet:
    return Tablet(schema, FsChunkStore(tempfile.mkdtemp(prefix="mvcc-")))


def _random_value(rng, col):
    if rng.random() < 0.2:
        return None
    ty = col.type.value
    if ty == "int64":
        return rng.randrange(-50, 50)
    if ty == "string":
        return rng.choice(["", "a", "bb", "zz", "édgé"])
    if ty == "double":
        return rng.choice([-1.5, 0.0, 2.25, 1e6])
    if ty == "boolean":
        return rng.random() < 0.5
    raise AssertionError(ty)


def _random_key(rng, schema):
    key = []
    for col in schema.key_columns:
        if col.type.value == "int64":
            key.append(rng.randrange(6) if rng.random() > 0.1 else None)
        else:
            key.append(rng.choice([b"p", b"q", None]))
    return tuple(key)


def _apply_workload(t, schema, rng, n_ops=120, allow_duplicates=True):
    """Random writes/partial writes/deletes with interleaved flushes.
    Timestamps mostly advance but REUSE an old (key, ts) once it is
    sealed in a chunk (duplicate-timestamp versions across sources);
    within one store they stay unique (the flush invariant).
    allow_duplicates=False for compaction workloads: merging every
    chunk into one surfaces cross-chunk duplicates to the
    versioned_rows invariant, in both implementations."""
    ts = 10
    key_names = schema.key_column_names
    value_cols = [c for c in schema if c.sort_order is None]
    store_seen: set = set()
    flushed: list = []      # (key, ts) sealed in chunks
    for _ in range(n_ops):
        key = _random_key(rng, schema)
        ts += rng.randrange(1, 4)
        use_ts = ts
        if allow_duplicates and flushed and rng.random() < 0.15:
            # Duplicate timestamp for a key whose twin is already sealed.
            key, use_ts = rng.choice(flushed)
        if (key, use_ts) in store_seen:
            use_ts = ts
        if (key, use_ts) in store_seen:
            continue
        store_seen.add((key, use_ts))
        roll = rng.random()
        if roll < 0.2:
            t.delete_row(key, timestamp=use_ts)
        else:
            row = dict(zip(key_names, key))
            update = roll < 0.5
            cols = value_cols if not update else \
                rng.sample(value_cols, rng.randrange(1, len(value_cols) + 1))
            for col in cols:
                row[col.name] = _random_value(rng, col)
            t.write_row(row, timestamp=use_ts, update=update)
        if rng.random() < 0.12:
            t.flush()
            flushed.extend(store_seen)
            store_seen.clear()
    return ts


@pytest.mark.parametrize("seed", range(6))
@pytest.mark.parametrize("schema_name", sorted(SCHEMAS))
def test_vectorized_select_matches_reference(schema_name, seed):
    schema = SCHEMAS[schema_name]
    rng = random.Random(1000 * seed + hash(schema_name) % 97)
    t = _tablet(schema)
    max_ts = _apply_workload(t, schema, rng)
    read_points = [5, max_ts // 3, max_ts // 2, max_ts - 1, max_ts,
                   MAX_TIMESTAMP]
    for ts in read_points:
        ref = t.read_snapshot_reference(ts).to_rows()
        vec = t.read_snapshot(ts).to_rows()
        assert vec == ref, f"ts={ts} seed={seed}"


@pytest.mark.parametrize("seed", range(4))
def test_vectorized_flush_matches_reference(seed):
    schema = SCHEMAS["int_key"]
    rng = random.Random(7000 + seed)
    t = _tablet(schema)
    _apply_workload(t, schema, rng, n_ops=60)
    # Expected flush output: ALL store rows (rotation folds the active
    # store in) under the Python sort oracle.
    rows = []
    for store in t.passive_stores + [t.active_store]:
        rows.extend(store.versioned_rows())
    rows.sort(key=_versioned_sort_key(schema))
    cid = t.flush()
    if not rows:
        assert cid is None
        return
    got = t.chunk_store.read_chunk(cid).to_rows()
    assert got == rows


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize("cut", ["low", "mid", "high"])
def test_vectorized_compaction_matches_reference(seed, cut):
    schema = SCHEMAS["int_key"]
    rng = random.Random(9000 + seed)
    t = _tablet(schema)
    max_ts = _apply_workload(t, schema, rng, n_ops=80,
                             allow_duplicates=False)
    t.flush()
    retention = {"low": 5, "mid": max_ts // 2, "high": max_ts + 10}[cut]
    value_names = [c.name for c in schema if c.sort_order is None]
    rows = []
    for cid in t.chunk_ids:
        for row in t.chunk_store.read_chunk(cid).to_rows():
            for name in value_names:
                row[f"$w:{name}"] = _written(row, name)
            rows.append(row)
    rows.sort(key=_versioned_sort_key(schema))
    expected = _drop_superseded(rows, schema, retention)
    new_id = t.compact(retention_timestamp=retention)
    if not expected:
        assert new_id is None and t.chunk_ids == []
        return
    got = t.chunk_store.read_chunk(new_id).to_rows()
    assert got == expected
    # And the post-compaction visible state still matches the oracle.
    assert t.read_snapshot().to_rows() == \
        t.read_snapshot_reference().to_rows()


def test_duplicate_timestamp_across_chunk_and_store():
    """The same (key, ts) sealed in a chunk AND rewritten in the store:
    source concatenation order (chunks first) breaks the tie, in both
    implementations."""
    schema = SCHEMAS["int_key"]
    t = _tablet(schema)
    t.write_row({"k": 1, "a": 1, "b": "chunk", "c": 0.5}, timestamp=100)
    t.flush()
    t.write_row({"k": 1, "a": 2, "b": "store", "c": 0.5}, timestamp=100)
    assert t.read_snapshot().to_rows() == \
        t.read_snapshot_reference().to_rows()


def test_select_path_performs_zero_to_rows(monkeypatch):
    """Regression guard: the vectorized select path must never fall back
    to row materialization — no chunk.to_rows() anywhere under
    read_snapshot."""
    schema = SCHEMAS["int_key"]
    t = _tablet(schema)
    for i in range(30):
        t.write_row({"k": i % 7, "a": i, "b": f"v{i}", "c": i / 2},
                    timestamp=10 + i)
    t.flush()
    t.write_row({"k": 3, "a": 99}, timestamp=100, update=True)
    t.delete_row((5,), timestamp=101)

    def _boom(self):
        raise AssertionError("to_rows() on the select path")
    monkeypatch.setattr(ColumnarChunk, "to_rows", _boom)
    out = t.read_snapshot()
    assert out.row_count > 0
    out = t.read_snapshot(timestamp=50)      # historical reads too
    assert out.row_count > 0


# --- snapshot cache -----------------------------------------------------------


def test_snapshot_cache_hit_and_invalidation():
    from ytsaurus_tpu.tablet import tablet as tablet_mod
    schema = SCHEMAS["int_key"]
    t = _tablet(schema)
    for i in range(10):
        t.write_row({"k": i, "a": i, "b": "x", "c": 0.0}, timestamp=10 + i)
    t.flush()
    hits0 = tablet_mod._SNAP_HITS.get()
    c1 = t.read_snapshot()
    c2 = t.read_snapshot()
    assert c2 is c1                     # memoized chunk object
    assert tablet_mod._SNAP_HITS.get() == hits0 + 1
    # A timestamp at/above the newest committed version is latest-class
    # and shares the cached snapshot (pinned "now" timestamps hit).
    assert t.read_snapshot(timestamp=10_000) is c1
    # Historical reads below the newest version bypass the cache.
    assert t.read_snapshot(timestamp=12) is not c1
    # Write → invalidated.
    t.write_row({"k": 99, "a": 1, "b": "y", "c": 1.0}, timestamp=200)
    c3 = t.read_snapshot()
    assert c3 is not c1
    assert any(r["k"] == 99 for r in c3.to_rows())
    # Flush → invalidated (generation bump), contents unchanged.
    t.flush()
    c4 = t.read_snapshot()
    assert c4 is not c3 and c4.to_rows() == c3.to_rows()
    # Compact → invalidated.
    t.compact()
    c5 = t.read_snapshot()
    assert c5 is not c4 and c5.to_rows() == c4.to_rows()
    stats = tablet_mod.snapshot_cache_stats()
    assert stats["evictions"] >= 2 and stats["bytes_pinned"] > 0


def test_snapshot_cache_disabled_via_config():
    set_tablet_config(TabletConfig(vectorized_scan_min_rows=0,
                                   snapshot_cache_enabled=False))
    t = _tablet(SCHEMAS["int_key"])
    t.write_row({"k": 1, "a": 1, "b": "x", "c": 0.0}, timestamp=10)
    assert t.read_snapshot() is not t.read_snapshot()


def test_snapshot_cache_on_monitoring_endpoints():
    import json
    import urllib.request

    from ytsaurus_tpu.server.monitoring import MonitoringServer
    t = _tablet(SCHEMAS["int_key"])
    t.write_row({"k": 1, "a": 1, "b": "x", "c": 0.0}, timestamp=10)
    t.read_snapshot()
    t.read_snapshot()
    server = MonitoringServer()
    server.start()
    try:
        with urllib.request.urlopen(
                f"http://{server.address}/tablet", timeout=5) as resp:
            snap = json.loads(resp.read())["snapshot_cache"]
        assert snap["hits"] >= 1 and snap["misses"] >= 1
        with urllib.request.urlopen(
                f"http://{server.address}/metrics", timeout=5) as resp:
            body = resp.read().decode()
        assert "tablet_snapshot_cache_hits" in body
        assert "tablet_snapshot_cache_bytes_pinned" in body
    finally:
        server.stop()


# --- host-plane LRU -----------------------------------------------------------


def test_host_planes_lru_promotes_on_hit():
    set_tablet_config(TabletConfig(host_plane_cache_capacity=2))
    t = _tablet(SCHEMAS["int_key"])
    cids = []
    for i in range(3):
        t.write_row({"k": i, "a": i, "b": "x", "c": 0.0}, timestamp=10 + i)
        cids.append(t.flush())
    t._host_planes.clear()
    t._chunk_host_planes_locked(cids[0])
    t._chunk_host_planes_locked(cids[1])
    t._chunk_host_planes_locked(cids[0])        # promote: [1, 0]
    t._chunk_host_planes_locked(cids[2])        # evicts 1, NOT the promoted 0
    assert cids[0] in t._host_planes
    assert cids[1] not in t._host_planes
    assert cids[2] in t._host_planes


# --- chunk-meta pruning stats ---------------------------------------------


def test_stats_sealed_into_chunk_meta(tmp_path):
    store = FsChunkStore(str(tmp_path))
    schema = TableSchema.make([("k", "int64"), ("s", "string")])
    chunk = ColumnarChunk.from_rows(
        schema, [{"k": 3, "s": "b"}, {"k": -1, "s": "a"},
                 {"k": 7, "s": None}])
    cid = store.write_chunk(chunk)
    meta = store.read_meta(cid)
    k_stats = dict(meta["column_stats"]["k"])
    # The NDV sketch (ISSUE 14) rides next to the bounds — fixed 64
    # registers, never data-sized.
    sketch = k_stats.pop("ndv_sketch")
    assert len(sketch.encode("utf-8") if isinstance(sketch, str)
               else sketch) == 64
    assert k_stats == {"min": -1, "max": 7, "has_null": False}
    stats = store.read_stats(cid)
    assert stats["k"]["max"] == 7 and stats["$row_count"] == 3
    assert stats["s"]["has_null"] is True


def test_stats_backfill_for_pre_stats_chunks(tmp_path):
    """Chunks written before stats persisted (no column_stats in meta)
    decode once and compute host-side."""
    from ytsaurus_tpu import yson
    from ytsaurus_tpu.chunks.encoding import (
        MAGIC,
        read_chunk_meta,
        serialize_chunk,
    )
    from ytsaurus_tpu.utils.varint import encode_varint_u

    store = FsChunkStore(str(tmp_path))
    schema = TableSchema.make([("k", "int64")])
    chunk = ColumnarChunk.from_rows(schema, [{"k": 5}, {"k": 9}])
    blob = serialize_chunk(chunk)
    meta = read_chunk_meta(blob)
    data_start = meta.pop("_data_start")
    del meta["column_stats"]
    meta_blob = yson.dumps(meta, binary=True)
    legacy = b"".join([MAGIC, encode_varint_u(len(meta_blob)), meta_blob,
                       blob[data_start:]])
    cid = store.put_blob("ab" + "0" * 30, legacy)
    assert store.read_meta(cid).get("column_stats") is None
    stats = store.read_stats(cid)
    assert {k: stats["k"][k] for k in ("min", "max", "has_null")} == \
        {"min": 5, "max": 9, "has_null": False}
    # The backfill computes the full payload, sketch included.
    assert stats["k"].get("ndv_sketch") is not None
    # Memoized: a second read serves from memory.
    assert store.read_stats(cid) is stats


# --- coordinator single-sync fan-out -------------------------------------------


def test_deferred_shard_dispatch_matches_sync():
    from ytsaurus_tpu.chunks.columnar import concat_chunks
    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.coordinator import coordinate_and_execute
    from ytsaurus_tpu.query.engine.evaluator import Evaluator

    schema = TableSchema.make([("k", "int64"), ("g", "int64"),
                               ("v", "int64")])
    rng = random.Random(3)
    shards = [ColumnarChunk.from_rows(
        schema, [{"k": s * 100 + i, "g": rng.randrange(5),
                  "v": rng.randrange(100)} for i in range(40)])
        for s in range(5)]
    plan = build_query(
        "g, sum(v) AS s, count(*) AS c FROM [//t] WHERE v < 90 GROUP BY g",
        {"//t": schema})
    # No LIMIT/early-exit → the deferred path dispatches all five shard
    # programs before the single synchronization.
    out = coordinate_and_execute(plan, shards, evaluator=Evaluator())
    want = Evaluator().run_plan(plan, concat_chunks(shards))
    key = lambda r: r["g"]
    assert sorted(out.to_rows(), key=key) == sorted(want.to_rows(), key=key)


def test_finish_all_single_transfer():
    from ytsaurus_tpu.query.builder import build_query
    from ytsaurus_tpu.query.engine.evaluator import Evaluator, finish_all

    schema = TableSchema.make([("k", "int64"), ("v", "int64")])
    chunks = [ColumnarChunk.from_rows(
        schema, [{"k": i, "v": i * j} for i in range(10)])
        for j in range(1, 4)]
    plan = build_query("k, v FROM [//t] WHERE v >= 0", {"//t": schema})
    ev = Evaluator()
    pendings = [ev.run_plan_async(plan, c) for c in chunks]
    results = finish_all(pendings)
    assert [r.row_count for r in results] == [10, 10, 10]
    # finish() after finish_all returns the same chunk (idempotent).
    assert pendings[0].finish() is results[0]
