from ytsaurus_tpu.chunks.columnar import (
    Column,
    ColumnarChunk,
    concat_chunks,
    next_pow2,
    pad_capacity,
    unify_dictionaries,
)
