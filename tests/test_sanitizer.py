"""Runtime concurrency sanitizer (ISSUE 15): instrumented-lock unit
tests (edges, inversions, hold budget, sync-under-lock, condition
semantics, re-entrancy), the disabled fast path, and the dynamic⊆static
reconciliation over the REAL registered lock set (the full-suite gate
additionally runs in conftest.pytest_sessionfinish)."""

import threading
import time

import pytest

from ytsaurus_tpu.utils import sanitizers
from ytsaurus_tpu.utils.sanitizers import (
    InstrumentedCondition,
    InstrumentedLock,
    InstrumentedRLock,
    LockSanitizer,
)


@pytest.fixture
def san():
    """A private sanitizer: deliberate violations in these tests must
    not pollute the process-global instance the tier-1 gate reads."""
    return LockSanitizer(hold_budget=0.02)


def make(san, name, hot=True):
    return InstrumentedLock(san, name, hot=hot)


# --- edges + inversions -------------------------------------------------------


def test_nested_acquire_records_edge(san):
    a, b = make(san, "A"), make(san, "B")
    with a:
        with b:
            pass
    assert ("A", "B") in san.edges
    assert ("B", "A") not in san.edges
    assert san.counters()["edges_observed"] == 1
    assert san.counters()["inversions"] == 0


def test_lock_order_inversion_detected_with_stacks(san):
    a, b = make(san, "A"), make(san, "B")
    with a:
        with b:
            pass

    def inverted():
        with b:
            with a:
                pass

    t = threading.Thread(target=inverted)
    t.start()
    t.join()
    assert san.counters()["inversions"] == 1
    inv = san.inversions[0]
    assert inv["holding"] == "B" and inv["acquiring"] == "A"
    assert inv["stack"], "acquisition stack must be attached"
    assert inv["prior_order_stack"], "the A->B sighting rides along"


def test_held_sets_are_per_thread(san):
    a, b = make(san, "A"), make(san, "B")
    started = threading.Event()
    release = threading.Event()

    def holder():
        with a:
            started.set()
            release.wait(5)

    t = threading.Thread(target=holder)
    t.start()
    started.wait(5)
    # This thread holds nothing: acquiring B is edge-free even though
    # another thread currently holds A.
    with b:
        pass
    release.set()
    t.join()
    assert ("A", "B") not in san.edges


def test_triple_nesting_records_all_held_edges(san):
    a, b, c = make(san, "A"), make(san, "B"), make(san, "C")
    with a:
        with b:
            with c:
                pass
    assert {("A", "B"), ("A", "C"), ("B", "C")} <= set(san.edges)


def test_sibling_instances_of_one_site_are_not_an_edge(san):
    # Two Counter instances share one site name: nesting them is not an
    # ordering edge (the static graph has one node for the site).
    a1, a2 = make(san, "A"), make(san, "A")
    with a1:
        with a2:
            pass
    assert san.edges == {}


def test_rlock_reentrancy_emits_one_span(san):
    r = InstrumentedRLock(san, "R")
    b = make(san, "B")
    with r:
        with r:
            with b:
                pass
        # Still held here: only the OUTERMOST release pops the frame.
        assert "R" in san.held_names()
    assert san.held_names() == []
    assert ("R", "B") in san.edges and ("R", "R") not in san.edges


# --- hold budget --------------------------------------------------------------


def test_hold_budget_violation_recorded(san):
    a = make(san, "A")
    with a:
        time.sleep(0.05)
    assert san.counters()["hold_violations"] == 1
    violation = san.hold_violations[0]
    assert violation["lock"] == "A"
    assert violation["held_seconds"] >= 0.02


def test_hold_budget_exempts_cold_locks(san):
    a = make(san, "A", hot=False)
    with a:
        time.sleep(0.05)
    assert san.counters()["hold_violations"] == 0


# --- sync/blocking under lock -------------------------------------------------


def test_blocking_io_under_hot_lock_flagged(san):
    a = make(san, "A")
    with a:
        san.note_blocking("io", "chunks.store.write")
    event = san.sync_under_lock[0]
    assert event["locks_held"] == ["A"]
    assert event["detail"] == "chunks.store.write"


def test_blocking_without_lock_is_silent(san):
    san.note_blocking("io", "chunks.store.write")
    assert san.counters()["sync_under_lock"] == 0


def test_blocking_under_cold_lock_exempt(san):
    a = make(san, "A", hot=False)
    with a:
        san.note_blocking("io", "aot.disk.write")
    assert san.counters()["sync_under_lock"] == 0


def test_host_sync_under_lock_via_traced_jnp_op(san):
    """The jax-shaped repro: materializing a traced computation while a
    hot lock is held — finish() calls note_host_sync, which must
    attribute the sync to the held lock."""
    import jax
    import jax.numpy as jnp

    a = make(san, "evaluator.fake._lock")
    fn = jax.jit(lambda x: (x * 2).sum())
    with a:
        value = fn(jnp.arange(8))
        # the sanctioned sync point runs under the lock: flagged
        import ytsaurus_tpu.utils.sanitizers as global_san
        san.note_blocking("host-sync", "evaluator.finish")
        assert int(value) == 56
    event = san.sync_under_lock[0]
    assert event["kind"] == "host-sync"
    assert event["locks_held"] == ["evaluator.fake._lock"]


# --- condition semantics ------------------------------------------------------


def test_condition_wait_releases_held_set(san):
    cond = InstrumentedCondition(san, "C")
    seen_during_wait = []
    woken = threading.Event()

    def waiter():
        with cond:
            cond.wait(timeout=5)
            woken.set()

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cond:
        # waiter is blocked in wait(): ITS held set must not contain C
        # (we can observe indirectly: this acquire succeeded, and no
        # self-edge/inversion was produced)
        seen_during_wait.append(san.held_names())
        cond.notify_all()
    woken.wait(5)
    t.join()
    assert seen_during_wait == [["C"]]
    assert san.counters()["inversions"] == 0


def test_condition_hold_time_excludes_wait(san):
    cond = InstrumentedCondition(san, "C")

    def notifier():
        time.sleep(0.05)
        with cond:
            cond.notify_all()

    t = threading.Thread(target=notifier)
    t.start()
    with cond:
        cond.wait(timeout=1)       # >> budget, but NOT held time
    t.join()
    assert san.counters()["hold_violations"] == 0


# --- registration + disabled fast path ----------------------------------------


def test_register_lock_enabled_returns_instrumented(monkeypatch):
    monkeypatch.setenv("YT_TPU_SANITIZE", "1")
    lock = sanitizers.register_lock("test.fixture._lock")
    assert isinstance(lock, InstrumentedLock)
    assert "test.fixture._lock" in sanitizers.registered_sites()


def test_register_lock_disabled_returns_plain_lock(monkeypatch):
    monkeypatch.delenv("YT_TPU_SANITIZE", raising=False)
    monkeypatch.setattr(sanitizers, "_config_enabled", False)
    lock = sanitizers.register_lock("test.disabled._lock")
    assert type(lock) is type(threading.Lock()), \
        "disabled path must hand out the PLAIN lock, not a wrapper"
    cond = sanitizers.register_condition("test.disabled._cond")
    assert isinstance(cond, threading.Condition)
    rlock = sanitizers.register_rlock("test.disabled._rlock")
    assert type(rlock) is type(threading.RLock())


def test_config_gating(monkeypatch):
    """The full config path: DaemonConfig.sanitizer parses, and
    set_sanitizer_config applies it (None restores the disabled
    default, like every other config setter)."""
    from ytsaurus_tpu import config as cfg
    monkeypatch.delenv("YT_TPU_SANITIZE", raising=False)
    monkeypatch.setattr(sanitizers, "_config_enabled", False)
    assert not sanitizers.enabled()
    daemon_cfg = cfg.DaemonConfig.from_dict(
        {"sanitizer": {"enabled": True, "hold_budget_seconds": 1.5}})
    assert daemon_cfg.sanitizer.enabled
    try:
        cfg.set_sanitizer_config(daemon_cfg.sanitizer)
        assert cfg.sanitizer_config().enabled
        assert sanitizers.enabled()
        assert sanitizers.get_sanitizer().hold_budget == 1.5
        cfg.set_sanitizer_config(None)
        assert not cfg.sanitizer_config().enabled
        assert not sanitizers.enabled()
    finally:
        cfg.set_sanitizer_config(None)
        monkeypatch.setattr(sanitizers, "_config_enabled", False)
        # restore the suite-wide budget for later tests
        sanitizers.get_sanitizer().hold_budget = \
            sanitizers.DEFAULT_HOLD_BUDGET


def test_snapshot_shape_and_counters():
    report = sanitizers.snapshot()
    # conftest arms YT_TPU_SANITIZE for the whole suite
    assert report["enabled"] is True
    for key in ("inversions", "hold_violations", "sync_under_lock",
                "edges_observed"):
        assert key in report["counters"]
    assert isinstance(report["edges"], list)


def test_monitoring_sanitizer_endpoint():
    import json
    import urllib.request

    from ytsaurus_tpu.server.monitoring import MonitoringServer
    server = MonitoringServer()
    server.start()
    try:
        with urllib.request.urlopen(
                f"http://{server.address}/sanitizer", timeout=5) as resp:
            body = json.loads(resp.read())
        assert body["enabled"] is True
        assert "edges" in body and "counters" in body
        # the counters mirror onto /metrics at snapshot time
        with urllib.request.urlopen(
                f"http://{server.address}/metrics", timeout=5) as resp:
            metrics = resp.read().decode()
        assert "sanitizer_edges_observed" in metrics
    finally:
        server.stop()


def test_orchid_sanitizer_mount():
    from ytsaurus_tpu.server.orchid import default_orchid
    tree = default_orchid()
    value = tree.get("/sanitizer")
    assert value["enabled"] is True


# --- the dynamic ⊆ static reconciliation gate ---------------------------------


import functools


@functools.lru_cache(maxsize=1)
def _reconciliation_inputs():
    import os
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    from tools.analyze import guard_inference, load_files
    return guard_inference.reconciliation_graph(load_files(repo))


def test_dynamic_graph_is_subgraph_of_static_over_real_locks():
    """Exercise real cross-lock paths on the PROCESS-GLOBAL sanitizer
    (accounting under admission, sensor creation under the workload log,
    history sampling over live sensors), then assert every observed
    edge between registered sites exists in the static reconciliation
    graph — the same check pytest_sessionfinish runs over the whole
    tier-1 run, failing with acquisition stacks on any miss."""
    from ytsaurus_tpu.query.accounting import get_accountant
    from ytsaurus_tpu.query.workload import get_workload_log
    from ytsaurus_tpu.utils.profiling import MetricsHistory, Profiler

    # accounting: fold usage (accountant lock -> pool sensor counters)
    get_accountant().fold("pool-a", "user", lookups=1, lookup_keys=2)
    # workload: record a lookup (log lock -> sensor creation)
    get_workload_log().observe_lookup("//tmp/t", [(1,)], pool="pool-a")
    # telemetry: sample every live sensor under the history lock
    Profiler("/sanitizer_test").counter("ticks").increment()
    MetricsHistory(sample_period=10.0).sample_once()

    san = sanitizers.get_sanitizer()
    assert san is not None, "conftest arms YT_TPU_SANITIZE suite-wide"
    assert san.edge_snapshot(), "the exercise above must record edges"

    graph = _reconciliation_inputs()
    violations = sanitizers.reconcile(graph["edges"], graph["site_map"])
    assert violations == [], "\n".join(violations)


def test_site_map_covers_every_registered_site():
    """Every lock the process registered resolves to a static node —
    a registration whose site string drifts from the code location
    would silently fall out of the reconciliation gate."""
    graph = _reconciliation_inputs()
    site_map = graph["site_map"]
    missing = [site for site in sanitizers.registered_sites()
               if site not in site_map and not site.startswith("test.")]
    assert missing == [], missing


def test_reconcile_reports_missing_edge_with_stack(san):
    a, b = make(san, "site.a"), make(san, "site.b")
    with a:
        with b:
            pass
    site_map = {"site.a": "x.py::A._lock", "site.b": "y.py::B._lock"}
    violations = sanitizers.reconcile(
        [], site_map, observed=san.edge_snapshot())
    assert len(violations) == 1
    assert "site.a -> site.b" in violations[0]
    assert "MISSING" in violations[0]
    # the edge is sanctioned once the static graph carries it
    ok = sanitizers.reconcile(
        [["x.py::A._lock", "y.py::B._lock", "x.py:1"]], site_map,
        observed=san.edge_snapshot())
    assert ok == []


def test_reconcile_ignores_unregistered_sites(san):
    a, b = make(san, "test.only.a"), make(san, "test.only.b")
    with a:
        with b:
            pass
    violations = sanitizers.reconcile([], {}, observed=san.edge_snapshot())
    assert violations == []
