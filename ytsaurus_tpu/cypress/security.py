"""Security: users, groups, accounts with quotas, ACL permission checks.

Ref shape: server/master/security_server/security_manager.h — principals
(users/groups) and accounts are first-class objects; every Cypress node
carries an ACL (list of ACEs: action/subjects/permissions) inherited down
the tree unless @inherit_acl is false; accounts meter node counts and disk
space against hierarchical limits.

Redesign: principals/accounts live IN the Cypress tree (//sys/users/...,
//sys/groups/..., //sys/accounts/...) so they persist through the ordinary
WAL/snapshot pipeline — no separate authority.  The authenticated user is
ambient (contextvar) set per RPC request by the driver service, matching
the reference's per-request authenticated-user stack
(security_manager.h TAuthenticatedUserGuard).
"""

from __future__ import annotations

import contextvars
from typing import Optional

from ytsaurus_tpu.errors import EErrorCode, YtError

PERMISSIONS = ("read", "write", "remove", "administer", "use", "mount")

ROOT_USER = "root"
SUPERUSERS = "superusers"
DEFAULT_ACCOUNT = "default"

_current_user: contextvars.ContextVar[str] = \
    contextvars.ContextVar("authenticated_user", default=ROOT_USER)


def current_user() -> str:
    return _current_user.get()


class authenticated_user:
    """Context manager: run a block as a given principal."""

    def __init__(self, user: str):
        self.user = user
        self._token = None

    def __enter__(self):
        self._token = _current_user.set(self.user)
        return self

    def __exit__(self, *exc):
        _current_user.reset(self._token)
        return False


class SecurityManager:
    """Permission + quota authority over one Cypress tree (via its master
    so principal mutations replicate through the WAL)."""

    def __init__(self, master):
        import threading
        self.master = master
        # Serializes read-modify-write cycles on usage/membership state:
        # driver requests run on a thread pool, and an unlocked RMW loses
        # concurrent charges (quota drift).
        self.metering_lock = threading.RLock()

    @property
    def tree(self):
        return self.master.tree

    # -- bootstrap -------------------------------------------------------------

    def ensure_defaults(self) -> None:
        """Idempotent: //sys scaffolding + root/superusers/default account."""
        m = self.master
        for path in ("//sys/users", "//sys/groups", "//sys/accounts"):
            if not self.tree.exists(path):
                m.commit_mutation("create", path=path, type="map_node",
                                  recursive=True)
        if not self.tree.exists(f"//sys/users/{ROOT_USER}"):
            self.create_user(ROOT_USER)
        if not self.tree.exists(f"//sys/groups/{SUPERUSERS}"):
            self.create_group(SUPERUSERS, members=[ROOT_USER])
        if not self.tree.exists(f"//sys/accounts/{DEFAULT_ACCOUNT}"):
            self.create_account(DEFAULT_ACCOUNT)

    # -- principals ------------------------------------------------------------

    def create_user(self, name: str) -> None:
        self.master.commit_mutation(
            "create", path=f"//sys/users/{name}", type="map_node",
            recursive=True, attributes={"user": True, "banned": False})

    def create_group(self, name: str, members: Optional[list] = None) -> None:
        self.master.commit_mutation(
            "create", path=f"//sys/groups/{name}", type="map_node",
            recursive=True, attributes={"members": list(members or [])})

    def add_member(self, group: str, member: str) -> None:
        path = f"//sys/groups/{group}/@members"
        with self.metering_lock:
            members = list(self.tree.get(path))
            if member not in members:
                members.append(member)
                self.master.commit_mutation("set", path=path, value=members)

    def remove_member(self, group: str, member: str) -> None:
        path = f"//sys/groups/{group}/@members"
        with self.metering_lock:
            members = [m for m in self.tree.get(path) if m != member]
            self.master.commit_mutation("set", path=path, value=members)

    def user_exists(self, name: str) -> bool:
        return self.tree.exists(f"//sys/users/{name}")

    def groups_of(self, user: str) -> set[str]:
        groups = {"everyone"}
        groups_node = self.tree.try_resolve("//sys/groups")
        if groups_node is None:
            return groups
        for name, node in groups_node.children.items():
            if user in (node.attributes.get("members") or []):
                groups.add(name)
        return groups

    # -- accounts --------------------------------------------------------------

    def create_account(self, name: str,
                       resource_limits: Optional[dict] = None) -> None:
        limits = {"node_count": 100_000, "disk_space": 1 << 44,
                  "chunk_count": 1 << 30}
        limits.update(resource_limits or {})
        self.master.commit_mutation(
            "create", path=f"//sys/accounts/{name}", type="map_node",
            recursive=True,
            attributes={"resource_limits": limits,
                        "resource_usage": {"node_count": 0, "disk_space": 0,
                                           "chunk_count": 0}})

    def account_of(self, path: str) -> str:
        """Nearest @account walking up from the node (defaults apply)."""
        node = self.tree.try_resolve(path)
        while path not in ("/", "//"):
            if node is not None:
                account = node.attributes.get("account")
                if account:
                    return account
            path = path.rsplit("/", 1)[0] or "/"
            node = self.tree.try_resolve(path) if path != "/" else None
        return DEFAULT_ACCOUNT

    def charge_account(self, account: str, *, node_count: int = 0,
                       disk_space: int = 0, chunk_count: int = 0) -> None:
        """Meter usage; raises AccountLimitExceeded when a POSITIVE delta
        would cross a limit (frees always apply)."""
        acc_path = f"//sys/accounts/{account}"
        if not self.tree.exists(acc_path):
            raise YtError(f"No such account {account!r}",
                          code=EErrorCode.ResolveError)
        with self.metering_lock:
            usage = dict(self.tree.get(f"{acc_path}/@resource_usage"))
            limits = self.tree.get(f"{acc_path}/@resource_limits")
            deltas = {"node_count": node_count, "disk_space": disk_space,
                      "chunk_count": chunk_count}
            for key, delta in deltas.items():
                new = usage.get(key, 0) + delta
                if delta > 0 and new > limits.get(key, 0):
                    raise YtError(
                        f"Account {account!r} is over its {key!r} limit: "
                        f"{new} > {limits.get(key, 0)}",
                        code=EErrorCode.AccountLimitExceeded,
                        attributes={"account": account, "resource": key})
                usage[key] = max(0, new)
            self.master.commit_mutation(
                "set", path=f"{acc_path}/@resource_usage", value=usage)

    # -- permission checks -----------------------------------------------------

    def check_permission(self, user: str, permission: str,
                         path: str) -> bool:
        """Walk the node's ancestor chain collecting ACEs; DENY beats ALLOW
        anywhere on the effective list (ref ACL evaluation order)."""
        if permission not in PERMISSIONS:
            raise YtError(f"Unknown permission {permission!r}")
        if user == ROOT_USER or SUPERUSERS in self.groups_of(user):
            return True
        subjects = self.groups_of(user) | {user}
        allowed = False
        tokens = [t for t in path.split("/") if t and not t.startswith("@")]
        chain = ["//" + "/".join(tokens[:i]) for i in
                 range(len(tokens), 0, -1)] + ["/"]
        inherit = True
        for ancestor in chain:
            node = self.tree.try_resolve(ancestor) \
                if ancestor != "/" else self.tree.root
            if node is None:
                continue
            for ace in node.attributes.get("acl") or []:
                ace_subjects = set(ace.get("subjects") or [])
                ace_perms = set(ace.get("permissions") or [])
                if not (subjects & ace_subjects) \
                        or permission not in ace_perms:
                    continue
                if ace.get("action") == "deny":
                    return False
                allowed = True
            if not node.attributes.get("inherit_acl", True):
                inherit = False
                break
        # Without any matching ACE: default-open for reads (friendly local
        # clusters), closed for everything else — unless nothing demands
        # security (no non-root users defined).
        if allowed:
            return True
        if inherit and not self._has_acls():
            return True
        return permission == "read" and inherit

    def _has_acls(self) -> bool:
        users = self.tree.try_resolve("//sys/users")
        return users is not None and \
            any(name != ROOT_USER for name in users.children)

    def validate_permission(self, permission: str, path: str,
                            user: Optional[str] = None) -> None:
        user = user or current_user()
        if not self.user_exists(user) and user != ROOT_USER:
            raise YtError(f"Unknown user {user!r}",
                          code=EErrorCode.AuthenticationError)
        if self.tree.exists(f"//sys/users/{user}/@banned") and \
                self.tree.get(f"//sys/users/{user}/@banned"):
            raise YtError(f"User {user!r} is banned",
                          code=EErrorCode.AuthenticationError)
        if not self.check_permission(user, permission, path):
            raise YtError(
                f"Access denied: user {user!r} lacks {permission!r} "
                f"permission on {path!r}",
                code=EErrorCode.AuthorizationError,
                attributes={"user": user, "permission": permission,
                            "path": path})
