"""Error-taxonomy pass (`yt analyze --pass errors`).

The error-code registry (`errors.EErrorCode`) is the wire contract:
clients dispatch on codes (`RetryingChannel` retries TransportError,
treats DeadlineExceeded as terminal, honors RequestThrottled hints), so
the registry must stay sound:

  duplicate-code      two EErrorCode members share a value.  IntEnum
                      silently ALIASES duplicates — `EErrorCode.B = 500`
                      after `A = 500` makes B just another name for A,
                      every `find(B)` matches A's errors, and nothing
                      throws.  Only a static check catches this.
  unregistered-code   a raise site passes `code=<int literal>` that no
                      EErrorCode member defines — invisible to every
                      `contains`/`find` dispatch written against the
                      registry.
  literal-code        a raise site uses a registered value as a bare
                      int instead of the EErrorCode member (warning:
                      greppability + rename safety).
"""

from __future__ import annotations

import ast

from tools.analyze.core import Finding, SourceFile, dotted_name

PASS_NAME = "errors"

ERRORS_MODULE = "ytsaurus_tpu/errors.py"
ENUM_CLASS = "EErrorCode"

# Error constructors whose `code=` kwarg is registry-checked.
_ERROR_CTORS = {"YtError", "YtResponseError", "errors.YtError"}


def registry_from(files: "list[SourceFile]"
                  ) -> "tuple[dict[str, int], list[Finding]]":
    """name -> value from the EErrorCode class body, plus duplicate
    findings.  Pure AST — the enum is never imported."""
    findings: list[Finding] = []
    values: dict[str, int] = {}
    errors_file = next((f for f in files if f.path == ERRORS_MODULE or
                        f.path.endswith("/errors.py")), None)
    if errors_file is None:
        return values, findings
    enum_node = next((n for n in ast.walk(errors_file.tree)
                      if isinstance(n, ast.ClassDef)
                      and n.name == ENUM_CLASS), None)
    if enum_node is None:
        return values, findings
    by_value: dict[int, str] = {}
    for stmt in enum_node.body:
        if not (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, int)):
            continue
        name = stmt.targets[0].id
        value = stmt.value.value
        prior = by_value.get(value)
        if prior is not None:
            findings.append(Finding(
                PASS_NAME, "duplicate-code", errors_file.path,
                stmt.lineno,
                f"EErrorCode.{name} = {value} duplicates "
                f"EErrorCode.{prior} — IntEnum silently aliases them, "
                f"so every find({name}) would match {prior} errors"))
        else:
            by_value[value] = name
        values[name] = value
    return values, findings


def _check_raise_sites(f: SourceFile, registry: "dict[str, int]",
                       findings: "list[Finding]") -> None:
    registered_values = set(registry.values())
    for node in ast.walk(f.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func)
        if callee.rsplit(".", 1)[-1] not in {c.rsplit(".", 1)[-1]
                                             for c in _ERROR_CTORS}:
            continue
        for kw in node.keywords:
            if kw.arg != "code":
                continue
            value = kw.value
            if isinstance(value, ast.Constant) and \
                    isinstance(value.value, int):
                if f.waived("error-code", value.lineno):
                    continue
                if value.value not in registered_values:
                    findings.append(Finding(
                        PASS_NAME, "unregistered-code", f.path,
                        node.lineno,
                        f"raise site uses code={value.value} which no "
                        f"EErrorCode member defines — register it in "
                        f"errors.py or use an existing member"))
                else:
                    member = next(n for n, v in registry.items()
                                  if v == value.value)
                    findings.append(Finding(
                        PASS_NAME, "literal-code", f.path, node.lineno,
                        f"raise site spells code={value.value} as a "
                        f"bare int — use EErrorCode.{member}",
                        severity="warning"))
            elif isinstance(value, ast.Attribute):
                name = dotted_name(value)
                if name.startswith("EErrorCode.") and \
                        name[len("EErrorCode."):] not in registry and \
                        registry:
                    findings.append(Finding(
                        PASS_NAME, "unregistered-code", f.path,
                        node.lineno,
                        f"raise site references {name} but errors.py "
                        f"defines no such member"))


def run(files: "list[SourceFile]") -> "list[Finding]":
    registry, findings = registry_from(files)
    if not registry:
        return findings
    for f in files:
        _check_raise_sites(f, registry, findings)
    return findings
