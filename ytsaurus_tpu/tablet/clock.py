"""Clock quorum: HLC timestamps from an elected clock leader backed by
quorum-persisted ceilings.

Ref mapping:
  cluster clock quorum      → ClockServer (server/clock_server/
                              cluster_clock/ — a Hydra cell whose only
                              state is the timestamp ceiling)
  timestamp provider daemon → ClockService (server/timestamp_provider/)
  client batching           → QuorumTimestampProvider
                              (ytlib/transaction_client/ — concurrent
                              requests coalesce into one RPC)

The safety argument is the reference's: the leader NEVER hands out a
timestamp above the last quorum-persisted ceiling.  Ceilings advance in
coarse quanta (~1s of timestamp space), so persistence is amortized over
thousands of generations ("batched generation"), and a new leader after
failover starts strictly above the old leader's ceiling — monotonicity
survives any failover, including a clock-leader kill -9.

Election + fencing reuse the journal plane (cypress/election.py,
cypress/quorum.py): the clock WAL is just another quorum journal on the
data nodes, so a split-brain clock leader fail-stops on its first
ceiling append exactly like a split-brain master.
"""

from __future__ import annotations

import os
import threading
from typing import Optional, Sequence

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.tablet.timestamp import COUNTER_BITS
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("clock")

CLOCK_JOURNAL = "clock_wal"
# Timestamp space claimed per persisted ceiling bump: one wall second's
# worth of counters — thousands of generations per quorum write.
CEILING_QUANTUM = 1 << COUNTER_BITS


class NotClockLeader(YtError):
    def __init__(self, address: str = ""):
        super().__init__(f"clock peer {address or 'here'} is not the "
                         "leader", code=EErrorCode.PeerUnavailable)


class ClockServer:
    """One clock-quorum peer: elects over the journal plane, serves
    monotone HLC timestamps under a persisted ceiling when leading."""

    def __init__(self, root: str, journal_channels: Sequence,
                 index: int = 0, lease_ttl: float = 3.0):
        from ytsaurus_tpu.cypress.quorum import QuorumWal

        os.makedirs(root, exist_ok=True)
        self._channels = list(journal_channels)
        self._index = index
        self._lease_ttl = lease_ttl
        majority = len(self._channels) // 2 + 1
        # Remote-only quorum: a restarted clock peer recovers from the
        # SHARED locations (same argument as multi-master WAL).
        self.wal = QuorumWal(os.path.join(root, "clock.wal"),
                             CLOCK_JOURNAL, self._channels,
                             quorum=majority, lease_ttl=lease_ttl,
                             count_local_ack=False)
        self._lock = threading.Condition()
        self._last = 0                  # last handed-out timestamp
        self._ceiling = 0               # quorum-persisted upper bound
        self._bumping = False           # a ceiling append is in flight
        self._leading = False
        self._stopped = False
        self._elector = None
        self._thread: "Optional[threading.Thread]" = None

    def _new_elector(self):
        """Fresh elector per candidacy: LeaderElector.stop() latches its
        stop flag forever, so a peer that lost its lease needs a new one
        to ever campaign again (the master daemon does the same)."""
        from ytsaurus_tpu.cypress.election import LeaderElector
        return LeaderElector(
            CLOCK_JOURNAL, self._channels,
            writer_id=self.wal.writer_id,
            lease_ttl=self._lease_ttl, hold_down=self._index * 1.0)

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "ClockServer":
        self._thread = threading.Thread(target=self._campaign,
                                        daemon=True, name="clock-elect")
        self._thread.start()
        return self

    def _all_locations_fresh(self) -> bool:
        """True iff a majority of journal locations answer and NONE has
        ever held this journal — the only state in which seeding an
        empty log is safe (a partitioned-but-initialized quorum must
        never be re-seeded)."""
        answered = 0
        for replica in self.wal.replicas:
            try:
                body, _ = replica.channel.call(
                    "data_node", "journal_read",
                    {"journal": CLOCK_JOURNAL})
            except YtError:
                continue
            answered += 1
            if body.get("initialized", True):
                return False
        return answered >= len(self.wal.replicas) // 2 + 1

    def _campaign(self) -> None:
        while not self._stopped:
            try:
                self._campaign_once()
            except Exception:   # noqa: BLE001 — candidacy must survive
                logger.exception("clock campaign iteration failed")
                import time as _time
                _time.sleep(0.5)

    def _campaign_once(self) -> None:
        elector = self._new_elector()
        self._elector = elector
        if not elector.wait_until_electable(timeout=60.0):
            return
        if self._stopped:
            return
        # Fence BEFORE reading: a deposed-but-alive leader could
        # otherwise persist one more ceiling between our recovery read
        # and our epoch acquisition, and we would start below
        # timestamps it already issued.  With the fence first, any such
        # late append is rejected by the quorum and the read sees every
        # ceiling that could ever have backed an issued timestamp.
        try:
            self.wal.acquire_epoch()
            records = self.wal.recover()
        except YtError:
            if not self._all_locations_fresh():
                return
            # First-ever leader of a fresh quorum: seed an empty log
            # (identical seeds from racing candidates are
            # indistinguishable; epoch fencing arbitrates appends).
            self.wal.bootstrap_from_local = True
            try:
                records = self.wal.recover()
            except YtError:
                return
            finally:
                self.wal.bootstrap_from_local = False
        ceiling = 0
        for record in records:
            ceiling = max(ceiling, int(record.get("ceiling", 0)))
        with self._lock:
            # Strictly above everything any previous leader COULD have
            # issued: its ceiling is the proof.
            self._last = ceiling
            self._ceiling = ceiling
            self._leading = True
        logger.info("clock leader (epoch %s, ceiling %s)",
                    self.wal.epoch, ceiling)
        lost = threading.Event()
        self._lost_event = lost

        def on_lease_lost():
            with self._lock:
                self._leading = False
                self._lock.notify_all()
            lost.set()

        elector.start_renewing(lambda: self.wal.epoch, on_lease_lost)
        lost.wait()
        elector.stop()
        # Re-enter candidacy with a fresh elector (a fenced leader's
        # appends fail-stop it out of generate() regardless).

    def stop(self) -> None:
        self._stopped = True
        with self._lock:
            self._leading = False
            self._lock.notify_all()
        if self._elector is not None:
            self._elector.stop()
        lost = getattr(self, "_lost_event", None)
        if lost is not None:
            lost.set()          # release a blocked campaign thread

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self._leading

    # -- generation ------------------------------------------------------------

    def generate_batch(self, count: int = 1) -> "tuple[int, int]":
        """(first, count) of a contiguous strictly-monotone timestamp
        range.  Persists a new ceiling (quorum append, epoch-fenced)
        only when the range would cross the current one — and the
        append happens OUTSIDE the serving lock, so a slow journal node
        stalls only the bumping thread, not every generation (nor
        clock_state probes)."""
        import time as _time
        if count < 1:
            raise YtError("count must be >= 1")
        while True:
            with self._lock:
                while self._bumping and self._leading:
                    self._lock.wait(0.5)
                if not self._leading:
                    raise NotClockLeader()
                wall = int(_time.time()) << COUNTER_BITS
                first = max(wall, self._last + 1)
                last = first + count - 1
                if last < self._ceiling:
                    self._last = last
                    return first, count
                self._bumping = True
                target = last + CEILING_QUANTUM
            try:
                # Epoch fencing makes this the linearization point: a
                # deposed leader's append is rejected by the quorum and
                # it steps down here.
                self.wal.append({"ceiling": target})
            except YtError:
                with self._lock:
                    self._leading = False
                    self._bumping = False
                    self._lock.notify_all()
                raise NotClockLeader()
            with self._lock:
                self._ceiling = max(self._ceiling, target)
                self._bumping = False
                self._lock.notify_all()
            # Loop back: serve under the freshly published ceiling.


from ytsaurus_tpu.rpc.server import Service, rpc_method


class ClockService(Service):
    """RPC surface of one clock peer (ref timestamp_provider service)."""

    name = "clock"

    def __init__(self, server: ClockServer):
        self.server = server

    @rpc_method()
    def generate_timestamps(self, body, attachments):
        first, count = self.server.generate_batch(
            int(body.get("count", 1)))
        return {"first": first, "count": count}

    @rpc_method()
    def clock_state(self, body, attachments):
        return {"leader": self.server.is_leader}


class QuorumTimestampProvider:
    """TimestampProvider-shaped client over clock peers: leader-sticky
    with failover, and CONCURRENT generate() calls coalesce into one
    batched RPC (ref transaction_client's timestamp batcher)."""

    def __init__(self, addresses: Sequence[str], timeout: float = 10.0,
                 failover_deadline: float = 30.0):
        self.addresses = list(addresses)
        self.timeout = timeout
        self.failover_deadline = failover_deadline
        self._lock = threading.Lock()
        self._observed = 0
        self._leader: "Optional[str]" = None
        self._channels: dict = {}
        # Batcher state: one in-flight RPC; joiners queue a waiter and
        # the flight leader requests len(waiters) timestamps.
        self._flight = threading.Lock()
        self._waiters: list = []

    def _channel(self, address: str):
        from ytsaurus_tpu.rpc import Channel
        if address not in self._channels:
            self._channels[address] = Channel(address,
                                              timeout=self.timeout)
        return self._channels[address]

    def close(self) -> None:
        for channel in self._channels.values():
            try:
                channel.close()
            except Exception:   # noqa: BLE001
                pass
        self._channels.clear()

    # -- TimestampProvider interface -------------------------------------------

    def generate(self) -> int:
        return self.generate_batch(1)[0]

    def generate_batch(self, count: int = 1) -> "list[int]":
        """count contiguous timestamps from the quorum leader.  Multiple
        threads arriving together share one RPC: whoever holds the
        flight lock drains the whole waiter queue (looping until empty),
        so every queued waiter is served by SOME flight holder."""
        import time as _time
        waiter: dict = {"count": count, "event": threading.Event(),
                        "first": None, "error": None}
        with self._lock:
            self._waiters.append(waiter)
        deadline = _time.monotonic() + self.failover_deadline * 2
        while not waiter["event"].is_set():
            if self._flight.acquire(blocking=False):
                try:
                    self._drain_flight()
                finally:
                    self._flight.release()
                # Queued before acquiring → drained by now (drain loops
                # until the queue is empty under the flight lock).
            elif not waiter["event"].wait(0.05) and \
                    _time.monotonic() > deadline:
                with self._lock:
                    if waiter in self._waiters:
                        self._waiters.remove(waiter)
                raise YtError("timestamp batch timed out",
                              code=EErrorCode.Timeout)
        if waiter["error"] is not None:
            raise waiter["error"]
        first = waiter["first"]
        return list(range(first, first + count))

    def _drain_flight(self) -> None:
        """Serve every queued waiter with ONE leader RPC (repeats until
        the queue is empty — late joiners ride the next iteration)."""
        while True:
            with self._lock:
                batch, self._waiters = self._waiters, []
            if not batch:
                return
            total = sum(w["count"] for w in batch)
            try:
                first = self._rpc_generate(total)
            except YtError as exc:
                for w in batch:
                    w["error"] = exc
                    w["event"].set()
                continue
            cursor = first
            for w in batch:
                w["first"] = cursor
                cursor += w["count"]
                w["event"].set()
            with self._lock:
                self._observed = max(self._observed, cursor - 1)

    def _rpc_generate(self, count: int) -> int:
        import time as _time
        deadline = _time.monotonic() + self.failover_deadline
        last_error: "Optional[YtError]" = None
        while _time.monotonic() < deadline:
            candidates = [self._leader] if self._leader else []
            candidates += [a for a in self.addresses
                           if a not in candidates]
            for address in candidates:
                try:
                    body, _ = self._channel(address).call(
                        "clock", "generate_timestamps",
                        {"count": count})
                    self._leader = address
                    return int(body["first"])
                except YtError as exc:
                    last_error = exc
                    if self._leader == address:
                        self._leader = None
                    # Dead channels must not be reused after failure.
                    ch = self._channels.pop(address, None)
                    if ch is not None:
                        try:
                            ch.close()
                        except Exception:   # noqa: BLE001
                            pass
            _time.sleep(0.3)
        raise last_error or YtError("no clock leader reachable",
                                    code=EErrorCode.PeerUnavailable)

    def last(self) -> int:
        with self._lock:
            return self._observed

    def observe(self, ts: int) -> None:
        """HLC observe: remote commits only advance the CLIENT-side
        floor; the quorum leader's ceiling already dominates all issued
        timestamps."""
        with self._lock:
            if ts > self._observed:
                self._observed = ts
