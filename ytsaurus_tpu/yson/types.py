"""YSON object model: plain Python values + attribute-bearing wrappers.

Ref: yt/yt/core/yson + core/ytree node model.  Values map to Python as
  int64/uint64 → int (YsonUint64 marks the unsigned flavor)
  double → float;  boolean → bool;  string → bytes (YsonString) or str
  entity (#) → None / YsonEntity;  map → dict;  list → list
Any node can carry attributes (`<a=1>value`); wrappers expose `.attributes`.
"""

from __future__ import annotations


class YsonType:
    """Mixin: YSON node with attributes."""
    attributes: dict

    def has_attributes(self) -> bool:
        return bool(getattr(self, "attributes", None))


class YsonString(bytes, YsonType):
    def __new__(cls, value=b"", attributes=None):
        obj = super().__new__(cls, value)
        obj.attributes = dict(attributes or {})
        return obj


class YsonUnicode(str, YsonType):
    def __new__(cls, value="", attributes=None):
        obj = super().__new__(cls, value)
        obj.attributes = dict(attributes or {})
        return obj


class YsonInt64(int, YsonType):
    def __new__(cls, value=0, attributes=None):
        obj = super().__new__(cls, value)
        obj.attributes = dict(attributes or {})
        return obj


class YsonUint64(int, YsonType):
    def __new__(cls, value=0, attributes=None):
        if not (0 <= int(value) < 2**64):
            raise ValueError(f"uint64 out of range: {value}")
        obj = super().__new__(cls, value)
        obj.attributes = dict(attributes or {})
        return obj


class YsonDouble(float, YsonType):
    def __new__(cls, value=0.0, attributes=None):
        obj = super().__new__(cls, value)
        obj.attributes = dict(attributes or {})
        return obj


class YsonBoolean(int, YsonType):
    """bool is not subclassable; YsonBoolean(1)/YsonBoolean(0) with bool
    equality semantics."""

    def __new__(cls, value=False, attributes=None):
        obj = super().__new__(cls, 1 if value else 0)
        obj.attributes = dict(attributes or {})
        return obj

    def __repr__(self):
        return "YsonBoolean(%s)" % bool(self)


class YsonList(list, YsonType):
    def __init__(self, value=(), attributes=None):
        super().__init__(value)
        self.attributes = dict(attributes or {})


class YsonMap(dict, YsonType):
    def __init__(self, value=(), attributes=None):
        super().__init__(value)
        self.attributes = dict(attributes or {})


class YsonEntity(YsonType):
    def __init__(self, attributes=None):
        self.attributes = dict(attributes or {})

    def __eq__(self, other):
        return other is None or isinstance(other, YsonEntity)

    def __hash__(self):
        return hash(None)

    def __bool__(self):
        return False

    def __repr__(self):
        return "YsonEntity(%r)" % self.attributes


def get_attributes(value) -> dict:
    return getattr(value, "attributes", None) or {}


def to_yson_type(value, attributes=None):
    """Wrap a plain value so it can carry attributes."""
    if attributes is None:
        return value
    if value is None:
        return YsonEntity(attributes)
    if isinstance(value, bool):
        return YsonBoolean(value, attributes)
    if isinstance(value, int):
        return YsonInt64(value, attributes)
    if isinstance(value, float):
        return YsonDouble(value, attributes)
    if isinstance(value, bytes):
        return YsonString(value, attributes)
    if isinstance(value, str):
        return YsonUnicode(value, attributes)
    if isinstance(value, dict):
        return YsonMap(value, attributes)
    if isinstance(value, list):
        return YsonList(value, attributes)
    raise TypeError(f"Cannot attach attributes to {type(value).__name__}")
