"""Plan auto-parameterization (ISSUE 10 tentpole, piece a + b):
literal-hoisted shape fingerprints agree with workload normalization,
parameterized execution is bit-identical to literal-baked execution
over seeded query corpora (NULL/string/float/negative literals
included), one program serves every constant of a shape (compile-once),
LIMIT/OFFSET pow2-bucket instead of hoisting, IN-lists bucket pow2,
and the shape spectrum per fingerprint stays O(log) bounded.
"""

import random

import numpy as np
import pytest

from ytsaurus_tpu import config as yt_config
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query import parameterize as pz
from ytsaurus_tpu.query import workload as wl
from ytsaurus_tpu.query.builder import build_query
from ytsaurus_tpu.schema import TableSchema


@pytest.fixture(autouse=True)
def _fresh_configs():
    yield
    yt_config.set_compile_config(None)
    yt_config.set_workload_config(None)
    from ytsaurus_tpu.query.engine.evaluator import (
        get_compile_observatory,
    )
    get_compile_observatory().reset()


SCHEMA = TableSchema.make(
    [("k", "int64"), ("v", "int64"), ("d", "double"), ("s", "string")])


def _chunk(n=64, seed=0, with_nulls=True):
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    rng = random.Random(seed)
    rows = []
    for i in range(n):
        rows.append({
            "k": i,
            "v": None if (with_nulls and rng.random() < 0.1)
            else rng.randrange(-50, 50),
            "d": None if (with_nulls and rng.random() < 0.1)
            else rng.uniform(-5.0, 5.0),
            "s": None if (with_nulls and rng.random() < 0.1)
            else rng.choice(["alpha", "beta", "gamma", "x'y", ""]),
        })
    return ColumnarChunk.from_rows(SCHEMA, rows)


def _plan(q):
    return build_query(q, {"//t": SCHEMA})


# -- fingerprint agreement (satellite: one hoisting implementation) ------------

AGREEMENT_PAIRS = [
    ("k FROM [//t] WHERE v = 1", "k FROM [//t] WHERE v = 999"),
    ("k FROM [//t] WHERE s = 'a'", "k FROM [//t] WHERE s = 'zzz'"),
    ("k FROM [//t] WHERE d < 1.5", "k FROM [//t] WHERE d < 2.25"),
    # Negative literals are unary minus in BOTH planes (the lexer emits
    # `- ?`, the builder TUnary(-)) — consistently one shape.
    ("k FROM [//t] WHERE d < -1.5", "k FROM [//t] WHERE d < -2.25"),
    ("k FROM [//t] WHERE v IN (1, 2, 3)",
     "k FROM [//t] WHERE v IN (7, 8, 9)"),
    ("k FROM [//t] WHERE v BETWEEN 1 AND 5",
     "k FROM [//t] WHERE v BETWEEN 9 AND 40"),
    ("k FROM [//t] WHERE substr(s, 0, 2) = 'al'",
     "k FROM [//t] WHERE substr(s, 0, 2) = 'be'"),
    ("k, sum(v) AS t FROM [//t] GROUP BY k HAVING sum(v) > 10",
     "k, sum(v) AS t FROM [//t] GROUP BY k HAVING sum(v) > 77"),
]


def test_workload_and_evaluator_fingerprints_agree():
    """THE dedup satellite: queries that normalize to one workload text
    must share one evaluator (plan shape) fingerprint — the two planes
    can no longer silently diverge.  Before ISSUE 10 the evaluator
    fingerprint varied per literal while the workload one did not."""
    for qa, qb in AGREEMENT_PAIRS:
        na, _ = wl.normalize_query(qa)
        nb, _ = wl.normalize_query(qb)
        assert na == nb, (qa, qb)
        assert wl.query_fingerprint(na) == wl.query_fingerprint(nb)
        fa = pz.plan_fingerprint(_plan(qa))
        fb = pz.plan_fingerprint(_plan(qb))
        assert fa == fb, f"plan shape fingerprints diverge: {qa} / {qb}"
        # The historical per-constant fingerprint DID diverge — the
        # recompile pathology the parameterized one removes.
        assert ir.fingerprint(_plan(qa)) != ir.fingerprint(_plan(qb))


def test_different_shapes_keep_different_fingerprints():
    pairs = [
        ("k FROM [//t] WHERE v = 1", "k FROM [//t] WHERE v > 1"),
        ("k FROM [//t] WHERE v = 1", "k FROM [//t] WHERE d = 1.0"),
        ("k FROM [//t] WHERE v IN (1, 2)",
         "k FROM [//t] WHERE v IN (1, 2, 3, 4, 5)"),   # bucket 2 vs 8
        ("k FROM [//t] WHERE v = 1", "k FROM [//t] WHERE v = null"),
        ("k FROM [//t] WHERE v = 1 LIMIT 4",
         "k FROM [//t] WHERE v = 1 LIMIT 9"),          # bucket 4 vs 16
    ]
    for qa, qb in pairs:
        assert pz.plan_fingerprint(_plan(qa)) != \
            pz.plan_fingerprint(_plan(qb)), (qa, qb)


def test_normalize_query_is_the_shared_implementation():
    assert wl.normalize_query is pz.hoist_literals


def test_hoisted_parameters_walk():
    params = pz.hoisted_parameters(
        _plan("k FROM [//t] WHERE v = 7 AND s = 'abc' "
              "AND k IN (1, 2) ORDER BY k LIMIT 3"))
    values = [v for _kind, v in params]
    assert 7 in values and b"abc" in values
    assert 1 in values and 2 in values


# -- correctness property tests ------------------------------------------------

CORPUS_SHAPES = [
    "k, v FROM [//t] WHERE v = {i}",
    "k FROM [//t] WHERE v < {i} AND d >= {f}",
    "k FROM [//t] WHERE v IN ({i}, {j}, null)",
    "k FROM [//t] WHERE v BETWEEN {j} AND {i}",
    "k, s FROM [//t] WHERE s = '{s}'",
    "k FROM [//t] WHERE s LIKE '%{s}%'",
    "k FROM [//t] WHERE substr(s, 0, {u}) = '{s}'",
    "k FROM [//t] WHERE if_null(v, {i}) > {j}",
    "k, v * {i} AS scaled FROM [//t] WHERE v % {u2} = 0",
    "g2, sum(v) AS t FROM [//t] WHERE d < {f} "
    "GROUP BY k % {u2} AS g2 HAVING sum(v) > {j}",
    "k, v FROM [//t] WHERE v > {j} ORDER BY v, k LIMIT {u}",
    "k FROM [//t] WHERE v != {i} ORDER BY k OFFSET {u} LIMIT {u}",
    "k FROM [//t] WHERE transform(v, ({i}, {j}), (1, 2), 0) = {one}",
]


def _draw(rng):
    return {
        "i": rng.randrange(-60, 60),
        "j": rng.randrange(-60, 60),
        "f": round(rng.uniform(-5.0, 5.0), 3),
        "s": rng.choice(["alpha", "beta", "x", ""]),
        "u": rng.randrange(1, 9),
        "u2": rng.randrange(2, 6),
        "one": rng.choice([0, 1, 2]),
    }


def test_parameterized_results_bit_identical_to_literal_baked():
    """ISSUE 10 acceptance property: for seeded corpora over shapes
    with NULL/string/float/negative literals, evaluating through the
    SHARED parameterized program (queries 2..n reuse query 1's compiled
    executable) is bit-identical to literal-baked evaluation with a
    per-query fresh compile."""
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    chunk = _chunk(96, seed=3)
    param_ev = Evaluator()          # shared: shapes hit its cache
    for shape_i, shape in enumerate(CORPUS_SHAPES):
        rng = random.Random(100 + shape_i)
        for draw in range(4):
            q = shape.format(**_draw(rng))
            plan = _plan(q)
            yt_config.set_compile_config(
                yt_config.CompileConfig(parameterize=True))
            got = param_ev.run_plan(plan, chunk).to_rows()
            # Literal-baked oracle: parameterization off, cold cache.
            yt_config.set_compile_config(
                yt_config.CompileConfig(parameterize=False))
            want = Evaluator().run_plan(plan, chunk).to_rows()
            assert got == want, f"diverged on {q!r}"


def test_compile_once_across_constants():
    """The steady-state promise: N same-shape queries with different
    constants compile exactly ONE program; queries 2..N are hits."""
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    from ytsaurus_tpu.query.statistics import QueryStatistics
    chunk = _chunk(64, seed=5)
    ev = Evaluator()
    stats = QueryStatistics()
    for i in range(12):
        ev.run_plan(_plan(f"k FROM [//t] WHERE v < {i * 7 - 30}"),
                    chunk, stats=stats)
    assert stats.compile_count == 1
    assert stats.cache_hits == 11


def test_limit_buckets_share_programs_within_pow2():
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    from ytsaurus_tpu.query.statistics import QueryStatistics
    chunk = _chunk(64, seed=7, with_nulls=False)
    ev = Evaluator()
    stats = QueryStatistics()
    rows_by_limit = {}
    for limit in (5, 6, 7, 8):       # one pow2 bucket (8)
        out = ev.run_plan(
            _plan(f"k, v FROM [//t] ORDER BY v, k LIMIT {limit}"),
            chunk, stats=stats)
        rows_by_limit[limit] = out.to_rows()
    assert stats.compile_count == 1, "limits 5..8 must share a program"
    for limit, rows in rows_by_limit.items():
        assert len(rows) == limit
    # Exactness: each limit's rows prefix the next's.
    assert rows_by_limit[5] == rows_by_limit[8][:5]
    # A different bucket compiles separately but stays correct.
    out9 = ev.run_plan(_plan("k, v FROM [//t] ORDER BY v, k LIMIT 9"),
                       chunk, stats=stats)
    assert stats.compile_count == 2
    assert out9.to_rows()[:8] == rows_by_limit[8]


def test_in_list_pow2_bucketing_shares_programs():
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    from ytsaurus_tpu.query.statistics import QueryStatistics
    chunk = _chunk(64, seed=9, with_nulls=False)
    ev = Evaluator()
    stats = QueryStatistics()
    r3 = ev.run_plan(_plan("k FROM [//t] WHERE k IN (3, 4, 5)"),
                     chunk, stats=stats).to_rows()
    r4 = ev.run_plan(_plan("k FROM [//t] WHERE k IN (1, 2, 3, 4)"),
                     chunk, stats=stats).to_rows()
    assert stats.compile_count == 1, "len 3 and 4 share the 4-bucket"
    assert [r["k"] for r in r3] == [3, 4, 5]
    assert [r["k"] for r in r4] == [1, 2, 3, 4]


def test_shape_spectrum_stays_pow2_bounded():
    """Acceptance: the observatory's shape-spectrum cardinality for one
    fingerprint is bounded by the pow2 bucket count, not by the number
    of distinct constants/limits thrown at it."""
    from ytsaurus_tpu.query.engine.evaluator import (
        Evaluator,
        get_compile_observatory,
    )
    obs = get_compile_observatory()
    obs.reset()
    chunk = _chunk(64, seed=11, with_nulls=False)
    ev = Evaluator()
    for limit in range(1, 33):       # 32 distinct limits
        ev.run_plan(
            _plan(f"k FROM [//t] ORDER BY k LIMIT {limit}"), chunk)
    rows = [r for r in obs.top(0)]
    assert len(rows) >= 1
    # 32 limits span buckets {1,2,4,8,16,32}: <= 6 fingerprints, each
    # with ONE shape — against 32 programs pre-parameterization.
    assert len(rows) <= 6
    assert all(r["shape_count"] == 1 for r in rows)


def test_parameterize_off_restores_per_constant_fingerprints():
    yt_config.set_compile_config(
        yt_config.CompileConfig(parameterize=False))
    fa = pz.plan_fingerprint(_plan("k FROM [//t] WHERE v = 1"))
    fb = pz.plan_fingerprint(_plan("k FROM [//t] WHERE v = 2"))
    assert fa != fb


def test_join_cache_keys_carry_baked_concat_widths():
    """Sharing-contract regression (review finding): concat's pair
    multiplier `nb` bakes into the join phase programs, and two join
    shapes with SWAPPED operand vocab sizes (2x3 vs 3x2) agree on
    fingerprint, capacities, merged-vocab length and padded binding
    shapes — only the bind-phase structure notebook distinguishes
    them.  Both must produce correct matches from one shared cache."""
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    from ytsaurus_tpu.query.engine.joins import execute_join
    from ytsaurus_tpu.schema import EValueType

    self_schema = TableSchema.make([("a", "string"), ("b", "string")])

    def side(avals, bvals):
        return ColumnarChunk.from_rows(
            self_schema,
            [{"a": x, "b": y} for x in avals for y in bvals])

    chunk1 = side(["x", "y"], ["p", "q", "r"])        # na=2, nb=3
    chunk2 = side(["x", "y", "z"], ["p", "q"])        # na=3, nb=2
    foreign_schema = TableSchema.make([("k", "string"), ("v", "int64")])
    pairs = sorted({x + y for x in ["x", "y", "z"]
                    for y in ["p", "q", "r"]})
    foreign = ColumnarChunk.from_rows(
        foreign_schema, [{"k": k, "v": i} for i, k in enumerate(pairs)])
    v_of = {k.encode(): i for i, k in enumerate(pairs)}
    join = ir.JoinClause(
        foreign_table="//d", foreign_schema=foreign_schema, alias=None,
        self_equations=(ir.TFunction(
            type=EValueType.string, name="concat",
            args=(ir.TReference(type=EValueType.string, name="a"),
                  ir.TReference(type=EValueType.string, name="b"))),),
        foreign_equations=(
            ir.TReference(type=EValueType.string, name="k"),),
        foreign_columns=("v",), is_left=False)
    combined = TableSchema.make(
        [("a", "string"), ("b", "string"), ("v", "int64")])
    cache: dict = {}
    for chunk in (chunk1, chunk2):
        out = execute_join(chunk, combined, join, foreign, cache)
        rows = out.to_rows()
        assert len(rows) == chunk.row_count
        for row in rows:
            assert row["v"] == v_of[row["a"] + row["b"]], rows
    assert len(cache) == 2, "swapped concat widths must not share"


# -- cost-based join planning vs the compile-once ladder (ISSUE 14) -----------
#
# Planner DECISIONS (join order, side strategy, pushdown column sets)
# must fold into the compile cache key; estimates and pushdown VALUES
# must not.  Stats drift that flips a decision → NEW fingerprint (a
# stale program can never serve); drift that flips nothing → the same
# key (100% cache hit).

JFACT = TableSchema.make(
    [("k", "int64"), ("ok", "int64"), ("sk", "int64")])
JDA = TableSchema.make([("a_k", "int64"), ("a_v", "int64")])
JDB = TableSchema.make([("b_k", "int64"), ("b_v", "int64")])
JSCHEMAS = {"//t": JFACT, "//a": JDA, "//b": JDB}
JQUERY = ("a_v, b_v, k FROM [//t] JOIN [//a] ON ok = a_k "
          "JOIN [//b] ON sk = b_k")


def _jfact_chunk(n=64):
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    return ColumnarChunk.from_rows(JFACT, [
        {"k": i, "ok": i % 16, "sk": i % 8} for i in range(n)])


def _dim(schema, kname, vname, keys, dup=1, base=0):
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    return ColumnarChunk.from_rows(schema, [
        {kname: base + key, vname: key * 10 + r}
        for key in keys for r in range(dup)])


def test_stats_drift_flips_join_order_new_fingerprint():
    """Foreign-side duplication drifting (unique dim ↔ expanding dim)
    flips the planner's greedy order; the reordered plan's fingerprint
    — every compile cache's key — must move with it, while decision-
    neutral drift (key bounds shifting) keeps key AND token stable."""
    from ytsaurus_tpu.query import planner
    plan = _plan_joins()
    # //a unique (expansion 1.0), //b 4x duplicated (expansion 4.0):
    # the planner runs //a first regardless of declared order.
    f1 = {"//a": _dim(JDA, "a_k", "a_v", range(16)),
          "//b": _dim(JDB, "b_k", "b_v", range(8), dup=4)}
    ordered1, jp1 = planner.reorder_for_chunks(plan, 64, f1)
    assert jp1.order == (0, 1)
    # Drifted: duplication swaps sides — order flips, fingerprint flips.
    f2 = {"//a": _dim(JDA, "a_k", "a_v", range(16), dup=4),
          "//b": _dim(JDB, "b_k", "b_v", range(8))}
    ordered2, jp2 = planner.reorder_for_chunks(plan, 64, f2)
    assert jp2.order == (1, 0)
    assert jp1.token() != jp2.token()
    assert pz.plan_fingerprint(ordered1) != pz.plan_fingerprint(ordered2)
    # Stable stats (fresh chunk objects, same shape of data): the same
    # order, token, and fingerprint — nothing recompiles.
    f3 = {"//a": _dim(JDA, "a_k", "a_v", range(16)),
          "//b": _dim(JDB, "b_k", "b_v", range(8), dup=4)}
    ordered3, jp3 = planner.reorder_for_chunks(plan, 64, f3)
    assert jp3.order == jp1.order and jp3.token() == jp1.token()
    assert pz.plan_fingerprint(ordered3) == pz.plan_fingerprint(ordered1)
    # Decision-neutral drift: the dim's key RANGE moves (pushdown
    # bounds shift) but no decision changes — token identical, so the
    # bounds ride runtime bindings, not the cache key.
    f4 = {"//a": _dim(JDA, "a_k", "a_v", range(16), base=100),
          "//b": _dim(JDB, "b_k", "b_v", range(8), dup=4)}
    _ordered4, jp4 = planner.reorder_for_chunks(plan, 64, f4)
    assert jp4.token() == jp1.token()


def _plan_joins():
    return build_query(JQUERY, JSCHEMAS)


def test_broadcast_flip_changes_token_order_does_not():
    """The side-strategy decision is part of the token: a foreign table
    growing past `broadcast_join_rows` flips broadcast → partition and
    the token (hence every fused-program cache key) must differ."""
    from ytsaurus_tpu.query import planner
    plan = _plan_joins()
    yt_config.set_compile_config(
        yt_config.CompileConfig(broadcast_join_rows=20))
    f_small = {"//a": _dim(JDA, "a_k", "a_v", range(16)),
               "//b": _dim(JDB, "b_k", "b_v", range(8))}
    jp_small = planner.plan_for_chunks(plan, 64, f_small)
    assert [d.strategy for d in jp_small.decisions] == \
        ["broadcast", "broadcast"]
    f_grown = {"//a": _dim(JDA, "a_k", "a_v", range(32)),
               "//b": _dim(JDB, "b_k", "b_v", range(8))}
    jp_grown = planner.plan_for_chunks(plan, 64, f_grown)
    grown_a = [d for d in jp_grown.decisions if d.index == 0][0]
    assert grown_a.strategy == "partition"
    assert jp_small.token() != jp_grown.token()


def test_local_cascade_stable_stats_cache_hit_drift_recompiles():
    """End to end through the local evaluator: repeated queries at
    stable stats grow NO cache entries (100% hit); an order-flipping
    drift compiles fresh programs and still answers correctly."""
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    plan = _plan_joins()
    chunk = _jfact_chunk()
    f1 = {"//a": _dim(JDA, "a_k", "a_v", range(16)),
          "//b": _dim(JDB, "b_k", "b_v", range(8), dup=4)}
    ev = Evaluator()
    want = ev.run_plan(plan, chunk, f1).to_rows()
    size1 = ev.cache_size()
    assert ev.run_plan(plan, chunk, f1).to_rows() == want
    assert ev.cache_size() == size1, \
        "stable stats must serve the cached program"
    # Drift flips the order: new programs (cache grows), right answer
    # (INNER reorder is semantics-preserving — same multiset of rows).
    f2 = {"//a": _dim(JDA, "a_k", "a_v", range(16), dup=4),
          "//b": _dim(JDB, "b_k", "b_v", range(8))}
    got = ev.run_plan(plan, chunk, f2).to_rows()
    assert ev.cache_size() > size1, \
        "an order-flipping drift must not reuse the stale program"
    fresh = Evaluator().run_plan(plan, chunk, f2).to_rows()
    key = lambda r: sorted(tuple(sorted(x.items())) for x in r)  # noqa: E731
    assert key(got) == key(fresh)


def test_distributed_shape_fingerprints(tpu_mesh=None):
    """The SPMD evaluator keys on the shape fingerprint too: same-shape
    plans reuse one cached exchange program (cache size stays flat)."""
    jax = pytest.importorskip("jax")
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        ShardedTable,
    )
    from ytsaurus_tpu.parallel.mesh import make_mesh
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    mesh = make_mesh()
    n = mesh.devices.size
    chunks = [ColumnarChunk.from_rows(
        TableSchema.make([("k", "int64"), ("v", "int64")]),
        [{"k": i * 10 + j, "v": j} for j in range(8)])
        for i in range(n)]
    table = ShardedTable.from_chunks(mesh, chunks)
    ev = DistributedEvaluator(mesh)
    schema = {"//t": chunks[0].schema}
    r1 = ev.run(build_query("k, v FROM [//t] WHERE v < 3", schema),
                table)
    size_after_first = len(ev._cache)
    r2 = ev.run(build_query("k, v FROM [//t] WHERE v < 6", schema),
                table)
    assert len(ev._cache) == size_after_first, \
        "second constant must not grow the SPMD program cache"
    assert {r["v"] for r in r2.to_rows()} == {0, 1, 2, 3, 4, 5}
    assert {r["v"] for r in r1.to_rows()} == {0, 1, 2}


# -- NEAREST shapes (ISSUE 16 satellite): one program per k-bucket -------------

VDIM = 8
VSCHEMA = TableSchema.make(
    [("k", "int64"), ("emb", f"vector<float, {VDIM}>")])


def _vchunk(n=64, seed=0):
    from ytsaurus_tpu.chunks.columnar import ColumnarChunk
    rng = np.random.default_rng(seed)
    return ColumnarChunk.from_rows(VSCHEMA, [
        {"k": i, "emb": [float(x) for x in rng.integers(-5, 6, VDIM)]}
        for i in range(n)])


def _vplan(k, vec, metric="l2"):
    return build_query(
        f"k FROM [//t] NEAREST(emb, ?, {k}, '{metric}')",
        {"//t": VSCHEMA}, params=[list(vec)])


def test_nearest_fingerprint_stable_across_query_vectors():
    """The query vector is a hoisted runtime binding: distinct vectors
    share one plan-shape fingerprint, and k's within one pow2 bucket
    share it too (k rides the LIMIT bucket)."""
    fa = pz.plan_fingerprint(_vplan(7, [1.0] * VDIM))
    fb = pz.plan_fingerprint(_vplan(8, [-3.0, 2.0] * (VDIM // 2)))
    assert fa == fb
    # Bucket edge: k=9 is the 16-bucket — a different program.
    assert fa != pz.plan_fingerprint(_vplan(9, [1.0] * VDIM))
    # Metric changes the distance fn — a different shape.
    assert fa != pz.plan_fingerprint(_vplan(7, [1.0] * VDIM, "dot"))


def test_nearest_compile_once_across_vectors_and_k():
    """ISSUE 16 satellite acceptance: NEAREST over distinct query
    vectors and k in 1..64 compiles ONE program per (table-shape,
    k-bucket) — 7 buckets, not 64x programs."""
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    from ytsaurus_tpu.query.statistics import QueryStatistics
    rng = np.random.default_rng(42)
    chunk = _vchunk(64, seed=1)
    ev = Evaluator()
    stats = QueryStatistics()
    for k in range(1, 65):
        vec = [float(x) for x in rng.integers(-5, 6, VDIM)]
        out = ev.run_plan(_vplan(k, vec), chunk, stats=stats)
        assert len(out.to_rows()) == k
    # k in 1..64 spans buckets {1,2,4,8,16,32,64}: exactly 7 compiles.
    assert stats.compile_count == 7, stats.compile_count
    assert stats.cache_hits == 64 - 7


def test_nearest_spmd_cache_stays_flat(mesh8):
    """Distinct query vectors against the fused SPMD path reuse one
    cached whole-plan program."""
    from ytsaurus_tpu.parallel.distributed import (
        DistributedEvaluator,
        ShardedTable,
    )
    from ytsaurus_tpu.parallel.whole_plan import run_whole_plan
    rng = np.random.default_rng(7)
    chunks = [_vchunk(32 + 8 * s, seed=10 + s) for s in range(8)]
    table = ShardedTable.from_chunks(mesh8, chunks)
    ev = DistributedEvaluator(mesh8)
    run_whole_plan(ev, _vplan(5, [1.0] * VDIM), table)
    fc = ev.fresh_compiles
    for _ in range(3):
        vec = [float(x) for x in rng.integers(-5, 6, VDIM)]
        run_whole_plan(ev, _vplan(5, vec), table)
    assert ev.fresh_compiles == fc, \
        "new query vectors must not fresh-compile the SPMD program"


def test_nearest_aot_restart_zero_fresh_compiles(tmp_path):
    """AOT restart leg: compile a NEAREST shape once, then a FRESH
    evaluator over the same disk cache serves a different query vector
    with zero fresh compiles."""
    from ytsaurus_tpu.query.engine.evaluator import Evaluator
    from ytsaurus_tpu.query.statistics import QueryStatistics
    yt_config.set_compile_config(
        yt_config.CompileConfig(disk_cache_dir=str(tmp_path)))
    chunk = _vchunk(64, seed=2)
    s1 = QueryStatistics()
    Evaluator().run_plan(_vplan(6, [2.0] * VDIM), chunk, stats=s1)
    assert s1.compile_count == 1
    s2 = QueryStatistics()
    out = Evaluator().run_plan(
        _vplan(6, [-1.0, 4.0] * (VDIM // 2)), chunk, stats=s2)
    assert len(out.to_rows()) == 6
    assert s2.compile_disk_hit == 1
    assert s2.compile_count - s2.compile_disk_hit == 0, \
        "restart must serve NEAREST from the AOT tier"
