"""Load-aware replica routing for the serving plane (ISSUE 17).

Ref shape: the reference's replica-aware channel picks peers by
tracked load/health rather than blind hedging (hedging duplicates
work; under overload it DOUBLES the storm).  `ReplicaRouter` scrapes
each serving replica's monitoring `/serving` endpoint — the queue
depth, hold-EWMA, and brown-out rung the admission controller already
exports — and routes every request to the replica with the lowest
estimated drain time, blending in the client-observed latency EWMA.

The router is transport-agnostic: a replica is (name, rpc address,
monitoring address); `pick()` returns the replica to use and
`report()` feeds back what the client actually observed (latency or a
hard error, which quarantines the replica for `penalty_seconds`).
`RoutedYtClient` composes it with one RemoteYtClient per replica.

Failpoint site `serving.route_scrape` fires per scrape attempt: error
mode simulates an unreachable monitoring endpoint (the routing-scrape
timeout chaos leg), which must degrade the router to its last-known
loads, never fail a query.

Sensors (`/serving/routing/*`): scrapes, scrape_errors, picks{replica=},
failovers.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Optional, Sequence

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.utils import failpoints
from ytsaurus_tpu.utils.logging import get_logger
from ytsaurus_tpu.utils.profiling import Profiler
from ytsaurus_tpu.utils import sanitizers

logger = get_logger("ReplicaRouter")

_FP_ROUTE_SCRAPE = failpoints.register_site(
    "serving.route_scrape",
    error=lambda s: YtError(f"injected routing scrape failure at {s}",
                            code=EErrorCode.TransportError))

# A replica whose scrape failed scores as if this many seconds of
# backlog were queued — routed to only when every peer looks worse.
_UNKNOWN_PENALTY = 30.0


class Replica:
    """One serving replica: identity plus the router's live view."""

    __slots__ = ("name", "address", "monitor_address", "queue_depth",
                 "in_flight", "hold_ewma", "rung", "latency_ewma",
                 "pools", "pool_latency", "scraped_at", "scrape_ok",
                 "penalized_until", "picks_n", "errors_n")

    def __init__(self, name: str, address: str, monitor_address: str):
        self.name = name
        self.address = address
        self.monitor_address = monitor_address
        self.queue_depth = 0
        self.in_flight = 0
        self.hold_ewma = 0.05
        self.rung = 0
        self.latency_ewma = 0.0
        # Per-pool scraped view: pool -> (waiting, in_flight,
        # fair_slots) — fair-share admission means MY wait prospects
        # depend on MY pool's backlog against MY pool's slots, not on
        # how deep some other tenant's queue happens to be.
        self.pools: dict = {}
        self.pool_latency: dict = {}       # pool -> latency EWMA
        self.scraped_at: Optional[float] = None
        self.scrape_ok = False
        self.penalized_until = 0.0
        self.picks_n = 0
        self.errors_n = 0

    def view(self) -> dict:
        return {"name": self.name, "address": self.address,
                "monitor_address": self.monitor_address,
                "queue_depth": self.queue_depth,
                "in_flight": self.in_flight,
                "hold_ewma": round(self.hold_ewma, 6),
                "rung": self.rung,
                "latency_ewma": round(self.latency_ewma, 6),
                "pools": {name: {"waiting": w, "in_flight": f,
                                 "fair_slots": round(s, 2)}
                          for name, (w, f, s) in self.pools.items()},
                "scrape_ok": self.scrape_ok,
                "picks": self.picks_n,
                "errors": self.errors_n}


class ReplicaRouter:
    """Routes requests to the least-loaded serving replica by REPORTED
    load (scraped from `/serving`), not by blind hedging."""

    def __init__(self, replicas: Sequence[tuple],
                 scrape_period: float = 0.5,
                 scrape_timeout: float = 1.0,
                 penalty_seconds: float = 2.0,
                 latency_alpha: float = 0.3):
        # guards: _replicas, _rr
        self._lock = sanitizers.register_lock(
            "routing.ReplicaRouter._lock", hot=False)
        self._replicas: list[Replica] = []
        for spec in replicas:
            name, address, monitor = self._spec(spec)
            self._replicas.append(Replica(name, address, monitor))
        self.scrape_period = scrape_period
        self.scrape_timeout = scrape_timeout
        self.penalty_seconds = penalty_seconds
        self.latency_alpha = latency_alpha
        self._rr = 0                      # tie-break rotation
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        prof = Profiler("/serving/routing")
        self._prof = prof
        self._scrapes = prof.counter("scrapes")
        self._scrape_errors = prof.counter("scrape_errors")
        self._failovers = prof.counter("failovers")
        self.scrapes_n = 0
        self.scrape_errors_n = 0
        self.failovers_n = 0

    @staticmethod
    def _spec(spec) -> tuple:
        if isinstance(spec, Replica):
            return spec.name, spec.address, spec.monitor_address
        if len(spec) == 2:
            address, monitor = spec
            return address, address, monitor
        return tuple(spec)

    # -- membership ------------------------------------------------------------

    def add_replica(self, spec) -> Replica:
        """Register a replica joining live (the mid-storm scale-out
        arm); it starts un-scraped and picks up load on the next
        scrape."""
        name, address, monitor = self._spec(spec)
        replica = Replica(name, address, monitor)
        with self._lock:
            self._replicas.append(replica)
        return replica

    def remove_replica(self, name: str) -> None:
        with self._lock:
            self._replicas = [r for r in self._replicas
                              if r.name != name]

    def replicas(self) -> list[Replica]:
        with self._lock:
            return list(self._replicas)

    # -- scraping --------------------------------------------------------------

    def scrape_once(self) -> int:
        """Scrape every replica's /serving; returns how many succeeded.
        A failed scrape marks the replica UNKNOWN (penalized in scoring)
        but never raises — routing degrades to last-known loads."""
        ok = 0
        for replica in self.replicas():
            try:
                _FP_ROUTE_SCRAPE.hit()
                with urllib.request.urlopen(
                        f"http://{replica.monitor_address}/serving",
                        timeout=self.scrape_timeout) as resp:
                    payload = json.loads(resp.read().decode())
                self._absorb(replica, payload)
                ok += 1
            except Exception as exc:   # noqa: BLE001 — the scrape is
                # best-effort: an unreachable monitoring endpoint must
                # degrade routing, never fail it.
                with self._lock:
                    replica.scrape_ok = False
                    replica.scraped_at = time.monotonic()
                self.scrape_errors_n += 1
                self._scrape_errors.increment()
                logger.debug("scrape of %s failed: %r",
                             replica.monitor_address, exc)
        self.scrapes_n += 1
        self._scrapes.increment()
        return ok

    def _absorb(self, replica: Replica, payload: dict) -> None:
        queue = in_flight = rung = 0
        hold = 0.05
        pools: dict = {}
        for gw in payload.get("gateways", []):
            admission = gw.get("admission") or {}
            hold = max(hold, float(admission.get("hold_ewma", 0.05)))
            rung = max(rung, int((admission.get("brownout") or {})
                                 .get("rung", 0)))
            for name, pool in (admission.get("pools") or
                               gw.get("pools") or {}).items():
                w = int(pool.get("waiting", 0))
                f = int(pool.get("in_flight", 0))
                s = float(pool.get("fair_slots", 0.0))
                queue += w
                in_flight += f
                pw, pf, ps = pools.get(name, (0, 0, 0.0))
                pools[name] = (pw + w, pf + f, ps + s)
        with self._lock:
            replica.queue_depth = queue
            replica.in_flight = in_flight
            replica.hold_ewma = hold
            replica.rung = rung
            replica.pools = pools
            replica.scrape_ok = True
            replica.scraped_at = time.monotonic()

    def start(self) -> "ReplicaRouter":
        self.scrape_once()                 # seed before first pick
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="replica-router")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.scrape_period):
            self.scrape_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- routing ---------------------------------------------------------------

    def _score(self, replica: Replica, now: float,
               pool: Optional[str] = None) -> float:
        """Estimated seconds until this replica would serve a new
        request: its backlog drain estimate plus the client-observed
        latency EWMA, with brown-out rungs and quarantine as explicit
        step penalties (a rung-2 replica is actively shedding — route
        around it while ANY alternative exists).

        Pool-aware (fair-share-aware) when the request names a pool the
        scrape knows: under fair-share admission this request's wait
        prospects are ITS pool's backlog against ITS pool's fair slots.
        Scoring by the global queue would let one greedy tenant's
        thousand-deep backlog blind the router for every OTHER tenant —
        both replicas look identically terrible, picks degrade to
        round-robin, and the innocent pool's p99 pays for the collisions.
        The latency EWMA is per-pool for the same reason: the greedy
        pool's multi-second queue waits must not poison the estimate
        for a pool that is not queued at all."""
        if now < replica.penalized_until:
            return _UNKNOWN_PENALTY * 10.0
        stats = replica.pools.get(pool) if pool else None
        if stats is not None:
            waiting, in_flight, fair_slots = stats
            backlog = (waiting + in_flight) * replica.hold_ewma / \
                max(fair_slots, 1.0)
            latency = replica.pool_latency.get(pool, 0.0)
        else:
            backlog = (replica.queue_depth + replica.in_flight) * \
                replica.hold_ewma
            latency = replica.latency_ewma
        if not replica.scrape_ok:
            backlog += _UNKNOWN_PENALTY
        return backlog + latency + replica.rung * _UNKNOWN_PENALTY

    def pick(self, pool: Optional[str] = None) -> Replica:
        now = time.monotonic()
        with self._lock:
            if not self._replicas:
                raise YtError("ReplicaRouter has no replicas",
                              code=EErrorCode.PeerUnavailable)
            self._rr += 1
            candidates = self._replicas[self._rr % len(self._replicas):] \
                + self._replicas[:self._rr % len(self._replicas)]
            best = min(candidates,
                       key=lambda r: self._score(r, now, pool))
            best.picks_n += 1
        self._prof.with_tags(replica=best.name).counter(
            "picks").increment()
        return best

    def report(self, replica: Replica, latency: Optional[float] = None,
               error: bool = False,
               pool: Optional[str] = None) -> None:
        """Client-observed outcome feedback: latency folds into the
        replica's EWMA (the named pool's, when given); a hard error
        quarantines it for `penalty_seconds` (the next picks fail over)
        until a successful scrape or call clears the view."""
        with self._lock:
            if error:
                replica.errors_n += 1
                replica.penalized_until = time.monotonic() + \
                    self.penalty_seconds
                self.failovers_n += 1
            else:
                replica.penalized_until = 0.0
                if latency is not None:
                    prev = replica.latency_ewma
                    replica.latency_ewma = prev + self.latency_alpha * \
                        (latency - prev)
                    if pool is not None:
                        prev = replica.pool_latency.get(pool, 0.0)
                        replica.pool_latency[pool] = prev + \
                            self.latency_alpha * (latency - prev)
        if error:
            self._failovers.increment()

    def snapshot(self) -> dict:
        with self._lock:
            return {"replicas": [r.view() for r in self._replicas],
                    "scrapes": self.scrapes_n,
                    "scrape_errors": self.scrape_errors_n,
                    "failovers": self.failovers_n}


class RoutedYtClient:
    """A thin multi-replica facade: every read routes through the
    ReplicaRouter to the least-loaded replica's client; a hard
    transport failure reports the replica (quarantine) and fails over
    to the next pick, ONCE — the per-replica channels already retry
    transport blips, and unbounded failover is its own storm."""

    def __init__(self, router: ReplicaRouter, clients: dict):
        self.router = router
        self._clients = dict(clients)      # replica name -> client

    def add_replica(self, spec, client) -> None:
        replica = self.router.add_replica(spec)
        self._clients[replica.name] = client

    def _call(self, method: str, *args, **kwargs):
        last_err = None
        pool = kwargs.get("pool")
        for _attempt in range(2):
            replica = self.router.pick(pool=pool)
            client = self._clients[replica.name]
            t0 = time.monotonic()
            try:
                out = getattr(client, method)(*args, **kwargs)
            except YtError as err:
                if err.code in (EErrorCode.TransportError,
                                EErrorCode.RpcTimeout,
                                EErrorCode.PeerUnavailable):
                    self.router.report(replica, error=True)
                    last_err = err
                    continue
                raise
            self.router.report(replica,
                               latency=time.monotonic() - t0,
                               pool=pool)
            return out
        raise last_err

    def lookup_rows(self, *args, **kwargs):
        return self._call("lookup_rows", *args, **kwargs)

    def select_rows(self, *args, **kwargs):
        return self._call("select_rows", *args, **kwargs)

    def nearest_rows(self, *args, **kwargs):
        return self._call("nearest_rows", *args, **kwargs)

    def snapshot(self) -> dict:
        return self.router.snapshot()
