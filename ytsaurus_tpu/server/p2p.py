"""P2P hot-chunk distribution on data nodes.

Ref: server/node/data_node/p2p.h:227 (TP2PDistributor) — a data node
holding a chunk that suddenly gets hammered (a hot dictionary table,
a fan-in join side) temporarily seeds copies onto peer nodes, so read
load spreads instead of saturating the RF holders.

Redesign for this runtime: the unit is the whole chunk (our reads are
chunk-granular decodes, not block fetches).  Each node counts reads per
chunk over a sliding window; past the hot threshold it pushes the chunk
to `fanout` peers that do not already hold it (the same node-to-node
path the replicator's repair jobs use), records what it seeded, and
evicts its seeds after a cool-down with no continued heat.  Seeded
copies are ordinary store chunks: the client's fallback/hedged read
paths find them with no protocol change, and if an eviction ever races
a replicator placement the next replicator scan restores RF — the
healing loop bounds the damage.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Sequence

from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.utils.logging import get_logger

logger = get_logger("p2p")


class P2PDistributor:
    def __init__(self, store, self_address_provider: Callable[[], str],
                 peers_provider: Callable[[], "Sequence[str]"],
                 hot_threshold: int = 20, window: float = 5.0,
                 fanout: int = 2, cooldown: float = 60.0,
                 tick: float = 1.0):
        self.store = store
        self._self_address = self_address_provider
        self._peers = peers_provider
        self.hot_threshold = hot_threshold
        self.window = window
        self.fanout = fanout
        self.cooldown = cooldown
        self.tick = tick
        self.stats = {"hot_chunks": 0, "seeded_copies": 0,
                      "evicted_copies": 0}
        self._lock = threading.Lock()
        self._counts: "dict[str, int]" = {}
        self._window_start = time.monotonic()
        # chunk_id → {"targets": [addr...], "expiry": t} for seeds WE
        # pushed (pre-existing holders are never evicted by us).
        self._seeded: "dict[str, dict]" = {}
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    # -- read accounting (called from the get_chunk RPC) -----------------------

    def _expire_window_locked(self) -> None:
        now = time.monotonic()
        if now - self._window_start > self.window:
            self._counts.clear()
            self._window_start = now

    def record_read(self, chunk_id: str) -> None:
        with self._lock:
            self._expire_window_locked()
            self._counts[chunk_id] = self._counts.get(chunk_id, 0) + 1

    # -- lifecycle -------------------------------------------------------------

    def start(self) -> "P2PDistributor":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="p2p-distributor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.tick):
            try:
                self.tick_once()
            except Exception:   # noqa: BLE001 — distribution is advisory
                logger.exception("p2p tick failed")

    # -- distribution ----------------------------------------------------------

    def _call(self, address: str, method: str, body: dict,
              attachments=()):
        from ytsaurus_tpu.rpc import Channel
        channel = Channel(address, timeout=15)
        try:
            out, _ = channel.call("data_node", method, body,
                                  attachments=attachments)
            return out
        finally:
            channel.close()

    def tick_once(self) -> None:
        with self._lock:
            # The tick expires the window too: if reads stop entirely,
            # record_read never runs again and stale counts would keep
            # "reheating" the seeds forever.
            self._expire_window_locked()
            hot = [cid for cid, n in self._counts.items()
                   if n >= self.hot_threshold
                   and cid not in self._seeded]
            reheated = {cid for cid, n in self._counts.items()
                        if n >= self.hot_threshold}
            now = time.monotonic()
            expired = [cid for cid, entry in self._seeded.items()
                       if now >= entry["expiry"] and cid not in reheated]
            # Continued heat extends the seeds' lease.
            for cid in reheated:
                if cid in self._seeded:
                    self._seeded[cid]["expiry"] = now + self.cooldown
        peers = None
        if hot:
            # ONE peer-discovery RPC per tick, shared by every hot
            # chunk (not one per chunk per tick).
            me = self._self_address()
            peers = [p for p in self._peers() if p and p != me]
        for cid in hot:
            self._seed(cid, peers)
        for cid in expired:
            self._evict(cid)

    def _seed(self, chunk_id: str, peers: "Sequence[str]") -> None:
        from ytsaurus_tpu.server.services import chunk_push_request
        if not self.store.exists(chunk_id):
            return
        targets = []
        body = None
        blob = None
        failures = 0
        for peer in peers:
            if len(targets) >= self.fanout:
                break
            try:
                if self._call(peer, "has_chunk",
                              {"chunk_id": chunk_id}).get("exists"):
                    continue        # a real holder: never ours to evict
                if blob is None:
                    # One read (erasure chunks RECONSTRUCT on read)
                    # serves every fanout target.
                    body, blob = chunk_push_request(self.store, chunk_id)
                self._call(peer, "put_chunk", body, attachments=[blob])
                targets.append(peer)
            except YtError as exc:
                failures += 1
                logger.warning("p2p seed of %s to %s failed: %s",
                               chunk_id, peer, exc)
        if not targets and failures:
            # Every attempt errored (blip, peer restart): do NOT record
            # — the next tick must retry while the chunk stays hot.
            return
        # An empty-but-clean result IS recorded: every eligible peer
        # already holds the chunk, and re-probing the whole fan-out on
        # every tick while the heat lasts would be pure RPC churn.
        with self._lock:
            self._seeded[chunk_id] = {
                "targets": targets,
                "expiry": time.monotonic() + self.cooldown}
        if targets:
            self.stats["hot_chunks"] += 1
            self.stats["seeded_copies"] += len(targets)
            logger.info("p2p: seeded hot chunk %s to %s", chunk_id,
                        targets)

    def _evict(self, chunk_id: str) -> None:
        with self._lock:
            entry = self._seeded.get(chunk_id)
        if entry is None:
            return
        remaining = []
        for peer in entry["targets"]:
            try:
                self._call(peer, "remove_chunk", {"chunk_id": chunk_id})
                self.stats["evicted_copies"] += 1
            except YtError:
                # Transient failure must NOT leak the copy forever:
                # keep the target and retry on a later tick.
                remaining.append(peer)
        with self._lock:
            attempts = entry.get("evict_attempts", 0) + 1
            if remaining and attempts < 5:
                entry["targets"] = remaining
                entry["evict_attempts"] = attempts
                entry["expiry"] = time.monotonic() + \
                    min(self.cooldown, 10.0)
            else:
                # All removed, or the peer is presumed dead (its disk
                # went with it — nothing left to evict).
                self._seeded.pop(chunk_id, None)
