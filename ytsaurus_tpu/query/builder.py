"""AST → typed plan IR: reference resolution, type inference, aggregate
extraction, CASE/LIKE desugaring.

Analog of the reference's expression builders + PreparePlanFragment
(library/query/base/expr_builder_v2.cpp, query_preparer.cpp).
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.query import ast
from ytsaurus_tpu.query import ir
from ytsaurus_tpu.query.functions import (
    AGGREGATE_FUNCTIONS,
    SCALAR_FUNCTIONS,
    TWO_ARG_AGGREGATES,
    WINDOW_FUNCTIONS,
    is_aggregate,
    is_numeric,
    promote_numeric,
    unify,
)
from ytsaurus_tpu.query.parser import parse_query
from ytsaurus_tpu.schema import EValueType, TableSchema, VectorType

_COMPARISONS = ("=", "!=", "<", "<=", ">", ">=")
_LOGICAL = ("and", "or")
_ARITH = ("+", "-", "*", "/", "%")
_BITWISE = ("|", "&", "^", "<<", ">>")


def render_expr(e: ast.Expr) -> str:
    """Stable source-ish rendering, used to name unaliased items (ref:
    InferName in base/query_preparer.cpp)."""
    if isinstance(e, ast.Literal):
        return repr(e.value)
    if isinstance(e, ast.Reference):
        return f"{e.table}.{e.name}" if e.table else e.name
    if isinstance(e, ast.FunctionCall):
        return f"{e.name}({', '.join(render_expr(a) for a in e.args)})"
    if isinstance(e, ast.UnaryOp):
        return f"{e.op}({render_expr(e.operand)})"
    if isinstance(e, ast.BinaryOp):
        return f"({render_expr(e.lhs)} {e.op} {render_expr(e.rhs)})"
    if isinstance(e, ast.InExpr):
        return f"({', '.join(render_expr(o) for o in e.operands)}) in {e.values!r}"
    if isinstance(e, ast.BetweenExpr):
        return f"({', '.join(render_expr(o) for o in e.operands)}) between {e.ranges!r}"
    if isinstance(e, ast.TransformExpr):
        return f"transform({', '.join(render_expr(o) for o in e.operands)})"
    if isinstance(e, ast.CaseExpr):
        return "case(...)"
    if isinstance(e, ast.LikeExpr):
        return f"{render_expr(e.text)} like {render_expr(e.pattern)}"
    if isinstance(e, ast.WindowExpr):
        return (f"{e.function}({', '.join(render_expr(a) for a in e.args)})"
                " over (...)")
    if isinstance(e, ast.Placeholder):
        return "?"
    return "expr"


def _literal_type(value, is_uint=False) -> "EValueType | VectorType":
    if value is None:
        return EValueType.null
    if isinstance(value, bool):
        return EValueType.boolean
    if isinstance(value, int):
        if is_uint:
            return EValueType.uint64
        return EValueType.int64 if -(2**63) <= value < 2**63 else EValueType.uint64
    if isinstance(value, float):
        return EValueType.double
    if isinstance(value, (str, bytes)):
        return EValueType.string
    if isinstance(value, (list, tuple)) and value and all(
            isinstance(x, (int, float)) and not isinstance(x, bool)
            for x in value):
        # A flat number sequence is a vector literal (the NEAREST query
        # vector arriving through a `?` param).
        return VectorType(len(value))
    raise YtError(f"Unsupported literal {value!r}", code=EErrorCode.QueryTypeError)


def _as_bytes(v):
    return v.encode("utf-8") if isinstance(v, str) else v


class _ExprBuilder:
    """Types expressions against a flat name→type namespace."""

    def __init__(self, namespace: Mapping[str, EValueType],
                 alias_map: Mapping[str, str] | None = None,
                 allow_aggregates: bool = False):
        # Shared (not copied): joins extend the namespace after this builder
        # is constructed and must stay visible.
        self.namespace = namespace if isinstance(namespace, dict) \
            else dict(namespace)
        self.alias_map = alias_map if isinstance(alias_map, dict) \
            else dict(alias_map or {})
        self.allow_aggregates = allow_aggregates

    def resolve_reference(self, ref: ast.Reference) -> str:
        if ref.table is not None:
            qualified = f"{ref.table}.{ref.name}"
            if qualified in self.alias_map:
                return self.alias_map[qualified]
            if qualified in self.namespace:
                return qualified
            raise YtError(f"Undefined reference {qualified!r}",
                          code=EErrorCode.QueryTypeError)
        if ref.name in self.namespace:
            return ref.name
        if ref.name in self.alias_map:
            return self.alias_map[ref.name]
        raise YtError(f"Undefined reference {ref.name!r}",
                      code=EErrorCode.QueryTypeError)

    def build(self, e: ast.Expr) -> ir.TExpr:
        if isinstance(e, ast.Literal):
            ty = _literal_type(e.value, e.is_uint)
            if isinstance(ty, VectorType):
                value = tuple(float(x) for x in e.value)
                if not all(v == v and abs(v) != float("inf") for v in value):
                    raise YtError("Non-finite component in vector literal",
                                  code=EErrorCode.QueryTypeError)
                return ir.TLiteral(type=ty, value=value)
            value = _as_bytes(e.value) if ty is EValueType.string else e.value
            return ir.TLiteral(type=ty, value=value)

        if isinstance(e, ast.Placeholder):
            raise YtError(
                f"Unbound placeholder ?{e.index}: pass `params` to "
                "select_rows/build_query", code=EErrorCode.QueryTypeError)

        if isinstance(e, ast.Reference):
            name = self.resolve_reference(e)
            return ir.TReference(type=self.namespace[name], name=name)

        if isinstance(e, ast.UnaryOp):
            operand = self.build(e.operand)
            if e.op == "not":
                if operand.type not in (EValueType.boolean, EValueType.null):
                    raise YtError("NOT requires a boolean operand",
                                  code=EErrorCode.QueryTypeError)
                return ir.TUnary(type=EValueType.boolean, op="not", operand=operand)
            if e.op == "-":
                if not is_numeric(operand.type) and operand.type is not EValueType.null:
                    raise YtError("Unary minus requires a numeric operand",
                                  code=EErrorCode.QueryTypeError)
                return ir.TUnary(type=operand.type, op="-", operand=operand)
            if e.op == "~":
                if operand.type not in (EValueType.int64, EValueType.uint64,
                                        EValueType.null):
                    raise YtError("Bitwise NOT requires an integer operand",
                                  code=EErrorCode.QueryTypeError)
                return ir.TUnary(type=operand.type, op="~", operand=operand)
            raise YtError(f"Unknown unary operator {e.op!r}")

        if isinstance(e, ast.BinaryOp):
            lhs, rhs = self.build(e.lhs), self.build(e.rhs)
            op = e.op
            if op in _LOGICAL:
                for side in (lhs, rhs):
                    if side.type not in (EValueType.boolean, EValueType.null):
                        raise YtError(f"{op.upper()} requires boolean operands",
                                      code=EErrorCode.QueryTypeError)
                return ir.TBinary(type=EValueType.boolean, op=op, lhs=lhs, rhs=rhs)
            if op in _COMPARISONS:
                if isinstance(lhs.type, VectorType) or \
                        isinstance(rhs.type, VectorType):
                    raise YtError(
                        f"Vectors are not comparable with {op!r}; use a "
                        "distance function (l2_distance/cosine_distance/"
                        "dot_product)", code=EErrorCode.QueryUnsupported)
                unify(lhs.type, rhs.type, f"comparison {op!r}")
                return ir.TBinary(type=EValueType.boolean, op=op, lhs=lhs, rhs=rhs)
            if op in _ARITH:
                ty = promote_numeric(lhs.type, rhs.type, f"operator {op!r}")
                return ir.TBinary(type=ty, op=op, lhs=lhs, rhs=rhs)
            if op in _BITWISE:
                for side in (lhs, rhs):
                    if side.type not in (EValueType.int64, EValueType.uint64,
                                        EValueType.null):
                        raise YtError(f"Operator {op!r} requires integer operands",
                                      code=EErrorCode.QueryTypeError)
                ty = promote_numeric(lhs.type, rhs.type, f"operator {op!r}")
                return ir.TBinary(type=ty, op=op, lhs=lhs, rhs=rhs)
            raise YtError(f"Unknown operator {op!r}")

        if isinstance(e, ast.FunctionCall):
            if is_aggregate(e.name):
                raise YtError(
                    f"Aggregate function {e.name!r} is not allowed here",
                    code=EErrorCode.QueryTypeError)
            return self.build_scalar_call(e)

        if isinstance(e, ast.InExpr):
            operands = tuple(self.build(o) for o in e.operands)
            self._check_tuples(operands, e.values, "IN")
            values = tuple(tuple(_as_bytes(v) for v in tup) for tup in e.values)
            return ir.TIn(type=EValueType.boolean, operands=operands, values=values)

        if isinstance(e, ast.BetweenExpr):
            operands = tuple(self.build(o) for o in e.operands)
            for lower, upper in e.ranges:
                self._check_tuples(operands, [lower, upper], "BETWEEN",
                                   allow_prefix=True)
            ranges = tuple(
                (tuple(_as_bytes(v) for v in lo), tuple(_as_bytes(v) for v in up))
                for lo, up in e.ranges)
            return ir.TBetween(type=EValueType.boolean, operands=operands,
                               ranges=ranges, negated=e.negated)

        if isinstance(e, ast.TransformExpr):
            operands = tuple(self.build(o) for o in e.operands)
            self._check_tuples(operands, e.from_values, "TRANSFORM")
            default = self.build(e.default) if e.default is not None else None
            to_types = {_literal_type(v) for v in e.to_values if v is not None}
            ty = EValueType.null
            for t in to_types:
                ty = unify(ty, t, "TRANSFORM values")
            if default is not None:
                ty = unify(ty, default.type, "TRANSFORM default")
            to_values = tuple(
                _as_bytes(v) if isinstance(v, (str, bytes)) else v
                for v in e.to_values)
            return ir.TTransform(
                type=ty, operands=operands,
                from_values=tuple(tuple(_as_bytes(v) for v in tup)
                                  for tup in e.from_values),
                to_values=to_values, default=default)

        if isinstance(e, ast.CaseExpr):
            return self.build(_desugar_case(e))

        if isinstance(e, ast.WindowExpr):
            raise YtError(
                "Window functions are only allowed in the SELECT list "
                "of a non-grouped query",
                code=EErrorCode.QueryTypeError)

        if isinstance(e, ast.LikeExpr):
            text = self.build(e.text)
            if text.type not in (EValueType.string, EValueType.null):
                raise YtError("LIKE requires a string operand",
                              code=EErrorCode.QueryTypeError)
            if not isinstance(e.pattern, ast.Literal) or \
                    _literal_type(e.pattern.value) is not EValueType.string:
                raise YtError("LIKE pattern must be a string literal",
                              code=EErrorCode.QueryUnsupported)
            pattern = _as_bytes(e.pattern.value)
            if e.escape is not None:
                raise YtError("LIKE ESCAPE is not supported yet",
                              code=EErrorCode.QueryUnsupported)
            return ir.TStringPredicate(
                type=EValueType.boolean, operand=text, kind="like",
                pattern=pattern, case_insensitive=e.case_insensitive,
                negated=e.negated)

        raise YtError(f"Cannot build expression from {type(e).__name__}")

    def build_scalar_call(self, e: ast.FunctionCall) -> ir.TExpr:
        # String predicates get vocabulary-level nodes.
        if e.name in ("is_prefix", "is_substr") and len(e.args) == 2 and \
                isinstance(e.args[0], ast.Literal):
            operand = self.build(e.args[1])
            if operand.type not in (EValueType.string, EValueType.null):
                raise YtError(f"{e.name} requires a string operand",
                              code=EErrorCode.QueryTypeError)
            kind = "prefix" if e.name == "is_prefix" else "substr"
            return ir.TStringPredicate(
                type=EValueType.boolean, operand=operand, kind=kind,
                pattern=_as_bytes(e.args[0].value))
        if e.name == "regex_full_match" and len(e.args) == 2 and \
                isinstance(e.args[0], ast.Literal):
            operand = self.build(e.args[1])
            return ir.TStringPredicate(
                type=EValueType.boolean, operand=operand, kind="regex",
                pattern=_as_bytes(e.args[0].value))
        fn = SCALAR_FUNCTIONS.get(e.name)
        if fn is None:
            raise YtError(f"Unknown function {e.name!r}",
                          code=EErrorCode.QueryTypeError)
        if not (fn.min_args <= len(e.args) <= fn.max_args):
            raise YtError(
                f"Function {e.name!r} expects {fn.min_args}"
                + (f"..{fn.max_args}" if fn.max_args != fn.min_args else "")
                + f" arguments, got {len(e.args)}",
                code=EErrorCode.QueryTypeError)
        args = tuple(self.build(a) for a in e.args)
        result = fn.infer(tuple(a.type for a in args))
        return ir.TFunction(type=result, name=e.name, args=args)

    def _check_tuples(self, operands, tuples, context, allow_prefix=False):
        for operand in operands:
            if isinstance(operand.type, VectorType):
                raise YtError(f"{context} does not accept vector operands",
                              code=EErrorCode.QueryUnsupported)
        for tup in tuples:
            if allow_prefix:
                if len(tup) > len(operands):
                    raise YtError(f"{context} tuple wider than operand list",
                                  code=EErrorCode.QueryTypeError)
            elif len(tup) != len(operands):
                raise YtError(f"{context} tuple arity mismatch",
                              code=EErrorCode.QueryTypeError)
            for operand, v in zip(operands, tup):
                unify(operand.type, _literal_type(v), context)


def _desugar_case(e: ast.CaseExpr) -> ast.Expr:
    """CASE → nested if(); ref does the same in expr builders."""
    result: ast.Expr = e.default if e.default is not None else ast.Literal(None)
    for cond, value in reversed(e.when_then):
        if e.operand is not None:
            cond = ast.BinaryOp("=", e.operand, cond)
        result = ast.FunctionCall("if", (cond, value, result))
    return result


class _AggregatingBuilder(_ExprBuilder):
    """Builds post-GROUP-BY expressions: group-item subtrees become references,
    aggregate calls become AggregateItem slots (evaluated in the base
    namespace), everything else must type-check in the post-group namespace."""

    def __init__(self, base_builder: _ExprBuilder,
                 group_exprs: dict[ast.Expr, str],
                 group_namespace: Mapping[str, EValueType]):
        super().__init__(group_namespace, alias_map={})
        self.base_builder = base_builder
        self.group_exprs = group_exprs  # AST expr -> group item name
        self.aggregates: list[ir.AggregateItem] = []
        self._agg_cache: dict[tuple, str] = {}

    def build(self, e: ast.Expr) -> ir.TExpr:
        name = self.group_exprs.get(e)
        if name is not None:
            return ir.TReference(type=self.namespace[name], name=name)
        if isinstance(e, ast.FunctionCall) and is_aggregate(e.name):
            return self.build_aggregate(e)
        if isinstance(e, ast.Reference):
            # A bare column must be a group key (possibly under its alias).
            resolved = self.namespace.get(e.name)
            if resolved is None:
                raise YtError(
                    f"Column {render_expr(e)!r} is neither aggregated nor in "
                    f"GROUP BY", code=EErrorCode.QueryTypeError)
            return ir.TReference(type=resolved, name=e.name)
        if isinstance(e, ast.CaseExpr):
            return self.build(_desugar_case(e))
        if isinstance(e, (ast.Literal,)):
            return super().build(e)
        if isinstance(e, ast.UnaryOp):
            return super().build(e)
        if isinstance(e, ast.BinaryOp):
            return super().build(e)
        if isinstance(e, ast.FunctionCall):
            return super().build(e)
        if isinstance(e, (ast.InExpr, ast.BetweenExpr, ast.TransformExpr,
                          ast.LikeExpr)):
            return super().build(e)
        raise YtError(f"Cannot build post-group expression {render_expr(e)!r}")

    def build_aggregate(self, e: ast.FunctionCall) -> ir.TExpr:
        fn = AGGREGATE_FUNCTIONS[e.name]
        two_arg = e.name in TWO_ARG_AGGREGATES
        expected = 2 if two_arg else 1
        if len(e.args) != expected:
            raise YtError(
                f"Aggregate {e.name!r} expects exactly {expected} argument(s)",
                code=EErrorCode.QueryTypeError)
        argument = self.base_builder.build(e.args[0])
        by_argument = None
        if two_arg:
            by_argument = self.base_builder.build(e.args[1])
            if not by_argument.type.is_comparable:
                raise YtError(f"{e.name} comparison key must be comparable",
                              code=EErrorCode.QueryTypeError)
        key = (e.name, ir._repr_expr(argument),
               ir._repr_expr(by_argument) if by_argument else "")
        slot = self._agg_cache.get(key)
        if slot is None:
            slot = f"_agg{len(self.aggregates)}"
            self.aggregates.append(ir.AggregateItem(
                name=slot, function=e.name, argument=argument,
                type=fn.infer_result(argument.type),
                state_type=fn.infer_state(argument.type),
                by_argument=by_argument))
            self._agg_cache[key] = slot
            self.namespace[slot] = self.aggregates[-1].type
        return ir.TReference(type=self.namespace[slot], name=slot)


def _normalize_frame(frame: "tuple[ast.FrameBound, ast.FrameBound]"
                     ) -> ir.Frame:
    """ROWS BETWEEN bounds → the signed-offset Frame tuple."""
    lower, upper = frame

    def conv(bound: ast.FrameBound, is_start: bool) -> tuple[str, int]:
        if bound.kind == "unbounded_preceding":
            if not is_start:
                raise YtError("Frame end cannot be UNBOUNDED PRECEDING",
                              code=EErrorCode.QueryParseError)
            return ("unbounded", 0)
        if bound.kind == "unbounded_following":
            if is_start:
                raise YtError("Frame start cannot be UNBOUNDED FOLLOWING",
                              code=EErrorCode.QueryParseError)
            return ("unbounded", 0)
        if bound.kind == "current_row":
            return ("offset", 0)
        if bound.kind == "preceding":
            return ("offset", -int(bound.offset))
        if bound.kind == "following":
            return ("offset", int(bound.offset))
        raise YtError(f"Unknown frame bound {bound.kind!r}")

    lo_kind, lo_off = conv(lower, True)
    hi_kind, hi_off = conv(upper, False)
    if lo_kind == "offset" and hi_kind == "offset" and lo_off > hi_off:
        raise YtError("Frame start must not follow frame end",
                      code=EErrorCode.QueryParseError)
    return (lo_kind, lo_off, hi_kind, hi_off)


class _WindowBuilder(_ExprBuilder):
    """Builds SELECT/ORDER expressions of a non-grouped query, turning
    window calls into WindowItem slots (the analog of how
    _AggregatingBuilder extracts AggregateItems).  All window calls in a
    query must share one (PARTITION BY, ORDER BY) spec — one sort serves
    every item; per-item ROWS frames may differ."""

    def __init__(self, base_builder: _ExprBuilder):
        super().__init__(base_builder.namespace, base_builder.alias_map)
        self.base_builder = base_builder
        self.partition: "Optional[tuple[ast.Expr, ...]]" = None
        self.order: "Optional[tuple[ast.OrderItem, ...]]" = None
        self.items: list[ir.WindowItem] = []
        self._cache: dict[tuple, str] = {}

    def build(self, e: ast.Expr) -> ir.TExpr:
        if isinstance(e, ast.WindowExpr):
            return self.build_window(e)
        if isinstance(e, ast.CaseExpr):
            return self.build(_desugar_case(e))
        return super().build(e)

    def build_window(self, e: ast.WindowExpr) -> ir.TExpr:
        fn = WINDOW_FUNCTIONS.get(e.function)
        if fn is None:
            raise YtError(f"Unknown window function {e.function!r}",
                          code=EErrorCode.QueryTypeError)
        if not (fn.min_args <= len(e.args) <= fn.max_args):
            raise YtError(
                f"Window function {e.function!r} expects "
                f"{fn.min_args}..{fn.max_args} arguments, got {len(e.args)}",
                code=EErrorCode.QueryTypeError)
        # One shared partition spec per query; ONE common ORDER BY among
        # the items that order at all (an order-less item has a whole-
        # partition frame, so the shared sort cannot change its result).
        if self.partition is None:
            self.partition = e.spec.partition_by
        elif self.partition != e.spec.partition_by:
            raise YtError(
                "All window functions in one query must share the same "
                "PARTITION BY spec", code=EErrorCode.QueryUnsupported)
        if e.spec.order_by:
            if self.order is None:
                self.order = e.spec.order_by
            elif self.order != e.spec.order_by:
                raise YtError(
                    "All ordered window functions in one query must share "
                    "the same ORDER BY spec",
                    code=EErrorCode.QueryUnsupported)
        if fn.needs_order and not e.spec.order_by:
            raise YtError(f"{e.function} requires ORDER BY in OVER (...)",
                          code=EErrorCode.QueryTypeError)
        if e.spec.frame is not None and not fn.is_aggregate:
            raise YtError(
                f"{e.function} does not accept a ROWS frame",
                code=EErrorCode.QueryTypeError)
        if e.spec.frame is not None and not e.spec.order_by:
            raise YtError("A ROWS frame requires ORDER BY in OVER (...)",
                          code=EErrorCode.QueryTypeError)

        argument = None
        offset = 1
        default = None
        if e.function in ("lag", "lead"):
            argument = self.base_builder.build(e.args[0])
            if len(e.args) > 1:
                if not isinstance(e.args[1], ast.Literal) or \
                        not isinstance(e.args[1].value, int) or \
                        isinstance(e.args[1].value, bool) or \
                        e.args[1].value < 0:
                    raise YtError(
                        f"{e.function} offset must be a non-negative "
                        "integer literal", code=EErrorCode.QueryTypeError)
                offset = int(e.args[1].value)
            if len(e.args) > 2:
                default = self.base_builder.build(e.args[2])
                unify(argument.type, default.type, f"{e.function} default")
            result_type = argument.type if argument.type is not \
                EValueType.null else \
                (default.type if default is not None else argument.type)
        elif fn.min_args > 0 or e.args:
            argument = self.base_builder.build(e.args[0]) if e.args else None
            result_type = fn.infer_result(
                argument.type if argument is not None else None)
        else:
            result_type = fn.infer_result(None)

        if fn.is_aggregate:
            # Implicit default with ORDER BY = the standard RANGE
            # UNBOUNDED PRECEDING..CURRENT ROW: the frame extends to the
            # end of the current PEER group, so tied order keys share
            # one value.  An explicit ROWS frame stays row-exact.
            frame = _normalize_frame(e.spec.frame) \
                if e.spec.frame is not None else \
                (ir.PEERS_FRAME if e.spec.order_by
                 else ir.WHOLE_PARTITION_FRAME)
        else:
            frame = ir.WHOLE_PARTITION_FRAME

        key = (e.function,
               ir._repr_expr(argument) if argument is not None else "",
               frame, offset,
               ir._repr_expr(default) if default is not None else "")
        slot = self._cache.get(key)
        if slot is None:
            slot = f"_win{len(self.items)}"
            self.items.append(ir.WindowItem(
                name=slot, function=e.function, argument=argument,
                type=result_type, frame=frame, offset=offset,
                default=default))
            self._cache[key] = slot
            self.namespace[slot] = result_type
        return ir.TReference(type=self.namespace[slot], name=slot)

    def window_clause(self) -> "Optional[ir.WindowClause]":
        if not self.items:
            return None
        partition_items = tuple(
            ir.NamedExpr(name=f"_winp{i}", expr=self.base_builder.build(p))
            for i, p in enumerate(self.partition or ()))
        order_items = tuple(
            ir.OrderItem(expr=self.base_builder.build(oi.expr),
                         descending=oi.descending)
            for oi in (self.order or ()))
        return ir.WindowClause(partition_items=partition_items,
                               order_items=order_items,
                               items=tuple(self.items))


def _walk_placeholders(node, visit):
    """Generic AST walk: calls `visit` on every Placeholder; returns the
    (possibly rebuilt) node when visit returns a replacement, else the
    original object (identity-preserving so untouched trees stay shared)."""
    import dataclasses as _dc
    if isinstance(node, ast.Placeholder):
        return visit(node)
    if _dc.is_dataclass(node) and not isinstance(node, type):
        changes = {}
        for f in _dc.fields(node):
            old = getattr(node, f.name)
            new = _walk_placeholders(old, visit)
            if new is not old:
                changes[f.name] = new
        return _dc.replace(node, **changes) if changes else node
    if isinstance(node, tuple):
        rebuilt = tuple(_walk_placeholders(x, visit) for x in node)
        return rebuilt if any(a is not b for a, b in zip(rebuilt, node)) \
            else node
    return node


def substitute_params(q: ast.QueryAst,
                      params: "Optional[Sequence]") -> ast.QueryAst:
    """Replace `?` placeholders with literals from `params` (positional).
    A flat number sequence becomes a vector literal; scalars keep their
    natural literal type.  Loud on arity mismatch either way."""
    seen: set[int] = set()

    def visit(p: ast.Placeholder):
        seen.add(p.index)
        if params is None or p.index >= len(params):
            raise YtError(
                f"Query has placeholder ?{p.index} but only "
                f"{0 if params is None else len(params)} params were given",
                code=EErrorCode.QueryTypeError)
        value = params[p.index]
        if isinstance(value, (list, tuple)):
            return ast.Literal(tuple(float(x) for x in value))
        return ast.Literal(value)

    out = _walk_placeholders(q, visit)
    if params is not None and len(params) > len(seen):
        raise YtError(
            f"Got {len(params)} params for {len(seen)} placeholders",
            code=EErrorCode.QueryTypeError)
    return out


def build_query(source: str | ast.QueryAst,
                schemas: Mapping[str, TableSchema],
                params: "Optional[Sequence]" = None) -> ir.Query:
    """Parse + build a typed plan.

    `schemas` maps table path → schema; the FROM table plus every JOIN table
    must be present.  `params` binds `?` placeholders positionally (the
    NEAREST query vector rides here as a list of floats).
    """
    q = parse_query(source) if isinstance(source, str) else source
    if params is not None:
        q = substitute_params(q, params)
    if q.source is None:
        raise YtError("Query has no FROM clause", code=EErrorCode.QueryParseError)
    if q.source not in schemas:
        raise YtError(f"Unknown table {q.source!r}", code=EErrorCode.ResolveError)
    self_schema = schemas[q.source]

    # Flat combined namespace: self columns + qualified foreign columns.
    namespace: dict[str, EValueType] = {
        c.name: c.type for c in self_schema}
    alias_map: dict[str, str] = {}
    join_clauses: list[ir.JoinClause] = []
    base_builder = _ExprBuilder(namespace, alias_map)

    for join in q.joins:
        if join.table not in schemas:
            raise YtError(f"Unknown join table {join.table!r}",
                          code=EErrorCode.ResolveError)
        foreign_schema = schemas[join.table]
        alias = join.alias
        self_eqs: list[ir.TExpr] = []
        foreign_eqs: list[ir.TExpr] = []
        foreign_builder = _ExprBuilder(
            {c.name: c.type for c in foreign_schema},
            alias_map={f"{join.alias}.{c.name}": c.name
                       for c in foreign_schema} if join.alias else {})
        if join.using:
            skip_columns = set(join.using)
            for name in join.using:
                self_eqs.append(base_builder.build(ast.Reference(name=name)))
                foreign_eqs.append(foreign_builder.build(ast.Reference(name=name)))
        else:
            skip_columns = set()
            if not join.on:
                raise YtError("JOIN requires USING or ON",
                              code=EErrorCode.QueryParseError)
            for lhs, rhs in join.on:
                self_eqs.append(base_builder.build(lhs))
                foreign_eqs.append(foreign_builder.build(rhs))
        # Merge foreign columns into the flat namespace.
        foreign_columns = []
        for col in foreign_schema:
            if col.name in skip_columns:
                continue
            flat = f"{alias}.{col.name}" if alias else col.name
            if flat in namespace:
                raise YtError(f"Ambiguous column {flat!r} from join; use an alias",
                              code=EErrorCode.QueryTypeError)
            namespace[flat] = col.type
            foreign_columns.append(col.name)
            if alias:
                alias_map[f"{alias}.{col.name}"] = flat
                # Unqualified access allowed when unambiguous.
                if col.name not in namespace and col.name not in alias_map:
                    alias_map[col.name] = flat
        for eq in zip(self_eqs, foreign_eqs):
            unify(eq[0].type, eq[1].type, "JOIN equation")
        join_clauses.append(ir.JoinClause(
            foreign_table=join.table, foreign_schema=foreign_schema,
            alias=alias, self_equations=tuple(self_eqs),
            foreign_equations=tuple(foreign_eqs),
            foreign_columns=tuple(foreign_columns), is_left=join.is_left))

    combined_schema = TableSchema.make(
        [(name, ty.value) for name, ty in namespace.items()])

    where = base_builder.build(q.where) if q.where is not None else None
    if where is not None and where.type not in (EValueType.boolean, EValueType.null):
        raise YtError("WHERE predicate must be boolean",
                      code=EErrorCode.QueryTypeError)

    group_clause = None
    having = None
    final_builder: _ExprBuilder
    if q.group_by:
        group_items = []
        group_exprs: dict[ast.Expr, str] = {}
        group_namespace: dict[str, EValueType] = {}
        for i, item in enumerate(q.group_by):
            name = item.alias or render_expr(item.expr)
            expr = base_builder.build(item.expr)
            if isinstance(expr.type, VectorType):
                raise YtError("GROUP BY does not accept vector expressions",
                              code=EErrorCode.QueryUnsupported)
            group_items.append(ir.NamedExpr(name=name, expr=expr))
            group_exprs[item.expr] = name
            # An aliased group item is also addressable by its alias.
            if item.alias is not None:
                group_exprs[ast.Reference(name=item.alias)] = name
            group_namespace[name] = expr.type
        agg_builder = _AggregatingBuilder(base_builder, group_exprs,
                                          group_namespace)
        if q.having is not None:
            having = agg_builder.build(q.having)
            if having.type not in (EValueType.boolean, EValueType.null):
                raise YtError("HAVING predicate must be boolean",
                              code=EErrorCode.QueryTypeError)
        final_builder = agg_builder
    else:
        if q.having is not None:
            raise YtError("HAVING requires GROUP BY",
                          code=EErrorCode.QueryParseError)
        # Non-grouped queries may carry window calls in the SELECT list.
        final_builder = _WindowBuilder(base_builder)

    project = None
    if q.select is not None:
        items = []
        for item in q.select:
            expr = final_builder.build(item.expr)
            if isinstance(expr.type, VectorType) and \
                    not isinstance(expr, ir.TReference):
                raise YtError(
                    "Vector expressions in SELECT must be plain column "
                    "references", code=EErrorCode.QueryUnsupported)
            name = item.alias or render_expr(item.expr)
            items.append(ir.NamedExpr(name=name, expr=expr))
        project = ir.ProjectClause(items=tuple(items))

    order = None
    if q.order_by:
        order_items = []
        for oi in q.order_by:
            expr = final_builder.build(oi.expr)
            if isinstance(expr.type, VectorType):
                raise YtError(
                    "ORDER BY does not accept a raw vector (no total "
                    "order); order by a distance function instead",
                    code=EErrorCode.QueryUnsupported)
            order_items.append(ir.OrderItem(expr=expr, descending=oi.descending))
        order = ir.OrderClause(items=tuple(order_items))

    if q.group_by:
        agg_builder = final_builder  # type: ignore[assignment]
        group_clause = ir.GroupClause(
            group_items=tuple(group_items),
            aggregate_items=tuple(agg_builder.aggregates),  # type: ignore[attr-defined]
            totals=q.with_totals)

    if q.order_by and q.limit is None:
        raise YtError("ORDER BY requires LIMIT (ref QL semantics)",
                      code=EErrorCode.QueryParseError)

    window_clause = None
    if isinstance(final_builder, _WindowBuilder):
        window_clause = final_builder.window_clause()

    return ir.Query(
        schema=combined_schema,
        source=q.source,
        joins=tuple(join_clauses),
        where=where,
        group=group_clause,
        window=window_clause,
        having=having,
        order=order,
        project=project,
        offset=q.offset or 0,
        limit=q.limit)
