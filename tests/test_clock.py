"""Clock quorum: HLC timestamps from elected clock peers with
quorum-persisted ceilings.

Ref model: server/clock_server/cluster_clock (the quorum whose only
state is the timestamp ceiling), server/timestamp_provider (the serving
daemon), ytlib/transaction_client (client-side request batching).
"""

import threading
import time

import pytest

from ytsaurus_tpu.errors import YtError
from ytsaurus_tpu.rpc import RpcServer
from ytsaurus_tpu.tablet.clock import (
    CEILING_QUANTUM,
    ClockService,
    QuorumTimestampProvider,
)


class _FakeClock:
    """Always-leader clock core for provider-side unit tests."""

    def __init__(self):
        self._last = 1000
        self.calls = 0
        self._lock = threading.Lock()

    def generate_batch(self, count=1):
        with self._lock:
            self.calls += 1
            first = self._last + 1
            self._last += count
            return first, count

    @property
    def is_leader(self):
        return True


@pytest.fixture
def fake_clock_server():
    core = _FakeClock()
    server = RpcServer([ClockService(core)], port=0)
    server.start()
    yield core, server.address
    server.stop()


def test_provider_generates_unique_monotone(fake_clock_server):
    core, address = fake_clock_server
    provider = QuorumTimestampProvider([address])
    got = [provider.generate() for _ in range(20)]
    assert got == sorted(got) and len(set(got)) == 20
    batch = provider.generate_batch(50)
    assert len(batch) == 50 and batch[0] > got[-1]
    assert batch == sorted(set(batch))
    provider.close()


def test_provider_coalesces_concurrent_requests(fake_clock_server):
    """Threads arriving together share RPCs (the transaction_client
    batcher): far fewer server calls than client generate() calls."""
    core, address = fake_clock_server
    provider = QuorumTimestampProvider([address])
    provider.generate()                       # warm the channel
    calls_before = core.calls
    results: list[int] = []
    lock = threading.Lock()

    def worker():
        ts = provider.generate()
        with lock:
            results.append(ts)

    threads = [threading.Thread(target=worker) for _ in range(40)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert len(results) == 40
    assert len(set(results)) == 40            # all unique
    assert core.calls - calls_before < 40     # coalescing happened
    provider.close()


def test_provider_fails_over_between_peers(fake_clock_server):
    core, address = fake_clock_server
    provider = QuorumTimestampProvider(
        ["127.0.0.1:1", address], failover_deadline=20.0)
    ts = provider.generate()                  # dead peer skipped
    assert ts > 0
    provider.close()


# -- full-stack quorum ---------------------------------------------------------


def test_clock_leader_failover_stays_monotone(tmp_path):
    """Kill-the-clock-leader: the standby takes over and every new
    timestamp is strictly above every pre-kill one (the persisted
    ceiling is the proof)."""
    from ytsaurus_tpu.environment import LocalCluster

    with LocalCluster(str(tmp_path / "c"), n_nodes=3, n_masters=1,
                      n_clocks=2, lease_ttl=3.0) as cluster:
        provider = QuorumTimestampProvider(cluster.clock_addresses,
                                           failover_deadline=60.0)
        before = provider.generate_batch(500)
        assert before == sorted(set(before))
        killed = cluster.kill_clock_leader()
        after = provider.generate_batch(500)
        assert after[0] > before[-1]          # monotone across failover
        assert after == sorted(set(after))
        assert cluster.clock_leader_index(timeout=60) != killed
        provider.close()


@pytest.mark.slow   # ~15s; tier-1 keeps clock-quorum coverage via
# test_clock_leader_failover_stays_monotone (real processes) and the
# provider failover units above.
def test_tablet_commits_use_quorum_with_primary_down(tmp_path):
    """The VERDICT done-criterion: with the primary master KILLED, the
    successor keeps committing tablet transactions, and their
    timestamps (from the clock quorum, which never restarted) stay
    strictly monotone across the master failover."""
    from ytsaurus_tpu.environment import LocalCluster
    from ytsaurus_tpu.remote_client import connect_remote
    from ytsaurus_tpu.schema import TableSchema

    with LocalCluster(str(tmp_path / "c"), n_nodes=3, n_masters=2,
                      n_clocks=2, lease_ttl=3.0) as cluster:
        client = connect_remote(cluster.master_addresses)
        schema = TableSchema.make([("k", "int64", "ascending"),
                                   ("v", "string")])
        client.create("table", "//dyn", recursive=True,
                      attributes={"schema": schema, "dynamic": True})
        client.mount_table("//dyn")
        client.insert_rows("//dyn", [{"k": 1, "v": "pre"}])
        tx = client.start_transaction()
        ts_before = tx.start_timestamp
        client.abort_transaction(tx)
        assert ts_before > 0

        killed = cluster.kill_leader()
        # The successor serves; retry through the failover window.
        deadline = time.monotonic() + 120
        ts_after = None
        while time.monotonic() < deadline:
            try:
                tx = client.start_transaction()
                ts_after = tx.start_timestamp
                client.abort_transaction(tx)
                break
            except YtError:
                time.sleep(0.5)
        assert ts_after is not None, "successor never served"
        assert ts_after > ts_before      # quorum clock: monotone across
        assert cluster.leader_index(timeout=60) != killed

        # Tablet commits land on the successor with quorum timestamps.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            try:
                client.create("table", "//dyn2", recursive=True,
                              attributes={"schema": schema,
                                          "dynamic": True})
                client.mount_table("//dyn2")
                client.insert_rows("//dyn2", [{"k": 7, "v": "post"}])
                break
            except YtError:
                time.sleep(0.5)
        rows = client.lookup_rows("//dyn2", [(7,)])
        assert rows[0]["v"] == b"post"
        client.close()
