"""Table replication: sync/async replicas, replicator, health tracker.

Ref mapping (server/node/tablet_node + server/replicated_table_tracker):
  table_replicator.cpp            → TableReplicator (pulls committed
                                    versions newer than the replica
                                    checkpoint, applies them in timestamp
                                    order to the replica table)
  transaction.cpp:737-830 (sync   → sync replicas are enrolled as extra
  replica fanout in ModifyRows)     participants of the SAME upstream 2PC
                                    commit (client.insert_rows/delete_rows)
  replicated_table_tracker        → ReplicatedTableTracker (health probes,
                                    demote broken sync replicas, promote
                                    caught-up async ones to honor
                                    @min_sync_replicas)
  hedging_channel.h / client      → replica fallback reads: lookup falls
  hedging                           back to the freshest enabled replica

Design delta (TPU-first): there is no separate replication-log table.  The
versioned snapshot planes ARE the log — every committed version carries its
timestamp and per-column $w written flags, so "what changed after ts X" is
a single vectorized filter over the versioned planes, not a per-row log
tail.  Replica applies preserve upstream timestamps (the provider is a
hybrid logical clock: `TimestampProvider.observe` folds replicated
timestamps into the replica clock so local commits stay monotone).
"""

from __future__ import annotations

from typing import Optional

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.tablet.timestamp import _global_provider

REPLICAS_ATTR = "replicas"


def replica_descriptors(client, table_path: str) -> dict:
    """The @replicas attribute: replica_id → descriptor dict.  Reads the
    node attribute directly — this sits on the hot write path, so the
    common non-replicated case must not build/catch an exception."""
    node = client._table_node(table_path)
    return dict(node.attributes.get(REPLICAS_ATTR) or {})


def set_replica_descriptors(client, table_path: str, replicas: dict) -> None:
    client.set(table_path + "/@" + REPLICAS_ATTR, replicas)


def events_since(client, table_path: str, checkpoint_ts: int) -> list:
    """Committed modifications with timestamp > checkpoint_ts, oldest first.

    Each event is (ts, "write"|"delete", row_or_key).  Write payloads carry
    only the columns that version actually wrote (per-column $w planes) so
    partial writes replicate as partial writes (versioned_row_merger
    semantics, ytlib/table_client/versioned_row_merger.h).
    """
    tablets = client._mounted_tablets(table_path)
    schema = tablets[0].schema
    key_names = schema.key_column_names
    value_names = [c.name for c in schema if c.sort_order is None]
    events = []
    for tablet in tablets:
        for vrow in tablet.versioned_rows_snapshot():
            ts = vrow["$timestamp"]
            if ts <= checkpoint_ts:
                continue
            key = tuple(vrow[k] for k in key_names)
            if vrow["$tombstone"]:
                events.append((ts, "delete", key))
            else:
                row = dict(zip(key_names, key))
                for name in value_names:
                    if vrow.get(f"$w:{name}"):
                        row[name] = vrow[name]
                events.append((ts, "write", row))
    events.sort(key=lambda e: e[0])
    return events


def apply_events(replica_client, replica_path: str, events: list) -> int:
    """Apply replicated events to the replica table with PRESERVED upstream
    timestamps (writes go straight into the tablet stores; ordering and
    conflict-freedom come from replaying in commit order)."""
    if not events:
        return 0
    tablets = replica_client._mounted_tablets(replica_path)
    applied = 0
    for ts, op, payload in events:
        _global_provider.observe(ts)
        routed = replica_client._route_rows(replica_path, tablets, [payload])
        for idx, part in routed.items():
            for item in part:
                if op == "delete":
                    tablets[idx].delete_row(tuple(item), ts)
                else:
                    tablets[idx].write_row(item, ts, update=True)
        applied += 1
    return applied


class TableReplicator:
    """Pull-based async replicator (ref table_replicator.cpp).

    One instance serves any number of replicated tables; remote-cluster
    clients (replicas living under a different root_dir) are cached.
    """

    def __init__(self, client):
        self.client = client
        self._remote_clients: dict[str, object] = {}

    def replica_client(self, cluster_root: Optional[str]):
        if cluster_root is None or \
                cluster_root == self.client.cluster.root_dir:
            return self.client
        cached = self._remote_clients.get(cluster_root)
        if cached is None:
            from ytsaurus_tpu.client import connect
            cached = connect(cluster_root)
            self._remote_clients[cluster_root] = cached
        return cached

    def sync_replica(self, table_path: str, replica_id: str) -> int:
        """Catch one replica up to the upstream head; returns the number of
        events applied.  Raises (and records the error on the descriptor)
        if the replica is unreachable."""
        replicas = replica_descriptors(self.client, table_path)
        info = replicas.get(replica_id)
        if info is None:
            raise YtError(f"No such replica {replica_id!r} of {table_path!r}",
                          code=EErrorCode.ResolveError)
        try:
            rc = self.replica_client(info.get("cluster_root"))
            events = events_since(self.client, table_path,
                                  int(info.get("last_replicated_ts", 0)))
            applied = apply_events(rc, info["path"], events)
            if events:
                info["last_replicated_ts"] = max(e[0] for e in events)
            info["error"] = None
        except YtError as err:
            info["error"] = str(err)
            set_replica_descriptors(self.client, table_path, replicas)
            raise
        set_replica_descriptors(self.client, table_path, replicas)
        return applied

    def replicate_step(self, table_path: str) -> dict:
        """One replicator pass: catch up every enabled async replica.
        Returns replica_id → applied-event count (or -1 on error)."""
        out = {}
        for rid, info in replica_descriptors(self.client, table_path).items():
            if not info.get("enabled") or info.get("mode") != "async":
                continue
            try:
                out[rid] = self.sync_replica(table_path, rid)
            except YtError:
                out[rid] = -1
        return out

    def lag(self, table_path: str, replica_id: str) -> int:
        """Unreplicated-event count (upstream versions past checkpoint)."""
        info = replica_descriptors(self.client, table_path)[replica_id]
        return len(events_since(self.client, table_path,
                                int(info.get("last_replicated_ts", 0))))


class ReplicatedTableTracker:
    """Health-based sync/async mode management
    (ref server/replicated_table_tracker).

    step() probes every replica, demotes broken sync replicas to async,
    and promotes caught-up healthy async replicas until the table's
    @min_sync_replicas (default 1) healthy sync replicas exist.
    """

    def __init__(self, replicator: TableReplicator):
        self.replicator = replicator
        self.client = replicator.client

    def probe(self, info: dict) -> Optional[str]:
        """None when healthy, else the failure reason."""
        if not info.get("enabled"):
            return "disabled"
        try:
            rc = self.replicator.replica_client(info.get("cluster_root"))
            if not rc.exists(info["path"]):
                return "replica table missing"
            if rc.get(info["path"] + "/@tablet_state") != "mounted":
                return "replica table not mounted"
        except YtError as err:
            return str(err)
        return None

    def step(self, table_path: str) -> dict:
        try:
            min_sync = int(self.client.get(
                table_path + "/@min_sync_replicas"))
        except YtError:
            min_sync = 1
        replicas = replica_descriptors(self.client, table_path)
        health = {rid: self.probe(info) for rid, info in replicas.items()}
        # Demote broken sync replicas.
        for rid, info in replicas.items():
            if info.get("mode") == "sync" and health[rid] is not None:
                info["mode"] = "async"
        sync_count = sum(1 for rid, info in replicas.items()
                         if info.get("mode") == "sync"
                         and health[rid] is None)
        set_replica_descriptors(self.client, table_path, replicas)
        # Promote healthy async replicas (catch them up first so the flip
        # to sync does not serve stale reads).
        for rid, info in sorted(
                replicas.items(),
                key=lambda kv: -int(kv[1].get("last_replicated_ts", 0))):
            if sync_count >= min_sync:
                break
            if info.get("mode") != "async" or health[rid] is not None:
                continue
            try:
                self.replicator.sync_replica(table_path, rid)
            except YtError:
                continue
            replicas = replica_descriptors(self.client, table_path)
            replicas[rid]["mode"] = "sync"
            set_replica_descriptors(self.client, table_path, replicas)
            sync_count += 1
        return {"health": health, "sync_count": sync_count}
