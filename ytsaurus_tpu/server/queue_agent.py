"""Queue agent: consumer registrations, offsets, lag tracking, auto-trim.

Ref mapping (server/queue_agent + client/queue_client):
  consumer tables (consumer_client.h)     → sorted dynamic table with the
                                            standard consumer schema
                                            (queue_path, partition_index)
                                            → offset
  RegisterQueueConsumer                   → register_consumer (recorded in
                                            the queue's @registrations)
  AdvanceConsumer (monotonic unless       → advance_consumer
  client passes expected offset)
  queue_agent controller passes           → QueueAgent.step(): per-queue
  (queue_controller.cpp)                    partition stats, consumer lags,
                                            auto-trim up to the minimum
                                            vital-consumer offset
  @queue_status / orchid export           → @queue_status attribute on the
                                            queue node

Design delta: queues are single-partition ordered tablets today; the
consumer schema and status layout carry partition_index so multi-partition
queues slot in without an API change.
"""

from __future__ import annotations

from typing import Optional

from ytsaurus_tpu.errors import EErrorCode, YtError
from ytsaurus_tpu.schema import TableSchema

CONSUMER_SCHEMA = TableSchema.make([
    ("queue_path", "string", "ascending"),
    ("partition_index", "int64", "ascending"),
    ("offset", "int64"),
], unique_keys=True)


def is_consumer_schema(schema: TableSchema) -> bool:
    return [c.name for c in schema] == [c.name for c in CONSUMER_SCHEMA]


def _consumer_offset(client, consumer_path: str, queue_path: str,
                     partition_index: int = 0) -> int:
    # System path: consumer-offset bookkeeping must not queue behind
    # user read admission.
    rows = client._lookup_rows_direct(consumer_path,
                                      [(queue_path, partition_index)])
    return int(rows[0]["offset"]) if rows[0] is not None else 0


class QueueAgent:
    """Background queue controller (one instance serves a cluster)."""

    def __init__(self, client):
        self.client = client

    def queue_status(self, queue_path: str) -> dict:
        """Partition stats + per-consumer offsets and lags."""
        (tablet,) = self.client._mounted_tablets(queue_path)
        total = tablet.row_count
        trimmed = tablet.trimmed_count
        consumers = {}
        node = self.client._table_node(queue_path)
        for cpath, reg in (node.attributes.get("registrations")
                           or {}).items():
            try:
                offset = _consumer_offset(self.client, cpath, queue_path)
            except YtError:
                offset = 0
            consumers[cpath] = {
                "offset": offset,
                "lag": max(total - offset, 0),
                "vital": bool(reg.get("vital", True)),
            }
        return {
            "partitions": [{
                "partition_index": 0,
                "upper_row_index": total,
                "trimmed_row_count": trimmed,
                "available_row_count": total - trimmed,
            }],
            "consumers": consumers,
        }

    def trim_queue(self, queue_path: str) -> int:
        """Trim rows every VITAL consumer has passed (ref auto-trim:
        vital consumers gate trimming; non-vital ones may lag forever).
        Returns the new trimmed_row_count."""
        status = self.queue_status(queue_path)
        vital_offsets = [c["offset"] for c in status["consumers"].values()
                         if c["vital"]]
        (tablet,) = self.client._mounted_tablets(queue_path)
        if not vital_offsets:
            return tablet.trimmed_count
        target = min(vital_offsets)
        if target > tablet.trimmed_count:
            tablet.trim_rows(target)
        return tablet.trimmed_count

    def step(self) -> dict:
        """One agent pass over every registered queue: refresh
        @queue_status, auto-trim queues whose @auto_trim_config enables it.
        Returns queue_path → status."""
        out = {}
        for queue_path in self._registered_queues():
            try:
                node = self.client._table_node(queue_path)
                auto_trim = (node.attributes.get("auto_trim_config")
                             or {}).get("enable", False)
                if auto_trim:
                    self.trim_queue(queue_path)
                status = self.queue_status(queue_path)
                self.client.set(queue_path + "/@queue_status", status)
                out[queue_path] = status
            except YtError as err:
                out[queue_path] = {"error": str(err)}
        return out

    def _registered_queues(self) -> list[str]:
        """Queues = dynamic tables with an unsorted schema that carry at
        least one registration (scan mirrors the agent's Cypress poll)."""
        found = []
        stack = [("/", self.client.cluster.master.tree.root)]
        while stack:
            path, node = stack.pop()
            if node.type == "table" and \
                    node.attributes.get("registrations"):
                found.append(path)
            for name, child in node.children.items():
                stack.append((f"/{path.rstrip('/')}/{name}", child))
        return sorted(found)


def register_consumer(client, queue_path: str, consumer_path: str,
                      vital: bool = True) -> None:
    """Create (if needed) the consumer table and record the registration
    on the queue node (ref RegisterQueueConsumer)."""
    (tablet,) = client._mounted_tablets(queue_path)
    from ytsaurus_tpu.tablet.ordered import OrderedTablet
    if not isinstance(tablet, OrderedTablet):
        raise YtError(f"{queue_path!r} is not an ordered (queue) table",
                      code=EErrorCode.QueryUnsupported)
    if not client.exists(consumer_path):
        client.create("table", consumer_path, recursive=True,
                      attributes={"schema": CONSUMER_SCHEMA,
                                  "dynamic": True,
                                  "treat_as_queue_consumer": True})
        client.mount_table(consumer_path)
    else:
        schema = client._node_schema(client._table_node(consumer_path))
        if schema is None or not is_consumer_schema(schema):
            raise YtError(f"{consumer_path!r} is not a consumer table",
                          code=EErrorCode.QueryTypeError)
    regs = dict(client._table_node(queue_path).attributes.get(
        "registrations") or {})
    regs[consumer_path] = {"vital": bool(vital)}
    client.set(queue_path + "/@registrations", regs)


def unregister_consumer(client, queue_path: str,
                        consumer_path: str) -> None:
    regs = dict(client._table_node(queue_path).attributes.get(
        "registrations") or {})
    regs.pop(consumer_path, None)
    client.set(queue_path + "/@registrations", regs)


def advance_consumer(client, consumer_path: str, queue_path: str,
                     new_offset: int,
                     old_offset: Optional[int] = None,
                     partition_index: int = 0) -> None:
    """Move a consumer's offset forward.  old_offset, when given, must
    match the stored offset (optimistic concurrency, ref AdvanceConsumer);
    offsets never move backwards."""
    current = _consumer_offset(client, consumer_path, queue_path,
                               partition_index)
    if old_offset is not None and old_offset != current:
        raise YtError(
            f"Consumer offset mismatch: expected {old_offset}, "
            f"stored {current}", code=EErrorCode.TransactionLockConflict)
    if new_offset < current:
        raise YtError(f"Consumer offset may not move backwards "
                      f"({current} -> {new_offset})",
                      code=EErrorCode.QueryTypeError)
    client.insert_rows(consumer_path, [{
        "queue_path": queue_path, "partition_index": partition_index,
        "offset": new_offset}])


def pull_consumer(client, consumer_path: str, queue_path: str,
                  limit: Optional[int] = None,
                  partition_index: int = 0) -> tuple[list[dict], int]:
    """Read rows from the consumer's current offset.  Returns (rows,
    next_offset); the caller advances explicitly after processing
    (at-least-once delivery, ref pull_consumer)."""
    offset = _consumer_offset(client, consumer_path, queue_path,
                              partition_index)
    rows = client.pull_queue(queue_path, offset=offset, limit=limit)
    # Trimming may have advanced past the stored offset: next_offset comes
    # from the actual row indexes served, not offset + len(rows).  When
    # the trim passed the offset AND nothing is live (rows == []), the
    # cursor must still land on the trim boundary — returning the stale
    # offset would park the consumer below trimmed_count forever (its
    # lag never drains, and a later advance_consumer(next_offset) would
    # be a no-op loop).  Surfaced by the view-daemon tail loop
    # (ISSUE 13 satellite); regression-tested in tests/test_views.py.
    if rows:
        next_offset = rows[-1]["$row_index"] + 1
    else:
        (tablet,) = client._mounted_tablets(queue_path)
        next_offset = max(offset, tablet.trimmed_count)
    return rows, next_offset
